"""Evaluation metrics (reference src/metric/: factory ``Metric::CreateMetric``
in metric.cpp:16-65; regression_metric.hpp, binary_metric.hpp,
multiclass_metric.hpp, rank_metric.hpp + dcg_calculator.cpp, map_metric.hpp,
xentropy_metric.hpp — 24 metrics).

Metrics run host-side on numpy copies of the scores once per ``metric_freq``
iterations — they are O(N) or O(N log N) and off the training hot path, so
device residency buys nothing (the reference likewise evaluates metrics on
CPU outside the tree-growing loop)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from ..dataset import Metadata

__all__ = ["create_metrics", "Metric", "METRIC_ALIASES"]

METRIC_ALIASES = {
    "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "mean_squared_error": "l2", "mse": "l2", "regression": "l2",
    "regression_l2": "l2",
    "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "mean_absolute_percentage_error": "mape",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg", "xendcg": "ndcg",
    "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg", "xendcg_mart": "ndcg",
    "mean_average_precision": "map",
    "xentropy": "cross_entropy", "xentlambda": "cross_entropy_lambda",
    "kldiv": "kullback_leibler",
    "multi_logloss": "multi_logloss", "softmax": "multi_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
}


class Metric:
    """Base metric (reference include/LightGBM/metric.h:24)."""

    name = "base"
    is_higher_better = False

    def __init__(self, config: Config) -> None:
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.label = metadata.label
        self.weight = metadata.weight
        self.sum_weight = (float(np.sum(self.weight))
                           if self.weight is not None else float(num_data))
        self.query_boundaries = metadata.query_boundaries
        self.num_data = num_data

    def eval(self, score: np.ndarray) -> List[Tuple[str, float, bool]]:
        """score: raw (untransformed) ensemble score, (N,) or (N, K)."""
        raise NotImplementedError

    @property
    def eval_names(self) -> List[str]:
        """One entry per value ``eval`` returns (the reference's
        Metric::GetName() vector — multi-position metrics like ndcg/map
        report one value per eval_at position, c_api.cpp GetEvalCounts)."""
        return [self.name]

    # helpers
    def _avg(self, pointwise: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(pointwise * self.weight) / self.sum_weight)
        return float(np.mean(pointwise))


def _sigmoid(x: np.ndarray, k: float = 1.0) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-k * np.clip(x, -500, 500)))


def _softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


EPS = 1e-15


# ---------------------------------------------------------------- regression
class L2Metric(Metric):
    name = "l2"

    def eval(self, score):
        return [("l2", self._avg((score - self.label) ** 2), False)]


class RMSEMetric(Metric):
    name = "rmse"

    def eval(self, score):
        return [("rmse", float(np.sqrt(self._avg((score - self.label) ** 2))), False)]


class L1Metric(Metric):
    name = "l1"

    def eval(self, score):
        return [("l1", self._avg(np.abs(score - self.label)), False)]


class QuantileMetric(Metric):
    name = "quantile"

    def eval(self, score):
        a = float(self.config.alpha)
        d = self.label - score
        loss = np.where(d >= 0, a * d, (a - 1.0) * d)
        return [("quantile", self._avg(loss), False)]


class MapeMetric(Metric):
    name = "mape"

    def eval(self, score):
        loss = np.abs((self.label - score) / np.maximum(1.0, np.abs(self.label)))
        return [("mape", self._avg(loss), False)]


class HuberMetric(Metric):
    name = "huber"

    def eval(self, score):
        a = float(self.config.alpha)
        d = np.abs(score - self.label)
        loss = np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
        return [("huber", self._avg(loss), False)]


class FairMetric(Metric):
    name = "fair"

    def eval(self, score):
        c = float(self.config.fair_c)
        x = np.abs(score - self.label)
        loss = c * x - c * c * np.log1p(x / c)
        return [("fair", self._avg(loss), False)]


class PoissonMetric(Metric):
    name = "poisson"

    def eval(self, score):
        # score is log-mean (regression_metric.hpp PoissonMetric: eval on exp)
        mu = np.exp(score)
        loss = mu - self.label * score
        return [("poisson", self._avg(loss), False)]


class GammaMetric(Metric):
    name = "gamma"

    def eval(self, score):
        mu = np.exp(score)
        psi = self.label / mu + score  # -log likelihood up to const
        return [("gamma", self._avg(psi), False)]


class GammaDevianceMetric(Metric):
    name = "gamma_deviance"

    def eval(self, score):
        mu = np.exp(score)
        eps = 1e-9
        d = 2.0 * (-np.log((self.label + eps) / mu) + (self.label + eps) / mu - 1.0)
        return [("gamma_deviance", self._avg(d), False)]


class TweedieMetric(Metric):
    name = "tweedie"

    def eval(self, score):
        rho = float(self.config.tweedie_variance_power)
        mu = np.exp(score)
        a = self.label * np.power(mu, 1.0 - rho) / (1.0 - rho)
        b = np.power(mu, 2.0 - rho) / (2.0 - rho)
        return [("tweedie", self._avg(-a + b), False)]


# -------------------------------------------------------------------- binary
class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score):
        p = np.clip(_sigmoid(score, float(self.config.sigmoid)), EPS, 1 - EPS)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [("binary_logloss", self._avg(loss), False)]


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score):
        p = _sigmoid(score, float(self.config.sigmoid))
        err = ((p > 0.5) != (self.label > 0)).astype(np.float64)
        return [("binary_error", self._avg(err), False)]


def _weighted_auc(label: np.ndarray, score: np.ndarray,
                  weight: Optional[np.ndarray]) -> float:
    """Weighted ROC-AUC with tie handling (reference binary_metric.hpp
    AUCMetric::Eval — cumulative trapezoids over score-sorted groups)."""
    w = weight if weight is not None else np.ones_like(label, dtype=np.float64)
    order = np.argsort(-score, kind="stable")
    s, y, ww = score[order], label[order], w[order]
    wpos = ww * (y > 0)
    wneg = ww * (y <= 0)
    tp = np.cumsum(wpos)
    fp = np.cumsum(wneg)
    # group boundaries: last index of each tied score run
    is_end = np.r_[s[1:] != s[:-1], True]
    tp_e = tp[is_end]
    fp_e = fp[is_end]
    tp_prev = np.r_[0.0, tp_e[:-1]]
    fp_prev = np.r_[0.0, fp_e[:-1]]
    area = np.sum((fp_e - fp_prev) * (tp_e + tp_prev) * 0.5)
    denom = tp_e[-1] * fp_e[-1]
    return float(area / denom) if denom > 0 else 0.5


class AUCMetric(Metric):
    name = "auc"
    is_higher_better = True

    def eval(self, score):
        return [("auc", _weighted_auc(self.label, score, self.weight), True)]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    is_higher_better = True

    def eval(self, score):
        w = self.weight if self.weight is not None else np.ones_like(self.label,
                                                                     np.float64)
        order = np.argsort(-score, kind="stable")
        y, ww = self.label[order], w[order]
        tp = np.cumsum(ww * (y > 0))
        total = np.cumsum(ww)
        pos_total = tp[-1]
        if pos_total <= 0:
            return [("average_precision", 0.0, True)]
        precision = tp / np.maximum(total, EPS)
        rec_delta = np.diff(np.r_[0.0, tp]) / pos_total
        return [("average_precision", float(np.sum(precision * rec_delta)), True)]


# ---------------------------------------------------------------- multiclass
class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score):
        if self.config.objective == "multiclassova":
            p = _sigmoid(score, float(self.config.sigmoid))
            p = p / np.maximum(p.sum(axis=1, keepdims=True), EPS)
        else:
            p = _softmax(score)
        y = self.label.astype(np.int64)
        py = np.clip(p[np.arange(len(y)), y], EPS, 1.0)
        return [("multi_logloss", self._avg(-np.log(py)), False)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score):
        y = self.label.astype(np.int64)
        k = int(self.config.multi_error_top_k)
        if k <= 1:
            err = (np.argmax(score, axis=1) != y).astype(np.float64)
        else:
            topk = np.argsort(-score, axis=1)[:, :k]
            err = (~(topk == y[:, None]).any(axis=1)).astype(np.float64)
        return [(f"multi_error{'@' + str(k) if k > 1 else ''}",
                 self._avg(err), False)]


class AucMuMetric(Metric):
    """auc_mu multiclass AUC (reference multiclass_metric.hpp:368 region;
    Kleiman & Page, "AUC-mu")."""
    name = "auc_mu"
    is_higher_better = True

    def eval(self, score):
        y = self.label.astype(np.int64)
        k = score.shape[1]
        w = self.weight if self.weight is not None else np.ones(len(y))
        aucs = []
        for a in range(k):
            for b in range(a + 1, k):
                sel = (y == a) | (y == b)
                if sel.sum() == 0 or len(np.unique(y[sel])) < 2:
                    continue
                # partition by score difference along the (a,b) direction
                s = score[sel, a] - score[sel, b]
                lab = (y[sel] == a).astype(np.float64)
                aucs.append(_weighted_auc(lab, s, w[sel]))
        val = float(np.mean(aucs)) if aucs else 0.5
        return [("auc_mu", val, True)]


# ------------------------------------------------------------------- ranking
class NDCGMetric(Metric):
    name = "ndcg"
    is_higher_better = True

    @property
    def eval_names(self):
        return [f"ndcg@{int(k)}" for k in self.config.eval_at]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            raise ValueError("ndcg metric requires query data")
        self.label_gain = np.asarray(self.config.label_gain, dtype=np.float64)

    def eval(self, score):
        eval_at = [int(k) for k in self.config.eval_at]
        qb = self.query_boundaries
        results = {k: [] for k in eval_at}
        for i in range(len(qb) - 1):
            lab = self.label[qb[i]:qb[i + 1]].astype(np.int64)
            s = score[qb[i]:qb[i + 1]]
            order = np.argsort(-s, kind="stable")
            ideal = np.sort(lab)[::-1]
            for k in eval_at:
                kk = min(k, len(lab))
                disc = 1.0 / np.log2(np.arange(kk) + 2.0)
                dcg = float((self.label_gain[lab[order[:kk]]] * disc).sum())
                idcg = float((self.label_gain[ideal[:kk]] * disc).sum())
                results[k].append(dcg / idcg if idcg > 0 else 1.0)
        return [(f"ndcg@{k}", float(np.mean(results[k])), True) for k in eval_at]


class MapMetric(Metric):
    name = "map"

    @property
    def eval_names(self):
        return [f"map@{int(k)}" for k in self.config.eval_at]
    is_higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            raise ValueError("map metric requires query data")

    def eval(self, score):
        eval_at = [int(k) for k in self.config.eval_at]
        qb = self.query_boundaries
        results = {k: [] for k in eval_at}
        for i in range(len(qb) - 1):
            lab = (self.label[qb[i]:qb[i + 1]] > 0).astype(np.float64)
            s = score[qb[i]:qb[i + 1]]
            order = np.argsort(-s, kind="stable")
            rel = lab[order]
            hits = np.cumsum(rel)
            prec = hits / (np.arange(len(rel)) + 1.0)
            for k in eval_at:
                kk = min(k, len(rel))
                npos = rel[:kk].sum()
                ap = float((prec[:kk] * rel[:kk]).sum() / npos) if npos > 0 else 0.0
                results[k].append(ap)
        return [(f"map@{k}", float(np.mean(results[k])), True) for k in eval_at]


# ------------------------------------------------------------- cross-entropy
class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, score):
        p = np.clip(_sigmoid(score), EPS, 1 - EPS)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [("cross_entropy", self._avg(loss), False)]


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score):
        # p = 1 - exp(-w * log1p(exp(score))) (xentropy_metric.hpp)
        w = self.weight if self.weight is not None else 1.0
        hhat = np.log1p(np.exp(np.clip(score, -500, 500)))
        p = np.clip(1.0 - np.exp(-w * hhat), EPS, 1 - EPS)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [("cross_entropy_lambda", float(np.mean(loss)), False)]


class KullbackLeiblerMetric(Metric):
    name = "kullback_leibler"

    def eval(self, score):
        p = np.clip(_sigmoid(score), EPS, 1 - EPS)
        y = np.clip(self.label, EPS, 1 - EPS)
        kl = y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p))
        return [("kullback_leibler", self._avg(kl), False)]


_REGISTRY = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "mape": MapeMetric, "huber": HuberMetric,
    "fair": FairMetric, "poisson": PoissonMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric, "ndcg": NDCGMetric, "map": MapMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KullbackLeiblerMetric,
}

_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape",
    "gamma": "gamma", "tweedie": "tweedie", "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metrics(config: Config) -> List[Metric]:
    """Factory (reference src/metric/metric.cpp:16).  Resolves the metric
    list from config (default = the objective's own metric)."""
    names = config.metric
    if names in (None, [], ""):
        default = _DEFAULT_FOR_OBJECTIVE.get(config.objective)
        names = [default] if default else []
    if isinstance(names, str):
        names = [names]
    out = []
    seen = set()
    for raw in names:
        name = METRIC_ALIASES.get(str(raw), str(raw))
        if name in ("none", "null", "na", "custom", ""):
            continue
        if name in seen:
            continue
        seen.add(name)
        if name not in _REGISTRY:
            raise ValueError(f"Unknown metric: {raw}")
        out.append(_REGISTRY[name](config))
    return out
