"""Cross-validation through the vmapped model axis (the ``engine.cv``
fast path).

Folds are just models: fold k trains with a held-out sample mask over
the PARENT dataset — binning happens once, the binned matrix lives on
device once, and all folds grow their trees inside one compiled grower
program (``batched.BatchTrainer``).  Because the grower assigns EVERY
row to a leaf (masked-out rows contribute zero to the histogram sums
but still ride the partition), each fold's held-out predictions are
already sitting in the trainer's (M, N) score matrix — the per-fold
validation metric reads its test rows straight out of the training
scores, with no separate tree walk.

Aggregation and early stopping are engine.cv's OWN bookkeeping
(the shared ``engine.CVAggregator``): per-iteration mean/stdv across folds, stopping on the
aggregated validation means (``first_metric_only`` restricts to the
first metric key), results truncated to the best iteration.

Fold models are bit-identical to a ``BatchTrainer`` run on the same
masks; versus the legacy per-fold loop (compacted ``Dataset.subset``
row copies) the values agree to float32 reduction tolerance — the
masked histogram sums run over N rows where the compacted ones run over
the fold's subset, so XLA picks different (but per-run deterministic)
reduction shapes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import collections

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..metric import create_metrics
from ..utils.log import log_info
from .batched import BatchTrainer, MultiTrainError, _subset_metadata

__all__ = ["cv_many"]


def cv_reject_reason(fobj, feval, fpreproc, init_model,
                     callbacks) -> Optional[str]:
    """Why engine.cv cannot route through the batched fold driver (None
    = it can; config-level limits are checked by BatchTrainer itself)."""
    if fobj is not None:
        return "custom objective (fobj)"
    if feval is not None:
        return "custom metric (feval)"
    if fpreproc is not None:
        return "fpreproc rewrites per-fold params"
    if init_model is not None:
        return "init_model continuation"
    if callbacks:
        return "user callbacks observe per-fold boosters"
    return None


def cv_many(params: Dict[str, Any], train_set: Dataset,
            num_boost_round: int, folds, cfg: Config,
            eval_train_metric: bool = False,
            return_cvbooster: bool = False) -> Dict[str, Any]:
    """Run ``engine.cv``'s fold loop as ONE vmapped training batch.

    ``folds`` is the materialized list of (train_idx, test_idx) pairs
    engine.cv built (user-supplied or ``_make_n_folds``).  Raises
    :class:`MultiTrainError` when the config cannot batch — the caller
    falls back to the legacy per-fold loop."""
    from ..engine import CVAggregator, CVBooster  # deferred: engine
    # imports this module lazily inside cv()

    nfold = len(folds)
    if nfold == 0:
        raise MultiTrainError("empty fold list")
    n = train_set.num_data()
    masks = np.zeros((nfold, n), np.float32)
    for k, (train_idx, _) in enumerate(folds):
        masks[k, np.asarray(train_idx, np.int64)] = 1.0

    trainer = BatchTrainer([dict(params) for _ in range(nfold)], train_set,
                           sample_masks=masks)
    md = train_set.metadata

    # per-fold metric sets over the held-out (and optionally in-fold)
    # rows; device-side row indices so the per-iteration host pull is
    # only the rows the metrics read, never the (nfold, N) matrix
    test_rows_dev: List[jnp.ndarray] = []
    valid_metrics: List[list] = []
    train_metrics: List[list] = []
    train_rows_dev: List[jnp.ndarray] = []
    for k, (train_idx, test_idx) in enumerate(folds):
        test_idx = np.asarray(test_idx, np.int64)
        test_rows_dev.append(jnp.asarray(test_idx))
        mts = create_metrics(trainer.cfgs[k])
        for mt in mts:
            mt.init(_subset_metadata(md, test_idx), len(test_idx))
        valid_metrics.append(mts)
        if eval_train_metric:
            train_idx = np.asarray(train_idx, np.int64)
            train_rows_dev.append(jnp.asarray(train_idx))
            mts = create_metrics(trainer.cfgs[k])
            for mt in mts:
                mt.init(_subset_metadata(md, train_idx), len(train_idx))
            train_metrics.append(mts)

    aggr = CVAggregator(cfg, num_boost_round)
    for it in range(num_boost_round):
        trainer.step_once(it)
        agg = collections.defaultdict(list)
        hib_map: Dict[str, bool] = {}
        for k in range(nfold):
            # host_lane_score hands back the standalone score layout —
            # (rows,) or (rows, K) for multiclass fold batches
            held_out = trainer.host_lane_score(k, test_rows_dev[k])
            for mt in valid_metrics[k]:
                for name, val, hib in mt.eval(held_out):
                    agg[f"valid {name}"].append(val)
                    hib_map[f"valid {name}"] = hib
            if eval_train_metric:
                in_fold = trainer.host_lane_score(k, train_rows_dev[k])
                for mt in train_metrics[k]:
                    for name, val, _ in mt.eval(in_fold):
                        agg[f"train {name}"].append(val)
        if aggr.update(it, agg, hib_map):
            break

    log_info(f"cv: trained {nfold} folds in one vmapped program "
             f"({trainer._steps} rounds)")
    cvbooster = CVBooster()
    out: Dict[str, Any] = aggr.finalize(cvbooster)
    if return_cvbooster:
        for bst in trainer.finalize():
            cvbooster.append(bst)
        out["cvbooster"] = cvbooster
    return out
