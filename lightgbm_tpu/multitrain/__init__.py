"""One-program multi-model training (``train_many``).

Serving millions of users means thousands of per-cohort models,
cross-validation folds and hyperparameter sweeps — and every standalone
``train()`` call leaves the accelerator mostly idle on small datasets.
``train_many`` stacks M boosters along a vmapped model axis and trains
them all inside ONE compiled program, sharing the binned dataset and the
compile cache, with every extracted model bit-identical to the booster a
standalone ``train()`` with the same params would produce.

    import lightgbm_tpu as lgb
    mb = lgb.train_many(params, train_set,
                        variants=[{"lambda_l1": v} for v in grid],
                        num_boost_round=100)
    mb[3].predict(X)           # a full standalone Booster

Entry points:

* :func:`train_many` — batch-train a variant list (or ``replicas=M``
  bagging-decorrelated copies) of one base config.
* :class:`GridSearchCVMany` (multitrain/sweep.py) — a
  ``sklearn.model_selection.GridSearchCV``-compatible sweep where every
  (combo, fold) model trains in the same program.
* ``engine.cv`` routes through the batched fold driver
  (multitrain/cv.py) automatically when ``tpu_cv_many`` (default true)
  and the config supports it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..telemetry.metrics import default_registry
from ..telemetry.slo import register_metric_ensurer, slo as _slo
from ..utils.log import log_info, log_warning
from .batched import BatchTrainer, MultiTrainError
from .variants import (HOST_SWEEP, SWEEPABLE, TRACED_SWEEP, group_variants,
                       normalize_variants)

__all__ = ["train_many", "ManyBooster", "MultiTrainError",
           "GridSearchCVMany", "TRACED_SWEEP", "HOST_SWEEP", "SWEEPABLE"]


# ---------------------------------------------------------------------------
# fallback telemetry: never again a silently-sequential sweep
# ---------------------------------------------------------------------------
# ``multitrain_fallback_total`` fires once per model that dropped off the
# vmapped axis, labeled with the bounded structural reason prefix (the
# free-text detail after " (" is stripped so the series stays low-
# cardinality).  The SLO reads it against every model REQUESTED through
# train_many — with the PR-20 lifts (GOSS/DART/multiclass/ranking) the
# fallback set is only the genuinely unstackable configs (RF, CEGB,
# linear_tree, distributed learners, custom fobj), so a drifting ratio
# means a lift regressed, exactly the serve_compiler_fallback shape.

FALLBACK_COUNTER = "multitrain_fallback_total"
REQUESTED_COUNTER = "multitrain_models_requested_total"

_slo("multitrain/fallback_rate", metric=FALLBACK_COUNTER,
     total_metric=REQUESTED_COUNTER, kind="ratio", target=0.95,
     bad_labels={"reason": "*"}, min_events=20,
     note="share of train_many models that fell off the vmapped model "
          "axis to sequential train()")


@register_metric_ensurer
def _ensure_multitrain_metrics(reg) -> None:
    reg.counter(FALLBACK_COUNTER,
                "train_many models that fell back to sequential train(), "
                "by structural reason", labels=("reason",))
    reg.counter(REQUESTED_COUNTER,
                "models requested through train_many (batched or not)")


def _note_fallback(reason: str, count: int) -> None:
    # bounded label: keep the structural prefix, drop the per-config
    # free text ("boosting=rf (averaged-score training)" -> "boosting=rf")
    short = reason.split(" (")[0].strip() or "unknown"
    default_registry().counter(
        FALLBACK_COUNTER,
        "train_many models that fell back to sequential train(), "
        "by structural reason", labels=("reason",)).inc(count, reason=short)


class ManyBooster:
    """Result of :func:`train_many`: a list-like container of standalone
    per-model :class:`~lightgbm_tpu.basic.Booster` handles plus the batch
    bookkeeping (eval histories, which models batched vs fell back)."""

    def __init__(self) -> None:
        self.boosters: List = []
        self.variant_params: List[Dict[str, Any]] = []
        self.eval_histories: List[Dict] = []
        self.batched_indices: List[int] = []
        self.fallback_indices: List[int] = []
        self.num_groups = 0

    def __len__(self) -> int:
        return len(self.boosters)

    def __getitem__(self, i):
        return self.boosters[i]

    def __iter__(self):
        return iter(self.boosters)

    @property
    def best_iteration(self) -> List[int]:
        return [b.best_iteration for b in self.boosters]

    def predict(self, X, **kwargs) -> np.ndarray:
        """(M, rows[, ...]) stacked predictions of every model."""
        return np.stack([b.predict(X, **kwargs) for b in self.boosters])


def train_many(params: Dict[str, Any], train_set: Dataset,
               num_boost_round: int = 100,
               variants: Optional[Sequence[Dict[str, Any]]] = None,
               replicas: Optional[int] = None,
               sample_masks=None,
               valid_sets: Optional[List[Dataset]] = None,
               valid_names: Optional[List[str]] = None,
               allow_fallback: bool = True,
               strict: bool = False,
               force_traced: bool = False,
               **kwargs: Any) -> ManyBooster:
    """Train M boosters in one traced program.

    Args:
      params: base parameters (every variant inherits them).
      variants: per-model override dicts, or a ``param -> list`` column
        dict.  Sweepable params (``multitrain.SWEEPABLE``) batch into one
        program; structurally differing variants group into same-structure
        batches; unsupported ones fall back to sequential ``train()``.
      replicas: instead of ``variants``, train M bagging-decorrelated
        copies of the base params (per-model seeds derived by
        ``utils.random.model_stream_seed`` and materialized into
        ``result.variant_params``).
      sample_masks: optional (M, N) per-model training-row masks
        (fold/cohort training against the SHARED binned dataset; 0 rows
        are excluded exactly like a row subset).
      valid_sets/valid_names: shared validation Datasets (per-model
        early stopping runs against per-model scores).
      allow_fallback: False raises :class:`MultiTrainError` instead of
        training unsupported variants sequentially.
      strict: alias for ``allow_fallback=False`` (the never-silent
        contract: a sweep that silently went sequential is a perf
        regression, not a convenience) — every fallback also bumps the
        ``multitrain_fallback_total{reason}`` counter either way.
      force_traced: trace every sweepable hyperparameter even when it
        does not vary (testing hook: exercises the traced program).

    Returns:
      :class:`ManyBooster`; ``result[m]`` is bit-identical to
      ``train(result.variant_params[m], train_set, num_boost_round)``.
    """
    params = dict(params or {})
    params.update(kwargs)
    if strict:
        allow_fallback = False
    if sample_masks is not None:
        sample_masks = np.asarray(sample_masks, np.float32)
        num_models = sample_masks.shape[0]
    else:
        num_models = None
    vparams = normalize_variants(params, variants, replicas,
                                 num_models=num_models)
    M = len(vparams)
    if sample_masks is not None and sample_masks.shape[0] != M:
        raise ValueError(f"sample_masks rows ({sample_masks.shape[0]}) != "
                         f"number of variants ({M})")

    result = ManyBooster()
    result.boosters = [None] * M
    result.eval_histories = [None] * M
    result.variant_params = vparams

    groups = group_variants(vparams)
    result.num_groups = len(groups)
    cap = max(1, int(Config(params).tpu_multitrain_batch))
    default_registry().counter(
        REQUESTED_COUNTER,
        "models requested through train_many (batched or not)").inc(M)

    def _fallback(indices: List[int], reason: str) -> None:
        _note_fallback(reason, len(indices))
        if not allow_fallback:
            raise MultiTrainError(reason)
        log_warning(f"train_many: {len(indices)} variant(s) fall back to "
                    f"sequential train(): {reason}")
        from ..engine import train as engine_train
        from ..callback import record_evaluation
        for i in indices:
            if sample_masks is not None:
                raise MultiTrainError(
                    f"sample_masks with a non-batchable variant: {reason}")
            hist: Dict = {}
            bst = engine_train(vparams[i], train_set,
                               num_boost_round=num_boost_round,
                               valid_sets=valid_sets,
                               valid_names=valid_names,
                               callbacks=[record_evaluation(hist)])
            result.boosters[i] = bst
            result.eval_histories[i] = hist
            result.fallback_indices.append(i)

    for indices in groups:
        for lo in range(0, len(indices), cap):
            chunk = indices[lo:lo + cap]
            sub_params = [vparams[i] for i in chunk]
            sub_masks = (sample_masks[chunk] if sample_masks is not None
                         else None)
            try:
                trainer = BatchTrainer(sub_params, train_set,
                                       sample_masks=sub_masks,
                                       valid_sets=valid_sets,
                                       valid_names=valid_names,
                                       force_traced=force_traced)
            except MultiTrainError as e:
                _fallback(chunk, str(e))
                continue
            trainer.run(num_boost_round)
            boosters = trainer.finalize()
            for i, bst, st in zip(chunk, boosters, trainer.states):
                result.boosters[i] = bst
                result.eval_histories[i] = st.history
                result.batched_indices.append(i)
            log_info(f"train_many: batched {len(chunk)} models in one "
                     f"program ({trainer._steps} rounds)")
    return result


def __getattr__(name):
    # lazy: sweep imports sklearn glue which may be absent
    if name == "GridSearchCVMany":
        from .sweep import GridSearchCVMany
        return GridSearchCVMany
    raise AttributeError(name)
