"""Variant normalization and same-structure grouping for ``train_many``.

A *variant* is a per-model parameter override dict.  Two classes of
parameters can vary inside ONE compiled batch:

* **traced sweepables** (``TRACED_SWEEP``): regularization /
  split-threshold scalars that flow only through jnp arithmetic in the
  split scan (ops/split.py ``TRACEABLE_PARAMS``).  They ride a
  ``(M, S)`` array through the vmapped grower, so variants differing in
  them share one executable.
* **host sweepables** (``HOST_SWEEP``): parameters consumed purely on
  the host side of the boosting loop — sampling seeds/fractions (the
  masks they produce are per-model *inputs* to the device step),
  learning_rate (a traced ``(M,)`` scalar applied at the score update),
  early-stopping knobs and metric choice (host bookkeeping only).

Everything else is **structural**: it changes the traced program
(num_leaves, max_bin, objective, grower mode, ...) or host behavior in
ways the batch cannot express.  Variants are grouped by their structural
fingerprint; each group trains as one vmapped batch and the remainder
falls back to sequential ``train()`` calls.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..config import Config, resolve_param_aliases
from ..ops.split import TRACEABLE_PARAMS
from ..utils.random import model_stream_seed

__all__ = ["TRACED_SWEEP", "HOST_SWEEP", "SWEEPABLE", "normalize_variants",
           "structure_key", "group_variants"]

# sweepable along the traced model axis (see ops/split.py)
TRACED_SWEEP: Tuple[str, ...] = TRACEABLE_PARAMS

# sweepable host-side (per-model masks / seeds / bookkeeping); the GOSS
# rates and DART drop knobs are host draws too (gbdt.goss_sample_np /
# the per-lane drop bookkeeping in batched._ModelState), so they sweep
# inside one batch — boosting TYPE itself stays structural
HOST_SWEEP: Tuple[str, ...] = (
    "learning_rate", "bagging_seed", "bagging_fraction",
    "pos_bagging_fraction", "neg_bagging_fraction", "feature_fraction",
    "feature_fraction_seed", "seed", "extra_seed",
    "early_stopping_round", "first_metric_only", "metric",
    "top_rate", "other_rate",
    "drop_rate", "max_drop", "skip_drop", "uniform_drop",
    "xgboost_dart_mode", "drop_seed",
)

SWEEPABLE: Tuple[str, ...] = TRACED_SWEEP + HOST_SWEEP

# seeds that replicas=M derives per model (recorded INTO the variant
# params so ``train(variant_params_m)`` is the exact standalone
# counterpart of batch model m)
_REPLICA_SEED_KEYS = ("seed", "bagging_seed", "feature_fraction_seed",
                      "extra_seed")


def normalize_variants(base_params: Dict[str, Any],
                       variants: Optional[Sequence[Dict[str, Any]]],
                       replicas: Optional[int] = None,
                       num_models: Optional[int] = None
                       ) -> List[Dict[str, Any]]:
    """Expand the user's variant spec into canonical per-model FULL param
    dicts (aliases resolved, base params merged).

    ``variants`` may be a list of override dicts or a dict of
    ``param -> list`` columns (all the same length, zipped per model).
    ``replicas=M`` spawns M bagging-decorrelated copies of the base
    params via :func:`~lightgbm_tpu.utils.random.model_stream_seed` —
    the derived seeds are materialized into each variant so model m's
    standalone counterpart is ``train(variants[m])`` verbatim."""
    base = resolve_param_aliases(base_params or {})
    if variants is not None and replicas is not None:
        raise ValueError("pass either variants or replicas, not both")
    if variants is None and replicas is None:
        m = int(num_models or 1)
        out = [dict(base) for _ in range(m)]
        return out
    if replicas is not None:
        cfg = Config(base)
        out = []
        for m in range(int(replicas)):
            v = dict(base)
            for key in _REPLICA_SEED_KEYS:
                v[key] = model_stream_seed(int(getattr(cfg, key)), m)
            out.append(v)
        return out
    if isinstance(variants, dict):
        cols = {k: list(v) for k, v in variants.items()}
        lens = {len(v) for v in cols.values()}
        if len(lens) != 1:
            raise ValueError(f"variant columns have differing lengths: "
                             f"{ {k: len(v) for k, v in cols.items()} }")
        m = lens.pop()
        variants = [{k: cols[k][i] for k in cols} for i in range(m)]
    out = []
    for v in variants:
        v = resolve_param_aliases(dict(v))
        out.append({**base, **v})
    return out


def _hashable(v: Any) -> Any:
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def structure_key(full_params: Dict[str, Any]) -> Tuple:
    """Hashable fingerprint of everything that is NOT sweepable inside a
    batch.  Variants with equal keys share one traced program."""
    skip = set(SWEEPABLE)
    return tuple(sorted((k, _hashable(v)) for k, v in full_params.items()
                        if k not in skip))


def group_variants(variant_params: List[Dict[str, Any]]
                   ) -> List[List[int]]:
    """Group variant indices by structural fingerprint, preserving the
    first-seen order of groups and the variant order within a group."""
    groups: Dict[Tuple, List[int]] = {}
    order: List[Tuple] = []
    for i, p in enumerate(variant_params):
        key = structure_key(p)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)
    return [groups[k] for k in order]
