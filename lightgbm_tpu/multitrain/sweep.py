"""``GridSearchCVMany``: an sklearn ``GridSearchCV``-compatible
hyperparameter sweep where every (combo, fold) model trains inside one
compiled program.

sklearn's ``GridSearchCV`` refits the estimator from scratch for every
parameter combination and fold — ``n_combos * n_folds`` boosting loops,
each re-binning the data and re-compiling its kernels.  Here the whole
sweep is ONE ``train_many`` call: the dataset is binned once, folds
become per-model sample masks, sweepable parameters (lambda_l1/l2,
min_child_weight/samples, min_split_gain, learning_rate, seeds) ride the
traced model axis, and structurally differing combos (num_leaves,
max_depth, ...) group into one compiled batch per structure.

    from lightgbm_tpu.multitrain import GridSearchCVMany
    gs = GridSearchCVMany(LGBMRegressor(n_estimators=50),
                          {"reg_lambda": [0, 0.1, 1.0],
                           "min_child_samples": [10, 20]}, cv=5)
    gs.fit(X, y)
    gs.best_params_, gs.best_score_, gs.cv_results_["mean_test_score"]

GOSS, DART, multiclass and ranking estimators all ride the model axis
(PR 20); only combos it genuinely cannot express (RF, CEGB, linear
trees, custom objectives, ...) fall back to sequential per-fold fits of
the wrapped estimator — never silently: each bumps
``multitrain_fallback_total{reason}``.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..dataset import Dataset
from ..utils.log import log_info, log_warning
from .batched import MultiTrainError

__all__ = ["GridSearchCVMany"]

# params fixed at Dataset.construct time: the batched sweep shares ONE
# binned dataset, so combos differing here must refit sequentially
# (each sequential est.fit re-bins its own Dataset, like sklearn's
# GridSearchCV semantics)
_DATASET_PARAMS = ("max_bin", "bin_construct_sample_cnt",
                   "min_data_in_bin", "data_random_seed", "enable_bundle",
                   "feature_pre_filter", "zero_as_missing", "use_missing",
                   "categorical_feature", "linear_tree", "pre_partition")


class GridSearchCVMany:
    """Drop-in for ``sklearn.model_selection.GridSearchCV`` over the
    lightgbm_tpu sklearn estimators, batching the whole sweep through
    :func:`~lightgbm_tpu.multitrain.train_many`.

    Exposes the sklearn result surface: ``cv_results_`` (params,
    split scores, mean/std/rank), ``best_index_``, ``best_params_``,
    ``best_score_``, and — with ``refit=True`` — ``best_estimator_``
    fitted on the full data."""

    def __init__(self, estimator, param_grid, *, cv: int = 5,
                 scoring=None, refit: bool = True,
                 return_train_score: bool = False) -> None:
        self.estimator = estimator
        self.param_grid = param_grid
        self.cv = cv
        self.scoring = scoring
        self.refit = refit
        self.return_train_score = return_train_score

    # -- sklearn plumbing ----------------------------------------------------
    def _make_estimator(self, combo: Dict[str, Any]):
        base = self.estimator.get_params()
        base.update(combo)
        return type(self.estimator)(**base)

    def _scorer(self):
        from sklearn.metrics import check_scoring
        scoring = self.scoring
        if scoring is None:
            from ..sklearn import LGBMClassifier
            scoring = ("accuracy" if isinstance(self.estimator,
                                                LGBMClassifier) else "r2")
        return check_scoring(self.estimator, scoring=scoring)

    def fit(self, X, y, sample_weight=None) -> "GridSearchCVMany":
        from sklearn.model_selection import ParameterGrid, check_cv
        from ..sklearn import LGBMClassifier

        combos: List[Dict[str, Any]] = list(ParameterGrid(self.param_grid))
        if not combos:
            raise ValueError("empty param_grid")
        X = np.asarray(X)
        y_arr = np.asarray(y).ravel()
        is_clf = isinstance(self.estimator, LGBMClassifier)
        splitter = check_cv(self.cv, y_arr, classifier=is_clf)
        folds = list(splitter.split(X, y_arr))
        nfold = len(folds)
        scorer = self._scorer()

        # label encoding + base params from a template estimator (the
        # encoding is combo-independent)
        tmpl = self._make_estimator(combos[0])
        y_fit, extra = tmpl._process_label(y_arr, tmpl._make_params())
        classes = getattr(tmpl, "_classes", None)

        # one (combo, fold) model per lane; masks select the fold's rows
        n = len(y_fit)
        M = len(combos) * nfold
        variants: List[Dict[str, Any]] = []
        masks = np.zeros((M, n), np.float32)
        for ci, combo in enumerate(combos):
            est_c = self._make_estimator(combo)
            vp = est_c._make_params()
            vp.update(extra)
            for k, (train_idx, _) in enumerate(folds):
                variants.append(dict(vp))
                masks[ci * nfold + k, np.asarray(train_idx, np.int64)] = 1.0

        base_params = dict(tmpl._make_params())
        base_params.update(extra)
        n_estimators = int(self.estimator.n_estimators)
        ds = Dataset(X, label=y_fit, weight=sample_weight,
                     params=base_params)

        try:
            for vp in variants:
                drift = [k for k in _DATASET_PARAMS
                         if vp.get(k) != base_params.get(k)]
                if drift:
                    raise MultiTrainError(
                        f"grid sweeps dataset-construction params {drift}")
            from . import train_many
            mb = train_many({}, ds, num_boost_round=n_estimators,
                            variants=variants, sample_masks=masks,
                            allow_fallback=False)
            fitted = []
            for m, bst in enumerate(mb):
                est = self._make_estimator(combos[m // nfold])
                est._Booster = bst
                est._n_features = bst.num_feature()
                est._classes = classes
                fitted.append(est)
        except MultiTrainError as e:
            log_warning(f"GridSearchCVMany: sweep cannot batch ({e}); "
                        f"fitting {M} models sequentially")
            fitted = []
            for ci, combo in enumerate(combos):
                for train_idx, _ in folds:
                    est = self._make_estimator(combo)
                    sw = (None if sample_weight is None
                          else np.asarray(sample_weight)[train_idx])
                    est.fit(X[train_idx], y_arr[train_idx],
                            sample_weight=sw)
                    fitted.append(est)

        # sklearn-shaped cv_results_
        results: Dict[str, Any] = {"params": combos}
        for key in combos[0] if combos[0] else ():
            results[f"param_{key}"] = [c.get(key) for c in combos]
        test_scores = np.zeros((len(combos), nfold))
        train_scores = np.zeros((len(combos), nfold))
        for ci in range(len(combos)):
            for k, (train_idx, test_idx) in enumerate(folds):
                est = fitted[ci * nfold + k]
                test_scores[ci, k] = scorer(est, X[test_idx],
                                            y_arr[test_idx])
                if self.return_train_score:
                    train_scores[ci, k] = scorer(est, X[train_idx],
                                                 y_arr[train_idx])
        for k in range(nfold):
            results[f"split{k}_test_score"] = test_scores[:, k]
        results["mean_test_score"] = test_scores.mean(axis=1)
        results["std_test_score"] = test_scores.std(axis=1)
        order = np.argsort(-results["mean_test_score"], kind="stable")
        ranks = np.empty(len(combos), np.int32)
        ranks[order] = np.arange(1, len(combos) + 1)
        results["rank_test_score"] = ranks
        if self.return_train_score:
            for k in range(nfold):
                results[f"split{k}_train_score"] = train_scores[:, k]
            results["mean_train_score"] = train_scores.mean(axis=1)
            results["std_train_score"] = train_scores.std(axis=1)

        self.cv_results_ = results
        self.best_index_ = int(np.argmax(results["mean_test_score"]))
        self.best_params_ = combos[self.best_index_]
        self.best_score_ = float(
            results["mean_test_score"][self.best_index_])
        self.n_splits_ = nfold
        if self.refit:
            self.best_estimator_ = self._make_estimator(self.best_params_)
            self.best_estimator_.fit(X, y_arr, sample_weight=sample_weight)
        log_info(f"GridSearchCVMany: {len(combos)} combos x {nfold} folds "
                 f"= {M} models; best {self.best_params_} "
                 f"(score {self.best_score_:.6g})")
        return self

    # -- post-fit conveniences ----------------------------------------------
    def _check_fitted(self):
        if not hasattr(self, "best_index_"):
            raise RuntimeError("GridSearchCVMany not fitted, call fit first")

    def predict(self, X):
        self._check_fitted()
        if not self.refit:
            raise RuntimeError("predict requires refit=True")
        return self.best_estimator_.predict(X)

    def score(self, X, y):
        self._check_fitted()
        if not self.refit:
            raise RuntimeError("score requires refit=True")
        return float(self._scorer()(self.best_estimator_, np.asarray(X),
                                    np.asarray(y).ravel()))
