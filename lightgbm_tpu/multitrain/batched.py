"""One-program multi-model training: the vmapped batch boosting driver.

M boosters train inside ONE compiled program: per-model state (scores,
gradients, bagging/feature masks, RNG keys, swept hyperparameters) is
stacked along a leading model axis and the single-tree grower — the SAME
factory-built function a standalone ``train()`` uses
(learner/serial.py ``SerialTreeLearner.build_grow_fn``) — is ``jax.vmap``-ed
over it.  The binned dataset, the feature descriptors and the compiled
step are shared across all M models.

Bit-identity contract: model m of a batch is bit-identical to the model a
standalone ``train(variants[m])`` with the same seeds would produce.
This holds because

* the grower's histogram build + split scan are value-deterministic
  under vmap (each model's lane runs the same reduction tree — asserted
  by tests/test_multitrain.py on the partition and wave paths);
* host-side sampling draws are single-sourced
  (models/gbdt.py ``bagging_mask_np`` / ``feature_mask_np`` /
  ``goss_sample_np``) and keyed per model by the variant's own seeds;
* swept hyperparameters enter the traced program as per-model scalars
  that flow through the exact arithmetic the constant-folded standalone
  program runs (ops/split.py ``TRACEABLE_PARAMS``);
* the per-iteration dispatch BOUNDARIES mirror the standalone loop
  (eager gradients, one jitted grower program, an eager
  ``leaf_value * shrinkage`` multiply, the jitted gather+add score
  update, the jitted valid-set walk plus an eager add).  Fusing them
  into one program is NOT value-safe: XLA contracts the multiply into
  the score add as a single-rounding FMA — ``optimization_barrier``
  does not stop it on the CPU backend — and drifts 1 ulp off the
  standalone trajectory.

Boosting/objective variants ride the same axis (the PR-20 lift):

* **GOSS** (arXiv:1806.11248) — per-lane top-a%/random-b% draws come
  from the shared host sampler (``gbdt.goss_sample_np``) applied to the
  already-eager (M, N) gradient matrix; the amplified small-gradient
  multipliers hit the stacked gradients in one eager elementwise
  multiply and the 0/1 survivorship folds into the per-lane grower
  mask, so every lane's inputs equal its standalone counterpart's.
* **DART** — per-lane drop sets are ``utils/random.host_rng`` host
  bookkeeping in ``_ModelState``; each iteration's raw per-tree
  predictions are cached as ONE stacked (L, N) gather, and drop
  subtraction / re-add / valid renormalization are batched
  ``jnp.where``-masked axpys over all lanes, so lanes never
  desynchronize the dispatch boundaries.  Tree shrink-factor replays
  happen at finalize in standalone chronological order.
* **multiclass** — an (M, K) lane grid flattened to L = M*K device
  lanes: softmax/OVA gradients are vmapped per model on the (N, K)
  score view, every class tree of an iteration grows in the same
  vmapped program (the standalone class loop's trees are mutually
  independent within an iteration), and extraction interleaves class
  trees exactly like the standalone loop.
* **ranking** — lambdarank/rank-xendcg gradients vectorize across lanes
  over the one shared padded query-segment layout (per-lane scores in);
  ``train_set.metadata.group`` is no longer a reject.

The per-iteration host work is only mask refreshes, DART/GOSS draws and
metric evaluation; the heavy lifting (histogram build + split scan for
all M*K lanes) is the single vmapped grower program per iteration.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.contracts import memory_budget
from ..basic import Booster
from ..callback import CallbackEnv, EarlyStopException, early_stopping
from ..config import Config
from ..dataset import Dataset, Metadata
from ..learner.serial import GrownTree, SerialTreeLearner
from ..metric import create_metrics
from ..models.gbdt import (EPSILON, _grown_to_tree, _mappers_equal,
                           _update_score_by_leaf, bagging_mask_np,
                           feature_mask_np, goss_sample_np, make_walk_fn)
from ..objective import create_objective
from ..resilience.checkpoint import reject_checkpointing
from ..resilience.faults import faults
from ..telemetry.metrics import default_registry
from ..telemetry.train_record import TrainRecord, set_last_train_record
from ..utils.random import host_rng
from .variants import TRACED_SWEEP

__all__ = ["MultiTrainError", "BatchTrainer", "batch_reject_reason"]


def multitrain_hbm_bytes(ctx):
    """Per-device HBM curve of the stacked vmapped grower program
    (lint-mem enforced): every wave-grower working buffer except the
    shared bin matrix picks up a leading lane axis of L = models *
    classes (the multiclass (M, K) grid flattens onto the same vmap
    axis), so the footprint is ~L x the standalone curve — the reason
    tpu_multitrain_batch caps a structure group at 256 models and the
    lane axis shard_map-shards across devices when L % ndev == 0 (each
    device then holds L/ndev lanes)."""
    from ..learner.wave import wave_grow_hbm_bytes
    m = max(1, int(ctx.get("models", 1)))
    k = max(1, int(ctx.get("classes", 1)))
    ndev = max(1, int(ctx.get("model_shards", 1)))
    lanes = -(-(m * k) // ndev)
    per_model = wave_grow_hbm_bytes(ctx)
    # 1.15: vmap stacks a few lane-wide temporaries the standalone
    # program frees between dispatches (measured at the lint-mem
    # geometry)
    return int(1.15 * lanes * per_model)


memory_budget("multitrain/stacked_state", ("multitrain", "multitrain_mc"),
              multitrain_hbm_bytes,
              note="M*K/ndev lanes x the wave-grower curve (shared bins)")


class MultiTrainError(ValueError):
    """The configuration cannot train on the vmapped model axis."""


# objectives the model axis cannot express: "none" means a custom fobj
# whose host callback cannot stack
_UNSUPPORTED_OBJECTIVES = ("none",)


def batch_reject_reason(cfg: Config, train_set: Dataset) -> Optional[str]:
    """Why this config cannot ride the vmapped model axis (None = it can).

    The excluded features either keep cross-tree host state whose score
    effects the batch cannot replay (RF's averaged scores, CEGB
    used-sets, linear-leaf refits, L1-style leaf renewal), or change the
    traced program per model (distributed learners).  GOSS, DART,
    multiclass and ranking all batch (PR 20): their host state stacks in
    ``_ModelState`` and their score adjustments are lane-masked device
    ops."""
    if cfg.boosting not in ("gbdt", "goss", "dart", ""):
        return f"boosting={cfg.boosting} (averaged-score training)"
    if cfg.objective in _UNSUPPORTED_OBJECTIVES:
        return f"objective={cfg.objective}"
    if cfg.tree_learner not in ("serial", ""):
        return f"tree_learner={cfg.tree_learner} (mesh collectives)"
    if cfg.linear_tree:
        return "linear_tree (host-side leaf fits)"
    if (cfg.cegb_penalty_split > 0 or cfg.cegb_penalty_feature_coupled or
            cfg.cegb_penalty_feature_lazy):
        return "CEGB penalties (cross-tree used-feature state)"
    if getattr(train_set, "distributed_rows", False):
        return "pre_partition-ed multi-process dataset"
    return None


def _objective_reject_reason(objective) -> Optional[str]:
    if objective is None:
        return "custom objective (fobj)"
    if getattr(objective, "is_renew_tree_output", False):
        return (f"objective {type(objective).__name__} renews leaf values "
                "host-side per tree")
    return None


def _subset_metadata(md: Metadata, rows: np.ndarray,
                     mask_vals: Optional[np.ndarray] = None) -> Metadata:
    """Metadata restricted to ``rows`` (the standalone counterpart's
    ``Dataset.subset`` view).  Fractional mask values fold into the
    weights so a soft-masked model's boost_from_average matches its
    effective objective."""
    sub = Metadata()
    if md.label is not None:
        sub.set_label(np.asarray(md.label)[rows])
    w = None if md.weight is None else np.asarray(md.weight)[rows]
    if mask_vals is not None and not np.all(mask_vals == 1.0):
        w = mask_vals if w is None else w * mask_vals
    if w is not None:
        sub.set_weight(w)
    if md.init_score is not None:
        sub.set_init_score(np.asarray(md.init_score)[rows])
    return sub


class _ModelState:
    """Host bookkeeping of one model lane group (all K class lanes)."""

    __slots__ = ("cfg", "params", "rows", "mask_vals", "bias", "active",
                 "kept_iters", "best_iteration", "best_score", "stopper",
                 "history", "metrics_per_valid", "stop_reason",
                 # DART host state (per model, mirrors models/boosting.py)
                 "weights", "sum_weight", "cur_shrinkage", "tree_shrink",
                 "tree_factors")

    def __init__(self, cfg: Config, params: Dict[str, Any]) -> None:
        self.cfg = cfg
        self.params = params
        self.rows: Optional[np.ndarray] = None
        self.mask_vals: Optional[np.ndarray] = None
        self.bias: Optional[np.ndarray] = None   # (K,) per-class init bias
        self.active = True
        self.kept_iters = 0
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self.stopper = None
        self.history: Dict[str, Dict[str, List[float]]] = {}
        self.metrics_per_valid: List[list] = []
        self.stop_reason = ""
        self.weights: List[float] = []       # DART per-tree current weight
        self.sum_weight = 0.0
        self.cur_shrinkage = float(cfg.learning_rate)
        self.tree_shrink: List[float] = []   # shrinkage at creation time
        self.tree_factors: List[List[float]] = []  # normalize replays


class BatchTrainer:
    """Trains one same-structure group of M variants in one program.

    Drivers (``train_many``, the CV fast path, the sweep) construct it,
    call :meth:`run` or drive :meth:`step_once` themselves, then
    :meth:`finalize` to extract per-model standalone ``Booster``s.

    Multiclass objectives put K = num_class lanes per model on the vmap
    axis (L = M*K device lanes, class-major within a model, matching the
    standalone per-iteration class loop); all host bookkeeping stays at
    model granularity and expands to lanes on upload."""

    def __init__(self, variant_params: List[Dict[str, Any]],
                 train_set: Dataset,
                 sample_rows: Optional[List[Optional[np.ndarray]]] = None,
                 sample_masks: Optional[np.ndarray] = None,
                 valid_sets: Optional[List[Dataset]] = None,
                 valid_names: Optional[List[str]] = None,
                 force_traced: bool = False) -> None:
        self.M = len(variant_params)
        if self.M == 0:
            raise MultiTrainError("empty variant batch")
        self.params = [dict(p) for p in variant_params]
        self.cfgs = [Config(p) for p in self.params]
        cfg = self.cfgs[0]
        self.cfg = cfg
        reject_checkpointing(cfg, "train_many")
        train_set.construct(cfg)
        reason = batch_reject_reason(cfg, train_set)
        if reason:
            raise MultiTrainError(reason)
        self.train_set = train_set
        self.n = train_set.num_data()
        self.num_features = train_set.num_feature()
        self.boosting = cfg.boosting or "gbdt"   # structural: whole batch
        self._goss = self.boosting == "goss"
        self._dart = self.boosting == "dart"

        # the shared objective: gradients are per-row (elementwise, or
        # row-local softmax / query-local lambdarank), so one instance
        # initialized on the FULL metadata serves every model (per-model
        # row masks never reach gradient VALUES)
        self.objective = (create_objective(cfg.objective, cfg)
                          if cfg.objective != "none" else None)
        reason = _objective_reject_reason(self.objective)
        if reason:
            raise MultiTrainError(reason)
        self.objective.init(train_set.metadata, self.n)
        self.K = int(self.objective.num_model_per_iteration)
        self.L = self.M * self.K
        self._ranking = train_set.metadata.group is not None
        if cfg.objective == "rank_xendcg" and \
                len({int(c.seed) for c in self.cfgs}) > 1:
            raise MultiTrainError(
                "rank_xendcg seed sweep (the sampled-lambda stream is "
                "shared across lanes)")

        # the learner: same selection path as GBDT._init_train
        from ..binning import MissingType
        mappers = [train_set.bin_mappers[j] for j in train_set.used_feature_map]
        self.max_bins = int(max(m.num_bin for m in mappers))
        num_bins = np.array([m.num_bin for m in mappers], np.int32)
        is_cat = np.array([m.is_categorical for m in mappers], bool)
        has_nan = np.array(
            [m.missing_type == MissingType.NAN for m in mappers], bool)
        from ..models.gbdt import GBDT
        shim = GBDT.__new__(GBDT)
        shim.config = cfg
        shim.train_set = train_set
        shim.num_features = self.num_features
        shim.max_bins = self.max_bins
        monotone = GBDT._inner_monotone(shim)
        self.learner = SerialTreeLearner(
            cfg, self.num_features, self.max_bins, num_bins, is_cat,
            has_nan, monotone, GBDT._parse_forced_splits(shim),
            efb=train_set.efb,
            interaction_groups=GBDT._parse_interaction_constraints(shim),
            feature_contri=GBDT._inner_contri(shim),
            cegb_lazy=())
        if self.learner.grow_mode == "masked":
            raise MultiTrainError(
                "pool-less (masked) grower: histogram pool exceeds budget")
        # The pallas histogram kernels batch on the model axis through
        # jax's pallas_call batching rule (the vmap axis becomes a
        # leading grid dimension), so batched training rides the SAME
        # fast kernels a standalone train() uses — per-lane bit-identity
        # vs standalone is asserted by tests/test_multitrain.py with the
        # interpret-mode kernels.  Only the row-padding contract differs:
        # _build_step pads the batch to the kernel row block.

        # per-model lanes
        self.states = [_ModelState(c, p)
                       for c, p in zip(self.cfgs, self.params)]
        if sample_rows is not None:
            for st, rows in zip(self.states, sample_rows):
                if rows is not None:
                    st.rows = np.asarray(rows, np.int64)
        if sample_masks is not None:
            sample_masks = np.asarray(sample_masks, np.float32)
            if sample_masks.shape != (self.M, self.n):
                raise MultiTrainError(
                    f"sample_masks shape {sample_masks.shape} != "
                    f"({self.M}, {self.n})")
            for m, st in enumerate(self.states):
                nz = np.nonzero(sample_masks[m] > 0)[0]
                st.rows = nz
                st.mask_vals = sample_masks[m][nz]
        any_rows = any(st.rows is not None for st in self.states)
        if any_rows and cfg.is_unbalance and \
                cfg.objective in ("binary", "multiclassova"):
            # the shared objective derives is_unbalance's label_weight
            # from the FULL dataset's pos/neg counts; a fold/cohort
            # model's standalone counterpart derives it from ITS rows —
            # masked gradients would silently weight wrong
            raise MultiTrainError(
                "is_unbalance with per-model sample masks (label_weight "
                "depends on the fold's own pos/neg counts)")
        if any_rows and self._ranking:
            # a fold's standalone counterpart re-segments ITS rows into
            # queries; the shared padded segment layout spans the full
            # dataset and cannot express per-lane query subsets
            raise MultiTrainError(
                "ranking objectives with per-model sample masks (query "
                "segments derive from the full dataset)")

        # swept hyperparameters -> traced (M, S) matrix; fields equal
        # across the batch stay static (max constant folding)
        self.sweep_fields = tuple(
            f for f in TRACED_SWEEP
            if force_traced or len({float(getattr(c, f))
                                    for c in self.cfgs}) > 1)
        self.sweep = np.asarray(
            [[np.float32(getattr(c, f)) for f in self.sweep_fields]
             for c in self.cfgs], np.float32).reshape(self.M,
                                                      len(self.sweep_fields))
        self.lr = np.asarray([np.float32(c.learning_rate)
                              for c in self.cfgs], np.float32)

        self._init_scores()
        self._init_valid(valid_sets or [], valid_names or [])
        self._init_keys()
        self._build_step()

        self._grown: List[GrownTree] = []       # stacked per-iteration
        self._leaves: List[Any] = []            # device (L,) per iteration
        self._dart_base: List[jnp.ndarray] = []  # per iter: raw (L, N) pred
        self._dart_vb: List[List[jnp.ndarray]] = []  # per iter, per valid
        self._steps = 0
        self.record = TrainRecord(meta={
            "boosting": self.boosting, "objective": str(cfg.objective),
            "tree_learner": "serial",
            "multitrain_models": self.M,
            "multitrain_classes": self.K,
            "num_leaves": int(cfg.num_leaves),
            "num_data": int(self.n),
            "num_features": int(self.num_features),
        })
        set_last_train_record(self.record)
        reg = default_registry()
        reg.counter("multitrain_batches_total",
                    "vmapped train_many batches started").inc()
        reg.counter("multitrain_models_total",
                    "models trained on the vmapped model axis").inc(self.M)

    # -- lane helpers --------------------------------------------------------
    def _lanes(self, arr: np.ndarray) -> np.ndarray:
        """(M, ...) host array -> (L, ...): repeat each model's row K times
        (class-major lane order, lane = m*K + c)."""
        return arr if self.K == 1 else np.repeat(arr, self.K, axis=0)

    # -- setup ---------------------------------------------------------------
    def _init_scores(self) -> None:
        md = self.train_set.metadata
        K = self.K
        score0 = np.zeros((self.L, self.n), np.float32)
        for m, st in enumerate(self.states):
            st.bias = np.zeros(K)
            if md.init_score is not None:
                init = md.init_score.reshape(self.n, K) if K > 1 else \
                    md.init_score.reshape(self.n)
                for c in range(K):
                    col = init[:, c] if K > 1 else init
                    score0[m * K + c] += col.astype(np.float32)
            elif st.cfg.boost_from_average:
                if st.rows is None:
                    obj = self.objective
                else:
                    # fold/cohort models: the standalone counterpart
                    # computes its average over ITS rows only
                    obj = create_objective(st.cfg.objective, st.cfg)
                    obj.init(_subset_metadata(md, st.rows, st.mask_vals),
                             len(st.rows))
                for c in range(K):
                    st.bias[c] = obj.boost_from_score(c)
                    score0[m * K + c] += np.float32(st.bias[c])
        self.score = jnp.asarray(score0)

    def _init_valid(self, valid_sets: List[Dataset],
                    valid_names: List[str]) -> None:
        self.valid_sets: List[Tuple[str, Dataset]] = []
        self.vbins: List[jnp.ndarray] = []
        K = self.K
        vscores = []
        for i, vs in enumerate(valid_sets):
            if vs is self.train_set:
                raise MultiTrainError(
                    "valid_sets containing the train set (training "
                    "metrics) is not batched; drop it or use train()")
            name = (valid_names[i] if i < len(valid_names)
                    else f"valid_{i}")
            if not vs.constructed and \
                    getattr(vs, "reference", None) is not self.train_set:
                vs.reference = self.train_set
            vs.construct(self.cfg)
            if vs.bin_mappers is not self.train_set.bin_mappers and \
                    not _mappers_equal(vs.bin_mappers,
                                       self.train_set.bin_mappers):
                raise ValueError(
                    "cannot add validation data: it was constructed "
                    "without reference to the training Dataset")
            nv = vs.num_data()
            v0 = np.zeros((self.L, nv), np.float32)
            for m, st in enumerate(self.states):
                if vs.metadata.init_score is not None:
                    init = vs.metadata.init_score.reshape(nv, K) if K > 1 \
                        else vs.metadata.init_score.reshape(nv)
                    for c in range(K):
                        col = init[:, c] if K > 1 else init
                        v0[m * K + c] += col.astype(np.float32)
                elif st.cfg.boost_from_average:
                    for c in range(K):
                        v0[m * K + c] += np.float32(st.bias[c])
            if "bins" not in vs._device_cache:
                vs._device_cache["bins"] = jnp.asarray(vs.X_binned)
            self.valid_sets.append((name, vs))
            self.vbins.append(vs._device_cache["bins"])
            vscores.append(jnp.asarray(v0))
            for st in self.states:
                metrics = create_metrics(st.cfg)
                for mt in metrics:
                    mt.init(vs.metadata, nv)
                st.metrics_per_valid.append(metrics)
        self.vscores = tuple(vscores)

    def _init_keys(self) -> None:
        lrn = self.learner
        self._need_quant_key = bool(lrn.quantized)
        sp = lrn.split_params
        self._need_node_key = (sp.feature_fraction_bynode < 1.0 or
                               sp.extra_trees)
        K = self.K
        if self._need_quant_key:
            self._quant_base = jnp.stack(
                [jax.random.PRNGKey(int(st.cfg.seed))
                 for st in self.states for _ in range(K)])
        if self._need_node_key:
            self._node_base = jnp.stack([jnp.stack([
                jax.random.PRNGKey(int(st.cfg.feature_fraction_seed)),
                jax.random.PRNGKey(int(st.cfg.extra_seed))])
                for st in self.states for _ in range(K)])
        # per-lane fold values: the standalone key stream folds with
        # it = iter_ * K + class_id (gbdt.py train_one_iter), so each
        # class lane folds its own value
        self._class_of_lane = np.tile(np.arange(K, dtype=np.int64), self.M)
        self._fold_one = jax.jit(jax.vmap(jax.random.fold_in,
                                          in_axes=(0, 0)))
        self._fold_two = jax.jit(jax.vmap(jax.vmap(jax.random.fold_in,
                                                   in_axes=(0, None)),
                                          in_axes=(0, 0)))

    def _fold_vals(self, it: int) -> jnp.ndarray:
        return jnp.asarray(it * self.K + self._class_of_lane)

    def _build_step(self) -> None:
        lrn = self.learner
        wave = lrn.grow_mode == "wave"
        # the pallas kernels' padded-row layout (pad_rows): the binned
        # matrix pads ONCE here; per-model gradient/mask lanes pad inside
        # the vmapped grower and row_leaf trims back to N
        n_pad = self.n
        if getattr(lrn, "pallas", False):
            from ..ops.histogram_pallas import pad_rows
            n_pad = pad_rows(self.n)
        self._row_pad = n_pad - self.n
        if wave and getattr(lrn, "pack4", False):
            # the Dataset caches the packed feature-major layout (half
            # the bytes), so repeated BatchTrainers (cv folds, sweeps)
            # share it — the row-major matrix never reaches the device
            self._X_arg = self.train_set.device_bins_packed4()
        else:
            X_dev = jnp.asarray(self.train_set.X_binned)
            if self._row_pad:
                X_dev = jnp.pad(X_dev, ((0, self._row_pad), (0, 0)))
            self._X_arg = jnp.asarray(jnp.swapaxes(X_dev, 0, 1)) if wave \
                else X_dev

        base_sp = lrn.split_params
        sweep_fields = self.sweep_fields
        efb_args = lrn._efb_args
        num_bins, is_cat, has_nan = lrn.num_bins, lrn.is_cat, lrn.has_nan
        monotone = lrn.monotone
        F = self.num_features
        quantized = self._need_quant_key
        need_nk = self._need_node_key
        objective = self.objective
        walk_fn = make_walk_fn(
            None if self.train_set.efb is None else (
                None, jnp.asarray(self.train_set.efb.f_bundle),
                jnp.asarray(self.train_set.efb.f_offset),
                jnp.asarray(self.train_set.efb.f_default),
                jnp.asarray(self.train_set.efb.f_nbins),
                jnp.asarray(self.train_set.efb.f_single)),
            not bool(np.any(np.asarray(lrn.is_cat))))

        row_pad = self._row_pad
        lrn_n = self.n

        def one_grow(X_arg, g, h, mk, fmask, sweep, qkey, nkey):
            sp = base_sp
            if sweep_fields:
                sp = sp._replace(**{f: sweep[i]
                                    for i, f in enumerate(sweep_fields)})
            grow = lrn.build_grow_fn(split_params=sp, jit=False)
            cegb0 = jnp.zeros((F,), jnp.float32)
            if row_pad:
                # pallas row-block padding: padded rows carry mask 0 and
                # contribute nothing (the standalone learner pads the
                # same way in SerialTreeLearner.train)
                g = jnp.pad(g, (0, row_pad))
                h = jnp.pad(h, (0, row_pad))
                mk = jnp.pad(mk, (0, row_pad))
            if wave:
                kw = {}
                if quantized:
                    kw["quant_key"] = qkey
                if need_nk:
                    kw["node_key"] = nkey
                grown = grow(X_arg, g, h, mk, num_bins, is_cat, has_nan,
                             monotone, cegb0, efb_args, fmask, **kw)
            else:
                nk = nkey if need_nk else jnp.zeros((2, 2), jnp.uint32)
                grown = grow(X_arg, g, h, mk, num_bins, is_cat, has_nan,
                             monotone, cegb0, nk, efb_args, fmask)
            if row_pad:
                grown = grown._replace(row_leaf=grown.row_leaf[:lrn_n])
            return grown

        # dispatch boundaries mirror the standalone loop (see module
        # docstring): gradients stay EAGER vmap (elementwise primitives
        # batch with the same per-op rounding the standalone's eager
        # get_gradients dispatches), the grower is ONE jitted program,
        # the score/valid updates ride the standalone's own jitted
        # helpers under eager vmap
        M, K, L, n = self.M, self.K, self.L, self.n
        base_grad = jax.vmap(objective.get_gradients)
        if K == 1:
            self._vm_grad = base_grad
        else:
            # the standalone multiclass objective sees an (N, K) score;
            # lanes are class-major, so the (L, N) state reshapes to the
            # per-model (N, K) view, gradients vmap per MODEL, and the
            # result flattens back — pure layout moves, no arithmetic
            def _vm_grad_mc(score_lanes):
                sc = jnp.swapaxes(score_lanes.reshape(M, K, n), 1, 2)
                g, h = base_grad(sc)
                return (jnp.swapaxes(g, 1, 2).reshape(L, n),
                        jnp.swapaxes(h, 1, 2).reshape(L, n))
            self._vm_grad = _vm_grad_mc
        vm_grow = jax.vmap(one_grow, in_axes=(None, 0, 0, 0, 0, 0, 0, 0))
        # lane-axis sharding: shard_map the vmapped grower over the
        # GLOBAL device mesh so each device grows L/k lanes concurrently
        # (per-device model lanes; multi-host pods shard the lane axis
        # across every host's devices).  Per-lane values are identical
        # either way (a vmap lane's arithmetic is batch-width
        # independent — the bit-identity suite pins this), so sharding
        # is purely a throughput choice.
        ndev = jax.device_count()
        self._shard = (bool(self.cfg.tpu_multitrain_shard) and ndev > 1
                       and self.L >= ndev and self.L % ndev == 0)
        if self._shard:
            from jax.sharding import PartitionSpec as P
            from ..parallel.mesh import get_mesh, shard_map_compat
            self._ndev = ndev
            mesh = get_mesh(ndev, "models")
            ax = mesh.axis_names[0]
            self._vm_grow = jax.jit(shard_map_compat(
                vm_grow, mesh=mesh,
                in_specs=(P(),) + (P(ax),) * 7,
                out_specs=P(ax)))
        else:
            self._vm_grow = jax.jit(vm_grow)
        self._vm_walk = jax.vmap(walk_fn,
                                 in_axes=(None, 0, 0, 0, 0, 0, 0, 0, 0, 0))
        self._vm_upd = jax.vmap(_update_score_by_leaf,
                                in_axes=(0, 0, 0, None))
        # raw per-tree train predictions for DART's drop bookkeeping:
        # one stacked eager gather, the vmap of the standalone's own
        # `leaf_value[row_leaf]`
        self._vm_base_pred = jax.vmap(lambda lv, rl: lv[rl])
        self._lr_dev = jnp.asarray(self._lanes(self.lr))
        # per-iteration shrinkage lanes: DART replaces this every
        # iteration (lr/(1+k_dropped) per model); others keep lr
        self._shrink_dev = self._lr_dev
        self._sweep_dev = jnp.asarray(self._sweep_lanes())

    def _sweep_lanes(self) -> np.ndarray:
        return self._lanes(self.sweep) if self.sweep.size else \
            np.zeros((self.L, 0), np.float32)

    # -- per-iteration host inputs ------------------------------------------
    def _masks_for_iter(self, it: int) -> Optional[np.ndarray]:
        """(M, N) f32 training-row BASE masks for this iteration, or None
        when unchanged from the previous one (device array reused).  The
        bag only moves at bagging-block boundaries (bagging_mask_np is a
        pure function of the block), so off-boundary iterations skip the
        host sampling AND the host->device transfer entirely.  GOSS lanes
        never bag (the standalone GOSS overrides sampling entirely); their
        base mask is the static rows indicator and the per-iteration GOSS
        survivorship multiplies on top in step_once."""
        def _bagged(st):
            if self._goss:
                return False
            c = st.cfg
            pos_neg = (c.objective == "binary" and
                       (c.pos_bagging_fraction < 1.0 or
                        c.neg_bagging_fraction < 1.0))
            return c.bagging_freq > 0 and (c.bagging_fraction < 1.0 or
                                           pos_neg)
        if it > 0 and not any(
                _bagged(st) and it % max(1, int(st.cfg.bagging_freq)) == 0
                for st in self.states):
            return None
        label = None
        if self.cfg.objective == "binary" and \
                self.train_set.metadata.label is not None:
            label = np.asarray(self.train_set.metadata.label)
        rows_out = []
        for st in self.states:
            base = None if self._goss else bagging_mask_np(
                st.cfg, self.n, it, label=label, rows=st.rows)
            if base is None:
                if st.rows is not None:
                    base = np.zeros(self.n, np.float32)
                    base[st.rows] = 1.0
                else:
                    base = np.ones(self.n, np.float32)
            if st.mask_vals is not None and st.rows is not None:
                sub = base[st.rows] * st.mask_vals
                base = np.zeros(self.n, np.float32)
                base[st.rows] = sub
            rows_out.append(base)
        return np.stack(rows_out)

    def _fmask_for_iter(self, it: int) -> Optional[np.ndarray]:
        any_ff = any(st.cfg.feature_fraction < 1.0 for st in self.states)
        if not any_ff:
            return None if it > 0 else np.ones((self.M, self.num_features),
                                               bool)
        out = np.ones((self.M, self.num_features), bool)
        for m, st in enumerate(self.states):
            fm = feature_mask_np(st.cfg, self.num_features, it)
            if fm is not None:
                out[m] = fm
        return out

    # -- GOSS (host draws over the eager gradient matrix) --------------------
    def _apply_goss(self, it: int, grad, hess):
        """Shared host GOSS draws per lane: multiplies the amplified
        small-gradient weights into the stacked gradients (one eager
        elementwise multiply — warmup/inactive lanes multiply by 1.0,
        which is bit-exact) and records the 0/1 survivorship per model
        for the grower mask."""
        mult = None
        gmask = None
        K = self.K
        # one host pull shared across lanes
        gnp = np.asarray(grad)
        hnp = np.asarray(hess)
        for m, st in enumerate(self.states):
            if not st.active:
                continue
            if K == 1:
                gm = goss_sample_np(st.cfg, gnp[m], hnp[m], it, rows=st.rows)
            else:
                g2 = gnp[m * K:(m + 1) * K].T   # (N, K) per-model view
                h2 = hnp[m * K:(m + 1) * K].T
                gm = goss_sample_np(st.cfg, g2, h2, it, rows=st.rows)
            if gm is None:
                continue
            if mult is None:
                mult = np.ones((self.L, self.n), np.float32)
                gmask = np.ones((self.M, self.n), np.float32)
            mask_m, mult_m = gm
            gmask[m] = mask_m
            for c in range(K):
                mult[m * K + c] = mult_m
        if mult is None:
            self._goss_mask = None
            return grad, hess
        self._goss_mask = gmask
        mdev = jnp.asarray(mult)
        return grad * mdev, hess * mdev

    # -- DART (host drop bookkeeping + lane-masked device axpys) -------------
    def _dart_pre(self, it: int) -> Dict[int, List[int]]:
        """Per-model drop draws (the standalone DART.train_one_iter loop,
        models/boosting.py) + batched dropped-tree score subtraction.
        Sets the per-iteration shrinkage lanes."""
        drops: Dict[int, List[int]] = {}
        shrink = np.empty(self.M, np.float32)
        for m, st in enumerate(self.states):
            cfg = st.cfg
            lr = float(cfg.learning_rate)
            if not st.active:
                st.cur_shrinkage = lr
                shrink[m] = np.float32(lr)
                continue
            rng = host_rng(cfg.drop_seed, it)
            t = it
            drop: List[int] = []
            if t > 0 and not (rng.random() < cfg.skip_drop):
                if cfg.uniform_drop:
                    p = cfg.drop_rate
                    if cfg.max_drop > 0:
                        p = min(p, cfg.max_drop / float(t))
                    for i in range(t):
                        if rng.random() < p:
                            drop.append(i)
                            if cfg.max_drop > 0 and len(drop) >= cfg.max_drop:
                                break
                else:
                    inv_avg = t / max(st.sum_weight, 1e-12)
                    p = cfg.drop_rate
                    if cfg.max_drop > 0:
                        p = min(p, cfg.max_drop * inv_avg /
                                max(st.sum_weight, 1e-12))
                    for i in range(t):
                        if rng.random() < p * st.weights[i] * inv_avg:
                            drop.append(i)
                            if cfg.max_drop > 0 and len(drop) >= cfg.max_drop:
                                break
            if drop:
                drops[m] = drop
            kd = float(len(drop))
            if cfg.xgboost_dart_mode:
                st.cur_shrinkage = lr if not drop else lr / (lr + kd)
            else:
                st.cur_shrinkage = lr / (1.0 + kd)
            shrink[m] = np.float32(st.cur_shrinkage)
        self._shrink_dev = jnp.asarray(self._lanes(shrink))
        # remove dropped trees from the TRAIN score (valid handled in
        # normalize, like the reference): one where-masked axpy per
        # distinct dropped tree index, all lanes in a shared dispatch
        for d in sorted({i for dl in drops.values() for i in dl}):
            wv = np.zeros(self.M, np.float32)
            sel = np.zeros(self.M, bool)
            for m, dl in drops.items():
                if d in dl:
                    wv[m] = np.float32(self.states[m].weights[d])
                    sel[m] = True
            sl = jnp.asarray(self._lanes(sel))
            wl = jnp.asarray(self._lanes(wv))
            self.score = jnp.where(
                sl[:, None],
                self.score - self._dart_base[d] * wl[:, None], self.score)
        return drops

    def _dart_normalize(self, drops: Dict[int, List[int]]) -> None:
        """The standalone DART._normalize: dropped trees rescale to
        weight*k/(k+1), the train score re-adds them at the new weight and
        valid scores adjust by the weight delta — batched as lane-masked
        axpys.  Tree shrink factors are recorded per model for the
        finalize-time replay (the standalone shrinks host trees in
        place)."""
        if not drops:
            return
        new_w = {}
        delta_w = {}
        for m, dl in drops.items():
            st = self.states[m]
            cfg = st.cfg
            kd = float(len(dl))
            lr = float(cfg.learning_rate)
            factor = kd / (kd + lr) if cfg.xgboost_dart_mode else \
                kd / (kd + 1.0)
            for d in dl:
                old = st.weights[d]
                new = old * factor
                st.weights[d] = new
                st.sum_weight -= old - new
                st.tree_factors[d].append(factor)
                new_w[(m, d)] = new
                delta_w[(m, d)] = new - old
        for d in sorted({i for dl in drops.values() for i in dl}):
            nw = np.zeros(self.M, np.float32)
            dw = np.zeros(self.M, np.float32)
            sel = np.zeros(self.M, bool)
            for m, dl in drops.items():
                if d in dl:
                    nw[m] = np.float32(new_w[(m, d)])
                    dw[m] = np.float32(delta_w[(m, d)])
                    sel[m] = True
            sl = jnp.asarray(self._lanes(sel))
            nwl = jnp.asarray(self._lanes(nw))
            self.score = jnp.where(
                sl[:, None],
                self.score + self._dart_base[d] * nwl[:, None], self.score)
            if self.vscores:
                dwl = jnp.asarray(self._lanes(dw))
                self.vscores = tuple(
                    jnp.where(sl[:, None],
                              vs + self._dart_vb[d][vi] * dwl[:, None], vs)
                    for vi, vs in enumerate(self.vscores))

    def step_once(self, it: int) -> None:
        faults.check_train_iter(it)
        masks = self._masks_for_iter(it)
        if masks is not None:
            self._base_masks_np = masks
            self._mask_dev = jnp.asarray(self._lanes(masks))
            if self._goss:
                self._base_mask_dev = self._mask_dev
        fmask = self._fmask_for_iter(it)
        if fmask is not None:
            self._fmask_dev = jnp.asarray(self._lanes(fmask))
        drops = self._dart_pre(it) if self._dart else None
        qk = (self._fold_one(self._quant_base, self._fold_vals(it))
              if self._need_quant_key else self._dummy_qk())
        nk = (self._fold_two(self._node_base, self._fold_vals(it))
              if self._need_node_key else self._dummy_nk())
        with self.record.phase("gradients"):
            grad, hess = self._vm_grad(self.score)
            if self._goss:
                grad, hess = self._apply_goss(it, grad, hess)
                if self._goss_mask is not None:
                    self._mask_dev = jnp.asarray(self._lanes(
                        self._base_masks_np * self._goss_mask))
                else:
                    self._mask_dev = self._base_mask_dev
        with self.record.phase("grow"):
            # sharded or not, one (L, ...) call: the shard_map lane
            # split happens on-device (no host (k, L/k) reshape)
            grown = self._vm_grow(self._X_arg, grad, hess,
                                  self._mask_dev, self._fmask_dev,
                                  self._sweep_dev, qk, nk)
        if self._dart:
            # raw (unshrunk) per-tree train predictions, one stacked
            # gather — the standalone's `leaf_value[row_leaf]`
            self._dart_base.append(
                self._vm_base_pred(grown.leaf_value, grown.row_leaf))
            for st in self.states:
                if st.active:
                    st.weights.append(st.cur_shrinkage)
                    st.sum_weight += st.cur_shrinkage
                    st.tree_shrink.append(st.cur_shrinkage)
                    st.tree_factors.append([])
                else:
                    # keep per-tree lists index-aligned with _dart_base
                    st.tree_shrink.append(float(st.cfg.learning_rate))
                    st.tree_factors.append([])
                    st.weights.append(0.0)
        # eager multiply: its rounding is the standalone
        # `grown.leaf_value * shrinkage` dispatch's rounding
        shrink_dev = self._shrink_dev if self._dart else self._lr_dev
        lv = grown.leaf_value * shrink_dev[:, None]
        self.score = self._vm_upd(self.score, grown.row_leaf, lv, 1.0)
        new_vscores = []
        vb_this = []
        for vb, vs in zip(self.vbins, self.vscores):
            dv = self._vm_walk(vb, grown.split_feature, grown.threshold_bin,
                               grown.nan_bin, grown.cat_member,
                               grown.decision_type, grown.left_child,
                               grown.right_child, lv, grown.num_leaves)
            nvs = vs + dv
            if self._dart:
                # the standalone's (after - before) / w valid base —
                # NOT dv / w: the add rounds, and the base must replay
                # exactly what the score absorbed
                vb_this.append((nvs - vs) / shrink_dev[:, None])
            new_vscores.append(nvs)
        self.vscores = tuple(new_vscores)
        if self._dart:
            self._dart_vb.append(vb_this)
            self._dart_normalize(drops or {})
        grown = grown._replace(row_leaf=jnp.zeros((self.L, 0), jnp.int32))
        self._grown.append(grown)
        leaves = grown.num_leaves
        if hasattr(leaves, "copy_to_host_async"):
            leaves.copy_to_host_async()
        self._leaves.append(leaves)
        self._steps += 1
        for m, st in enumerate(self.states):
            if st.active:
                st.kept_iters = self._steps
        self.record.add_tree(it, 0, grown.hist_passes[0],
                             grown.num_leaves[0])

    def _dummy_qk(self):
        if not hasattr(self, "_qk0"):
            self._qk0 = jnp.zeros((self.L, 2), jnp.uint32)
        return self._qk0

    def _dummy_nk(self):
        if not hasattr(self, "_nk0"):
            self._nk0 = jnp.zeros((self.L, 2, 2), jnp.uint32)
        return self._nk0

    # -- stump stop (lagged, like GBDT.train_one_iter) -----------------------
    def check_stumps(self, it: int) -> None:
        """Before stepping iteration ``it``: a model whose ENTIRE previous
        iteration grew no split stops (the standalone loop pops those
        trees and breaks, gbdt.cpp:430-450).  DART keeps the stump
        iteration's trees — its non-deferred standalone path records them
        before discovering the stop (models/boosting.py _defer_trees)."""
        if it < 1 or it - 1 >= len(self._leaves):
            return
        prev = np.asarray(jax.device_get(self._leaves[it - 1]))
        K = self.K
        for m, st in enumerate(self.states):
            if st.active and all(int(prev[m * K + c]) <= 1
                                 for c in range(K)):
                st.active = False
                st.stop_reason = "no-split"
                if self._dart:
                    st.kept_iters = it
                else:
                    # the stump iteration's trees are popped unless they
                    # are the model's only iteration (they carry the
                    # init bias)
                    st.kept_iters = max(1, it - 1)

    # -- evaluation / early stopping ----------------------------------------
    def _needs_eval(self) -> bool:
        return bool(self.valid_sets)

    def _host_valid_score(self, host_vs: np.ndarray, m: int) -> np.ndarray:
        """Model m's slice of a pulled (L, nv) valid score: (nv,) or the
        standalone's (nv, K) layout for multiclass."""
        if self.K == 1:
            return host_vs[m]
        return host_vs[m * self.K:(m + 1) * self.K].T

    def host_lane_score(self, m: int, rows_dev=None) -> np.ndarray:
        """Model m's current TRAIN score (optionally gathered at device
        row indices): (n,)/(rows,) or (n, K)/(rows, K) for multiclass.
        The CV fast path evaluates held-out metrics on this."""
        if self.K == 1:
            sc = self.score[m] if rows_dev is None else \
                self.score[m][rows_dev]
            return np.asarray(sc)
        sc = self.score[m * self.K:(m + 1) * self.K]
        if rows_dev is not None:
            sc = sc[:, rows_dev]
        return np.asarray(sc).T

    def eval_all(self, it: int, num_boost_round: int) -> None:
        if not self._needs_eval():
            return
        with self.record.phase("eval"):
            host_vs = [np.asarray(vs) for vs in self.vscores]
            for m, st in enumerate(self.states):
                if not st.active:
                    continue
                rows = []
                for vi, (vname, _) in enumerate(self.valid_sets):
                    sc = self._host_valid_score(host_vs[vi], m)
                    for mt in st.metrics_per_valid[vi]:
                        for name, val, hib in mt.eval(sc):
                            rows.append((vname, name, val, hib))
                for dn, en, val, _ in rows:
                    st.history.setdefault(dn, {}).setdefault(
                        en, []).append(val)
                if st.stopper is None and \
                        st.cfg.early_stopping_round and \
                        int(st.cfg.early_stopping_round) > 0:
                    st.stopper = early_stopping(
                        int(st.cfg.early_stopping_round),
                        st.cfg.first_metric_only, verbose=False)
                if st.stopper is not None:
                    env = CallbackEnv(None, {}, it, 0, num_boost_round,
                                      rows)
                    try:
                        st.stopper(env)
                    except EarlyStopException as e:
                        st.active = False
                        st.stop_reason = "early-stop"
                        st.kept_iters = it + 1
                        st.best_iteration = e.best_iteration + 1
                        for dn, en, sc, _ in e.best_score:
                            st.best_score.setdefault(dn, {})[en] = sc

    # -- driver loop ---------------------------------------------------------
    def run(self, num_boost_round: int) -> "BatchTrainer":
        for it in range(num_boost_round):
            self.check_stumps(it)
            if not any(st.active for st in self.states):
                break
            self.step_once(it)
            self.eval_all(it, num_boost_round)
            if not any(st.active for st in self.states):
                break
        return self

    # -- extraction ----------------------------------------------------------
    def finalize(self) -> List[Booster]:
        with self.record.phase("record"):
            pulled = jax.device_get(self._grown)
            scores = self.score
            K = self.K
            boosters = []
            for m, st in enumerate(self.states):
                trees = []
                lr = float(st.cfg.learning_rate)
                for t in range(st.kept_iters):
                    shrink = st.tree_shrink[t] if self._dart else lr
                    for c in range(K):
                        lane = m * K + c
                        g = GrownTree(*[np.asarray(f)[lane]
                                        for f in pulled[t]])
                        tree = _grown_to_tree(g, shrink, self.train_set)
                        if t == 0 and abs(st.bias[c]) > EPSILON:
                            tree.add_bias(st.bias[c])
                        if self._dart:
                            # normalize-time rescales, replayed in the
                            # standalone's chronological order
                            for f in st.tree_factors[t]:
                                tree.shrink(f)
                        trees.append(tree)
                bst = Booster(params=st.params, train_set=self.train_set)
                gb = bst._gbdt
                gb.models = trees
                gb.iter_ = st.kept_iters
                if K == 1:
                    gb.score = scores[m]
                else:
                    gb.score = jnp.swapaxes(
                        scores[m * K:(m + 1) * K], 0, 1)
                if self._dart:
                    kept = st.kept_iters
                    gb._weights = list(st.weights[:kept])
                    gb._sum_weight = float(sum(st.weights[:kept]))
                    gb._cur_shrinkage = st.cur_shrinkage
                bst.best_iteration = st.best_iteration
                bst.best_score = st.best_score
                rec = TrainRecord(meta={
                    "boosting": self.boosting,
                    "objective": str(st.cfg.objective),
                    "tree_learner": "serial",
                    "multitrain_model_index": m,
                    "multitrain_models": self.M,
                    "multitrain_classes": K,
                    "num_leaves": int(st.cfg.num_leaves),
                    "num_data": int(self.n),
                    "num_features": int(self.num_features),
                })
                for t, tr in enumerate(trees):
                    rec.add_tree(t // K, t % K, 0, tr.num_leaves)
                gb.train_record = rec
                boosters.append(bst)
            return boosters
