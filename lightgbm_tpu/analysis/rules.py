"""Trace-lint rule engine: program contracts checked on traced jaxprs.

Each rule inspects one :class:`TraceUnit` — a traced (never executed)
program plus its config context and the telemetry collective tally the
trace produced — and returns :class:`Violation`\\ s with site-named,
actionable messages.  The six shipped rules:

* :class:`CollectiveBudgetRule` — per-site collective count/byte
  ceilings from :mod:`.contracts`, cross-checked against the jaxpr's
  total collective op count so tallies and programs cannot drift;
* :class:`HostSyncRule` — host callbacks / infeed / outfeed / host
  transfers inside traced programs (a device_get-class sync inside a
  hot loop serializes the dispatch pipeline);
* :class:`DtypeRule` — silent f64 on device (and any extra
  config-forbidden dtypes, e.g. f32 histograms on an int-only
  quantized path);
* :class:`ConstantFoldRule` — closed-over constants / literal operands
  above a size threshold (the PR 4 ``%reduce.227`` 2s-constant-fold
  stall class);
* :class:`RetraceRule` — jaxpr-hash stability across repeated traces
  (boosting iterations, serve SHAPE_BUCKETS re-dispatch);
* :class:`DonationRule` — declared buffer donation must actually alias
  (donated in-aval matches an out-aval) on the score-update entries.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import ir
from .contracts import DonationContract, contract_for, resolve_limit

__all__ = ["Violation", "TraceUnit", "Rule", "CollectiveBudgetRule",
           "HostSyncRule", "DtypeRule", "ConstantFoldRule", "RetraceRule",
           "DonationRule", "DEFAULT_RULES", "run_rules"]


@dataclass(frozen=True)
class Violation:
    rule: str
    config: str
    site: str
    message: str
    severity: str = "error"

    def to_json(self) -> Dict[str, str]:
        return {"rule": self.rule, "config": self.config, "site": self.site,
                "message": self.message, "severity": self.severity}


@dataclass
class TraceUnit:
    """One traced matrix config handed to the rules.

    ``collectives`` is the telemetry ``note_collective`` delta produced
    *while tracing this program* (site -> {op, count, bytes});
    ``hashes`` the retrace probes: ``(label, jaxpr_hash)`` pairs where a
    label appearing with two different hashes is a retrace.
    """

    name: str
    jaxpr: Any = None                       # ClosedJaxpr (may be None)
    ctx: Dict[str, Any] = field(default_factory=dict)
    collectives: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    hashes: List[Tuple[str, str]] = field(default_factory=list)


class Rule:
    name = "rule"

    def check(self, unit: TraceUnit) -> List[Violation]:
        raise NotImplementedError

    def _v(self, unit: TraceUnit, site: str, message: str,
           severity: str = "error") -> Violation:
        return Violation(self.name, unit.name, site, message, severity)


class CollectiveBudgetRule(Rule):
    """Per-site collective op/count/byte ceilings.

    Validates the trace's telemetry tally against the contracts declared
    next to the collective code, then cross-checks the tally against the
    jaxpr itself: the program's total collective op count must equal the
    total tallied count, so an untallied collective (or a tally with no
    op behind it) is flagged even before any ceiling is exceeded."""

    name = "collective-budget"

    def check(self, unit: TraceUnit) -> List[Violation]:
        out: List[Violation] = []
        ctx = unit.ctx
        total_tallied = 0
        for site, rec in sorted(unit.collectives.items()):
            total_tallied += int(rec.get("count", 0))
            contract = contract_for(site)
            if contract is None:
                out.append(self._v(
                    unit, site,
                    f"collective site '{site}' ({rec.get('op')}, "
                    f"{rec.get('count')} call(s)) has no declared "
                    f"contract; declare one with "
                    f"analysis.contracts.collective_contract next to the "
                    f"note_collective call"))
                continue
            op = str(rec.get("op", ""))
            if contract.ops and op not in contract.ops:
                out.append(self._v(
                    unit, site,
                    f"site '{site}' tallied op '{op}' but its contract "
                    f"({contract.declared_in}) allows {contract.ops}"))
            max_count = resolve_limit(contract.max_count, ctx)
            count = int(rec.get("count", 0))
            if max_count is not None and count > max_count:
                out.append(self._v(
                    unit, site,
                    f"site '{site}' traced {count} collective(s); the "
                    f"contract in {contract.declared_in} allows "
                    f"{max_count} per traced program"))
            max_bpo = resolve_limit(contract.max_bytes_per_op, ctx)
            nbytes = int(rec.get("bytes", 0))
            if max_bpo is not None and count > 0 and \
                    nbytes > count * max_bpo:
                out.append(self._v(
                    unit, site,
                    f"site '{site}' moved {nbytes} bytes over {count} "
                    f"op(s) (mean {nbytes // max(count, 1)}); the contract "
                    f"in {contract.declared_in} budgets "
                    f"{max_bpo} bytes/op — a full-histogram payload "
                    f"leaked onto a sliced path?"))
            max_dcn = resolve_limit(contract.max_dcn_bytes_per_op, ctx)
            if max_dcn is not None and count > 0:
                # modeled cross-host slice of the mean per-op payload:
                # (H-1)/H of the bytes leave the host on a host-major
                # axis (contracts.dcn_fraction) — the pod-budget check
                # that fires at abstract W=64 before chips exist
                from .contracts import dcn_fraction
                dcn_bytes = int((nbytes / count) * dcn_fraction(ctx))
                if dcn_bytes > max_dcn:
                    out.append(self._v(
                        unit, site,
                        f"site '{site}' models {dcn_bytes} CROSS-HOST "
                        f"bytes/op at {ctx.get('hosts', 'derived')} "
                        f"host(s) (mean payload "
                        f"{nbytes // max(count, 1)} B); the contract in "
                        f"{contract.declared_in} budgets {max_dcn} DCN "
                        f"bytes/op — this path is not pod-safe"))
        if unit.jaxpr is not None and ctx.get("crosscheck_tally", True):
            in_program = sum(len(v) for v in
                             ir.collectives_of(unit.jaxpr).values())
            if in_program != total_tallied:
                out.append(self._v(
                    unit, "<program>",
                    f"traced program holds {in_program} collective op(s) "
                    f"but telemetry tallied {total_tallied}: a collective "
                    f"was added without a note_collective site (or a "
                    f"site fires off-trace) — contracts and tallies have "
                    f"drifted"))
        return out


class HostSyncRule(Rule):
    """Host round-trips inside traced programs.

    ``device_get`` / ``.item()`` never appear in a jaxpr (they act on
    concrete arrays between dispatches); what DOES appear — and silently
    serializes the async dispatch pipeline — is the callback family and
    host transfers.  Ops inside while/scan bodies are the hot-loop
    class the serving and boosting paths must never contain."""

    name = "host-sync"

    HOST_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                  "callback", "infeed", "outfeed")

    def check(self, unit: TraceUnit) -> List[Violation]:
        if unit.jaxpr is None:
            return []
        out: List[Violation] = []
        for info in ir.iter_eqns(unit.jaxpr):
            hit = info.prim in self.HOST_PRIMS
            if not hit and info.prim == "device_put":
                # flag explicit transfers to host memory spaces only
                devices = info.eqn.params.get("devices", ())
                hit = any("host" in str(d).lower() for d in
                          (devices if isinstance(devices, (list, tuple))
                           else [devices]))
            if hit:
                where = " inside a hot loop (" + \
                    "/".join(info.path) + ")" if info.in_loop else ""
                out.append(self._v(
                    unit, info.prim,
                    f"host-sync primitive '{info.prim}'{where}: each call "
                    f"stalls the device until the host round-trip "
                    f"returns; move it out of the traced program or "
                    f"behind telemetry's trace-time tallies"))
        return out


class DtypeRule(Rule):
    """No silent f64 on device; config-forbidden dtypes stay out.

    Host-side np.float64 (model fields in models/gbdt.py, the linear
    solver's lstsq) never enters a jaxpr and is deliberately NOT
    flagged — the rule sees only traced device programs.  ``ctx`` keys:
    ``forbid_dtypes`` extends the default {float64}; ``allow_f64`` (for
    an explicit x64 config) clears it."""

    name = "dtype"

    def check(self, unit: TraceUnit) -> List[Violation]:
        if unit.jaxpr is None:
            return []
        forbid = set(unit.ctx.get("forbid_dtypes", ()))
        if not unit.ctx.get("allow_f64", False):
            forbid |= {"float64", "complex128"}
        if not forbid:
            return []
        out: List[Violation] = []
        seen = 0
        for info in ir.iter_eqns(unit.jaxpr):
            for v in info.eqn.outvars:
                aval = getattr(v, "aval", None)
                dt = str(getattr(aval, "dtype", ""))
                if dt in forbid:
                    seen += 1
                    if seen > 8:  # one promotion cascades; cap the noise
                        continue
                    shape = tuple(getattr(aval, "shape", ()))
                    out.append(self._v(
                        unit, info.prim,
                        f"'{info.prim}' produces {dt}{shape} on device"
                        + (" inside " + "/".join(info.path)
                           if info.path else "")
                        + "; quantized/TPU paths must stay in narrow "
                          "dtypes — cast on the host or fix the "
                          "promotion"))
        if seen > 8:
            out.append(self._v(
                unit, "<program>",
                f"... and {seen - 8} more forbidden-dtype eqns"))
        return out


class ConstantFoldRule(Rule):
    """Closed-over constants / literal operands above a size threshold.

    The MULTICHIP_r05 stall class: XLA constant-folds ops over large
    literal operands at compile time (%reduce.227 spent >2s folding an
    argmax over an all-False constant); a big constant baked into the
    program is also re-shipped with every executable.  Threshold in
    elements via ``ctx['const_fold_max_elems']`` (default 2**16)."""

    name = "constant-fold-size"
    DEFAULT_MAX_ELEMS = 1 << 16

    def check(self, unit: TraceUnit) -> List[Violation]:
        if unit.jaxpr is None:
            return []
        limit = int(unit.ctx.get("const_fold_max_elems",
                                 self.DEFAULT_MAX_ELEMS))
        out: List[Violation] = []
        for const, path in ir.iter_consts(unit.jaxpr):
            shape = tuple(getattr(const, "shape", ()))
            elems = 1
            for d in shape:
                elems *= int(d)
            if elems > limit:
                where = "/".join(path) if path else "<top>"
                out.append(self._v(
                    unit, where,
                    f"closed-over constant {getattr(const, 'dtype', '?')}"
                    f"{shape} ({elems} elems > {limit}) baked into the "
                    f"program at {where}: pass it as an argument so XLA "
                    f"neither folds nor re-ships it (the cat_member "
                    f"constant-fold stall class)"))
        for lit, info in ir.literal_operands(unit.jaxpr, limit + 1):
            out.append(self._v(
                unit, info.prim,
                f"literal operand {lit.aval.dtype}{tuple(lit.aval.shape)} "
                f"inlined at '{info.prim}': lift it to an argument"))
        return out


class RetraceRule(Rule):
    """Jaxpr-hash stability across repeated traces.

    ``unit.hashes`` holds ``(label, hash)`` probes: the lint driver
    traces each program twice with freshly built same-shaped inputs
    (boosting iterations i and i+1; each serve bucket twice).  A label
    with two distinct hashes means XLA compiles again every iteration —
    the retrace/recompile budget is zero.  The compile-event counters
    jax.monitoring feeds telemetry (TrainRecord.compile_events) measure
    the same thing at run time; this rule catches it at trace time."""

    name = "retrace"

    def check(self, unit: TraceUnit) -> List[Violation]:
        by_label: Dict[str, List[str]] = {}
        for label, h in unit.hashes:
            by_label.setdefault(label, []).append(h)
        out: List[Violation] = []
        for label, hs in sorted(by_label.items()):
            if len(set(hs)) > 1:
                out.append(self._v(
                    unit, label,
                    f"program '{label}' traced to {len(set(hs))} distinct "
                    f"jaxprs across {len(hs)} same-shape traces "
                    f"(hashes {sorted(set(hs))}): every dispatch "
                    f"recompiles — hoist the varying Python value out of "
                    f"the trace or mark it static"))
        max_programs = unit.ctx.get("max_distinct_programs")
        if max_programs is not None:
            distinct = len({h for _, h in unit.hashes})
            if distinct > int(max_programs):
                out.append(self._v(
                    unit, "<ladder>",
                    f"{distinct} distinct compiled programs for "
                    f"{len(by_label)} labels exceeds the budget of "
                    f"{max_programs} (the serve SHAPE_BUCKETS ladder "
                    f"compiles one program per bucket, nothing more)"))
        return out


class DonationRule(Rule):
    """Declared buffer donation must be able to alias.

    For every :class:`~.contracts.DonationContract` the rule lowers the
    jitted entry on representative args and checks (a) the declaration
    survives to the lowering (``donate_argnums``), and (b) every donated
    input aval matches some output aval in shape+dtype — XLA only
    aliases exact matches, so a silent dtype/shape drift keeps both
    buffers live and doubles the score-update footprint."""

    name = "donation"

    def check(self, unit: TraceUnit) -> List[Violation]:
        contracts: Sequence[DonationContract] = unit.ctx.get(
            "donation_contracts", ())
        out: List[Violation] = []
        for c in contracts:
            out.extend(self.check_contract(c, unit))
        return out

    def check_contract(self, c: DonationContract,
                       unit: TraceUnit) -> List[Violation]:
        import jax
        out: List[Violation] = []
        try:
            fn = c.fn_ref()
            args = c.build_args()
            lowered = jax.jit(fn, donate_argnums=c.donate_argnums).lower(
                *args) if not hasattr(fn, "lower") else fn.lower(*args)
        except Exception as exc:  # lowering itself failed
            out.append(self._v(
                unit, c.name,
                f"donation contract '{c.name}' ({c.declared_in}) could "
                f"not be lowered: {exc}"))
            return out
        declared = getattr(lowered, "donate_argnums", None)
        if declared is not None and tuple(declared) != c.donate_argnums:
            out.append(self._v(
                unit, c.name,
                f"'{c.name}' declares donate_argnums={c.donate_argnums} "
                f"but the lowering carries {tuple(declared)}: the jit "
                f"wrapper dropped the donation"))
        # aval match: donated inputs must have an identically shaped+typed
        # output to alias with
        jaxpr = jax.make_jaxpr(fn)(*args) if not hasattr(fn, "lower") \
            else jax.make_jaxpr(lambda *a: fn(*a))(*args)
        in_avals = [v.aval for v in jaxpr.jaxpr.invars]
        out_avals = [v.aval for v in jaxpr.jaxpr.outvars]
        out_sigs = [(tuple(a.shape), str(a.dtype)) for a in out_avals]
        for argnum in c.donate_argnums:
            if argnum >= len(in_avals):
                out.append(self._v(
                    unit, c.name,
                    f"'{c.name}' donates argnum {argnum} but the entry "
                    f"takes {len(in_avals)} array args"))
                continue
            a = in_avals[argnum]
            sig = (tuple(a.shape), str(a.dtype))
            if sig not in out_sigs:
                out.append(self._v(
                    unit, c.name,
                    f"'{c.name}' donates arg {argnum} "
                    f"({sig[1]}{sig[0]}) but no output matches that "
                    f"shape+dtype — XLA cannot alias it, the donated "
                    f"score buffer is silently copied "
                    f"(outputs: {out_sigs})"))
        return out


DEFAULT_RULES: Tuple[Rule, ...] = (
    CollectiveBudgetRule(), HostSyncRule(), DtypeRule(), ConstantFoldRule(),
    RetraceRule(), DonationRule())


def run_rules(units: Sequence[TraceUnit],
              rules: Optional[Sequence[Rule]] = None) -> List[Violation]:
    """Run every rule over every unit, most-severe ordering preserved."""
    violations: List[Violation] = []
    for unit in units:
        for rule in (rules if rules is not None else DEFAULT_RULES):
            violations.extend(rule.check(unit))
    return violations
