"""Static analysis of traced programs: the trace-lint subsystem.

The distributed-performance story of this repo is a set of
*traced-program shape* contracts — exactly one reduce_scatter per
histogram-merge site, zero full-histogram psums on the sliced path,
ceil(log2 W) spec-ramp collectives, no host syncs or silent f64 in hot
programs, no giant constant-folded operands, zero retraces across
boosting iterations and serve buckets, donated score buffers that
really alias — plus the pod-scale pair this package grew in PR 11:
per-device HBM / per-kernel VMEM stays under a declared curve at any
(rows, world_size), and every conditional arm issues the identical
collective sequence (no static cross-host deadlocks).  This package
states those contracts once and machine-checks them:

* :mod:`.ir` — the recursive jaxpr walker every check rides
  (supersedes the three test-local walkers of PRs 4-5);
* :mod:`.contracts` — contract declarations living NEXT TO the code
  they constrain: collective budgets keyed by telemetry
  ``note_collective`` site names, donation entries, and
  :class:`~.contracts.MemoryBudget` HBM/VMEM curves;
* :mod:`.rules` — the rule engine (the six PR-10 checks);
* :mod:`.spmd` — SPMD-safety rules: collective-order deadlock
  detection + shard_map sharding consistency, world-size-scaled;
* :mod:`.memory` — the ``lint-mem`` peak-memory estimator (live-range
  jaxpr sweep, per-shard sizing, XLA memory_analysis cross-check);
* :mod:`.slo_cover` — SLO-coverage check: every declared
  service-level objective (telemetry/slo.py) must key to a registered
  metric series (the ``note_collective``-contract coverage pattern);
* :mod:`.lint` — the ``python -m lightgbm_tpu lint-trace`` matrix
  driver (serial / wave / DP-scatter / spec-ramp / multitrain / serve
  plus the SLO-coverage section), a blocking CI step.
"""

from . import contracts, ir, lint, memory, rules, slo_cover, spmd
from .contracts import (CollectiveContract, DonationContract, MemoryBudget,
                        all_contracts, all_memory_budgets,
                        collective_contract, contract_for,
                        donation_contract, memory_budget,
                        memory_budget_for, world_size)
from .ir import (collect_collectives, collectives_of, count_primitive,
                 is_collective, iter_consts, iter_eqns, stable_hash,
                 subjaxprs, trace, walk_eqns)
from .lint import (MATRIX_CONFIGS, Geometry, build_unit, environment_info,
                   run_lint)
from .memory import (MemoryBudgetRule, MemoryEstimate, estimate_memory,
                     run_lint_mem)
from .rules import (DEFAULT_RULES, CollectiveBudgetRule, ConstantFoldRule,
                    DonationRule, DtypeRule, HostSyncRule, RetraceRule,
                    Rule, TraceUnit, Violation, run_rules)
from .slo_cover import check_slo_coverage, slo_coverage_report
from .spmd import (SPMD_RULES, CollectiveOrderRule,
                   ShardingConsistencyRule, collective_trace)

__all__ = [
    "ir", "contracts", "rules", "lint", "memory", "slo_cover", "spmd",
    "check_slo_coverage", "slo_coverage_report",
    "collect_collectives", "collectives_of", "count_primitive",
    "is_collective", "iter_consts", "iter_eqns", "stable_hash",
    "subjaxprs", "trace", "walk_eqns",
    "CollectiveContract", "DonationContract", "MemoryBudget",
    "all_contracts", "all_memory_budgets", "collective_contract",
    "contract_for", "donation_contract", "memory_budget",
    "memory_budget_for", "world_size",
    "MATRIX_CONFIGS", "Geometry", "build_unit", "environment_info",
    "run_lint",
    "MemoryBudgetRule", "MemoryEstimate", "estimate_memory",
    "run_lint_mem",
    "DEFAULT_RULES", "CollectiveBudgetRule", "ConstantFoldRule",
    "DonationRule", "DtypeRule", "HostSyncRule", "RetraceRule",
    "Rule", "TraceUnit", "Violation", "run_rules",
    "SPMD_RULES", "CollectiveOrderRule", "ShardingConsistencyRule",
    "collective_trace",
]
