"""Static analysis of traced programs: the trace-lint subsystem.

The distributed-performance story of this repo is a set of
*traced-program shape* contracts — exactly one reduce_scatter per
histogram-merge site, zero full-histogram psums on the sliced path,
ceil(log2 W) spec-ramp collectives, no host syncs or silent f64 in hot
programs, no giant constant-folded operands, zero retraces across
boosting iterations and serve buckets, donated score buffers that
really alias.  This package states those contracts once and machine
checks them:

* :mod:`.ir` — the recursive jaxpr walker every check rides
  (supersedes the three test-local walkers of PRs 4-5);
* :mod:`.contracts` — contract declarations living NEXT TO the code
  they constrain, keyed by telemetry ``note_collective`` site names;
* :mod:`.rules` — the rule engine (six checks);
* :mod:`.lint` — the ``python -m lightgbm_tpu lint-trace`` matrix
  driver (serial / wave / DP-scatter / spec-ramp / multitrain / serve),
  a blocking CI step.
"""

from . import contracts, ir, lint, rules
from .contracts import (CollectiveContract, DonationContract,
                        all_contracts, collective_contract,
                        contract_for, donation_contract)
from .ir import (collect_collectives, collectives_of, count_primitive,
                 is_collective, iter_consts, iter_eqns, stable_hash,
                 subjaxprs, trace, walk_eqns)
from .lint import MATRIX_CONFIGS, build_unit, run_lint
from .rules import (DEFAULT_RULES, CollectiveBudgetRule, ConstantFoldRule,
                    DonationRule, DtypeRule, HostSyncRule, RetraceRule,
                    Rule, TraceUnit, Violation, run_rules)

__all__ = [
    "ir", "contracts", "rules", "lint",
    "collect_collectives", "collectives_of", "count_primitive",
    "is_collective", "iter_consts", "iter_eqns", "stable_hash",
    "subjaxprs", "trace", "walk_eqns",
    "CollectiveContract", "DonationContract", "all_contracts",
    "collective_contract", "contract_for", "donation_contract",
    "MATRIX_CONFIGS", "build_unit", "run_lint",
    "DEFAULT_RULES", "CollectiveBudgetRule", "ConstantFoldRule",
    "DonationRule", "DtypeRule", "HostSyncRule", "RetraceRule",
    "Rule", "TraceUnit", "Violation", "run_rules",
]
