"""Jaxpr IR walking — the shared traversal every trace contract rides.

The performance story of the distributed growers rests on *traced-program
shape* guarantees (one reduce_scatter per histogram-merge site, zero
full-histogram psums, ceil(log2 W) spec-ramp psums, no giant
constant-folded operands).  Before this module, three divergent ad-hoc
jaxpr walkers lived in tests/test_wave_scatter.py, tests/test_specramp.py
and tests/test_telemetry.py; they are superseded by the recursive
traversal here, which descends through every sub-jaxpr a program can
nest (pjit / while / cond branches / scan / shard_map / custom_jvp /
pallas_call kernels), so a contract checked "on the program" really sees
the whole program.

Everything here is pure inspection: no tracing side effects, no
execution.  :func:`trace` is a thin :func:`jax.make_jaxpr` wrapper kept
here so callers (tests, the lint driver) share one spelling.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, Iterator, List, NamedTuple, Tuple

__all__ = ["EqnInfo", "subjaxprs", "iter_eqns", "walk_eqns",
           "collect_collectives", "collectives_of", "count_primitive",
           "iter_consts", "aval_elems", "max_operand_elems", "trace",
           "stable_hash", "COLLECTIVE_PRIMITIVES", "is_collective"]


# Primitive names that move bytes across the mesh axis.  ``psum2`` is the
# spelling newer jax versions give lax.psum inside shard_map; the
# substring names cover reduce_scatter/all_reduce renames across
# versions (the same tolerance tests/test_wave_scatter.py shipped).
COLLECTIVE_PRIMITIVES = ("psum", "psum2", "pmax", "pmin", "all_gather",
                         "all_to_all", "ppermute")
_COLLECTIVE_SUBSTRINGS = ("reduce_scatter", "all_reduce")


def is_collective(primitive_name: str) -> bool:
    return (primitive_name in COLLECTIVE_PRIMITIVES or
            any(s in primitive_name for s in _COLLECTIVE_SUBSTRINGS))


class EqnInfo(NamedTuple):
    """One equation seen by the recursive walk.

    ``path`` is the tuple of enclosing primitive names (e.g.
    ``("shard_map", "while")`` for an eqn inside a while-loop body inside
    a shard_map) — rules use it to tell hot-loop eqns from setup eqns.
    """

    prim: str
    eqn: Any
    path: Tuple[str, ...]

    @property
    def in_loop(self) -> bool:
        return any(p in ("while", "scan", "fori_loop") for p in self.path)


def subjaxprs(val: Any) -> Iterator[Any]:
    """Sub-jaxprs inside an eqn param: raw Jaxpr (shard_map), ClosedJaxpr
    (pjit/while/cond/scan/pallas_call) or lists of either (cond
    branches).  Yields raw Jaxpr objects."""
    if hasattr(val, "eqns"):
        yield val
    elif hasattr(val, "jaxpr"):
        yield val.jaxpr
    elif isinstance(val, (list, tuple)):
        for item in val:
            yield from subjaxprs(item)


def _as_jaxpr(jaxpr_like: Any) -> Any:
    """Accept a Jaxpr or a ClosedJaxpr."""
    return jaxpr_like.jaxpr if hasattr(jaxpr_like, "jaxpr") else jaxpr_like


def iter_eqns(jaxpr_like: Any,
              path: Tuple[str, ...] = ()) -> Iterator[EqnInfo]:
    """Every equation in the program, depth-first through all nested
    sub-jaxprs, tagged with its enclosing-primitive path."""
    jaxpr = _as_jaxpr(jaxpr_like)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        yield EqnInfo(name, eqn, path)
        sub_path = path + (name,)
        for val in eqn.params.values():
            for sub in subjaxprs(val):
                yield from iter_eqns(sub, sub_path)


def aval_elems(var: Any) -> int:
    """Element count of a var/literal's abstract value (0 when shapeless)."""
    aval = getattr(var, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    size = 1
    for d in aval.shape:
        size *= int(d)
    return size


def max_operand_elems(eqn: Any) -> int:
    """Largest operand (in elements) of one equation — the quantity the
    collective-shape assertions bound (a psum's wire payload is its
    operand)."""
    size = 0
    for v in eqn.invars:
        size = max(size, aval_elems(v))
    return size


def walk_eqns(jaxpr_like: Any) -> Iterator[Tuple[str, int]]:
    """Yield every ``(primitive_name, max_operand_elems)``, descending
    into while/cond/pjit/scan/shard_map sub-jaxprs (the historical
    test-local walker API, now single-sourced here)."""
    for info in iter_eqns(jaxpr_like):
        yield info.prim, max_operand_elems(info.eqn)


def count_primitive(jaxpr_like: Any, name: str) -> int:
    """Number of eqns binding the named primitive anywhere in the
    program (replaces ``str(jaxpr).count(name)`` — substring counting
    breaks the day a primitive name embeds another's)."""
    return sum(1 for info in iter_eqns(jaxpr_like) if info.prim == name)


def collectives_of(jaxpr_like: Any) -> Dict[str, List[int]]:
    """Map collective primitive name -> operand sizes (elements), one
    entry per traced collective op."""
    out: Dict[str, List[int]] = {}
    for info in iter_eqns(jaxpr_like):
        if is_collective(info.prim):
            out.setdefault(info.prim, []).append(
                max_operand_elems(info.eqn))
    return out


def trace(fn: Callable, *args, **kwargs) -> Any:
    """``jax.make_jaxpr`` — trace without executing or compiling."""
    import jax
    return jax.make_jaxpr(fn)(*args, **kwargs)


def collect_collectives(fn: Callable, *args) -> Dict[str, List[int]]:
    """Trace ``fn`` and return its collective ops by primitive name
    (tests/test_wave_scatter.py's ``_collectives_of``, single-sourced)."""
    return collectives_of(trace(fn, *args))


def iter_consts(jaxpr_like: Any) -> Iterator[Tuple[Any, Tuple[str, ...]]]:
    """Every closed-over constant in the program: the top-level
    ClosedJaxpr's consts plus the consts of every nested ClosedJaxpr
    (pjit bodies keep their own).  Yields ``(const, path)``."""

    def _walk(closed: Any, path: Tuple[str, ...]) -> Iterator:
        consts = getattr(closed, "consts", None)
        if consts:
            for c in consts:
                yield c, path
        jaxpr = _as_jaxpr(closed)
        if not hasattr(jaxpr, "eqns"):
            return
        for eqn in jaxpr.eqns:
            sub_path = path + (eqn.primitive.name,)
            for val in eqn.params.values():
                if hasattr(val, "jaxpr"):  # ClosedJaxpr with own consts
                    yield from _walk(val, sub_path)
                elif isinstance(val, (list, tuple)):
                    for item in val:
                        if hasattr(item, "jaxpr"):
                            yield from _walk(item, sub_path)

    yield from _walk(jaxpr_like, ())


def stable_hash(jaxpr_like: Any) -> str:
    """Content hash of a traced program.

    The pretty-printer assigns variable names deterministically in
    traversal order, so two traces of the same Python program at the
    same shapes/dtypes print identically — the hash is the
    retrace-budget currency: a changed hash across boosting iterations
    or across serve bucket re-traces means XLA will compile again."""
    text = str(_as_jaxpr(jaxpr_like))
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def literal_operands(jaxpr_like: Any,
                     min_elems: int = 1) -> Iterator[Tuple[Any, EqnInfo]]:
    """Inline Literal operands of at least ``min_elems`` elements, with
    the eqn consuming them (scalar literals are the normal case; a big
    one is a constant XLA will fold at compile time)."""
    from jax.core import Literal
    for info in iter_eqns(jaxpr_like):
        for v in info.eqn.invars:
            if isinstance(v, Literal) and aval_elems(v) >= min_elems:
                yield v, info
