"""SPMD-safety lint: collective-order deadlock detection + sharding rules.

A pod-scale program is SPMD: every shard runs the SAME traced program,
and every collective is a rendezvous — all ranks must issue the same
collective sequence (same primitive, same mesh axes, same wire shape) or
rank 7 hangs forever inside an all-reduce the other ranks never enter.
The worker-kill chaos test (tests/test_multiprocess.py) catches this
class dynamically on a 2-process runtime; this module catches it
statically, on every traced program in the lint matrix:

* :func:`collective_trace` — the ordered collective sequence of a
  program per mesh axis: ``(primitive, axes, shape, dtype)`` tuples in
  program order, descending every sub-jaxpr.

* :class:`CollectiveOrderRule` — every conditional arm (``cond``
  branches, anywhere in the program, including donated/serve branches)
  must issue an IDENTICAL collective sequence.  A collective inside one
  arm of a cond is the canonical static deadlock: shards that take the
  other arm never reach the rendezvous.  (``while`` bodies are exempt:
  they are shared by all ranks, and their trip counts are data-uniform
  on the growers — the dynamic half the chaos suite owns.)

* :class:`ShardingConsistencyRule` — every ``shard_map`` must run over
  the DECLARED mesh axes (``ctx['mesh_axes']``), its in/out specs may
  reference only those axes, and every collective inside its body must
  name an axis the enclosing mesh binds.  A spec naming a stale or
  misspelled axis silently replicates the operand (k-times the memory
  and wire traffic) before it deadlocks anything.

Both rules ride the PR-10 walker (:mod:`.ir`) and join the lint-trace
matrix (:mod:`.lint`), so the pod path is machine-checked at W=4, W=8
and (trace-only, via AbstractMesh) W=64 — per ROADMAP item 1.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

from . import ir
from .rules import Rule, TraceUnit, Violation

__all__ = ["CollectiveOp", "collective_trace", "branch_signatures",
           "CollectiveOrderRule", "ShardingConsistencyRule", "SPMD_RULES"]


class CollectiveOp(NamedTuple):
    """(primitive, axes, operand shape, dtype) — one wire rendezvous."""

    prim: str
    axes: str
    shape: Tuple[int, ...]
    dtype: str

    def __str__(self) -> str:
        return f"{self.prim}[{self.axes}]{self.dtype}{self.shape}"


def _eqn_axes(eqn: Any) -> str:
    """The mesh axes a collective eqn synchronizes over, normalized to a
    stable string (psum/pmax/pmin carry ``axes``; ppermute/all_gather
    spell it ``axis_name``)."""
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, (list, tuple)):
        return ",".join(str(a) for a in axes)
    return str(axes)


def _wire_sig(eqn: Any) -> CollectiveOp:
    shape: Tuple[int, ...] = ()
    dtype = ""
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            shape = tuple(int(d) for d in aval.shape)
            dtype = str(getattr(aval, "dtype", ""))
            break
    return CollectiveOp(eqn.primitive.name, _eqn_axes(eqn), shape, dtype)


def collective_trace(jaxpr_like: Any) -> List[CollectiveOp]:
    """Ordered collective sequence of a program (depth-first program
    order, every sub-jaxpr descended) — the rendezvous schedule all
    shards must agree on."""
    return [_wire_sig(info.eqn) for info in ir.iter_eqns(jaxpr_like)
            if ir.is_collective(info.prim)]


def branch_signatures(eqn: Any) -> List[List[CollectiveOp]]:
    """Per-branch collective sequences of one ``cond`` eqn."""
    branches = eqn.params.get("branches", ())
    return [collective_trace(b) for b in branches]


class CollectiveOrderRule(Rule):
    """All arms of every conditional must issue identical collective
    sequences — the static form of the cross-host deadlock."""

    name = "collective-order"

    def check(self, unit: TraceUnit) -> List[Violation]:
        if unit.jaxpr is None:
            return []
        out: List[Violation] = []
        for info in ir.iter_eqns(unit.jaxpr):
            if info.prim != "cond":
                continue
            sigs = branch_signatures(info.eqn)
            if len(sigs) < 2 or all(s == sigs[0] for s in sigs[1:]):
                continue
            where = "/".join(info.path + ("cond",))
            rendered = "; ".join(
                f"arm {i}: [{', '.join(map(str, s)) or 'none'}]"
                for i, s in enumerate(sigs))
            out.append(self._v(
                unit, where,
                f"conditional arms at {where} issue DIVERGENT collective "
                f"sequences ({rendered}): shards taking different arms "
                f"rendezvous on different schedules — rank-level deadlock "
                f"on a real mesh; hoist the collective out of the cond or "
                f"issue it identically in every arm"))
        return out


def _spec_axes(names: Any) -> List[str]:
    """Mesh axes one shard_map in/out names dict references."""
    out: List[str] = []
    if isinstance(names, dict):
        for axes in names.values():
            for ax in (axes if isinstance(axes, (list, tuple)) else (axes,)):
                out.append(str(ax))
    return out


def _mesh_axes(eqn: Any) -> Tuple[str, ...]:
    mesh = eqn.params.get("mesh")
    try:
        return tuple(str(a) for a in mesh.axis_names)
    except Exception:
        return ()


class ShardingConsistencyRule(Rule):
    """shard_map meshes/specs must match the declared mesh axes, and
    body collectives must use axes the mesh binds."""

    name = "sharding-consistency"

    def check(self, unit: TraceUnit) -> List[Violation]:
        if unit.jaxpr is None:
            return []
        declared = tuple(unit.ctx.get("mesh_axes", ()))
        out: List[Violation] = []
        for info in ir.iter_eqns(unit.jaxpr):
            if info.prim != "shard_map":
                continue
            where = "/".join(info.path + ("shard_map",)) or "shard_map"
            mesh_axes = _mesh_axes(info.eqn)
            if declared and tuple(mesh_axes) != declared:
                out.append(self._v(
                    unit, where,
                    f"shard_map at {where} runs over mesh axes "
                    f"{mesh_axes} but this config declares "
                    f"{declared}: a stray mesh axis means the program "
                    f"is sharded over a mesh the launcher never built"))
            bound = set(mesh_axes)
            for kind, all_names in (("in", info.eqn.params.get("in_names",
                                                               ())),
                                    ("out", info.eqn.params.get("out_names",
                                                                ()))):
                for idx, names in enumerate(all_names):
                    bad = [a for a in _spec_axes(names) if a not in bound]
                    if bad:
                        out.append(self._v(
                            unit, where,
                            f"shard_map at {where} {kind}_specs[{idx}] "
                            f"references axis(es) {bad} the mesh "
                            f"{mesh_axes} does not bind — the operand "
                            f"silently replicates instead of sharding"))
            # body collectives must rendezvous over bound axes
            body = info.eqn.params.get("jaxpr")
            if body is not None and bound:
                for binfo in ir.iter_eqns(body):
                    if not ir.is_collective(binfo.prim):
                        continue
                    axes = [a for a in _eqn_axes(binfo.eqn).split(",") if a]
                    bad = [a for a in axes if a not in bound]
                    if bad:
                        out.append(self._v(
                            unit, where,
                            f"collective '{binfo.prim}' inside the "
                            f"shard_map body at {where} names axis(es) "
                            f"{bad} outside the mesh {mesh_axes}"))
        return out


SPMD_RULES: Tuple[Rule, ...] = (CollectiveOrderRule(),
                                ShardingConsistencyRule())
