"""SLO-coverage lint: every declared objective keys to a real series.

The ``note_collective``-contract coverage pattern applied to the SLO
layer: an SLO declared against a metric nobody registers would simply
never burn — the objective silently stops objecting.  This check
imports the SLO-declaring modules (serving stats / HTTP server /
admission / inference compiler), runs every registered *metric ensurer*
(each subsystem materializes its metric families into a registry with
no traffic needed), and then validates for each declared SLO that

  * ``metric`` (and ``total_metric`` for ratio SLOs) names a registered
    metric;
  * the metric's kind fits the SLO kind (latency objectives need a
    windowed histogram, ratio objectives counters);
  * every label key the SLO selects on exists in the metric's label
    schema (a selector on a label the series never carries matches
    nothing, forever).

Wired into ``lint-trace`` (``analysis/lint.py``) as the
``slo_coverage`` report section, so CI blocks on a dangling SLO the
same way it blocks on an undeclared collective site.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .rules import Violation
from ..telemetry.metrics import (Counter, Gauge, MetricsRegistry,
                                 WindowedHistogram)

__all__ = ["check_slo_coverage", "slo_coverage_report"]

RULE = "slo-coverage"


def _import_declaring_modules() -> None:
    """Import every module that declares SLOs / registers ensurers (the
    declarations live next to the code they bound, so importing the
    subsystems collects them)."""
    from ..explain import compiler as _explain_compiler  # noqa: F401
    from ..resilience import admission  # noqa: F401
    from ..serve import compiler, fleet, server, stats  # noqa: F401
    from .. import multitrain  # noqa: F401  (multitrain/fallback_rate)


def check_slo_coverage(registry: Optional[MetricsRegistry] = None
                       ) -> List[Violation]:
    from ..telemetry.slo import all_slos, ensure_metrics
    _import_declaring_modules()
    registry = registry if registry is not None else MetricsRegistry()
    ensure_metrics(registry)
    out: List[Violation] = []

    def v(site: str, message: str) -> None:
        out.append(Violation(RULE, "slo_coverage", site, message))

    for name, s in sorted(all_slos().items()):
        metrics = [("metric", s.metric)]
        if s.kind == "ratio":
            if not s.total_metric:
                v(name, "ratio SLO needs a total_metric denominator")
            else:
                metrics.append(("total_metric", s.total_metric))
        for role, mname in metrics:
            m = registry.get(mname)
            if m is None:
                v(name, f"{role} '{mname}' names no registered series "
                        f"(declared in {s.declared_in or '?'}); an SLO "
                        f"keyed to a metric nobody emits never burns")
                continue
            if s.kind == "latency" and role == "metric" and \
                    not isinstance(m, WindowedHistogram):
                v(name, f"latency SLO needs a windowed histogram but "
                        f"'{mname}' is a {m.kind}")
            if s.kind == "ratio" and not isinstance(m, Counter):
                v(name, f"ratio SLO needs counters but '{mname}' is a "
                        f"{m.kind}")
            if s.kind in ("gauge_floor", "gauge_ceiling") and \
                    not isinstance(m, Gauge):
                v(name, f"{s.kind} SLO needs a gauge but '{mname}' "
                        f"is a {m.kind}")
            selectors = dict(s.labels)
            if role == "metric":
                selectors.update(s.bad_labels)
            unknown = sorted(set(selectors) - set(m.label_names))
            if unknown:
                v(name, f"selector label(s) {unknown} not in "
                        f"'{mname}' label schema {list(m.label_names)}")
        if not (0.0 < s.target < 1.0):
            v(name, f"target must be in (0, 1), got {s.target}")
        if s.kind == "latency" and s.threshold_ms <= 0:
            v(name, f"latency SLO needs threshold_ms > 0, "
                    f"got {s.threshold_ms}")
        if s.kind == "gauge_floor" and s.floor <= 0:
            v(name, f"gauge_floor SLO needs floor > 0, got {s.floor}")
        if s.kind == "gauge_ceiling" and s.ceiling < 0:
            v(name, f"gauge_ceiling SLO needs ceiling >= 0, "
                    f"got {s.ceiling}")
    return out


def slo_coverage_report(registry: Optional[MetricsRegistry] = None,
                        violations: Optional[List[Violation]] = None
                        ) -> Dict[str, Any]:
    """JSON-ready section for the ``lint-trace`` report.  Pass
    ``violations`` when the check already ran (run_lint does) to avoid
    a second pass over the registry."""
    from ..telemetry.slo import all_slos
    if violations is None:
        violations = check_slo_coverage(registry)
    return {
        "ok": not violations,
        "violations": [x.to_json() for x in violations],
        "slos": {name: {"metric": s.metric, "kind": s.kind,
                        "target": s.target,
                        "declared_in": s.declared_in}
                 for name, s in sorted(all_slos().items())},
    }
