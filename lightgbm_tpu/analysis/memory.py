"""``lint-mem``: static peak-memory estimation + declared HBM/VMEM curves.

ROADMAP items 1 (pod-scale) and 2 (out-of-core) both stall on a question
no test answers statically: *will this traced program fit in HBM at 10^8
rows on W hosts?*  The reference framework answers it by construction —
its histogram pool and ``pipeline_reader.h`` bound the working set
(PAPER.md layers 0/3).  Here the same property is recovered by analysis:

* :func:`estimate_memory` runs a **live-range sweep** over a traced
  jaxpr: walk equations in program order, a buffer becomes live when the
  eqn that binds it runs and dies after its last use; the peak of the
  live set (inputs + consts + intermediates) is the HBM estimate.  The
  sweep descends pjit/scan/while/cond/shard_map sub-jaxprs, counting a
  nested body's interior peak (beyond its boundary buffers, which alias
  the call site's operands) as a transient at the call site.

* **Per-device sizing**: a ``shard_map`` body is traced at per-shard
  block avals — ``P(ax)`` operands arrive as global/k slices, ``P()``
  operands at full (replicated) size — so the body sweep IS the
  per-device estimate on mesh programs; device residency is decided
  inside the body, and the boundary buffers outside it are the same
  arrays the body counts at their sharded size.  Programs with no mesh
  report their global sweep.

* ``pallas_call`` equations stay opaque for the HBM sweep (their blocks
  live in VMEM, not HBM) and instead feed the **VMEM estimate**: the sum
  of a kernel's VMEM-resident block avals, checked against the ~16
  MB/core ceiling (pallas guide: HBM -> VMEM -> compute units).

* Where the backend reports one, the estimate is cross-checked against
  XLA's own ``lower().compile().memory_analysis()`` (argument + output +
  temp bytes) — the estimator must stay within 2x of the compiler's
  number or the lint fails, so the static answer cannot silently drift
  from what XLA actually allocates.

Budgets are :class:`~.contracts.MemoryBudget` curves declared next to
the code they constrain (``learner/wave.py``, ``parallel/
data_parallel.py``, ``serve/predictor.py``, ``multitrain/batched.py``)
as functions of (rows, features, bins, wave_size, leaves, world_size,
models) — ``lint-mem rows=1e8 devices=64`` evaluates the same
declarations at pod scale and answers the fit question for meshes no CI
host can run.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, NamedTuple, Optional, \
    Sequence, Tuple

from . import ir
from .contracts import (all_memory_budgets, memory_budget_for,
                        resolve_limit, world_size)
from .rules import Rule, TraceUnit, Violation

__all__ = ["BufferInfo", "MemoryEstimate", "estimate_memory",
           "kernel_vmem_bytes", "MemoryBudgetRule", "VMEM_BYTES_PER_CORE",
           "DEFAULT_HBM_GB", "xla_memory_analysis", "run_lint_mem", "main"]

# TPU memory-hierarchy constants (pallas guide "Memory Hierarchy" table:
# HBM = GBs off-chip, VMEM ~16 MB/core on-chip).  Overridable per ctx
# ("vmem_limit") and per CLI run (hbm-gb=) for other parts.
VMEM_BYTES_PER_CORE = 16 * 1024 * 1024
DEFAULT_HBM_GB = 16.0          # v5e-class part; hbm-gb= overrides

# Primitives whose sub-jaxpr buffers do NOT occupy HBM as jax arrays —
# pallas kernel bodies run out of VMEM/SMEM blocks and scratch.
_VMEM_BODY_PRIMS = ("pallas_call",)


class BufferInfo(NamedTuple):
    """One live buffer at the peak instant, for diagnostics."""

    what: str
    bytes: int
    aval: str
    path: Tuple[str, ...]

    def to_json(self) -> Dict[str, Any]:
        return {"what": self.what, "bytes": self.bytes, "aval": self.aval,
                "path": "/".join(self.path) or "<top>"}


class MemoryEstimate:
    """Result of one program sweep.

    ``peak_bytes`` — whole-program (global-aval) peak;
    ``peak_bytes_per_device`` — the per-shard peak: the largest
    shard_map body sweep on mesh programs (body avals are per-shard
    block shapes), the global sweep otherwise.  ``top_buffers`` are the
    largest buffers live at that peak, for site-named diagnostics.
    ``vmem_kernels`` maps each pallas_call site to the VMEM bytes of
    its kernel blocks."""

    def __init__(self) -> None:
        self.peak_bytes = 0
        self.peak_bytes_per_device = 0
        self.args_bytes = 0
        self.consts_bytes = 0
        self.top_buffers: List[BufferInfo] = []
        self.vmem_kernels: Dict[str, int] = {}

    def to_json(self) -> Dict[str, Any]:
        return {
            "peak_bytes": self.peak_bytes,
            "peak_bytes_per_device": self.peak_bytes_per_device,
            "args_bytes": self.args_bytes,
            "consts_bytes": self.consts_bytes,
            "top_buffers": [b.to_json() for b in self.top_buffers[:5]],
            "vmem_kernels": dict(self.vmem_kernels),
        }


def _aval_bytes(var: Any) -> int:
    aval = getattr(var, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    size = 1
    for d in aval.shape:
        size *= int(d)
    dt = getattr(aval, "dtype", None)
    return size * int(getattr(dt, "itemsize", 4) or 4)


def _aval_str(var: Any) -> str:
    aval = getattr(var, "aval", None)
    if aval is None:
        return "?"
    return f"{getattr(aval, 'dtype', '?')}{tuple(getattr(aval, 'shape', ()))}"


def kernel_vmem_bytes(eqn: Any) -> int:
    """VMEM-resident bytes of one pallas_call: the sum of kernel-body
    ref avals placed in VMEM (HBM/ANY-space refs are DMA'd manually by
    the kernel and excluded; unspecified spaces count, conservatively)."""
    kjaxpr = eqn.params.get("jaxpr")
    if kjaxpr is None:
        return 0
    total = 0
    for v in tuple(getattr(kjaxpr, "invars", ())) + \
            tuple(getattr(kjaxpr, "outvars", ())):
        aval = getattr(v, "aval", None)
        space = str(getattr(aval, "memory_space", "") or "").lower()
        if "hbm" in space or "any" in space:
            continue
        inner = getattr(aval, "inner_aval", aval)  # MemRef wraps the array
        size = 1
        for d in getattr(inner, "shape", ()):
            size *= int(d)
        dt = getattr(inner, "dtype", None)
        total += size * int(getattr(dt, "itemsize", 4) or 4)
    return total


def _sub_jaxprs_of(eqn: Any) -> Iterator[Any]:
    for val in eqn.params.values():
        yield from ir.subjaxprs(val)


def _is_literal(var: Any) -> bool:
    return type(var).__name__ == "Literal"


def _sweep(jaxpr_like: Any, path: Tuple[str, ...],
           est: MemoryEstimate) -> Tuple[int, List[BufferInfo]]:
    """Live-range sweep of one (sub-)jaxpr.

    Returns ``(peak_bytes, buffers_at_peak)``; peak includes the
    jaxpr's own inputs + consts (boundary buffers — callers descending
    a sub-jaxpr subtract them, since they alias the call operands)."""
    jaxpr = jaxpr_like.jaxpr if hasattr(jaxpr_like, "jaxpr") else jaxpr_like

    live: Dict[int, BufferInfo] = {}
    live_bytes = 0

    def _add(var: Any, what: str) -> None:
        nonlocal live_bytes
        b = _aval_bytes(var)
        if b <= 0 or id(var) in live:
            return
        live[id(var)] = BufferInfo(what, b, _aval_str(var), path)
        live_bytes += b

    def _drop(var: Any) -> None:
        nonlocal live_bytes
        info = live.pop(id(var), None)
        if info is not None:
            live_bytes -= info.bytes

    # last-use index per var (jaxpr outvars live to the end)
    last_use: Dict[int, int] = {}
    eqns = list(jaxpr.eqns)
    n_eqns = len(eqns)
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[id(v)] = i
    for v in jaxpr.outvars:
        if not _is_literal(v):
            last_use[id(v)] = n_eqns

    for v in jaxpr.invars:
        _add(v, "arg")
    for cv in jaxpr.constvars:
        _add(cv, "const")
    # a var with no last_use entry (unused arg/const) defaults to n_eqns
    # in the _drop check below, i.e. it stays live to the end
    peak = live_bytes
    peak_buffers = list(live.values())

    for i, eqn in enumerate(eqns):
        prim = eqn.primitive.name
        sub_path = path + (prim,)
        transient = 0
        if prim in _VMEM_BODY_PRIMS:
            # kernel blocks live in VMEM, not HBM: record for the VMEM
            # check; the HBM sweep sees only the eqn's in/out HBM avals
            key = f"{'/'.join(sub_path)}#{len(est.vmem_kernels)}"
            est.vmem_kernels[key] = kernel_vmem_bytes(eqn)
        else:
            for sub in _sub_jaxprs_of(eqn):
                sub_peak, _ = _sweep(sub, sub_path, est)
                sj = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                boundary = sum(_aval_bytes(v) for v in sj.invars) + \
                    sum(_aval_bytes(v) for v in sj.outvars)
                transient = max(transient, max(0, sub_peak - boundary))

        out_bytes = sum(_aval_bytes(v) for v in eqn.outvars)
        candidate = live_bytes + out_bytes + transient
        if candidate > peak:
            peak = candidate
            peak_buffers = list(live.values())
            for v in eqn.outvars:
                if _aval_bytes(v) > 0:
                    peak_buffers.append(BufferInfo(
                        f"out:{prim}", _aval_bytes(v), _aval_str(v),
                        sub_path))
            if transient > 0:
                peak_buffers.append(BufferInfo(
                    f"transient:{prim}", transient, "(sub-jaxpr interior)",
                    sub_path))
        for v in eqn.outvars:
            _add(v, f"out:{prim}")
        for v in list(eqn.invars) + list(eqn.outvars):
            if not _is_literal(v) and last_use.get(id(v), n_eqns) <= i:
                _drop(v)

    return peak, peak_buffers


def estimate_memory(jaxpr_like: Any) -> MemoryEstimate:
    """Static peak-live-buffer estimate of one traced program."""
    est = MemoryEstimate()
    jaxpr = jaxpr_like.jaxpr if hasattr(jaxpr_like, "jaxpr") else jaxpr_like
    est.args_bytes = sum(_aval_bytes(v) for v in jaxpr.invars)
    consts = getattr(jaxpr_like, "consts", None) or ()
    est.consts_bytes = sum(
        int(getattr(c, "nbytes", 0) or 0) for c in consts)
    est.peak_bytes, buffers = _sweep(jaxpr_like, (), est)
    # per-device: the largest shard_map body sweep (per-shard avals) on
    # mesh programs; the global sweep when no mesh is involved
    body_peak = 0
    body_buffers: List[BufferInfo] = []
    for info in ir.iter_eqns(jaxpr_like):
        if info.prim == "shard_map":
            for sub in _sub_jaxprs_of(info.eqn):
                p, bufs = _sweep(sub, info.path + ("shard_map",),
                                 MemoryEstimate())
                if p > body_peak:
                    body_peak, body_buffers = p, bufs
    if body_peak > 0:
        est.peak_bytes_per_device = body_peak
        est.top_buffers = sorted(body_buffers, key=lambda b: -b.bytes)[:8]
    else:
        est.peak_bytes_per_device = est.peak_bytes
        est.top_buffers = sorted(buffers, key=lambda b: -b.bytes)[:8]
    return est


# ---------------------------------------------------------------------------
# the rule
# ---------------------------------------------------------------------------

class MemoryBudgetRule(Rule):
    """Estimated per-device peak must stay under the declared HBM curve;
    every pallas kernel's VMEM blocks under the per-core ceiling; the
    estimate must track XLA's memory_analysis within 2x where reported.

    The unit's ctx carries the geometry the curve is evaluated at (rows,
    features, bins, wave_size, leaves, world_size, models) — the same
    dict ``lint-mem rows= devices=`` scales for the fit question.  A
    config with no declared budget is itself a violation: a new traced
    program family cannot land without a memory contract."""

    name = "memory-budget"

    def check(self, unit: TraceUnit) -> List[Violation]:
        if unit.jaxpr is None or not unit.ctx.get("check_memory", False):
            return []
        est: MemoryEstimate = unit.ctx.get("memory_estimate") \
            or estimate_memory(unit.jaxpr)
        out: List[Violation] = []
        budget = memory_budget_for(unit.name)
        if budget is None:
            out.append(self._v(
                unit, "<program>",
                f"config '{unit.name}' has no declared MemoryBudget; "
                f"declare one with analysis.contracts.memory_budget next "
                f"to the code that owns this program's footprint"))
            return out
        limit = resolve_limit(budget.hbm_per_device, unit.ctx)
        if limit is not None and est.peak_bytes_per_device > limit:
            top = ", ".join(
                f"{b.what} {b.aval} ({b.bytes >> 10} KiB) at "
                f"{'/'.join(b.path) or '<top>'}"
                for b in est.top_buffers[:3])
            out.append(self._v(
                unit, budget.name,
                f"estimated per-device peak {est.peak_bytes_per_device} B "
                f"exceeds the '{budget.name}' HBM budget {limit} B "
                f"({budget.declared_in}) at "
                f"rows={unit.ctx.get('rows')}, W={world_size(unit.ctx)}; "
                f"largest live buffers: {top}"))
        vmem_limit = resolve_limit(budget.vmem_per_kernel, unit.ctx)
        if vmem_limit is None:
            vmem_limit = int(unit.ctx.get("vmem_limit",
                                          VMEM_BYTES_PER_CORE))
        for kname, kbytes in est.vmem_kernels.items():
            if kbytes > vmem_limit:
                out.append(self._v(
                    unit, kname,
                    f"pallas kernel at {kname} holds {kbytes} B of VMEM "
                    f"blocks (> {vmem_limit} B per-core ceiling); shrink "
                    f"the block specs or stream via HBM refs + DMA"))
        xla = unit.ctx.get("xla_memory")
        if xla:
            total = int(xla.get("total_bytes", 0))
            if total > 0:
                ratio = est.peak_bytes_per_device / total
                lo, hi = unit.ctx.get("xla_ratio_bounds", (0.5, 2.0))
                if not (lo <= ratio <= hi):
                    out.append(self._v(
                        unit, "<xla-crosscheck>",
                        f"static estimate {est.peak_bytes_per_device} B is "
                        f"{ratio:.2f}x XLA memory_analysis() "
                        f"({total} B = args {xla.get('argument_bytes')} + "
                        f"out {xla.get('output_bytes')} + temp "
                        f"{xla.get('temp_bytes')}); the estimator has "
                        f"drifted outside [{lo}, {hi}]x of the compiler"))
        return out


# ---------------------------------------------------------------------------
# XLA cross-check
# ---------------------------------------------------------------------------

def xla_memory_analysis(fn: Any, args: tuple) -> Optional[Dict[str, int]]:
    """Compile ``fn`` (an unpartitioned program — see
    :func:`..lint.build_callable`) and read the backend's memory
    analysis, or None when the backend does not report one (some plugin
    backends)."""
    import jax
    try:
        stats = jax.jit(lambda *a: fn(*a)).lower(*args).compile() \
            .memory_analysis()
    except Exception:
        return None
    if stats is None:
        return None
    try:
        arg_b = int(stats.argument_size_in_bytes)
        out_b = int(stats.output_size_in_bytes)
        tmp_b = int(stats.temp_size_in_bytes)
        alias_b = int(getattr(stats, "alias_size_in_bytes", 0))
    except Exception:
        return None
    return {"argument_bytes": arg_b, "output_bytes": out_b,
            "temp_bytes": tmp_b, "alias_bytes": alias_b,
            "total_bytes": arg_b + out_b + tmp_b}


# ---------------------------------------------------------------------------
# the lint-mem driver
# ---------------------------------------------------------------------------

def _fit_report(fit_ctx: Dict[str, Any], hbm_gb: float) -> Dict[str, Any]:
    """Evaluate every declared HBM curve at a scaled ctx — the static
    answer to "will rows=R fit at W devices?"."""
    hbm_bytes = int(hbm_gb * (1 << 30))
    out: Dict[str, Any] = {"ctx": {k: v for k, v in sorted(fit_ctx.items())},
                           "hbm_gb_per_device": hbm_gb, "budgets": {}}
    for name, b in sorted(all_memory_budgets().items()):
        try:
            need = resolve_limit(b.hbm_per_device, fit_ctx)
        except Exception as exc:
            out["budgets"][name] = {"error": str(exc)}
            continue
        if need is None:
            continue
        out["budgets"][name] = {
            "hbm_bytes_per_device": need,
            "fits": bool(need <= hbm_bytes),
            "fraction_of_hbm": round(need / hbm_bytes, 4),
            "declared_in": b.declared_in,
        }
    # an errored curve was NOT evaluated — it must fail the verdict, not
    # silently count as fitting (the whole point of the fit question)
    out["all_fit"] = all(v.get("fits", False)
                         for v in out["budgets"].values())
    return out


def run_lint_mem(configs: Optional[Sequence[str]] = None, nshards: int = 8,
                 crosscheck: bool = True,
                 fit_ctx: Optional[Dict[str, Any]] = None,
                 hbm_gb: float = DEFAULT_HBM_GB) -> Dict[str, Any]:
    """Trace the matrix at memory-lint geometry, estimate, check the
    declared curves, cross-check XLA, and answer the fit question."""
    from . import lint

    # budgets register at module import; pull in every declaring module
    # so the check is import-order independent (learner/wave.py and
    # parallel/data_parallel.py load via the trace builders anyway)
    from ..ingest import stream  # noqa: F401
    from ..learner import wave  # noqa: F401
    from ..multitrain import batched  # noqa: F401
    from ..parallel import data_parallel  # noqa: F401
    from ..serve import predictor  # noqa: F401
    configs = tuple(configs) if configs else lint.MATRIX_CONFIGS
    geometry = lint.MEM_GEOMETRY
    report: Dict[str, Any] = {
        "schema": "lint-mem-v1",
        "environment": lint.environment_info(nshards),
        "configs": {},
    }
    violations: List[Violation] = []
    rule = MemoryBudgetRule()
    for name in configs:
        t0 = time.perf_counter()
        unit = lint.build_unit(name, nshards=nshards, geometry=geometry)
        est = estimate_memory(unit.jaxpr)
        unit.ctx["check_memory"] = True
        unit.ctx["memory_estimate"] = est
        entry: Dict[str, Any] = {"estimate": est.to_json()}
        budget = memory_budget_for(name)
        if budget is not None:
            entry["budget"] = {
                "name": budget.name,
                "hbm_per_device":
                    resolve_limit(budget.hbm_per_device, unit.ctx),
                "declared_in": budget.declared_in,
            }
        if crosscheck:
            fn_args = lint.build_callable(name, nshards=nshards,
                                          geometry=geometry)
            if fn_args is not None:
                fn, args = fn_args
                xla = xla_memory_analysis(fn, args)
                if xla is not None:
                    unit.ctx["xla_memory"] = xla
                    entry["xla_memory"] = xla
                    entry["estimate_over_xla"] = round(
                        est.peak_bytes_per_device /
                        max(1, xla["total_bytes"]), 3)
        vs = rule.check(unit)
        violations.extend(vs)
        entry["ok"] = not vs
        entry["violations"] = [v.to_json() for v in vs]
        entry["seconds"] = round(time.perf_counter() - t0, 3)
        report["configs"][name] = entry
    if fit_ctx is not None:
        report["fit"] = _fit_report(fit_ctx, hbm_gb)
    report["ok"] = not violations
    report["num_violations"] = len(violations)
    return report


def main(argv: Sequence[str]) -> int:
    """``python -m lightgbm_tpu lint-mem [configs=a,b] [out=report.json]
    [devices=8] [rows=1e8] [features=28] [bins=255] [hbm-gb=16]
    [crosscheck=1]``

    Without ``rows=``, checks the traced matrix against the declared
    curves (+ XLA cross-check) and exits nonzero on violation.  With
    ``rows=`` (and usually ``devices=``), additionally evaluates every
    declared HBM curve at that scale and prints the fit verdict — the
    static "will 10^8 rows fit at W=64?" answer."""
    import json

    from .lint import parse_kv_args

    configs: Optional[List[str]] = None
    out_path = ""
    nshards = 8
    crosscheck = True
    hbm_gb = DEFAULT_HBM_GB
    fit: Dict[str, int] = {}
    for key, value in parse_kv_args(argv).items():
        if key in ("configs", "config"):
            configs = [c.strip() for c in value.split(",") if c.strip()]
        elif key in ("out", "json", "json_out"):
            out_path = value
        elif key in ("devices", "nshards", "world_size"):
            nshards = int(float(value))
        elif key == "crosscheck":
            crosscheck = value.lower() not in ("0", "false", "no", "off")
        elif key == "hbm_gb":
            hbm_gb = float(value)
        elif key in ("rows", "features", "bins", "leaves", "wave_size",
                     "models", "itemsize", "bucket"):
            fit[key] = int(float(value))
    fit_ctx: Optional[Dict[str, Any]] = None
    if fit:
        fit_ctx = {
            "rows": fit.get("rows", 10 ** 8),
            "features": fit.get("features", 28),
            "bins": fit.get("bins", 255),
            "leaves": fit.get("leaves", 255),
            "wave_size": fit.get("wave_size", 42),
            "models": fit.get("models", 64),
            "itemsize": fit.get("itemsize", 4),
            "bucket": fit.get("bucket", 4096),
            "world_size": nshards,
            "nshards": nshards,
        }
    t0 = time.perf_counter()
    from .lint import _ensure_devices
    _ensure_devices(nshards)
    report = run_lint_mem(configs, nshards=nshards, crosscheck=crosscheck,
                          fit_ctx=fit_ctx, hbm_gb=hbm_gb)
    report["elapsed_seconds"] = round(time.perf_counter() - t0, 3)
    text = json.dumps(report, indent=2, sort_keys=False)
    print(text)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(text + "\n")
    if not report["ok"]:
        from ..utils.log import log_warning
        log_warning(f"lint-mem: {report['num_violations']} memory-contract "
                    f"violation(s)")
    return 0 if report["ok"] else 1
