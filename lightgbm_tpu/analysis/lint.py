"""``lint-trace``: trace the config matrix, enforce program contracts.

Drives :mod:`.ir` + :mod:`.rules` + :mod:`.spmd` over one traced (never
executed) program per supported training/serving shape:

* ``serial``     — the sequential wave grower (no mesh, no collectives);
* ``wave``       — the wave grower, Pallas kernels (interpret off-TPU);
* ``dp_scatter`` — W-shard DP wave, feature-sliced reduce-scatter merge;
* ``spec_ramp``  — DP wave + speculative ramp (the ceil(log2 W) budget);
* ``multitrain`` — the vmapped model axis over the wave grower;
* ``multitrain_mc`` — the same program at the multiclass (M, K) lane
  grid (L = M*K lanes), checking the K-scaled memory budget and that
  the wider lane count is retrace-stable;
* ``serve``      — the ensemble predictor across the SHAPE_BUCKETS
  ladder (one program per bucket, hash-stable on re-trace);
* ``serve_dense`` — the inference compiler's fused dense program
  (serve/compiler.py): bucket-ladder retrace probes plus the
  tree-sharded top-bucket program whose single score psum and
  per-shard memory are contract-checked;
* ``serve_zoo``  — the model zoo's stacked cross-model program
  (serve/zoo.py): M same-signature lanes vmapped over the dense
  program across the bucket ladder, plus the tree-sharded stacked
  top-bucket program whose ONE-psum-per-stack collective contract and
  M-scaled memory budget are machine-checked;
* ``serve_explain`` — the dense TreeSHAP explain program
  (explain/dense_shap.py) across the bucket ladder: retrace-stable per
  rung, zero while-loops in the row dimension (the whole point of the
  dense lowering), bounded by the serve/dense_explain memory budget.

Every config is traced TWICE with freshly built same-shape inputs so
the retrace rule sees real hash probes, and the telemetry collective
tally is snapshotted around each trace so the collective-budget rule
can cross-check contracts against both the tally and the jaxpr.

**World-size scaling**: the DP configs trace at any ``devices=W``.  Up
to the attached device count they run on a real submesh; past it the
trace rides a :class:`jax.sharding.AbstractMesh` (trace-only — shapes
and collectives are exact, nothing can execute), which is how the W=64
pod path is machine-checked on a laptop (ROADMAP item 1).

The report is JSON (``trace-lint-v1``) and the CLI exits 1 when any
violation is found (0 when clean) — CI runs this as a blocking step.
Each report records the jax/jaxlib version and the device/mesh shape it
traced under, so an 8-virtual-device run is distinguishable from a
real-chip run.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, \
    Sequence, Tuple

from . import ir
from .contracts import all_donation_contracts
from .rules import DEFAULT_RULES, TraceUnit, Violation, run_rules
from .spmd import SPMD_RULES

__all__ = ["MATRIX_CONFIGS", "Geometry", "TRACE_GEOMETRY", "MEM_GEOMETRY",
           "build_unit", "build_callable", "environment_info",
           "parse_kv_args", "run_lint", "main"]

MATRIX_CONFIGS = ("serial", "wave", "dp_scatter", "spec_ramp", "voting",
                  "multitrain", "multitrain_mc", "serve", "serve_dense",
                  "serve_zoo", "serve_explain", "ingest")

# every rule the matrix runs: the six PR-10 program-contract rules plus
# the SPMD-safety pair (collective-order, sharding-consistency)
ALL_RULES = tuple(DEFAULT_RULES) + tuple(SPMD_RULES)


class Geometry(NamedTuple):
    """Trace shapes for one lint pass.

    ``TRACE_GEOMETRY`` is the small-but-representative test-suite
    geometry (the endgame engages at 13 leaves / wave 4, scatter pads 6
    features to 8 blocks at k=8) — fast, used by ``lint-trace``.
    ``MEM_GEOMETRY`` is larger so the histogram working set dominates
    the row arrays and a footprint regression (an un-scattered merge, a
    doubled pool) moves the peak estimate well past curve noise — used
    by ``lint-mem``."""

    features: int = 6
    bins: int = 64
    leaves: int = 13
    wave: int = 4
    rows: int = 4096


TRACE_GEOMETRY = Geometry()
MEM_GEOMETRY = Geometry(features=64, bins=255, leaves=17, wave=16,
                        rows=8192)


def _backend_initialized() -> bool:
    """True once a jax client exists (then the device count is fixed).
    Must NOT itself initialize the backend — jax.devices() would."""
    try:
        from jax._src import xla_bridge
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def _ensure_devices(k: int) -> int:
    """Best-effort k virtual CPU devices.  Device count can only be set
    before the first jax client exists; afterwards fall back to
    whatever is visible (a larger requested W then traces over an
    AbstractMesh — see :func:`_trace_mesh`)."""
    import os

    import jax
    if not _backend_initialized():
        try:
            jax.config.update("jax_num_cpu_devices", k)
        except (AttributeError, RuntimeError):
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={k}"
                ).strip()
    try:
        return min(k, len(jax.devices()))
    except Exception:
        return 1


def _trace_mesh(k: int, axis_name: str = "workers"):
    """A k-way 1-D mesh for TRACING: a real submesh when k devices are
    attached, else an AbstractMesh (trace-only — a program traced over
    it can never execute, which is exactly what the lint wants).
    Returns ``(mesh, abstract)``."""
    avail = _ensure_devices(k)
    if avail >= k:
        from ..parallel.mesh import get_mesh
        return get_mesh(k, axis_name), False
    try:
        from jax.sharding import AbstractMesh
    except ImportError as exc:
        raise RuntimeError(
            f"devices={k} exceeds the {avail} attached device(s) and this "
            f"jax build has no AbstractMesh for trace-only meshes") from exc
    return AbstractMesh(((axis_name, k),)), True


def _mk_train_args(seed: int, n: int, geom: Geometry,
                   quantized: bool = False):
    import jax.numpy as jnp
    import numpy as np
    f, b = geom.features, geom.bins
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, b - 1, (f, n)).astype(np.uint8)
    logit = (bins[0].astype(np.float32) / b - 0.5) * 3
    y = (logit + rng.randn(n) * 0.7 > 0).astype(np.float32)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    mask = np.ones(n, np.float32)
    meta = (jnp.full((f,), b, jnp.int32), jnp.zeros((f,), bool),
            jnp.zeros((f,), bool), jnp.zeros((f,), jnp.int32),
            jnp.zeros((f,), jnp.float32), jnp.ones((f,), bool))
    return (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask)) + meta


def _mk_wave_grow(strategy, geom: Geometry, *, quantized: bool, spec: bool):
    from ..learner.wave import make_wave_grow_fn
    from ..ops.split import SplitParams
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    return make_wave_grow_fn(
        num_leaves=geom.leaves, num_features=geom.features,
        max_bins=geom.bins, max_depth=0, split_params=sp,
        hist_impl="pallas", any_cat=False, interpret=None, jit=False,
        wave_size=geom.wave, quantized=quantized, stochastic=False,
        spec_ramp=spec, spec_tol=0.02, strategy=strategy)


def _serial_entry(grow):
    def entry(bins, grad, hess, mask, nb, ic, hn, mono, cp, fm):
        return grow(bins, grad, hess, mask, nb, ic, hn, mono, cp, (), fm)
    return entry


def _dp_entry(grow, mesh, ax):
    import jax
    from jax.sharding import PartitionSpec as P
    from ..parallel.data_parallel import DataParallelTreeLearner
    from ..parallel.mesh import shard_map_compat
    return jax.jit(shard_map_compat(
        _serial_entry(grow), mesh=mesh,
        in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P(), P(), P(),
                  P(), P()),
        out_specs=DataParallelTreeLearner._tree_specs(ax)))


def _trace_with_tally(fn, args) -> Tuple[Any, Dict[str, Dict[str, Any]]]:
    """make_jaxpr plus the telemetry collective delta the trace fired."""
    from ..telemetry.train_record import collectives_snapshot
    before = collectives_snapshot()
    jaxpr = ir.trace(lambda *a: fn(*a), *args)
    after = collectives_snapshot()
    delta: Dict[str, Dict[str, Any]] = {}
    for site, rec in after.items():
        base = before.get(site, {"count": 0, "bytes": 0})
        dc = rec["count"] - base["count"]
        if dc > 0:
            delta[site] = {"op": rec["op"], "count": dc,
                           "bytes": rec["bytes"] - base["bytes"]}
    return jaxpr, delta


def _base_ctx(geom: Geometry, **kw) -> Dict[str, Any]:
    ctx: Dict[str, Any] = {
        "wave_size": geom.wave, "features": geom.features,
        "bins": geom.bins, "leaves": geom.leaves, "rows": geom.rows,
        "itemsize": 4, "nshards": 1, "world_size": 1, "quantized": False,
        "spec_ramp": False}
    from ..telemetry import _config as tele_config
    if not tele_config.enabled():
        # no tallies to cross-check against the program (the jaxpr-side
        # rules still run at full strength)
        ctx["crosscheck_tally"] = False
    ctx.update(kw)
    return ctx


def _unit_from_traces(name: str, build: Callable[[int], Tuple[Any, tuple]],
                      ctx: Dict[str, Any]) -> TraceUnit:
    """Trace a config twice (fresh same-shape args) for the retrace
    probe; rules run on the first trace's jaxpr + tally."""
    fn0, args0 = build(0)
    jaxpr0, tally = _trace_with_tally(fn0, args0)
    h0 = ir.stable_hash(jaxpr0)
    fn1, args1 = build(1)
    jaxpr1, _ = _trace_with_tally(fn1, args1)
    h1 = ir.stable_hash(jaxpr1)
    return TraceUnit(name=name, jaxpr=jaxpr0, ctx=ctx,
                     collectives=tally,
                     hashes=[("iteration", h0), ("iteration", h1)])


def _serial_builder(geom: Geometry, quantized: bool):
    from ..ops.histogram_pallas import pad_rows

    def build(i: int):
        grow = _mk_wave_grow(None, geom, quantized=quantized, spec=False)
        return _serial_entry(grow), _mk_train_args(
            i, pad_rows(geom.rows), geom, quantized)

    return build


def _dp_builder(k: int, geom: Geometry, spec: bool):
    from ..parallel.data_parallel import WaveDPStrategy
    mesh, _abstract = _trace_mesh(k)
    ax = mesh.axis_names[0]

    def build(i: int):
        grow = _mk_wave_grow(
            WaveDPStrategy(ax, nshards=k, hist_scatter=True), geom,
            quantized=True, spec=spec)
        return _dp_entry(grow, mesh, ax), _mk_train_args(
            i, k * 4096, geom, True)

    return build


def _voting_builder(k: int, geom: Geometry, top_k: int):
    """The voting-parallel wave grower (PV-Tree comms on the wave
    grower): local top-k vote, one O(W*k) id allgather, psum of the
    selected-2k histogram slices only — the config whose DCN contracts
    the W=64 abstract trace enforces."""
    from ..parallel.voting_parallel import WaveVotingStrategy
    mesh, _abstract = _trace_mesh(k)
    ax = mesh.axis_names[0]

    def build(i: int):
        grow = _mk_wave_grow(
            WaveVotingStrategy(ax, nshards=k, top_k=top_k), geom,
            quantized=True, spec=False)
        return _dp_entry(grow, mesh, ax), _mk_train_args(
            i, k * 4096, geom, True)

    return build


def _mk_ingest_chunk(geom: Geometry):
    """(fn, args) for the chunked-ingest per-chunk program: the fused
    row-update + histogram-accumulate step (ingest/grower.py) at one
    chunk of ``geom.rows`` rows.  This is the program whose footprint
    the ``ingest/chunk_pipeline`` MemoryBudget bounds — shapes are
    functions of (chunk_rows, features, bins, wave) only, which is the
    rows-independence the budget's no-rows-term contract states."""
    import jax.numpy as jnp
    import numpy as np
    from ..ingest.grower import ChunkedWaveGrower
    from ..ops.split import SplitParams

    def build(i: int):
        sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                         any_cat=False)
        gr = ChunkedWaveGrower(
            num_leaves=geom.leaves, num_features=geom.features,
            max_bins=geom.bins, max_depth=0, split_params=sp,
            num_bins=np.full(geom.features, geom.bins, np.int32),
            has_nan=np.zeros(geom.features, bool), hist_impl="segment",
            quantized=True, wave_size=geom.wave)
        W, F, B = gr.W, gr.F, gr.B
        c = geom.rows                       # one chunk's rows
        rng = np.random.RandomState(i)
        bins = jnp.asarray(rng.randint(0, B - 1, (c, F)).astype(np.uint8))
        rl = jnp.zeros((c,), jnp.uint8)
        grad = jnp.asarray(rng.randn(c).astype(np.float32))
        hess = jnp.full((c,), 0.25, jnp.float32)
        mask = jnp.ones((c,), jnp.float32)
        acc = jnp.zeros((W, F, B, 3), jnp.int32)
        zi = jnp.zeros((W,), jnp.int32)
        head = {"vals": jnp.ones((W,), jnp.float32),
                "sel_leaves": zi, "sel": jnp.ones((W,), jnp.bool_),
                "feat": zi, "thr": zi + 1, "dleft": jnp.zeros((W,),
                                                             jnp.bool_),
                "lsum": jnp.zeros((W, 3), jnp.float32),
                "rsum": jnp.ones((W, 3), jnp.float32),
                "member": jnp.zeros((W, B), jnp.bool_),
                "psum": jnp.ones((W, 3), jnp.float32),
                "new_ids": zi + 1, "node_ids": zi,
                "left_smaller": jnp.ones((W,), jnp.bool_),
                "fnan": jnp.zeros((W,), jnp.bool_),
                "f_nan_bin": zi - 1,
                "total_new": jnp.asarray(1, jnp.int32)}
        scales = (jnp.float32(0.1), jnp.float32(0.1))
        fn = lambda *a: gr._chunk_step(*a)
        return fn, (acc, bins, rl, grad, hess, mask, head, scales)

    return build


def _multitrain_builder(geom: Geometry, models: int = 3, classes: int = 1):
    def build(i: int):
        import jax
        import jax.numpy as jnp
        from ..ops.histogram_pallas import pad_rows
        grow = _mk_wave_grow(None, geom, quantized=False, spec=False)
        entry = _serial_entry(grow)
        # the model axis: per-lane grad/hess/mask over shared bins (the
        # multitrain/batched.py vm_grow shape).  Multiclass batches put
        # L = models * classes lanes on the SAME axis (batched.py's
        # (M, K) lane grid), so the multitrain_mc geometry is the same
        # program at a wider lane count — the (M, K)-scaled
        # MemoryBudget is what lint-mem checks.
        lanes = models * classes
        vm = jax.vmap(entry,
                      in_axes=(None, 0, 0, 0) + (None,) * 6)
        args = _mk_train_args(i, pad_rows(geom.rows), geom)
        stack = lambda a: jnp.stack([a * (0.5 ** m) for m in range(lanes)])
        vm_args = (args[0], stack(args[1]), stack(args[2]),
                   jnp.stack([args[3]] * lanes)) + args[4:]
        return vm, vm_args

    return build


def _mk_serve_ensemble(geom: Geometry):
    """A tiny hand-built 2-leaf/3-tree dense ensemble — the serving
    shape class, no training run needed."""
    import numpy as np
    from ..models.tree import Tree, TreeBatch, ensemble_serve_fields
    trees = []
    for t in range(3):
        trees.append(Tree(
            num_leaves=2,
            split_feature=np.array([t % geom.features], np.int32),
            threshold_bin=np.array([1], np.int32),
            nan_bin=np.array([-1], np.int32),
            threshold=np.array([0.5 + t], np.float64),
            decision_type=np.array([0], np.uint8),
            left_child=np.array([-1], np.int32),
            right_child=np.array([-2], np.int32),
            split_gain=np.array([1.0], np.float32),
            internal_value=np.array([0.0], np.float64),
            internal_weight=np.array([1.0], np.float64),
            internal_count=np.array([2], np.int64),
            leaf_value=np.array([0.1 * (t + 1), -0.1], np.float64),
            leaf_weight=np.array([1.0, 1.0], np.float64),
            leaf_count=np.array([1, 1], np.int64)))
    kind, fields, lin = ensemble_serve_fields(TreeBatch(trees))
    return ((fields, lin),), (kind,)


def _mk_serve_dense_ensemble(geom: Geometry):
    """A tiny hand-built mixed ensemble for the dense serving compiler:
    two numeric trees (one with a missing-nan/default-left node) plus a
    categorical tree whose bitset spans TWO uint32 words — the shape
    class of the fused dense program, no training run needed."""
    import numpy as np
    from ..models.tree import Tree

    def _tree(nl, sf, thr, dt, lc, rc, leaves, **kw):
        n = nl - 1
        return Tree(
            num_leaves=nl,
            split_feature=np.asarray(sf, np.int32),
            threshold_bin=np.zeros(n, np.int32),
            nan_bin=np.full(n, -1, np.int32),
            threshold=np.asarray(thr, np.float64),
            decision_type=np.asarray(dt, np.uint8),
            left_child=np.asarray(lc, np.int32),
            right_child=np.asarray(rc, np.int32),
            split_gain=np.ones(n, np.float32),
            internal_value=np.zeros(n, np.float64),
            internal_weight=np.ones(n, np.float64),
            internal_count=np.full(n, 2, np.int64),
            leaf_value=np.asarray(leaves, np.float64),
            leaf_weight=np.ones(nl, np.float64),
            leaf_count=np.ones(nl, np.int64), **kw)

    trees = [
        # numeric, 3 leaves, node1 missing-nan + default-left (dt 8|2)
        _tree(3, [0, 1], [0.5, -0.2], [0, 10], [1, -2], [-1, -3],
              [0.1, -0.2, 0.3]),
        # categorical on feature 2: rank-0 bitset over 2 words (cats
        # 1, 3 and 32 in the LEFT set)
        _tree(2, [2], [0.0], [1], [-1], [-2], [0.4, -0.4],
              cat_boundaries=np.asarray([0, 2], np.int32),
              cat_threshold=np.asarray([0b1010, 0b1], np.uint32)),
        _tree(2, [1], [1.5], [0], [-1], [-2], [-0.1, 0.2]),
        _tree(2, [0], [-0.5], [0], [-1], [-2], [0.05, -0.05]),
    ]
    return trees


def _build_serve_dense_unit(geom: Geometry, ctx: Dict[str, Any],
                            nshards: int) -> TraceUnit:
    """The fused dense serving compiler's lint unit: retrace-stability
    probes over the whole bucket ladder (unsharded) plus the
    tree-sharded program at the top bucket as the MAIN jaxpr, so the
    one-psum collective contract and the per-shard memory sweep are
    machine-checked."""
    import numpy as np
    from ..models.dense_predict import (dense_predict_raw, lower_ensemble,
                                        make_sharded_predict)
    from ..models.tree import SHAPE_BUCKETS
    # importing the compiler registers the serve/dense_predict
    # collective contract + memory budget
    from ..serve import compiler as _compiler  # noqa: F401
    trees = _mk_serve_dense_ensemble(geom)
    arrays, meta = lower_ensemble(trees, 1, geom.features)
    hashes: List[Tuple[str, str]] = []
    for bucket in SHAPE_BUCKETS:
        for rep in range(2):
            X = np.zeros((bucket, geom.features), np.float32) + rep
            jx = ir.trace(
                lambda Xa, A: dense_predict_raw(Xa, A, meta), X, arrays)
            hashes.append((f"bucket{bucket}", ir.stable_hash(jx)))
    k = max(2, min(nshards, 4))
    mesh, _abstract = _trace_mesh(k, "trees")
    sh_arrays, sh_meta = lower_ensemble(trees, 1, geom.features, shard=k)
    fn = make_sharded_predict(sh_arrays, sh_meta, mesh)
    Xtop = np.zeros((max(SHAPE_BUCKETS), geom.features), np.float32)
    jaxpr0, tally = _trace_with_tally(lambda Xa, A: fn(Xa, A),
                                      (Xtop, sh_arrays))
    jx1, _ = _trace_with_tally(lambda Xa, A: fn(Xa, A),
                               (Xtop + 1.0, sh_arrays))
    hashes.append(("sharded_top", ir.stable_hash(jaxpr0)))
    hashes.append(("sharded_top", ir.stable_hash(jx1)))
    ctx = dict(ctx)
    # one program per ladder rung plus the sharded top-bucket program
    ctx["max_distinct_programs"] = len(SHAPE_BUCKETS) + 1
    ctx["bucket"] = max(SHAPE_BUCKETS)
    ctx["trees"] = sh_arrays.path_dir.shape[0]
    ctx["leaves"] = sh_arrays.path_dir.shape[2]
    ctx["num_class"] = 1
    ctx["cat_cols"] = (0 if sh_arrays.cat_table is None
                      else sh_arrays.cat_table.shape[0])
    ctx["cat_nodes"] = (0 if sh_arrays.cat_table is None
                       else sh_arrays.cat_table.shape[1])
    ctx["nshards"] = k
    ctx["world_size"] = k
    ctx["mesh_axes"] = ("trees",)
    return TraceUnit(name="serve_dense", jaxpr=jaxpr0, ctx=ctx,
                     collectives=tally, hashes=hashes)


def _build_serve_zoo_unit(geom: Geometry, ctx: Dict[str, Any],
                          nshards: int) -> TraceUnit:
    """The zoo's stacked cross-model program: M same-signature lanes of
    the dense serving ensemble vmapped into one fused launch.  Retrace
    probes cover the whole bucket ladder (the stacked jit signature is
    fixed per (stack, bucket) — idle lanes ride zero-filled, so WHICH
    tenants are active can never force a trace); the MAIN jaxpr is the
    tree-sharded stacked top-bucket program, whose one-psum-per-STACK
    collective contract and M-scaled memory budget the rules check."""
    import numpy as np
    from ..models.dense_predict import (lower_ensemble,
                                        make_stacked_sharded_predict,
                                        stack_dense_arrays,
                                        stacked_predict_raw)
    from ..models.tree import SHAPE_BUCKETS
    # importing the zoo registers the serve/zoo_stack memory budget +
    # one-psum collective contract
    from ..serve import zoo as _zoo  # noqa: F401
    trees = _mk_serve_dense_ensemble(geom)
    m = 3
    arrays, meta = lower_ensemble(trees, 1, geom.features)
    stacked = stack_dense_arrays([arrays] * m)
    hashes: List[Tuple[str, str]] = []
    for bucket in SHAPE_BUCKETS:
        for rep in range(2):
            Xs = np.zeros((m, bucket, geom.features), np.float32) + rep
            jx = ir.trace(
                lambda Xa, S: stacked_predict_raw(Xa, S, meta),
                Xs, stacked)
            hashes.append((f"bucket{bucket}", ir.stable_hash(jx)))
    k = max(2, min(nshards, 4))
    mesh, _abstract = _trace_mesh(k, "trees")
    sh_arrays, sh_meta = lower_ensemble(trees, 1, geom.features, shard=k)
    sh_stacked = stack_dense_arrays([sh_arrays] * m)
    fn = make_stacked_sharded_predict(sh_stacked, sh_meta, mesh)
    Xtop = np.zeros((m, max(SHAPE_BUCKETS), geom.features), np.float32)
    jaxpr0, tally = _trace_with_tally(lambda Xa, S: fn(Xa, S),
                                      (Xtop, sh_stacked))
    jx1, _ = _trace_with_tally(lambda Xa, S: fn(Xa, S),
                               (Xtop + 1.0, sh_stacked))
    hashes.append(("sharded_top", ir.stable_hash(jaxpr0)))
    hashes.append(("sharded_top", ir.stable_hash(jx1)))
    ctx = dict(ctx)
    # one stacked program per ladder rung plus the sharded top bucket
    ctx["max_distinct_programs"] = len(SHAPE_BUCKETS) + 1
    ctx["models"] = m
    ctx["bucket"] = max(SHAPE_BUCKETS)
    ctx["trees"] = sh_arrays.path_dir.shape[0]
    ctx["leaves"] = sh_arrays.path_dir.shape[2]
    ctx["num_class"] = 1
    ctx["cat_cols"] = (0 if sh_arrays.cat_table is None
                       else sh_arrays.cat_table.shape[0])
    ctx["cat_nodes"] = (0 if sh_arrays.cat_table is None
                        else sh_arrays.cat_table.shape[1])
    ctx["nshards"] = k
    ctx["world_size"] = k
    ctx["mesh_axes"] = ("trees",)
    return TraceUnit(name="serve_zoo", jaxpr=jaxpr0, ctx=ctx,
                     collectives=tally, hashes=hashes)


def _mk_serve_explain(geom: Geometry):
    """(arrays, dmeta, exp, emeta) for the dense TreeSHAP program over
    the mixed serving ensemble — importing the explain compiler
    registers the serve/dense_explain memory budget the lint-mem pass
    bounds this config with."""
    from ..explain import compiler as _explain_compiler  # noqa: F401
    from ..explain.dense_shap import lower_explain
    from ..models.dense_predict import lower_ensemble
    trees = _mk_serve_dense_ensemble(geom)
    arrays, dmeta = lower_ensemble(trees, 1, geom.features)
    exp, emeta = lower_explain(trees, 1, geom.features + 1)
    return arrays, dmeta, exp, emeta


def _build_serve_explain_unit(geom: Geometry,
                              ctx: Dict[str, Any]) -> TraceUnit:
    """The explain lane's lint unit: the dense TreeSHAP program traced
    across the whole bucket ladder (retrace-stability probes per rung),
    with the top-bucket program as the MAIN jaxpr so the no-row-loop
    guarantee and the declared memory curve are machine-checked."""
    import numpy as np
    from ..explain.dense_shap import dense_explain
    from ..models.tree import SHAPE_BUCKETS
    arrays, dmeta, exp, emeta = _mk_serve_explain(geom)
    hashes: List[Tuple[str, str]] = []
    jaxpr0 = None
    tally: Dict[str, Dict[str, Any]] = {}
    for bucket in SHAPE_BUCKETS:
        for rep in range(2):
            X = np.zeros((bucket, geom.features), np.float32) + rep
            fn = lambda Xa, A, E: dense_explain(Xa, A, dmeta, E, emeta)
            jx, t = _trace_with_tally(fn, (X, arrays, exp))
            hashes.append((f"bucket{bucket}", ir.stable_hash(jx)))
            if bucket == max(SHAPE_BUCKETS):
                jaxpr0, tally = jx, t
    ctx = dict(ctx)
    # one explain program per ladder rung and not one more
    ctx["max_distinct_programs"] = len(SHAPE_BUCKETS)
    ctx["bucket"] = max(SHAPE_BUCKETS)
    ctx["trees"] = emeta.num_trees
    ctx["leaves"] = int(exp.leaf_val.shape[2])
    ctx["depth"] = emeta.depth
    ctx["num_class"] = emeta.num_class
    ctx["cols"] = emeta.num_cols
    return TraceUnit(name="serve_explain", jaxpr=jaxpr0, ctx=ctx,
                     collectives=tally, hashes=hashes)


def _build_serve_unit(geom: Geometry, ctx: Dict[str, Any]) -> TraceUnit:
    import numpy as np
    from ..models.tree import SHAPE_BUCKETS, predict_raw_ensemble
    per_class, kinds = _mk_serve_ensemble(geom)
    hashes: List[Tuple[str, str]] = []
    jaxpr0 = None
    tally: Dict[str, Dict[str, Any]] = {}
    for bucket in SHAPE_BUCKETS:
        for rep in range(2):
            X = np.zeros((bucket, geom.features), np.float32) + rep
            fn = lambda Xa, pc: predict_raw_ensemble(Xa, pc, kinds)
            jx, t = _trace_with_tally(fn, (X, per_class))
            hashes.append((f"bucket{bucket}", ir.stable_hash(jx)))
            if bucket == max(SHAPE_BUCKETS):
                jaxpr0, tally = jx, t
    ctx = dict(ctx)
    # one compiled program per ladder rung and not one more
    ctx["max_distinct_programs"] = len(SHAPE_BUCKETS)
    ctx["bucket"] = max(SHAPE_BUCKETS)
    ctx["trees"] = 3
    return TraceUnit(name="serve", jaxpr=jaxpr0, ctx=ctx,
                     collectives=tally, hashes=hashes)


def build_unit(name: str, nshards: int = 8,
               geometry: Optional[Geometry] = None) -> TraceUnit:
    """Trace one matrix config into a rule-ready :class:`TraceUnit`."""
    geom = geometry or TRACE_GEOMETRY
    if name == "serial":
        return _unit_from_traces("serial", _serial_builder(geom, False),
                                 _base_ctx(geom))
    if name == "wave":
        return _unit_from_traces("wave", _serial_builder(geom, True),
                                 _base_ctx(geom, quantized=True))
    if name == "dp_scatter":
        return _unit_from_traces(
            "dp_scatter", _dp_builder(nshards, geom, spec=False),
            _base_ctx(geom, nshards=nshards, world_size=nshards,
                      quantized=True, rows=nshards * 4096,
                      mesh_axes=("workers",)))
    if name == "spec_ramp":
        return _unit_from_traces(
            "spec_ramp", _dp_builder(nshards, geom, spec=True),
            _base_ctx(geom, nshards=nshards, world_size=nshards,
                      quantized=True, spec_ramp=True,
                      rows=nshards * 4096, mesh_axes=("workers",)))
    if name == "voting":
        # top_k=2 keeps 2k < F at the trace geometry so the voted psum
        # genuinely moves fewer bytes than the full (F,B,3) merge —
        # the ratio the DCN contracts bound
        return _unit_from_traces(
            "voting", _voting_builder(nshards, geom, top_k=2),
            _base_ctx(geom, nshards=nshards, world_size=nshards,
                      quantized=True, top_k=2, rows=nshards * 4096,
                      hosts=max(1, nshards // 8),
                      mesh_axes=("workers",)))
    if name == "multitrain":
        return _unit_from_traces("multitrain", _multitrain_builder(geom),
                                 _base_ctx(geom, models=3))
    if name == "multitrain_mc":
        return _unit_from_traces(
            "multitrain_mc", _multitrain_builder(geom, models=2, classes=3),
            _base_ctx(geom, models=2, classes=3))
    if name == "serve":
        return _build_serve_unit(geom, _base_ctx(geom))
    if name == "serve_dense":
        return _build_serve_dense_unit(geom, _base_ctx(geom), nshards)
    if name == "serve_zoo":
        return _build_serve_zoo_unit(geom, _base_ctx(geom), nshards)
    if name == "serve_explain":
        return _build_serve_explain_unit(geom, _base_ctx(geom))
    if name == "ingest":
        return _unit_from_traces(
            "ingest", _mk_ingest_chunk(geom),
            _base_ctx(geom, quantized=True, chunk_rows=geom.rows))
    raise ValueError(f"unknown lint config '{name}' "
                     f"(matrix: {', '.join(MATRIX_CONFIGS)})")


def build_callable(name: str, nshards: int = 8,
                   geometry: Optional[Geometry] = None
                   ) -> Optional[Tuple[Any, tuple]]:
    """The (fn, args) a config traces — for callers that need to
    LOWER/COMPILE it (the lint-mem XLA cross-check).  None for the mesh
    configs: XLA's ``memory_analysis()`` semantics on SPMD executables
    depend on the partition count (per-partition vs aggregate differs
    across backends/partitionings), so the compiler cross-check is
    restricted to unpartitioned programs — the mesh configs are bounded
    by their declared curves and the per-shard body sweep instead."""
    geom = geometry or TRACE_GEOMETRY
    if name in ("serial", "wave"):
        return _serial_builder(geom, name == "wave")(0)
    if name == "multitrain":
        return _multitrain_builder(geom)(0)
    if name == "multitrain_mc":
        return _multitrain_builder(geom, models=2, classes=3)(0)
    if name == "ingest":
        return _mk_ingest_chunk(geom)(0)
    if name == "serve":
        import numpy as np
        from ..models.tree import SHAPE_BUCKETS, predict_raw_ensemble
        per_class, kinds = _mk_serve_ensemble(geom)
        X = np.zeros((max(SHAPE_BUCKETS), geom.features), np.float32)
        return (lambda Xa, pc: predict_raw_ensemble(Xa, pc, kinds),
                (X, per_class))
    if name == "serve_dense":
        import numpy as np
        from ..models.dense_predict import dense_predict_raw, lower_ensemble
        from ..models.tree import SHAPE_BUCKETS
        trees = _mk_serve_dense_ensemble(geom)
        arrays, meta = lower_ensemble(trees, 1, geom.features)
        X = np.zeros((max(SHAPE_BUCKETS), geom.features), np.float32)
        return (lambda Xa, A: dense_predict_raw(Xa, A, meta), (X, arrays))
    if name == "serve_zoo":
        import numpy as np
        from ..models.dense_predict import (lower_ensemble,
                                            stack_dense_arrays,
                                            stacked_predict_raw)
        from ..models.tree import SHAPE_BUCKETS
        trees = _mk_serve_dense_ensemble(geom)
        arrays, meta = lower_ensemble(trees, 1, geom.features)
        stacked = stack_dense_arrays([arrays] * 3)
        Xs = np.zeros((3, max(SHAPE_BUCKETS), geom.features), np.float32)
        return (lambda Xa, S: stacked_predict_raw(Xa, S, meta),
                (Xs, stacked))
    if name == "serve_explain":
        import numpy as np
        from ..explain.dense_shap import dense_explain
        from ..models.tree import SHAPE_BUCKETS
        arrays, dmeta, exp, emeta = _mk_serve_explain(geom)
        X = np.zeros((max(SHAPE_BUCKETS), geom.features), np.float32)
        return (lambda Xa, A, E: dense_explain(Xa, A, dmeta, E, emeta),
                (X, arrays, exp))
    return None


def environment_info(nshards: int = 0) -> Dict[str, Any]:
    """The jax/device environment a lint report was produced under —
    reports from an 8-virtual-device CPU env must be distinguishable
    from real-chip runs."""
    import os

    import jax
    info: Dict[str, Any] = {"jax_version": jax.__version__}
    try:
        import jaxlib
        info["jaxlib_version"] = jaxlib.__version__
    except Exception:
        pass
    try:
        devs = jax.devices()
        info["backend"] = devs[0].platform
        info["device_count"] = len(devs)
        info["device_kind"] = getattr(devs[0], "device_kind", "")
        info["process_count"] = jax.process_count()
    except Exception as exc:
        info["backend"] = f"unavailable ({exc})"
        info["device_count"] = 0
    flags = os.environ.get("XLA_FLAGS", "")
    forced = "xla_force_host_platform_device_count" in flags
    try:
        forced = forced or int(getattr(jax.config, "jax_num_cpu_devices",
                                       0) or 0) > 1
    except Exception:
        pass
    info["virtual_devices"] = bool(info.get("backend") == "cpu" and forced)
    if nshards:
        info["requested_devices"] = nshards
        info["abstract_mesh"] = nshards > info.get("device_count", 0)
    return info


def _donation_unit() -> TraceUnit:
    """The declared-donation entries (score buffers), checked once."""
    # importing gbdt registers its donation contracts
    from ..models import gbdt  # noqa: F401
    return TraceUnit(name="score_update",
                     ctx={"donation_contracts":
                          tuple(all_donation_contracts().values()),
                          "crosscheck_tally": False})


def run_lint(configs: Optional[Sequence[str]] = None,
             nshards: int = 8) -> Dict[str, Any]:
    """Trace the matrix, run every rule, return the JSON-ready report."""
    configs = tuple(configs) if configs else MATRIX_CONFIGS
    units: List[TraceUnit] = []
    report_cfgs: Dict[str, Any] = {}
    for name in configs:
        t0 = time.perf_counter()
        unit = build_unit(name, nshards=nshards)
        units.append(unit)
        coll = {site: dict(rec) for site, rec in
                sorted(unit.collectives.items())}
        report_cfgs[name] = {
            "jaxpr_hash": ir.stable_hash(unit.jaxpr)
            if unit.jaxpr is not None else None,
            "eqns": sum(1 for _ in ir.iter_eqns(unit.jaxpr))
            if unit.jaxpr is not None else 0,
            "collectives": coll,
            "trace_seconds": round(time.perf_counter() - t0, 3),
        }
    units.append(_donation_unit())
    violations = run_rules(units, rules=ALL_RULES)
    # SLO-coverage check (slo_cover.py): declared objectives must key to
    # registered metric series — the note_collective-contract coverage
    # pattern applied to the SLO layer
    from .slo_cover import check_slo_coverage, slo_coverage_report
    slo_violations = check_slo_coverage()
    slo_section = slo_coverage_report(violations=slo_violations)
    violations.extend(slo_violations)
    by_cfg: Dict[str, List[Violation]] = {}
    for v in violations:
        by_cfg.setdefault(v.config, []).append(v)
    for name, entry in report_cfgs.items():
        entry["ok"] = name not in by_cfg
        entry["violations"] = [v.to_json() for v in by_cfg.get(name, [])]
    report_cfgs["score_update"] = {
        "ok": "score_update" not in by_cfg,
        "violations": [v.to_json() for v in by_cfg.get("score_update", [])],
    }
    report_cfgs["slo_coverage"] = slo_section
    from .contracts import all_contracts
    return {
        "schema": "trace-lint-v1",
        "ok": not violations,
        "num_violations": len(violations),
        "environment": environment_info(nshards),
        "rules": [r.name for r in ALL_RULES],
        "contracts": {site: {"ops": list(c.ops),
                             "declared_in": c.declared_in}
                      for site, c in sorted(all_contracts().items())},
        "configs": report_cfgs,
    }


def parse_kv_args(argv: Sequence[str]) -> Dict[str, str]:
    """The lint verbs' shared ``key=value`` CLI grammar: optional
    leading ``--``, ``-`` normalized to ``_`` in keys (``hbm-gb=`` and
    ``hbm_gb=`` both work), non-``=`` tokens ignored.  One parser for
    ``lint-trace`` and ``lint-mem`` so flag spelling cannot drift
    between the verbs."""
    out: Dict[str, str] = {}
    for arg in argv:
        if arg.startswith("--"):
            arg = arg[2:]
        if "=" not in arg:
            continue
        key, value = arg.split("=", 1)
        out[key.strip().replace("-", "_")] = value.strip()
    return out


def main(argv: Sequence[str]) -> int:
    """``python -m lightgbm_tpu lint-trace [configs=a,b] [out=report.json]
    [devices=8]`` — trace the matrix, print the JSON contract report,
    exit nonzero on any violation."""
    import json

    configs: Optional[List[str]] = None
    out_path = ""
    nshards = 8
    for key, value in parse_kv_args(argv).items():
        if key in ("configs", "config"):
            configs = [c.strip() for c in value.split(",") if c.strip()]
        elif key in ("out", "json", "json_out"):
            out_path = value
        elif key in ("devices", "nshards"):
            nshards = int(value)
    t0 = time.perf_counter()
    _ensure_devices(nshards)
    report = run_lint(configs, nshards=nshards)
    report["elapsed_seconds"] = round(time.perf_counter() - t0, 3)
    text = json.dumps(report, indent=2, sort_keys=False)
    print(text)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(text + "\n")
    if not report["ok"]:
        from ..utils.log import log_warning
        log_warning(f"lint-trace: {report['num_violations']} contract "
                    f"violation(s)")
    return 0 if report["ok"] else 1
