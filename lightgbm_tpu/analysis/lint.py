"""``lint-trace``: trace the config matrix, enforce program contracts.

Drives :mod:`.ir` + :mod:`.rules` over one traced (never executed)
program per supported training/serving shape:

* ``serial``     — the sequential wave grower (no mesh, no collectives);
* ``wave``       — the wave grower, Pallas kernels (interpret off-TPU);
* ``dp_scatter`` — 8-shard DP wave, feature-sliced reduce-scatter merge;
* ``spec_ramp``  — DP wave + speculative ramp (the ceil(log2 W) budget);
* ``multitrain`` — the vmapped model axis over the wave grower;
* ``serve``      — the ensemble predictor across the SHAPE_BUCKETS
  ladder (one program per bucket, hash-stable on re-trace).

Every config is traced TWICE with freshly built same-shape inputs so
the retrace rule sees real hash probes, and the telemetry collective
tally is snapshotted around each trace so the collective-budget rule
can cross-check contracts against both the tally and the jaxpr.

The report is JSON (``trace-lint-v1``) and the CLI exits 1 when any
violation is found (0 when clean) — CI runs this as a blocking step.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import ir
from .contracts import all_donation_contracts
from .rules import DEFAULT_RULES, TraceUnit, Violation, run_rules

__all__ = ["MATRIX_CONFIGS", "build_unit", "run_lint", "main"]

MATRIX_CONFIGS = ("serial", "wave", "dp_scatter", "spec_ramp",
                  "multitrain", "serve")

# shared small-but-representative shapes (the test-suite geometry: the
# endgame engages at 13 leaves / wave 4, scatter pads 6 features to 8
# blocks at k=8)
_F, _B, _LEAVES, _WAVE = 6, 64, 13, 4


def _backend_initialized() -> bool:
    """True once a jax client exists (then the device count is fixed).
    Must NOT itself initialize the backend — jax.devices() would."""
    try:
        from jax._src import xla_bridge
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def _ensure_devices(k: int) -> int:
    """Best-effort k virtual CPU devices.  Device count can only be set
    before the first jax client exists; afterwards fall back to
    whatever is visible (a short mesh still traces every contract, just
    at a smaller k)."""
    import os

    import jax
    if not _backend_initialized():
        try:
            jax.config.update("jax_num_cpu_devices", k)
        except (AttributeError, RuntimeError):
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={k}"
                ).strip()
    try:
        return min(k, len(jax.devices()))
    except Exception:
        return 1


def _mk_train_args(seed: int, n: int, quantized: bool = False):
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, _B - 1, (_F, n)).astype(np.uint8)
    logit = (bins[0].astype(np.float32) / _B - 0.5) * 3
    y = (logit + rng.randn(n) * 0.7 > 0).astype(np.float32)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    mask = np.ones(n, np.float32)
    meta = (jnp.full((_F,), _B, jnp.int32), jnp.zeros((_F,), bool),
            jnp.zeros((_F,), bool), jnp.zeros((_F,), jnp.int32),
            jnp.zeros((_F,), jnp.float32), jnp.ones((_F,), bool))
    return (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask)) + meta


def _mk_wave_grow(strategy, *, quantized: bool, spec: bool):
    from ..learner.wave import make_wave_grow_fn
    from ..ops.split import SplitParams
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    return make_wave_grow_fn(
        num_leaves=_LEAVES, num_features=_F, max_bins=_B, max_depth=0,
        split_params=sp, hist_impl="pallas", any_cat=False, interpret=None,
        jit=False, wave_size=_WAVE, quantized=quantized, stochastic=False,
        spec_ramp=spec, spec_tol=0.02, strategy=strategy)


def _serial_entry(grow):
    def entry(bins, grad, hess, mask, nb, ic, hn, mono, cp, fm):
        return grow(bins, grad, hess, mask, nb, ic, hn, mono, cp, (), fm)
    return entry


def _dp_entry(grow, mesh, ax):
    import jax
    from jax.sharding import PartitionSpec as P
    from ..parallel.data_parallel import DataParallelTreeLearner
    from ..parallel.mesh import shard_map_compat
    return jax.jit(shard_map_compat(
        _serial_entry(grow), mesh=mesh,
        in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P(), P(), P(),
                  P(), P()),
        out_specs=DataParallelTreeLearner._tree_specs(ax)))


def _trace_with_tally(fn, args) -> Tuple[Any, Dict[str, Dict[str, Any]]]:
    """make_jaxpr plus the telemetry collective delta the trace fired."""
    from ..telemetry.train_record import collectives_snapshot
    before = collectives_snapshot()
    jaxpr = ir.trace(lambda *a: fn(*a), *args)
    after = collectives_snapshot()
    delta: Dict[str, Dict[str, Any]] = {}
    for site, rec in after.items():
        base = before.get(site, {"count": 0, "bytes": 0})
        dc = rec["count"] - base["count"]
        if dc > 0:
            delta[site] = {"op": rec["op"], "count": dc,
                           "bytes": rec["bytes"] - base["bytes"]}
    return jaxpr, delta


def _base_ctx(**kw) -> Dict[str, Any]:
    ctx: Dict[str, Any] = {
        "wave_size": _WAVE, "features": _F, "bins": _B, "leaves": _LEAVES,
        "itemsize": 4, "nshards": 1, "quantized": False,
        "spec_ramp": False}
    from ..telemetry import _config as tele_config
    if not tele_config.enabled():
        # no tallies to cross-check against the program (the jaxpr-side
        # rules still run at full strength)
        ctx["crosscheck_tally"] = False
    ctx.update(kw)
    return ctx


def _unit_from_traces(name: str, build: Callable[[int], Tuple[Any, tuple]],
                      ctx: Dict[str, Any]) -> TraceUnit:
    """Trace a config twice (fresh same-shape args) for the retrace
    probe; rules run on the first trace's jaxpr + tally."""
    fn0, args0 = build(0)
    jaxpr0, tally = _trace_with_tally(fn0, args0)
    h0 = ir.stable_hash(jaxpr0)
    fn1, args1 = build(1)
    jaxpr1, _ = _trace_with_tally(fn1, args1)
    h1 = ir.stable_hash(jaxpr1)
    return TraceUnit(name=name, jaxpr=jaxpr0, ctx=ctx,
                     collectives=tally,
                     hashes=[("iteration", h0), ("iteration", h1)])


def _build_serial(i: int):
    from ..ops.histogram_pallas import pad_rows
    grow = _mk_wave_grow(None, quantized=False, spec=False)
    return _serial_entry(grow), _mk_train_args(i, pad_rows(4000))


def _build_wave(i: int):
    from ..ops.histogram_pallas import pad_rows
    grow = _mk_wave_grow(None, quantized=True, spec=False)
    return _serial_entry(grow), _mk_train_args(i, pad_rows(4000), True)


def _dp_builder(k: int, spec: bool):
    from ..parallel.data_parallel import WaveDPStrategy
    from ..parallel.mesh import get_mesh
    mesh = get_mesh(k)
    ax = mesh.axis_names[0]

    def build(i: int):
        grow = _mk_wave_grow(
            WaveDPStrategy(ax, nshards=k, hist_scatter=True),
            quantized=True, spec=spec)
        return _dp_entry(grow, mesh, ax), _mk_train_args(i, k * 4096, True)

    return build


def _build_multitrain(i: int):
    import jax
    from ..ops.histogram_pallas import pad_rows
    grow = _mk_wave_grow(None, quantized=False, spec=False)
    entry = _serial_entry(grow)
    # the model axis: per-lane grad/hess/mask over shared bins (the
    # multitrain/batched.py vm_grow shape, M=3 lanes)
    vm = jax.vmap(entry,
                  in_axes=(None, 0, 0, 0) + (None,) * 6)
    args = _mk_train_args(i, pad_rows(4000))
    import jax.numpy as jnp
    stack = lambda a: jnp.stack([a, a * 0.5, a * 0.25])
    vm_args = (args[0], stack(args[1]), stack(args[2]),
               jnp.stack([args[3]] * 3)) + args[4:]
    return vm, vm_args


def _mk_serve_ensemble():
    """A tiny hand-built 2-leaf/3-tree dense ensemble — the serving
    shape class, no training run needed."""
    import numpy as np
    from ..models.tree import Tree, TreeBatch, ensemble_serve_fields
    trees = []
    for t in range(3):
        trees.append(Tree(
            num_leaves=2,
            split_feature=np.array([t % _F], np.int32),
            threshold_bin=np.array([1], np.int32),
            nan_bin=np.array([-1], np.int32),
            threshold=np.array([0.5 + t], np.float64),
            decision_type=np.array([0], np.uint8),
            left_child=np.array([-1], np.int32),
            right_child=np.array([-2], np.int32),
            split_gain=np.array([1.0], np.float32),
            internal_value=np.array([0.0], np.float64),
            internal_weight=np.array([1.0], np.float64),
            internal_count=np.array([2], np.int64),
            leaf_value=np.array([0.1 * (t + 1), -0.1], np.float64),
            leaf_weight=np.array([1.0, 1.0], np.float64),
            leaf_count=np.array([1, 1], np.int64)))
    kind, fields, lin = ensemble_serve_fields(TreeBatch(trees))
    return ((fields, lin),), (kind,)


def _build_serve_unit(ctx: Dict[str, Any]) -> TraceUnit:
    import numpy as np
    from ..models.tree import SHAPE_BUCKETS, predict_raw_ensemble
    per_class, kinds = _mk_serve_ensemble()
    hashes: List[Tuple[str, str]] = []
    jaxpr0 = None
    tally: Dict[str, Dict[str, Any]] = {}
    for bucket in SHAPE_BUCKETS:
        for rep in range(2):
            X = np.zeros((bucket, _F), np.float32) + rep
            fn = lambda Xa, pc: predict_raw_ensemble(Xa, pc, kinds)
            jx, t = _trace_with_tally(fn, (X, per_class))
            hashes.append((f"bucket{bucket}", ir.stable_hash(jx)))
            if jaxpr0 is None:
                jaxpr0, tally = jx, t
    ctx = dict(ctx)
    # one compiled program per ladder rung and not one more
    ctx["max_distinct_programs"] = len(SHAPE_BUCKETS)
    return TraceUnit(name="serve", jaxpr=jaxpr0, ctx=ctx,
                     collectives=tally, hashes=hashes)


def build_unit(name: str, nshards: int = 8) -> TraceUnit:
    """Trace one matrix config into a rule-ready :class:`TraceUnit`."""
    if name == "serial":
        return _unit_from_traces("serial", _build_serial, _base_ctx())
    if name == "wave":
        return _unit_from_traces("wave", _build_wave,
                                 _base_ctx(quantized=True))
    if name == "dp_scatter":
        k = _ensure_devices(nshards)
        return _unit_from_traces(
            "dp_scatter", _dp_builder(k, spec=False),
            _base_ctx(nshards=k, quantized=True))
    if name == "spec_ramp":
        k = _ensure_devices(nshards)
        return _unit_from_traces(
            "spec_ramp", _dp_builder(k, spec=True),
            _base_ctx(nshards=k, quantized=True, spec_ramp=True))
    if name == "multitrain":
        return _unit_from_traces("multitrain", _build_multitrain,
                                 _base_ctx(models=3))
    if name == "serve":
        return _build_serve_unit(_base_ctx())
    raise ValueError(f"unknown lint config '{name}' "
                     f"(matrix: {', '.join(MATRIX_CONFIGS)})")


def _donation_unit() -> TraceUnit:
    """The declared-donation entries (score buffers), checked once."""
    # importing gbdt registers its donation contracts
    from ..models import gbdt  # noqa: F401
    return TraceUnit(name="score_update",
                     ctx={"donation_contracts":
                          tuple(all_donation_contracts().values()),
                          "crosscheck_tally": False})


def run_lint(configs: Optional[Sequence[str]] = None,
             nshards: int = 8) -> Dict[str, Any]:
    """Trace the matrix, run every rule, return the JSON-ready report."""
    configs = tuple(configs) if configs else MATRIX_CONFIGS
    units: List[TraceUnit] = []
    report_cfgs: Dict[str, Any] = {}
    for name in configs:
        t0 = time.perf_counter()
        unit = build_unit(name, nshards=nshards)
        units.append(unit)
        coll = {site: dict(rec) for site, rec in
                sorted(unit.collectives.items())}
        report_cfgs[name] = {
            "jaxpr_hash": ir.stable_hash(unit.jaxpr)
            if unit.jaxpr is not None else None,
            "eqns": sum(1 for _ in ir.iter_eqns(unit.jaxpr))
            if unit.jaxpr is not None else 0,
            "collectives": coll,
            "trace_seconds": round(time.perf_counter() - t0, 3),
        }
    units.append(_donation_unit())
    violations = run_rules(units)
    by_cfg: Dict[str, List[Violation]] = {}
    for v in violations:
        by_cfg.setdefault(v.config, []).append(v)
    for name, entry in report_cfgs.items():
        entry["ok"] = name not in by_cfg
        entry["violations"] = [v.to_json() for v in by_cfg.get(name, [])]
    report_cfgs["score_update"] = {
        "ok": "score_update" not in by_cfg,
        "violations": [v.to_json() for v in by_cfg.get("score_update", [])],
    }
    from .contracts import all_contracts
    return {
        "schema": "trace-lint-v1",
        "ok": not violations,
        "num_violations": len(violations),
        "rules": [r.name for r in DEFAULT_RULES],
        "contracts": {site: {"ops": list(c.ops),
                             "declared_in": c.declared_in}
                      for site, c in sorted(all_contracts().items())},
        "configs": report_cfgs,
    }


def main(argv: Sequence[str]) -> int:
    """``python -m lightgbm_tpu lint-trace [configs=a,b] [out=report.json]
    [devices=8]`` — trace the matrix, print the JSON contract report,
    exit nonzero on any violation."""
    import json

    configs: Optional[List[str]] = None
    out_path = ""
    nshards = 8
    for arg in argv:
        if arg.startswith("--"):
            arg = arg[2:]
        if "=" not in arg:
            continue
        key, value = arg.split("=", 1)
        key = key.strip()
        if key in ("configs", "config"):
            configs = [c.strip() for c in value.split(",") if c.strip()]
        elif key in ("out", "json", "json_out"):
            out_path = value.strip()
        elif key in ("devices", "nshards"):
            nshards = int(value)
    t0 = time.perf_counter()
    _ensure_devices(nshards)
    report = run_lint(configs, nshards=nshards)
    report["elapsed_seconds"] = round(time.perf_counter() - t0, 3)
    text = json.dumps(report, indent=2, sort_keys=False)
    print(text)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(text + "\n")
    if not report["ok"]:
        from ..utils.log import log_warning
        log_warning(f"lint-trace: {report['num_violations']} contract "
                    f"violation(s)")
    return 0 if report["ok"] else 1
