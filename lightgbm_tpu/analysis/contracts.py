"""Program contracts: declared budgets the trace lint enforces.

A *collective contract* is declared NEXT TO the code it constrains
(``learner/wave.py`` declares the wave merge-site budget,
``parallel/*.py`` declare their exchange/broadcast payloads) and keyed
by the same site name the code passes to
``telemetry.train_record.note_collective`` — so the contract, the
telemetry tally and the collective call site are one named thing and
cannot drift apart: the lint cross-checks (a) every tallied site has a
declared contract, (b) tallied counts/bytes stay under the declared
ceilings, and (c) the traced program's total collective op count equals
the tally (an untallied collective in the jaxpr is itself a violation).

This is the PV-Tree communication-budget analysis (arXiv:1611.01276) as
a machine-checked invariant: the per-pass collective byte budget the
papers argue with, stated once in code and validated on every PR.

Ceilings may be ints or callables of a ``ctx`` dict (wave_size,
nshards, features, bins, leaves, spec_ramp, itemsize ...) so one
declaration covers every config the lint matrix traces.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple, Union

__all__ = ["CollectiveContract", "collective_contract", "contract_for",
           "all_contracts", "resolve_limit", "DonationContract",
           "donation_contract", "all_donation_contracts", "MemoryBudget",
           "memory_budget", "memory_budget_for", "all_memory_budgets",
           "world_size", "hosts", "dcn_fraction"]

Limit = Union[int, Callable[[Dict[str, Any]], int], None]


def resolve_limit(limit: Limit, ctx: Dict[str, Any]) -> Optional[int]:
    """An int ceiling, a callable of the lint ctx, or None (unbounded)."""
    if limit is None:
        return None
    if callable(limit):
        return int(limit(ctx))
    return int(limit)


def world_size(ctx: Dict[str, Any]) -> int:
    """Mesh world size from a lint ctx.

    Every collective/memory contract scales its curve through this one
    accessor so the same declaration checks a W=4 virtual mesh, the W=8
    CI mesh and a W=64/256 trace-only pod mesh.  ``world_size`` is the
    canonical key; ``nshards`` is the historical spelling the W=8 lint
    matrix has always set — both stay honored so older ctx dicts keep
    resolving."""
    return max(1, int(ctx.get("world_size", ctx.get("nshards", 1))))


#: devices per host the pod model assumes when a ctx doesn't say
DEVICES_PER_HOST = 8


def hosts(ctx: Dict[str, Any]) -> int:
    """Host count from a lint ctx — the pod-topology half of the byte
    split.  Explicit ``hosts`` wins; otherwise the canonical model of
    one host per ``DEVICES_PER_HOST`` devices (a v5e host board), so a
    W=64 abstract trace models an 8-host pod without any ctx churn."""
    h = ctx.get("hosts")
    if h is not None:
        return max(1, int(h))
    return max(1, world_size(ctx) // DEVICES_PER_HOST)


def dcn_fraction(ctx: Dict[str, Any]) -> float:
    """Modeled cross-host share of an allreduce-family payload.

    On a host-major 1-D axis a hierarchical collective (intra-host ICI
    reduce, inter-host DCN exchange, intra-host ICI broadcast) moves
    (H-1)/H of the payload over DCN — the quantity PV-Tree optimizes and
    the one the per-host/cross-host contract split bounds."""
    h = hosts(ctx)
    return (h - 1) / h if h > 1 else 0.0


@dataclass(frozen=True)
class CollectiveContract:
    """Per-site ceiling on collective count and per-op payload bytes.

    ``site`` is the ``note_collective`` site name; ``ops`` the collective
    kinds the site may tally (a site like the wave winner exchange
    legitimately mixes pmax/pmin/psum).  ``max_count`` bounds tallied
    calls per traced program, ``max_bytes_per_op`` the mean per-op
    payload.  ``max_dcn_bytes_per_op`` additionally bounds the modeled
    CROSS-HOST slice of that payload (``dcn_fraction(ctx)`` of the mean
    per-op bytes on a host-major axis) — the pod-budget half of the
    split: a site may be cheap on ICI yet blow the DCN budget at W=64,
    and that is exactly what this ceiling catches at abstract trace
    time."""

    site: str
    ops: Tuple[str, ...]
    max_count: Limit = None
    max_bytes_per_op: Limit = None
    declared_in: str = ""
    note: str = ""
    max_dcn_bytes_per_op: Limit = None


_lock = threading.Lock()
_registry: Dict[str, CollectiveContract] = {}


def collective_contract(site: str, ops, *, max_count: Limit = None,
                        max_bytes_per_op: Limit = None,
                        max_dcn_bytes_per_op: Limit = None,
                        note: str = "") -> CollectiveContract:
    """Declare (or redeclare) the contract for one collective site.

    Call at module scope next to the ``note_collective`` site it
    constrains; ``declared_in`` records that module for diagnostics."""
    import inspect
    frame = inspect.currentframe()
    declared_in = ""
    if frame is not None and frame.f_back is not None:
        declared_in = frame.f_back.f_globals.get("__name__", "")
    if isinstance(ops, str):
        ops = (ops,)
    c = CollectiveContract(site=site, ops=tuple(ops), max_count=max_count,
                           max_bytes_per_op=max_bytes_per_op,
                           max_dcn_bytes_per_op=max_dcn_bytes_per_op,
                           declared_in=declared_in, note=note)
    with _lock:
        _registry[site] = c
    return c


def contract_for(site: str) -> Optional[CollectiveContract]:
    with _lock:
        return _registry.get(site)


def all_contracts() -> Dict[str, CollectiveContract]:
    with _lock:
        return dict(_registry)


def remove_collective_contract(site: str) -> None:
    """Unregister (tests planting temporary contracts clean up here)."""
    with _lock:
        _registry.pop(site, None)


# ---------------------------------------------------------------------------
# Donation contracts: jitted entries whose big buffers must alias
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DonationContract:
    """A jitted entry point that declares buffer donation.

    The lint verifies the declaration can actually alias: every donated
    argument's abstract value must match an output's shape+dtype, else
    XLA silently keeps both buffers live (the score-update class of bug:
    a dtype drift turns an in-place 4 MB update into an 8 MB copy).
    ``build_args`` makes small representative arguments for lowering."""

    name: str
    fn_ref: Callable[[], Any]          # lazy: returns the jitted fn
    donate_argnums: Tuple[int, ...]
    build_args: Callable[[], tuple] = field(repr=False, default=tuple)
    declared_in: str = ""


_donations: Dict[str, DonationContract] = {}


def donation_contract(name: str, fn_ref: Callable[[], Any],
                      donate_argnums, build_args) -> DonationContract:
    import inspect
    frame = inspect.currentframe()
    declared_in = ""
    if frame is not None and frame.f_back is not None:
        declared_in = frame.f_back.f_globals.get("__name__", "")
    c = DonationContract(name=name, fn_ref=fn_ref,
                         donate_argnums=tuple(donate_argnums),
                         build_args=build_args, declared_in=declared_in)
    with _lock:
        _donations[name] = c
    return c


def all_donation_contracts() -> Dict[str, DonationContract]:
    with _lock:
        return dict(_donations)


def remove_donation_contract(name: str) -> None:
    with _lock:
        _donations.pop(name, None)


# ---------------------------------------------------------------------------
# Memory budgets: static HBM/VMEM curves per traced program family
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MemoryBudget:
    """Declared peak-memory curve for one lint-matrix program family.

    ``configs`` names the lint configs the budget binds to (a budget for
    the wave grower covers both the ``serial`` and ``wave`` configs).
    ``hbm_per_device`` bounds the per-device peak-live-buffer estimate
    the memory lint computes from the jaxpr (live-range sweep, per-shard
    sizing inside shard_map bodies); ``vmem_per_kernel`` bounds the
    VMEM-resident block bytes of any single ``pallas_call`` in the
    program (the ~16 MB/core ceiling).  Both are functions of the lint
    ctx — (rows, features, bins, wave_size, leaves, world_size, models,
    itemsize) — so ``lint-mem --rows=1e8 --devices=64`` evaluates the
    same declaration at pod scale no CI host can run."""

    name: str
    configs: Tuple[str, ...]
    hbm_per_device: Limit
    vmem_per_kernel: Limit = None
    declared_in: str = ""
    note: str = ""


_mem_budgets: Dict[str, MemoryBudget] = {}


def memory_budget(name: str, configs, hbm_per_device: Limit, *,
                  vmem_per_kernel: Limit = None,
                  note: str = "") -> MemoryBudget:
    """Declare (or redeclare) the memory curve for one program family.

    Call at module scope next to the code whose footprint it bounds
    (the wave grower declares its (W,F,B,3) batch + pool curve, the DP
    strategy its 1/k sliced curve, the predictor the bucket ladder,
    multitrain the M-stacked state)."""
    import inspect
    frame = inspect.currentframe()
    declared_in = ""
    if frame is not None and frame.f_back is not None:
        declared_in = frame.f_back.f_globals.get("__name__", "")
    if isinstance(configs, str):
        configs = (configs,)
    b = MemoryBudget(name=name, configs=tuple(configs),
                     hbm_per_device=hbm_per_device,
                     vmem_per_kernel=vmem_per_kernel,
                     declared_in=declared_in, note=note)
    with _lock:
        _mem_budgets[name] = b
    return b


def memory_budget_for(config: str) -> Optional[MemoryBudget]:
    """The budget whose ``configs`` tuple claims this lint config."""
    with _lock:
        for b in _mem_budgets.values():
            if config in b.configs:
                return b
    return None


def all_memory_budgets() -> Dict[str, MemoryBudget]:
    with _lock:
        return dict(_mem_budgets)


def remove_memory_budget(name: str) -> None:
    with _lock:
        _mem_budgets.pop(name, None)
