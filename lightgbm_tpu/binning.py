"""Feature quantization: value -> integer bin codes.

TPU-native re-implementation of the reference BinMapper
(reference: include/LightGBM/bin.h:61 ``BinMapper``, src/io/bin.cpp:150
``GreedyFindBin`` / ``FindBinWithZeroAsOneBin`` / ``BinMapper::FindBin``).

Runs host-side (numpy) once at ingest; the result is a dense integer matrix
(uint8 for <=256 bins) that is ``device_put`` / mesh-sharded once and stays
on device for the whole training run.  Bin semantics follow the reference:

* zero gets its own bin (kZeroThreshold band), negatives/positives binned
  separately around it with greedy equal-frequency boundaries;
* missing handling is None / Zero / NaN (bin.h:26 ``MissingType``): with
  ``MissingType.NaN`` an extra trailing bin holds the NaNs;
* categorical features map category ids to bins by descending frequency,
  keeping categories that cover 99% of the sample (src/io/bin.cpp categorical
  branch of FindBin).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["MissingType", "BinMapper", "find_bin", "bin_matrix",
           "ColumnSummary", "summarize_column", "merge_column_summaries",
           "find_bin_from_summary"]

# reference include/LightGBM/bin.h:29 kZeroThreshold
ZERO_THRESHOLD = 1e-35
# reference include/LightGBM/bin.h:27 kSparseThreshold unused here (dense device layout)


class MissingType(enum.Enum):
    NONE = 0
    ZERO = 1
    NAN = 2


def _dbl_up(a: float) -> float:
    """Next representable double above ``a`` (common.h GetDoubleUpperBound;
    boundary values sit strictly above the midpoint so ValueToBin's
    left-search puts the midpoint's lower neighbor in the lower bin)."""
    return float(np.nextafter(a, np.inf))


def _greedy_find_bin(distinct_values, counts, max_bin: int, total_cnt: int,
                     min_data_in_bin: int) -> List[float]:
    """Greedy equal-frequency boundary search over distinct sample values —
    exact behavioral mirror of the reference (src/io/bin.cpp:78
    GreedyFindBin): big-count values get dedicated bins, the running mean
    bin size re-adapts as bins close, boundaries are the next double above
    the midpoint, and one-ULP-adjacent boundaries dedupe.

    Returns upper bin boundaries; the last boundary is +inf.
    """
    dv = [float(v) for v in distinct_values]
    ct = [int(c) for c in counts]
    nd = len(dv)
    out: List[float] = []
    if nd == 0:
        return [np.inf]
    if nd <= max_bin:
        cur = 0
        for i in range(nd - 1):
            cur += ct[i]
            if cur >= min_data_in_bin:
                val = _dbl_up((dv[i] + dv[i + 1]) / 2.0)
                if not out or val > _dbl_up(out[-1]):
                    out.append(val)
                    cur = 0
        out.append(np.inf)
        return out

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, int(total_cnt) // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = [c >= mean_bin_size for c in ct]
    rest_bin = max_bin - sum(is_big)
    rest_cnt = int(total_cnt) - sum(c for c, b in zip(ct, is_big) if b)
    mean_bin_size = rest_cnt / rest_bin if rest_bin else np.inf
    uppers: List[float] = []
    lowers: List[float] = [dv[0]]
    cur = 0
    for i in range(nd - 1):
        if not is_big[i]:
            rest_cnt -= ct[i]
        cur += ct[i]
        if is_big[i] or cur >= mean_bin_size or \
                (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5)):
            uppers.append(dv[i])
            lowers.append(dv[i + 1])
            if len(uppers) >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin -= 1
                mean_bin_size = rest_cnt / rest_bin if rest_bin else np.inf
    for i in range(len(uppers)):
        val = _dbl_up((uppers[i] + lowers[i + 1]) / 2.0)
        if not out or val > _dbl_up(out[-1]):
            out.append(val)
    out.append(np.inf)
    return out


def _distinct_with_zero(vals_sorted: np.ndarray, zero_cnt: int):
    """Distinct (value, count) pairs with the implicit zero block injected
    at its sorted position (BinMapper::FindBin's construction,
    bin.cpp:355-383: the sample carries only |v| > kZeroThreshold values;
    everything else is the zero block).  One-ULP-adjacent values merge,
    keeping the larger value."""
    return _distinct_with_zero_counts(
        vals_sorted, np.ones(len(vals_sorted), np.int64), zero_cnt)


def _distinct_with_zero_counts(dv: np.ndarray, cv: np.ndarray,
                               zero_cnt: int):
    """Counts-based core of :func:`_distinct_with_zero`: ``dv`` are sorted
    values (duplicates allowed — exact-duplicate runs are 0 ULP apart and
    merge into one group anyway), ``cv`` their multiplicities.  Operating
    on (value, count) pairs makes the construction *mergeable*: chunk
    summaries built by :func:`summarize_column` merge exactly and
    finalize through this same code, so streamed sketch binning is
    bit-identical to the one-shot path."""
    n = len(dv)
    if n == 0:
        return [0.0], [int(zero_cnt)]
    new_grp = np.empty(n, bool)
    new_grp[0] = True
    if n > 1:
        new_grp[1:] = dv[1:] > np.nextafter(dv[:-1], np.inf)
    starts = np.flatnonzero(new_grp)
    ends = np.append(starts[1:], n) - 1
    dl = np.asarray(dv)[ends].tolist()
    cl = np.add.reduceat(np.asarray(cv, np.int64), starts).tolist()
    out_d: List[float] = []
    out_c: List[int] = []
    if dl[0] > 0.0 and zero_cnt > 0:
        out_d.append(0.0)
        out_c.append(int(zero_cnt))
    for i, (d, c) in enumerate(zip(dl, cl)):
        if i > 0 and dl[i - 1] < 0.0 and d > 0.0:
            # the zero block sits between the signs (inserted even when
            # empty, like the reference)
            out_d.append(0.0)
            out_c.append(int(zero_cnt))
        out_d.append(float(d))
        out_c.append(int(c))
    if dl[-1] < 0.0 and zero_cnt > 0:
        out_d.append(0.0)
        out_c.append(int(zero_cnt))
    return out_d, out_c


def _split_zero_counts(distinct, counts):
    left_cnt_data = cnt_zero = right_cnt_data = 0
    for d, c in zip(distinct, counts):
        if d <= -ZERO_THRESHOLD:
            left_cnt_data += c
        elif d > ZERO_THRESHOLD:
            right_cnt_data += c
        else:
            cnt_zero += c
    left_cnt = next((i for i, d in enumerate(distinct)
                     if d > -ZERO_THRESHOLD), len(distinct))
    right_start = next((i for i in range(left_cnt, len(distinct))
                        if distinct[i] > ZERO_THRESHOLD), -1)
    return left_cnt_data, cnt_zero, right_cnt_data, left_cnt, right_start


def _find_bin_zero_as_one(distinct, counts, max_bin: int, total_cnt: int,
                          min_data_in_bin: int) -> List[float]:
    """Exact mirror of the reference's FindBinWithZeroAsOneBin
    (bin.cpp:256): the negative range gets a budget proportional to its
    row share (floored), its last boundary becomes -kZeroThreshold, the
    positive range takes whatever budget remains past the zero bin."""
    left_cnt_data, cnt_zero, right_cnt_data, left_cnt, right_start = \
        _split_zero_counts(distinct, counts)
    ub: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        left_max_bin = int(left_cnt_data / (total_cnt - cnt_zero) *
                           (max_bin - 1))
        left_max_bin = max(1, left_max_bin)
        ub = _greedy_find_bin(distinct[:left_cnt], counts[:left_cnt],
                              left_max_bin, left_cnt_data, min_data_in_bin)
        if ub:
            ub[-1] = -ZERO_THRESHOLD
    right_max_bin = max_bin - 1 - len(ub)
    if right_start >= 0 and right_max_bin > 0:
        rb = _greedy_find_bin(distinct[right_start:], counts[right_start:],
                              right_max_bin, right_cnt_data, min_data_in_bin)
        ub.append(ZERO_THRESHOLD)
        ub.extend(rb)
    else:
        ub.append(np.inf)
    return ub


def _find_bin_predefined(distinct, counts, max_bin: int, total_cnt: int,
                         min_data_in_bin: int, forced) -> List[float]:
    """Exact mirror of FindBinWithPredefinedBin (bin.cpp:157): zero-bin
    boundaries and inf seed the set, forced bounds outside the zero band
    fill up to the budget, and each inter-bound segment gets greedy
    sub-bins proportional to its row share."""
    (left_cnt_data, cnt_zero, right_cnt_data, left_cnt,
     right_start) = _split_zero_counts(distinct, counts)
    ub: List[float] = []
    if max_bin == 2:
        ub.append(ZERO_THRESHOLD if left_cnt == 0 else -ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            ub.append(-ZERO_THRESHOLD)
        if right_start >= 0:
            ub.append(ZERO_THRESHOLD)
    ub.append(np.inf)
    max_to_insert = max_bin - len(ub)
    num_inserted = 0
    for b in forced:
        if num_inserted >= max_to_insert:
            break
        if abs(float(b)) > ZERO_THRESHOLD:
            ub.append(float(b))
            num_inserted += 1
    ub.sort()
    free_bins = max_bin - len(ub)
    bounds_to_add: List[float] = []
    value_ind = 0
    nd = len(distinct)
    for i in range(len(ub)):
        cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < nd and distinct[value_ind] < ub[i]:
            cnt_in_bin += counts[value_ind]
            value_ind += 1
        bins_remaining = max_bin - len(ub) - len(bounds_to_add)
        num_sub_bins = int(np.floor(cnt_in_bin * free_bins / total_cnt + 0.5)) \
            if total_cnt > 0 else 0
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == len(ub) - 1:
            num_sub_bins = bins_remaining + 1
        nb = _greedy_find_bin(distinct[bin_start:value_ind],
                              counts[bin_start:value_ind], num_sub_bins,
                              cnt_in_bin, min_data_in_bin)
        bounds_to_add.extend(nb[:-1])  # last bound is inf
    ub.extend(bounds_to_add)
    ub.sort()
    return ub


@dataclass
class BinMapper:
    """Per-feature value->bin quantizer (reference bin.h:61)."""

    num_bin: int = 1
    is_categorical: bool = False
    missing_type: MissingType = MissingType.NONE
    # numerical: ascending upper boundaries, len == num_bin (minus NaN bin)
    bin_upper_bound: Optional[np.ndarray] = None
    # categorical: category id (int) -> bin
    cat_to_bin: Dict[int, int] = field(default_factory=dict)
    bin_to_cat: Optional[np.ndarray] = None
    default_bin: int = 0          # bin containing value 0.0 (bin.h GetDefaultBin)
    most_freq_bin: int = 0
    min_value: float = 0.0
    max_value: float = 0.0
    # set by the pre-filter when no boundary separates enough rows
    # (bin.cpp NeedFilter); the feature is dropped like num_bin <= 1
    forced_trivial: bool = False

    @property
    def is_trivial(self) -> bool:
        """True when the feature carries no split information."""
        return self.num_bin <= 1 or self.forced_trivial

    # -- quantization --------------------------------------------------------
    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value -> bin (reference bin.h:464 ValueToBin)."""
        values = np.asarray(values, dtype=np.float64)
        if self.is_categorical:
            out = np.zeros(values.shape, dtype=np.int32)
            nan_mask = ~np.isfinite(values)
            ivals = np.where(nan_mask, -1, np.nan_to_num(values, nan=-1)).astype(np.int64)
            # vectorized dict lookup through a dense table when ids are small
            if self.bin_to_cat is not None and len(self.cat_to_bin):
                max_cat = max(self.cat_to_bin)
                table = np.zeros(max_cat + 2, dtype=np.int32)  # unseen -> bin 0
                for cat, b in self.cat_to_bin.items():
                    table[cat] = b
                ivals = np.clip(ivals, -1, max_cat)
                out = np.where(ivals < 0, 0, table[np.clip(ivals, 0, max_cat)])
            return out.astype(np.int32)

        if len(values) >= (1 << 16):
            from .utils import native
            out = native.bin_numerical(
                values, self.bin_upper_bound, self.num_bin,
                self.missing_type == MissingType.NAN)
            if out is not None:
                return out.astype(np.int32)
        nan_mask = np.isnan(values)
        if self.missing_type != MissingType.NAN:
            values = np.where(nan_mask, 0.0, values)
        bins = np.searchsorted(self.bin_upper_bound, values, side="left").astype(np.int32)
        nbins = len(self.bin_upper_bound)
        bins = np.minimum(bins, nbins - 1)
        if self.missing_type == MissingType.NAN:
            bins = np.where(nan_mask, self.num_bin - 1, bins)
        return bins

    def bin_to_value(self, b: int) -> float:
        """Representative threshold value for a bin upper edge (used when
        serializing split thresholds as raw doubles, reference
        bin.h BinToValue)."""
        if self.is_categorical:
            return float(self.bin_to_cat[b]) if self.bin_to_cat is not None else float(b)
        ub = self.bin_upper_bound
        if b >= len(ub):
            b = len(ub) - 1
        v = ub[b]
        if not np.isfinite(v):
            v = self.max_value + 1.0
        return float(v)


@dataclass
class ColumnSummary:
    """Mergeable one-pass summary of one feature's sampled values.

    The streamed-sketch form of the reference's per-feature sample
    (dataset_loader.cpp:966): exact distinct nonzero finite values (or
    category ids) with multiplicities, plus NaN/total counters.  Two
    summaries over disjoint row sets merge *exactly*
    (:func:`merge_column_summaries`), and :func:`find_bin_from_summary`
    produces the same BinMapper a one-shot :func:`find_bin` over the
    concatenated sample would — the property the out-of-core ingest
    subsystem (lightgbm_tpu/ingest/) builds on.  Memory is bounded by the
    number of distinct sampled values, never by the dataset row count.
    """

    distinct: np.ndarray          # sorted distinct values / category ids
    counts: np.ndarray            # int64 multiplicities
    na_cnt: int = 0
    total_cnt: int = 0            # rows summarized (zeros + NaNs included)
    is_categorical: bool = False


def summarize_column(values: np.ndarray,
                     is_categorical: bool = False) -> ColumnSummary:
    """Summarize one chunk of one feature's values (NaN allowed)."""
    values = np.asarray(values, dtype=np.float64).ravel()
    na_cnt = int(np.isnan(values).sum())
    finite = values[~np.isnan(values)]
    if is_categorical:
        ivals = finite.astype(np.int64)
        if len(ivals) and ivals.min() < 0:
            raise ValueError(
                "categorical features must be non-negative integers")
        cats, counts = (np.unique(ivals, return_counts=True) if len(ivals)
                        else (np.array([], np.int64), np.array([], np.int64)))
        return ColumnSummary(distinct=cats.astype(np.float64),
                             counts=counts.astype(np.int64), na_cnt=na_cnt,
                             total_cnt=len(values), is_categorical=True)
    # only |v| > kZeroThreshold values are kept; zeros are implicit
    # (total - nonzero - na), exactly like the reference's sample
    vals = finite[np.abs(finite) > ZERO_THRESHOLD]
    distinct, counts = (np.unique(vals, return_counts=True) if len(vals)
                        else (np.array([], np.float64),
                              np.array([], np.int64)))
    return ColumnSummary(distinct=distinct, counts=counts.astype(np.int64),
                         na_cnt=na_cnt, total_cnt=len(values))


def merge_column_summaries(a: ColumnSummary,
                           b: ColumnSummary) -> ColumnSummary:
    """Exact merge of two disjoint-row summaries (order-insensitive)."""
    if a.is_categorical != b.is_categorical:
        raise ValueError("cannot merge categorical and numerical summaries")
    d = np.concatenate([a.distinct, b.distinct])
    c = np.concatenate([a.counts, b.counts]).astype(np.int64)
    ud, inv = np.unique(d, return_inverse=True)
    uc = np.zeros(len(ud), np.int64)
    np.add.at(uc, inv, c)
    return ColumnSummary(distinct=ud, counts=uc,
                         na_cnt=a.na_cnt + b.na_cnt,
                         total_cnt=a.total_cnt + b.total_cnt,
                         is_categorical=a.is_categorical)


def find_bin(sample_values: np.ndarray, max_bin: int, min_data_in_bin: int = 3,
             *, total_cnt: Optional[int] = None, is_categorical: bool = False,
             use_missing: bool = True, zero_as_missing: bool = False,
             forced_bounds: Optional[Sequence[float]] = None,
             pre_filter_cnt: int = 1) -> BinMapper:
    """Construct a BinMapper from a sample of one feature's values
    (reference src/io/bin.cpp BinMapper::FindBin).

    ``sample_values`` may contain NaN.  ``total_cnt`` is the full dataset row
    count when the sample is a subsample (affects zero-count accounting).
    ``forced_bounds`` are mandatory bin upper bounds from
    ``forcedbins_filename`` (reference dataset_loader.cpp:641
    ``DatasetLoader::GetForcedBins`` + bin.cpp FindBin forced_upper_bounds):
    they always appear as boundaries; the greedy boundaries fill the
    remaining budget.

    One thin wrapper over :func:`summarize_column` +
    :func:`find_bin_from_summary` — the SAME code path streamed sketch
    binning (lightgbm_tpu/ingest/sketch.py) and distributed summary-merge
    binning (dataset.py pre_partition) take, so all three produce
    identical mappers from identical samples.
    """
    summary = summarize_column(sample_values, is_categorical=is_categorical)
    return find_bin_from_summary(
        summary, max_bin, min_data_in_bin, total_cnt=total_cnt,
        use_missing=use_missing, zero_as_missing=zero_as_missing,
        forced_bounds=forced_bounds, pre_filter_cnt=pre_filter_cnt)


def find_bin_from_summary(summary: ColumnSummary, max_bin: int,
                          min_data_in_bin: int = 3, *,
                          total_cnt: Optional[int] = None,
                          use_missing: bool = True,
                          zero_as_missing: bool = False,
                          forced_bounds: Optional[Sequence[float]] = None,
                          pre_filter_cnt: int = 1) -> BinMapper:
    """BinMapper from a (possibly merged) :class:`ColumnSummary`."""
    if total_cnt is None:
        total_cnt = summary.total_cnt
    na_cnt = int(summary.na_cnt)

    if summary.is_categorical:
        return _find_bin_categorical_counts(
            summary.distinct.astype(np.int64),
            np.asarray(summary.counts, np.int64), max_bin, na_cnt,
            use_missing)

    if zero_as_missing:
        missing_type = MissingType.ZERO
    elif use_missing and na_cnt > 0:
        missing_type = MissingType.NAN
    else:
        missing_type = MissingType.NONE
        # without use_missing NaNs are folded into zero (bin.cpp FindBin)

    # The reference's per-feature sample holds only |v| > kZeroThreshold
    # values (dataset_loader.cpp:966); everything else is the implicit
    # zero block of size total - sample - na.
    nonzero_cnt = int(np.asarray(summary.counts, np.int64).sum())
    na_eff = na_cnt if missing_type == MissingType.NAN else 0
    zero_cnt = int(total_cnt - nonzero_cnt - na_eff)
    distinct, counts = _distinct_with_zero_counts(
        summary.distinct, summary.counts, zero_cnt)

    if missing_type == MissingType.NAN:
        mb, tot = max_bin - 1, int(total_cnt) - na_eff
    else:
        mb, tot = max_bin, int(total_cnt)
    forced = [float(b) for b in forced_bounds] if forced_bounds else []
    if forced:
        ub_list = _find_bin_predefined(distinct, counts, mb, tot,
                                       min_data_in_bin, forced)
    else:
        ub_list = _find_bin_zero_as_one(distinct, counts, mb, tot,
                                        min_data_in_bin)
    if missing_type == MissingType.ZERO and len(ub_list) == 2:
        missing_type = MissingType.NONE

    ub = np.asarray(ub_list, dtype=np.float64)
    num_bin = len(ub)
    if missing_type == MissingType.NAN:
        num_bin += 1  # trailing NaN bin

    # per-bin sample counts (the reference's cnt_in_bin walk) drive
    # most_freq_bin; when the winner is not the zero/default bin and the
    # feature is not sparse enough, the default bin wins (bin.cpp:506-514)
    cnt_in_bin = np.zeros(num_bin, np.int64)
    i_bin = 0
    for d, c in zip(distinct, counts):
        # `while`, not the reference's single-step `if`: forced bounds can
        # place two boundaries between consecutive distinct values, and a
        # single step would misattribute counts across the empty bin
        while d > ub[i_bin]:
            i_bin += 1
        cnt_in_bin[i_bin] += c
    if missing_type == MissingType.NAN:
        cnt_in_bin[num_bin - 1] = na_cnt

    mapper = BinMapper(
        num_bin=num_bin,
        is_categorical=False,
        missing_type=missing_type,
        bin_upper_bound=ub,
        min_value=float(distinct[0]),
        max_value=float(distinct[-1]),
    )
    # pre-filter: a feature no boundary of which can separate
    # pre_filter_cnt rows on both sides can never split (bin.cpp:489
    # NeedFilter; the threshold is min_data_in_leaf scaled to the sample)
    if num_bin > 1 and pre_filter_cnt > 0:
        sum_left = 0
        need = True
        for i in range(num_bin - 1):
            sum_left += int(cnt_in_bin[i])
            if sum_left >= pre_filter_cnt and \
                    int(total_cnt) - sum_left >= pre_filter_cnt:
                need = False
                break
        mapper.forced_trivial = need
    mapper.default_bin = int(np.searchsorted(ub, 0.0, side="left"))
    most_freq = int(cnt_in_bin.argmax())
    sparse_rate = cnt_in_bin[most_freq] / max(1, int(total_cnt))
    if most_freq != mapper.default_bin and sparse_rate < 0.8:
        most_freq = mapper.default_bin  # kSparseThreshold
    mapper.most_freq_bin = most_freq
    return mapper


def _find_bin_categorical(finite: np.ndarray, max_bin: int, na_cnt: int,
                          use_missing: bool) -> BinMapper:
    ivals = finite.astype(np.int64)
    if len(ivals) and ivals.min() < 0:
        raise ValueError("categorical features must be non-negative integers")
    cats, counts = (np.unique(ivals, return_counts=True) if len(ivals)
                    else (np.array([], np.int64), np.array([], np.int64)))
    return _find_bin_categorical_counts(cats, counts, max_bin, na_cnt,
                                        use_missing)


def _find_bin_categorical_counts(cats: np.ndarray, counts: np.ndarray,
                                 max_bin: int, na_cnt: int,
                                 use_missing: bool) -> BinMapper:
    """Counts-based core (``cats`` ascending-sorted distinct ids): the
    mergeable-summary form of the categorical FindBin, shared by the
    one-shot and streamed-sketch paths."""
    order = np.argsort(-counts, kind="stable")
    cats, counts = cats[order], counts[order]
    # keep categories covering 99% of samples, capped at max_bin
    # (reference bin.cpp categorical FindBin: cut_cnt = 99%)
    total = counts.sum()
    if len(cats) > max_bin - 1:
        keep = max_bin - 1
    else:
        keep = len(cats)
    if total > 0 and keep < len(cats):
        pass  # cap dominates
    elif total > 0:
        cum = np.cumsum(counts)
        keep = int(np.searchsorted(cum, 0.99 * total) + 1)
        keep = min(keep, len(cats))
    cats = cats[:keep]
    cat_to_bin = {int(c): i for i, c in enumerate(cats)}
    num_bin = max(len(cats), 1)
    # NaN categoricals map to the most frequent category (bin 0) at both
    # train and inference (tree.py stores default_left = (split category ==
    # most frequent) on cat nodes), so no NaN bin is allocated and
    # missing_type stays NONE — mirrors reference CategoricalDecision
    # semantics for missing values.
    mapper = BinMapper(
        num_bin=num_bin,
        is_categorical=True,
        missing_type=MissingType.NONE,
        cat_to_bin=cat_to_bin,
        bin_to_cat=cats.copy(),
        most_freq_bin=0,
    )
    return mapper


def bin_matrix(X: np.ndarray, mappers: Sequence[BinMapper]) -> np.ndarray:
    """Quantize a raw (N, F) float matrix into bin codes using per-feature
    mappers.  Returns uint8 when every feature fits in 256 bins else uint16.

    All-numerical uint8 matrices take the native threaded path
    (native/binning.cc) — numpy searchsorted is single-threaded and
    dominated Dataset.construct at 10M-row scale."""
    n, f = X.shape
    assert f == len(mappers)
    max_bins = max(m.num_bin for m in mappers)
    dtype = np.uint8 if max_bins <= 256 else np.uint16
    if dtype is np.uint8 and all(not m.is_categorical for m in mappers):
        from .utils import native
        nat = native.bin_matrix_numerical(
            X, [m.bin_upper_bound for m in mappers],
            [m.num_bin for m in mappers],
            [m.missing_type == MissingType.NAN for m in mappers])
        if nat is not None:
            return nat
    out = np.empty((n, f), dtype=dtype)
    for j, m in enumerate(mappers):
        out[:, j] = m.value_to_bin(X[:, j]).astype(dtype)
    return out
