"""Feature quantization: value -> integer bin codes.

TPU-native re-implementation of the reference BinMapper
(reference: include/LightGBM/bin.h:61 ``BinMapper``, src/io/bin.cpp:150
``GreedyFindBin`` / ``FindBinWithZeroAsOneBin`` / ``BinMapper::FindBin``).

Runs host-side (numpy) once at ingest; the result is a dense integer matrix
(uint8 for <=256 bins) that is ``device_put`` / mesh-sharded once and stays
on device for the whole training run.  Bin semantics follow the reference:

* zero gets its own bin (kZeroThreshold band), negatives/positives binned
  separately around it with greedy equal-frequency boundaries;
* missing handling is None / Zero / NaN (bin.h:26 ``MissingType``): with
  ``MissingType.NaN`` an extra trailing bin holds the NaNs;
* categorical features map category ids to bins by descending frequency,
  keeping categories that cover 99% of the sample (src/io/bin.cpp categorical
  branch of FindBin).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["MissingType", "BinMapper", "find_bin", "bin_matrix"]

# reference include/LightGBM/bin.h:29 kZeroThreshold
ZERO_THRESHOLD = 1e-35
# reference include/LightGBM/bin.h:27 kSparseThreshold unused here (dense device layout)


class MissingType(enum.Enum):
    NONE = 0
    ZERO = 1
    NAN = 2


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy equal-frequency bin boundary search over distinct sample values
    (behavioral equivalent of src/io/bin.cpp:150 GreedyFindBin).

    Returns upper bin boundaries; the last boundary is +inf.
    """
    num_distinct = len(distinct_values)
    bin_upper: List[float] = []
    if num_distinct == 0:
        return [np.inf]
    if num_distinct <= max_bin:
        # one bin per distinct value, merging forward until min_data_in_bin
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += int(counts[i])
            if cur_cnt >= min_data_in_bin:
                bin_upper.append((float(distinct_values[i]) + float(distinct_values[i + 1])) / 2.0)
                cur_cnt = 0
        bin_upper.append(np.inf)
        return bin_upper

    # more distinct values than bins: greedy packing with "big" value handling
    if min_data_in_bin > 0:
        max_bin = min(max_bin, max(1, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_cnt = int(total_cnt - counts[is_big].sum())
    rest_bins = int(max_bin - is_big.sum())
    if rest_bins > 0:
        mean_bin_size = rest_cnt / rest_bins

    uppers: List[float] = []
    lowers: List[float] = [float(distinct_values[0])]
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_cnt -= int(counts[i])
        cur_cnt += int(counts[i])
        # close the bin at a big value, before a big value, or when full
        if is_big[i] or cur_cnt >= mean_bin_size or \
           (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5)):
            uppers.append(float(distinct_values[i]))
            lowers.append(float(distinct_values[i + 1]))
            cur_cnt = 0
            if not is_big[i]:
                rest_bins -= 1
                if rest_bins > 0:
                    mean_bin_size = rest_cnt / rest_bins
            if len(uppers) >= max_bin - 1:
                break
    # convert (upper[i], lower[i+1]) pairs to midpoint boundaries
    bin_upper = [(uppers[i] + lowers[i + 1]) / 2.0 for i in range(len(uppers))]
    bin_upper.append(np.inf)
    return bin_upper


@dataclass
class BinMapper:
    """Per-feature value->bin quantizer (reference bin.h:61)."""

    num_bin: int = 1
    is_categorical: bool = False
    missing_type: MissingType = MissingType.NONE
    # numerical: ascending upper boundaries, len == num_bin (minus NaN bin)
    bin_upper_bound: Optional[np.ndarray] = None
    # categorical: category id (int) -> bin
    cat_to_bin: Dict[int, int] = field(default_factory=dict)
    bin_to_cat: Optional[np.ndarray] = None
    default_bin: int = 0          # bin containing value 0.0 (bin.h GetDefaultBin)
    most_freq_bin: int = 0
    min_value: float = 0.0
    max_value: float = 0.0

    @property
    def is_trivial(self) -> bool:
        """True when the feature carries no split information (num_bin <= 1)."""
        return self.num_bin <= 1

    # -- quantization --------------------------------------------------------
    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value -> bin (reference bin.h:464 ValueToBin)."""
        values = np.asarray(values, dtype=np.float64)
        if self.is_categorical:
            out = np.zeros(values.shape, dtype=np.int32)
            nan_mask = ~np.isfinite(values)
            ivals = np.where(nan_mask, -1, np.nan_to_num(values, nan=-1)).astype(np.int64)
            # vectorized dict lookup through a dense table when ids are small
            if self.bin_to_cat is not None and len(self.cat_to_bin):
                max_cat = max(self.cat_to_bin)
                table = np.zeros(max_cat + 2, dtype=np.int32)  # unseen -> bin 0
                for cat, b in self.cat_to_bin.items():
                    table[cat] = b
                ivals = np.clip(ivals, -1, max_cat)
                out = np.where(ivals < 0, 0, table[np.clip(ivals, 0, max_cat)])
            return out.astype(np.int32)

        if len(values) >= (1 << 16):
            from .utils import native
            out = native.bin_numerical(
                values, self.bin_upper_bound, self.num_bin,
                self.missing_type == MissingType.NAN)
            if out is not None:
                return out.astype(np.int32)
        nan_mask = np.isnan(values)
        if self.missing_type != MissingType.NAN:
            values = np.where(nan_mask, 0.0, values)
        bins = np.searchsorted(self.bin_upper_bound, values, side="left").astype(np.int32)
        nbins = len(self.bin_upper_bound)
        bins = np.minimum(bins, nbins - 1)
        if self.missing_type == MissingType.NAN:
            bins = np.where(nan_mask, self.num_bin - 1, bins)
        return bins

    def bin_to_value(self, b: int) -> float:
        """Representative threshold value for a bin upper edge (used when
        serializing split thresholds as raw doubles, reference
        bin.h BinToValue)."""
        if self.is_categorical:
            return float(self.bin_to_cat[b]) if self.bin_to_cat is not None else float(b)
        ub = self.bin_upper_bound
        if b >= len(ub):
            b = len(ub) - 1
        v = ub[b]
        if not np.isfinite(v):
            v = self.max_value + 1.0
        return float(v)


def find_bin(sample_values: np.ndarray, max_bin: int, min_data_in_bin: int = 3,
             *, total_cnt: Optional[int] = None, is_categorical: bool = False,
             use_missing: bool = True, zero_as_missing: bool = False,
             forced_bounds: Optional[Sequence[float]] = None) -> BinMapper:
    """Construct a BinMapper from a sample of one feature's values
    (reference src/io/bin.cpp BinMapper::FindBin).

    ``sample_values`` may contain NaN.  ``total_cnt`` is the full dataset row
    count when the sample is a subsample (affects zero-count accounting).
    ``forced_bounds`` are mandatory bin upper bounds from
    ``forcedbins_filename`` (reference dataset_loader.cpp:641
    ``DatasetLoader::GetForcedBins`` + bin.cpp FindBin forced_upper_bounds):
    they always appear as boundaries; the greedy boundaries fill the
    remaining budget.
    """
    sample_values = np.asarray(sample_values, dtype=np.float64).ravel()
    n_sample = len(sample_values)
    if total_cnt is None:
        total_cnt = n_sample
    na_cnt = int(np.isnan(sample_values).sum())
    finite = sample_values[~np.isnan(sample_values)]

    if is_categorical:
        return _find_bin_categorical(finite, max_bin, na_cnt, use_missing)

    if zero_as_missing:
        missing_type = MissingType.ZERO
    elif use_missing and na_cnt > 0:
        missing_type = MissingType.NAN
    else:
        missing_type = MissingType.NONE
        # without use_missing NaNs are folded into zero (bin.cpp FindBin)

    zero_cnt = int(((finite > -ZERO_THRESHOLD) & (finite < ZERO_THRESHOLD)).sum())
    # rows absent from a feature's sample are zeros in the reference's sparse
    # sample representation; here the sample is dense so only count sample zeros
    neg = finite[finite <= -ZERO_THRESHOLD]
    pos = finite[finite >= ZERO_THRESHOLD]

    boundaries: List[float] = []
    n_non_missing = len(neg) + len(pos) + zero_cnt
    if n_non_missing == 0:
        boundaries = [np.inf]
    else:
        # distribute bins proportionally around the dedicated zero bin
        # (bin.cpp FindBinWithZeroAsOneBin)
        budget = max_bin - 1 if missing_type == MissingType.NAN else max_bin
        budget = max(budget, 2)
        left_budget = int(round(budget * len(neg) / max(1, n_non_missing)))
        left_budget = min(max(left_budget, 1 if len(neg) else 0), budget - 1)
        right_budget = budget - left_budget - 1  # -1 for the zero bin
        if len(pos) == 0:
            right_budget = 0
        left_b: List[float] = []
        right_b: List[float] = []
        if len(neg):
            dv, cnt = np.unique(neg, return_counts=True)
            left_b = _greedy_find_bin(dv, cnt, left_budget, len(neg), min_data_in_bin)
            left_b = [b for b in left_b if b < -ZERO_THRESHOLD]
            left_b.append(-ZERO_THRESHOLD)
        if len(pos):
            dv, cnt = np.unique(pos, return_counts=True)
            right_b = _greedy_find_bin(dv, cnt, max(right_budget, 1), len(pos),
                                       min_data_in_bin)
        boundaries = sorted(set(left_b)) + [ZERO_THRESHOLD] + sorted(
            b for b in right_b if b > ZERO_THRESHOLD)
        if not np.isinf(boundaries[-1]):
            boundaries.append(np.inf)
        # drop the zero boundary if there is nothing on one side and no zeros
        if zero_cnt == 0 and (len(neg) == 0 or len(pos) == 0):
            boundaries = [b for b in boundaries
                          if not (-ZERO_THRESHOLD <= b <= ZERO_THRESHOLD)] or [np.inf]

    if forced_bounds:
        # forced boundaries first (truncated to the bin budget — the
        # reference caps at max_bin), then the zero-bin boundaries (the
        # dedicated zero/missing bin must survive, bin.cpp
        # FindBinWithZeroAsOneBin), then greedy boundaries sampled evenly
        # across the value range to fill the remainder
        budget = max(max_bin - (1 if missing_type == MissingType.NAN else 0),
                     2)
        forced = sorted({float(b) for b in forced_bounds})[:budget - 1]
        computed = sorted(set(boundaries))
        keep = set(forced) | {np.inf}
        for b in computed:
            if -ZERO_THRESHOLD <= b <= ZERO_THRESHOLD and \
                    len(keep) < budget:
                keep.add(float(b))
        rest = [b for b in computed if float(b) not in keep]
        need = budget - len(keep)
        if need > 0 and rest:
            idx = np.unique(np.linspace(0, len(rest) - 1,
                                        min(need, len(rest))).astype(int))
            keep.update(float(rest[i]) for i in idx)
        boundaries = sorted(keep)

    ub = np.asarray(sorted(set(boundaries)), dtype=np.float64)
    num_bin = len(ub)
    if missing_type == MissingType.NAN:
        num_bin += 1  # trailing NaN bin

    mapper = BinMapper(
        num_bin=num_bin,
        is_categorical=False,
        missing_type=missing_type,
        bin_upper_bound=ub,
        min_value=float(finite.min()) if len(finite) else 0.0,
        max_value=float(finite.max()) if len(finite) else 0.0,
    )
    mapper.default_bin = int(np.searchsorted(ub, 0.0, side="left"))
    if len(finite):
        binned = mapper.value_to_bin(sample_values)
        mapper.most_freq_bin = int(np.bincount(binned, minlength=num_bin).argmax())
    return mapper


def _find_bin_categorical(finite: np.ndarray, max_bin: int, na_cnt: int,
                          use_missing: bool) -> BinMapper:
    ivals = finite.astype(np.int64)
    if len(ivals) and ivals.min() < 0:
        raise ValueError("categorical features must be non-negative integers")
    cats, counts = (np.unique(ivals, return_counts=True) if len(ivals)
                    else (np.array([], np.int64), np.array([], np.int64)))
    order = np.argsort(-counts, kind="stable")
    cats, counts = cats[order], counts[order]
    # keep categories covering 99% of samples, capped at max_bin
    # (reference bin.cpp categorical FindBin: cut_cnt = 99%)
    total = counts.sum()
    if len(cats) > max_bin - 1:
        keep = max_bin - 1
    else:
        keep = len(cats)
    if total > 0 and keep < len(cats):
        pass  # cap dominates
    elif total > 0:
        cum = np.cumsum(counts)
        keep = int(np.searchsorted(cum, 0.99 * total) + 1)
        keep = min(keep, len(cats))
    cats = cats[:keep]
    cat_to_bin = {int(c): i for i, c in enumerate(cats)}
    num_bin = max(len(cats), 1)
    # NaN categoricals map to the most frequent category (bin 0) at both
    # train and inference (tree.py stores default_left = (split category ==
    # most frequent) on cat nodes), so no NaN bin is allocated and
    # missing_type stays NONE — mirrors reference CategoricalDecision
    # semantics for missing values.
    mapper = BinMapper(
        num_bin=num_bin,
        is_categorical=True,
        missing_type=MissingType.NONE,
        cat_to_bin=cat_to_bin,
        bin_to_cat=cats.copy(),
        most_freq_bin=0,
    )
    return mapper


def bin_matrix(X: np.ndarray, mappers: Sequence[BinMapper]) -> np.ndarray:
    """Quantize a raw (N, F) float matrix into bin codes using per-feature
    mappers.  Returns uint8 when every feature fits in 256 bins else uint16.

    All-numerical uint8 matrices take the native threaded path
    (native/binning.cc) — numpy searchsorted is single-threaded and
    dominated Dataset.construct at 10M-row scale."""
    n, f = X.shape
    assert f == len(mappers)
    max_bins = max(m.num_bin for m in mappers)
    dtype = np.uint8 if max_bins <= 256 else np.uint16
    if dtype is np.uint8 and all(not m.is_categorical for m in mappers):
        from .utils import native
        nat = native.bin_matrix_numerical(
            X, [m.bin_upper_bound for m in mappers],
            [m.num_bin for m in mappers],
            [m.missing_type == MissingType.NAN for m in mappers])
        if nat is not None:
            return nat
    out = np.empty((n, f), dtype=dtype)
    for j, m in enumerate(mappers):
        out[:, j] = m.value_to_bin(X[:, j]).astype(dtype)
    return out
