"""Booster: user-facing trained-model handle.

Mirrors the reference Python package's Booster
(reference: python-package/lightgbm/basic.py ``Booster`` — train/eval/
predict/save surface; the ctypes C-API indirection collapses because the
boosting driver is in-process).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .config import Config
from .dataset import Dataset
from .models.boosting import create_boosting
from .utils.log import log_warning

__all__ = ["Booster"]


class Booster:
    """Trained-model handle (reference basic.py Booster; C-side
    src/c_api.cpp:108 Booster wrapper)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 silent: bool = False) -> None:
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_data_name = "training"

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("train_set must be a Dataset")
            self.config = Config(self.params)
            train_set.construct(self.config)
            self._gbdt = create_boosting(self.config, train_set)
        elif model_file is not None:
            # binary-mode read: a corrupt file with stray invalid utf-8
            # must surface as ModelCorruptError, not UnicodeDecodeError
            with open(model_file, "rb") as fh:
                raw = fh.read()
            try:
                text = raw.decode("utf-8")
            except UnicodeDecodeError as exc:
                from .models.model_text import ModelCorruptError
                raise ModelCorruptError(str(model_file), exc.start,
                                        "not utf-8 text") from exc
            self._load_from_string(text, source=str(model_file))
        elif model_str is not None:
            self._load_from_string(model_str)
        else:
            raise ValueError("Booster needs train_set, model_file or model_str")

    def _load_from_string(self, model_str: str,
                          source: str = "<model string>") -> None:
        from .models.model_text import string_to_model
        self.config = Config(self.params)
        self._gbdt = string_to_model(model_str, self.config, source=source)

    # -- training ------------------------------------------------------------
    def update(self, train_set: Optional[Dataset] = None,
               fobj=None) -> bool:
        """One boosting iteration (reference LGBM_BoosterUpdateOneIter /
        basic.py Booster.update).  ``fobj(preds, train_set) -> (grad, hess)``
        enables custom objectives."""
        if train_set is not None and train_set is not self._gbdt.train_set:
            # the reference skips ResetTrainingData for the identical
            # Dataset (basic.py is_the_same_train_set check) — resetting
            # rebuilds scores over every tree, which would turn a cheap
            # no-op into O(trees x N) per update call
            self.reset_train_data(train_set)
        if fobj is not None:
            preds = np.asarray(self._gbdt.score)
            grad, hess = fobj(preds, self._gbdt.train_set)
            return self._gbdt.train_one_iter(np.asarray(grad), np.asarray(hess))
        return self._gbdt.train_one_iter()

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def reset_train_data(self, train_set: Dataset) -> "Booster":
        """Swap the training dataset under the existing model (reference
        Booster::ResetTrainingData / LGBM_BoosterResetTrainingData):
        trees are kept, scores rebuild on the new rows, and further
        ``update()`` calls continue boosting on them."""
        if not isinstance(train_set, Dataset):
            raise TypeError("train_set must be a Dataset")
        self._gbdt.reset_train_data(train_set)
        return self

    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Refit the existing tree structures on new data
        (reference basic.py:2976 Booster.refit -> LGBM_BoosterRefit ->
        GBDT::RefitTree): every tree keeps its splits; leaf values become
        ``decay_rate * old + (1 - decay_rate) * new`` where the new value is
        the closed-form output of the leaf's rows in ``data``."""
        if self._gbdt.objective is None:
            raise ValueError("Cannot refit due to null objective function.")
        leaf_preds = self.predict(data, pred_leaf=True, **kwargs)
        new_params = dict(self.params)
        new_params["refit_decay_rate"] = decay_rate
        train_set = Dataset(data, label)
        new_booster = Booster(params=new_params, train_set=train_set)
        new_booster._gbdt.refit_trees(self._gbdt, np.asarray(leaf_preds))
        return new_booster

    @property
    def train_record(self):
        """Telemetry record of this booster's training run
        (:class:`~lightgbm_tpu.telemetry.TrainRecord`): per-tree
        histogram passes, per-phase wall time, trace-time collective
        tallies, XLA compile events, device-memory watermark.  Call
        ``.snapshot()`` for a JSON-ready dict; the same record is
        exported by the serve ``/metrics`` endpoint as the process's
        last training run."""
        return self._gbdt.train_record

    @property
    def current_iteration(self) -> int:
        return self._gbdt.current_iteration

    def num_trees(self) -> int:
        return self._gbdt.num_trees()

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    def num_feature(self) -> int:
        # reference reports the ORIGINAL column count (num_total_features),
        # not the post-trivial-filter inner count
        return self._gbdt.feature_mapping()[1]

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        self._gbdt.add_valid(data, name)
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """reference basic.py reset_parameter -> LGBM_BoosterResetParameter;
        supports learning-rate style schedule changes."""
        self.params.update(params)
        self.config = self.config.update(params)
        self._gbdt.config = self.config
        return self

    # -- evaluation ----------------------------------------------------------
    def eval_train(self, feval=None) -> List[Tuple[str, str, float, bool]]:
        out = self._gbdt.eval_train()
        if feval is not None:
            out = out + self._run_feval(feval, "training",
                                        np.asarray(self._gbdt.score),
                                        self._gbdt.train_set)
        return out

    def eval_valid(self, feval=None) -> List[Tuple[str, str, float, bool]]:
        out = self._gbdt.eval_valid()
        if feval is not None:
            for vi, (vname, vset) in enumerate(self._gbdt.valid_sets):
                out = out + self._run_feval(
                    feval, vname, np.asarray(self._gbdt.valid_scores[vi]), vset)
        return out

    def _run_feval(self, feval, name, score, dset):
        res = feval(score, dset)
        if isinstance(res, tuple):
            res = [res]
        return [(name, r[0], float(r[1]), bool(r[2])) for r in res]

    # -- prediction ----------------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: Optional[int] = None,
                pred_early_stop_margin: Optional[float] = None,
                **kwargs) -> np.ndarray:
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else None
        if hasattr(data, "to_numpy"):
            data = data.to_numpy(dtype=np.float64, na_value=np.nan)
        if hasattr(data, "todense"):
            data = np.asarray(data.todense())
        return self._gbdt.predict(np.asarray(data, dtype=np.float64),
                                  raw_score=raw_score,
                                  start_iteration=start_iteration,
                                  num_iteration=num_iteration,
                                  pred_leaf=pred_leaf,
                                  pred_contrib=pred_contrib,
                                  pred_early_stop=pred_early_stop,
                                  pred_early_stop_freq=pred_early_stop_freq,
                                  pred_early_stop_margin=pred_early_stop_margin)

    def to_predictor(self, num_iteration: Optional[int] = None,
                     warmup: bool = False, **kwargs):
        """Serving handle for this model: a
        :class:`~lightgbm_tpu.serve.CompiledPredictor` holding the
        ensemble device-resident with jit-compiled prediction per shape
        bucket (``warmup=True`` compiles every bucket ahead of the first
        request).  See ``lightgbm_tpu.serve`` for the registry /
        micro-batching / HTTP layers above it."""
        from .serve import CompiledPredictor
        pred = CompiledPredictor(self, num_iteration=num_iteration, **kwargs)
        if warmup:
            pred.warmup()
        return pred

    # -- model IO ------------------------------------------------------------
    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        return self._gbdt.save_model_to_string(
            start_iteration, -1 if num_iteration is None else num_iteration)

    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        # temp + fsync + atomic rename: mid-train snapshots (and any other
        # save racing a crash) can never leave a truncated model file
        from .io_utils import atomic_write_text
        atomic_write_text(filename,
                          self.model_to_string(num_iteration, start_iteration,
                                               importance_type))
        return self

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> Dict[str, Any]:
        from .models.model_text import model_to_dict
        return model_to_dict(self._gbdt, start_iteration,
                             -1 if num_iteration is None else num_iteration)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        return self._gbdt.feature_importance(importance_type)

    def feature_name(self) -> List[str]:
        # full ORIGINAL column names (reference returns num_total_features
        # names, matching num_feature()/feature_importance() lengths)
        return self._gbdt.feature_mapping()[2]

    # network emulation (reference basic.py:2178 set_network) ---------------
    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120, num_machines: int = 1) -> "Booster":
        """Reference socket-mesh bootstrap.  Here distribution rides the JAX
        device mesh instead: single-host multi-chip needs only
        ``tree_learner='data'`` (+ ``num_devices``); multi-host processes
        must call ``lightgbm_tpu.distributed.init(...)`` before training.
        Raises rather than silently pretending a socket mesh exists."""
        n_machines = (len(machines.split(",")) if isinstance(machines, str)
                      else len(machines)) if machines else num_machines
        if n_machines > 1:
            raise NotImplementedError(
                "set_network(machines=...) maps to the JAX multi-process "
                "runtime here: call lightgbm_tpu.distributed.init(coordinator"
                "_address=..., num_processes=..., process_id=...) in every "
                "process, then train with tree_learner='data'. A socket mesh "
                "is never created, so returning success would be a lie.")
        log_warning("set_network with a single machine is a no-op: set "
                    "tree_learner='data'/'feature'/'voting' and num_devices "
                    "to shard over the local JAX mesh instead")
        return self

    def free_network(self) -> "Booster":
        return self
