"""Multi-host bootstrap: the TPU-native replacement for the reference's
machine-list network init.

The reference boots its socket mesh from ``machine_list_file`` + per-rank
TCP handshakes (reference: src/network/linkers_socket.cpp; CLI entry
application.cpp:168-178 ``Network::Init``; Python ``set_network``
basic.py:2178).  On TPU the equivalent is the JAX multi-process runtime:
every host process calls :func:`init` once, after which ``jax.devices()``
spans ALL hosts' chips and the parallel tree learners' ``shard_map``
collectives ride ICI within a slice and DCN across slices — no framework
transport code at all (SURVEY.md §2.5 TPU mapping).

Single-host multi-chip needs none of this: a local mesh over
``jax.local_devices()`` is built automatically from ``num_devices``.

Typical multi-host launch (one process per host, same program)::

    import lightgbm_tpu as lgb
    lgb.distributed.init(coordinator_address="10.0.0.1:1234",
                         num_processes=4, process_id=rank)
    bst = lgb.train({"tree_learner": "data", ...}, dset)
"""

from __future__ import annotations

from typing import Optional

from .utils.log import log_info, log_warning

_initialized = False


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         local_device_ids=None,
         cpu_collectives: str = "gloo") -> None:
    """Initialize the JAX multi-process runtime (replaces the reference's
    ``Network::Init`` rank-0 handshake, network.cpp:26-43).

    On managed TPU slices (GKE/TPU VM) all arguments are optional — JAX
    discovers the topology from the environment; pass them explicitly for
    manual clusters, mirroring machine_list_file + local_listen_port.

    After init, the parallel tree learners work UNCHANGED: their mesh
    spans all hosts' devices and every process runs the same SPMD driver
    with the full host-side data — the reference's default distributed
    mode without ``pre_partition`` (each machine loads all data,
    dataset_loader.cpp:181 ``LoadFromFile(rank, num_machines)``); device
    memory shards across hosts even though host memory does not.

    ``cpu_collectives`` selects the cross-process collective backend for
    CPU clusters (gloo; TPU meshes use ICI/DCN natively).
    """
    global _initialized
    if _initialized:
        log_warning("lightgbm_tpu.distributed.init called twice; ignoring")
        return
    import jax
    if cpu_collectives:
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              cpu_collectives)
        except AttributeError:
            # option absent on this jax version; invalid VALUES still
            # propagate so a typo'd backend fails loudly here rather than
            # hanging at the first cross-process collective
            log_warning("this jax version has no "
                        "jax_cpu_collectives_implementation option; "
                        "cross-process CPU collectives may be unavailable")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids)
    _initialized = True
    log_info(f"distributed runtime up: process {jax.process_index()}/"
             f"{jax.process_count()}, {len(jax.local_devices())} local / "
             f"{len(jax.devices())} global devices")


def shutdown() -> None:
    """Tear down the multi-process runtime (reference LGBM_NetworkFree)."""
    global _initialized
    if not _initialized:
        return
    import jax
    jax.distributed.shutdown()
    _initialized = False


def is_initialized() -> bool:
    return _initialized


def allgather_host(arr) -> "object":
    """Concatenate per-process host arrays along axis 0 in rank order.

    The host-side collective behind pre-partitioned ingest (the analog of
    the reference's BinMapper allgather, dataset_loader.cpp:1040-1130):
    bin-finding samples and metadata gathered once at Dataset.construct;
    variable per-rank lengths are handled by a max-pad + trim."""
    import numpy as np
    import jax
    from jax.experimental import multihost_utils
    arr = np.asarray(arr)
    if jax.process_count() == 1:
        return arr
    if arr.dtype == np.float64:
        # x64 is disabled in JAX by default, so a float64 array would be
        # silently rounded to float32 in transit; ship the raw bits as
        # uint32 pairs instead (bin boundaries and labels must survive
        # exactly for the serial/distributed parity contract)
        return allgather_host(arr.view(np.uint32)).view(np.float64)
    lens = np.asarray(multihost_utils.process_allgather(
        np.asarray([arr.shape[0]], np.int32))).ravel()
    m = int(lens.max())
    if m > arr.shape[0]:
        pad = np.zeros((m - arr.shape[0],) + arr.shape[1:], arr.dtype)
        arr = np.concatenate([arr, pad], axis=0)
    gathered = np.asarray(multihost_utils.process_allgather(arr))
    return np.concatenate(
        [gathered[r, :int(lens[r])] for r in range(len(lens))], axis=0)


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()
