from .histogram import build_histogram, histogram_subtract
from .split import best_split_per_feature, leaf_output
