"""Gradient quantization for int8 histogram training.

TPU-native analog of the reference's gradient discretizer
(reference: src/treelearner/gradient_discretizer.cpp DiscretizeGradients,
include/LightGBM/config.h use_quantized_grad / num_grad_quant_bins /
quant_train_renew_leaf / stochastic_rounding): per-tree linear scales map
gradients to signed and hessians to unsigned integer levels with
stochastic rounding, histograms accumulate exact int32 sums on the MXU
(ops/histogram_pallas.py build_histogram_pallas_leaves_q8), and split
gains are computed on the dequantized sums.  Differences from the
reference, by design:

* levels ride int8 MXU lanes, so up to 127 gradient levels are free —
  the reference's default ``num_grad_quant_bins=4`` is honored but any
  value up to 254 is accepted (we clamp levels to the int8 range);
* the count channel is an exact int32 row count (the reference packs
  grad/hess as int16 pairs and renormalizes; we keep three lanes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["quant_levels", "quantize_wch", "dequant_scales"]


def quant_levels(num_grad_quant_bins: int) -> tuple:
    """(gq_max, hq_max) integer level bounds for a quant-bin count.

    Gradients are symmetric in [-gq_max, gq_max]; hessians (non-negative)
    in [0, hq_max].  Both clamp to the int8 payload range."""
    qb = max(2, int(num_grad_quant_bins))
    return max(1, min(qb // 2, 127)), max(1, min(qb, 127))


@functools.partial(jax.jit, static_argnames=("gq_max", "hq_max",
                                             "stochastic"))
def quantize_wch(grad: jnp.ndarray, hess: jnp.ndarray, bag_mask: jnp.ndarray,
                 g_scale: jnp.ndarray, h_scale: jnp.ndarray,
                 key: jnp.ndarray, *, gq_max: int, hq_max: int,
                 stochastic: bool = True) -> jnp.ndarray:
    """(8, N) int8 FEATURE-MAJOR weight rows [g_q, h_q, count, 0, ...].

    ``g_scale``/``h_scale`` are the per-tree dequantization scales
    (g ~= g_q * g_scale); callers compute them from (cross-shard) maxima
    so data-parallel shards quantize identically.  The result is static
    for the whole tree — the per-wave leaf channel rides a separate
    (N,) int8 kernel input, so this buffer is never rewritten.
    Stochastic rounding ``floor(x + u)`` is unbiased for either sign;
    with ``stochastic=False`` it degrades to round-half-up.
    """
    n = grad.shape[0]
    gm = (grad * bag_mask) / g_scale
    hm = (hess * bag_mask) / h_scale
    if stochastic:
        ug = jax.random.uniform(jax.random.fold_in(key, 0), (n,))
        uh = jax.random.uniform(jax.random.fold_in(key, 1), (n,))
    else:
        ug = uh = jnp.float32(0.5)
    g_q = jnp.clip(jnp.floor(gm + ug), -gq_max, gq_max).astype(jnp.int8)
    h_q = jnp.clip(jnp.floor(hm + uh), 0, hq_max).astype(jnp.int8)
    cnt = (bag_mask > 0).astype(jnp.int8)
    z = jnp.zeros_like(cnt)
    return jnp.stack([g_q, h_q, cnt, z, z, z, z, z], axis=0)


def dequant_scales(g_scale, h_scale):
    """(3,) f32 multiplier turning int32 channel sums into f32 sums."""
    return jnp.stack([g_scale, h_scale, jnp.float32(1.0)])
