"""Pallas TPU histogram kernel — the hot op, on the MXU.

TPU-native analog of the reference's device histogram kernels
(reference: src/treelearner/ocl/histogram256.cl:476-505 local-memory float
atomics; src/treelearner/kernels/histogram_16_64_256.cu:23-341; CPU inner
loops src/io/dense_bin.hpp:18-52).  TPUs have no fast atomics, so scatter-add
is reformulated as a one-hot contraction — but unlike the XLA ``onehot`` path
(ops/histogram.py), the one-hot tile here never leaves VMEM:

  for each row-block (sequential grid) and each feature f:
      onehot = (bins[f, block] == iota(B))        # (B, R) bf16, in VMEM only
      hist[f] += onehot @ w_block                  # MXU, f32 accumulation

Precision: the MXU contracts bf16 operands into f32.  The 0/1 one-hot is
exact in bf16; gradients/hessians are carried as **bf16 hi+lo pairs**
(value = hi + lo, lo = value - f32(hi)), so each product is exact to f32
precision and the result matches a f32 matmul — the extra channels are free
because the MXU lane dimension is padded to 128 anyway (we use 5 of 128:
g_hi, g_lo, h_hi, h_lo, count).  This beats the reference GPU learner's
plain-f32 ``gpu_hist_t`` (gpu_tree_learner.h:79) in exactness per cycle.

Layout contract: bins arrive **feature-major** ``(F, N)`` so each feature's
row-block is a contiguous lane vector; N must be a multiple of the row block
R (the Dataset pads device uploads; masked rows carry w=0 and contribute
nothing).  Output is ``(F, B, 3)`` f32 (sum_grad, sum_hess, count).

MXU cycle floor: F * ceil(B/128) * N K-slices per full build — at Higgs
scale (10.5M x 28, B=256) ~0.1 s/full build; the tree grower's subtraction
trick (ops/histogram.py histogram_subtract) keeps builds to ~4 full-N
equivalents per 255-leaf tree.

Kernel v2 (PERF.md round 10): every entry point carries a ``pipeline``
switch — ``"dma"`` (the on-TPU default) streams the bins +
packed-weight row blocks HBM->VMEM through explicitly double-buffered
``make_async_copy`` pairs that overlap the contraction (the kernels
were measured 1.43x above the MXU floor on the implicit fetch; this
targets that residue), ``"blockspec"`` keeps the v1 implicit
per-grid-step fetch for A/B re-probing (and is the default under
off-TPU interpretation, where DMA machinery is emulation overhead).  When ``max_bin <= PACK4_MAX_BINS`` the bins may arrive
nibble-PACKED (``pack_bins4``: two 4-bit codes per int8 lane, the
reference dense_bin.hpp 4-bit layout) — half the streamed bin bytes;
the kernel unpacks in VMEM against pre-split even/odd weight halves.
Small-B one-hot tiles group MORE features per 128-row MXU tile instead
of padding bins (``_tile_params``).  Contract: quantized int32 sums
are bit-for-bit identical across every variant; f32 stays within the
hi/lo exactness budget.  ``interpret=None`` auto-interprets off TPU,
so all of this is testable on CPU, and the entry points batch under
``vmap`` through jax's pallas_call batching rule (the batch axis
becomes a leading grid dimension — what lets multitrain ride these
kernels).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["build_histogram_pallas", "build_histogram_pallas_leaves",
           "build_histogram_pallas_leaves_q8", "pack_weights8",
           "wave_trial_channels_pallas", "wave_row_update_pallas",
           "DEFAULT_ROW_BLOCK", "pad_rows", "LEAF_CHANNELS",
           "Q_LEAF_CHANNELS", "DEFAULT_PIPELINE", "resolve_pipeline",
           "resolve_interpret", "pack_bins4", "unpack_bins4",
           "PACK4_MAX_BINS"]

DEFAULT_ROW_BLOCK = 4096
_C = 8  # weight channels (5 used), padded to a power of two for clean tiles
_CB = 5  # channels per leaf block in the leaf-batched kernel (no padding)
LEAF_CHANNELS = 128 // _CB  # 25 leaves per pass (25*5 = 125 <= 128 lanes)
_QCB = 3  # quantized channels per leaf: g_q, h_q, count
Q_LEAF_CHANNELS = 128 // _QCB  # 42 leaves per pass (42*3 = 126 <= 128)

# 4-bit bin packing (reference src/io/dense_bin.hpp IS_4BIT specialization):
# two bin codes per int8 lane, applicable when every bin fits a nibble
PACK4_MAX_BINS = 16

# Kernel pipeline: "dma" streams row blocks of bins + packed weights
# HBM->VMEM through explicitly double-buffered async copies that overlap
# the MXU one-hot contraction; "blockspec" is the original implicit
# per-grid-step operand fetch.  Default: dma ON TPU (where the overlap
# is real); off-TPU the kernels run the interpreter, where the DMA
# machinery is pure emulation overhead, so unresolved calls default to
# the cheaper-to-emulate blockspec form — explicit pipeline="dma"
# forces the DMA form anywhere (the parity tests do).  Overridable via
# the environment (the measured-dead-ends guard rail: re-probe with
# LGBM_TPU_PALLAS_PIPELINE=blockspec before trusting a regression).
DEFAULT_PIPELINE = os.environ.get("LGBM_TPU_PALLAS_PIPELINE", "")


def resolve_pipeline(pipeline=None) -> str:
    p = pipeline or DEFAULT_PIPELINE
    if not p:
        from ..utils.backend import default_backend
        p = "dma" if default_backend() == "tpu" else "blockspec"
    if p not in ("dma", "blockspec"):
        raise ValueError(f"pallas pipeline must be dma|blockspec, got {p!r}")
    return p


def resolve_interpret(interpret=None) -> bool:
    """None -> interpret off TPU (Mosaic cannot lower elsewhere), so the
    kernels are runnable — and testable — on every backend."""
    if interpret is not None:
        return bool(interpret)
    from ..utils.backend import default_backend
    return default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def pad_rows(n: int, row_block: int = DEFAULT_ROW_BLOCK) -> int:
    """Rows the caller must pad to for the pallas path."""
    return _round_up(max(n, row_block), row_block)


def _check_rows(n: int, row_block: int, kernel: str) -> None:
    if n % row_block != 0 or n == 0:
        raise ValueError(
            f"{kernel} requires the row count to be a non-zero multiple of "
            f"row_block={row_block}, got N={n}; pad inputs to pad_rows(N) "
            f"== {pad_rows(max(n, 1), row_block)} first (masked/padded rows "
            "carry weight 0 and contribute nothing)")


def _check_same_rows(kernel: str, n: int, **named) -> None:
    for name, got in named.items():
        if got != n:
            raise ValueError(
                f"{kernel}: {name} carries {got} rows but the bin matrix "
                f"carries {n}; all row-aligned operands must be padded to "
                "the same pad_rows() length")


@jax.jit
def pack_bins4(bins_t: jnp.ndarray) -> jnp.ndarray:
    """(F, N) uint8 bin codes (all < 16) -> (F, N//2) nibble-packed bytes.

    Row 2j lives in the LOW nibble of byte j, row 2j+1 in the HIGH nibble
    (the reference's 4-bit dense_bin layout along the row axis).  N must
    be even — the pallas row blocks always are."""
    f, n = bins_t.shape
    lo = bins_t[:, 0::2]
    hi = bins_t[:, 1::2]
    return (lo | (hi << 4)).astype(jnp.uint8)


@jax.jit
def unpack_bins4(packed: jnp.ndarray) -> jnp.ndarray:
    """(..., N//2) packed bytes -> (..., N) interleaved bin codes."""
    lo = packed & jnp.uint8(0xF)
    hi = packed >> 4
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(*packed.shape[:-1], packed.shape[-1] * 2)


def _tile_params(num_bins: int, f: int, m_cap: int):
    """(padded bin count b, feature group g) for the one-hot contraction.

    The stacked one-hot M dim is g*b; g*b must be a whole number of
    128-row MXU tiles.  Unlike the v1 kernels (which padded b to 64/128),
    b here rounds to a multiple of 8 and small-B shapes fill the tile by
    stacking MORE features per contraction instead of padding bins: at
    B<=16, b=16 with g=8 runs the same 128-row tile with zero padded-bin
    waste (4x fewer MXU flops than b=64).  Per-(feature, bin) sums are
    unchanged — only dead padding moves — so this is bit-compatible."""
    b = max(16, _round_up(num_bins, 8))
    group = 1
    while (group * b) % 128 != 0 and group < 256:
        group *= 2
    while group * 2 <= f and group * 2 * b <= m_cap:
        group *= 2
    if group > f or (group * b) % 128 != 0:
        b = _round_up(num_bins, 128)
        group = 1
    return b, group


def _note_kernel(site: str, streamed_bytes: int) -> None:
    """Tally one kernel build (trace-time inside jitted growers; per call
    on eager paths) — exported by TrainRecord like the collective sites."""
    try:
        from ..telemetry.train_record import note_hist_kernel
        note_hist_kernel(site, streamed_bytes)
    except Exception:
        pass


def _split_hi_lo(v: jnp.ndarray):
    """Split f32 v into bf16 (hi, lo) with v ≈ hi + lo to ~2^-17 rel.

    hi is v with the low 16 mantissa bits masked off — explicitly via
    integer ops, because XLA's simplifier folds a bf16 round-trip
    (``v - f32(bf16(v))``) into zero under jit.  The masked hi is exactly
    representable in bf16 and ``v - hi`` is exact in f32.
    """
    bits = jax.lax.bitcast_convert_type(v, jnp.uint32)
    hi32 = jax.lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000),
                                        jnp.float32)
    return hi32.astype(jnp.bfloat16), (v - hi32).astype(jnp.bfloat16)


def _hist_kernel(bins_ref, w_ref, out_ref, *, num_features: int,
                 num_bins: int, group: int, fstep: int):
    """Accumulate (F*B, C) histograms over one row block.

    ``group`` features share one MXU contraction: their one-hot tiles are
    stacked along M with per-feature bin offsets, so the dot is
    (group*B, R) @ (R, C) — fewer, larger matmuls pipeline better than
    per-feature ones.  The grid is (feature tiles, row blocks) with the row
    dimension innermost, so each feature tile's accumulator stays resident
    in VMEM across the row sweep (bounds VMEM for wide datasets)."""
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    w = w_ref[...]  # (R, C) bf16
    r = w.shape[0]
    b = num_bins
    iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b

    # fori_loop (not Python unrolling) keeps one set of intermediates live
    # in VMEM regardless of the tile's feature count.  Each iteration loads
    # an ALIGNED ``fstep``-feature block (Mosaic requires provably-aligned
    # dynamic slice starts) and sweeps it in static ``group``-sized slices;
    # num_features is a multiple of ``fstep`` by construction (padded).
    def do(i, carry):
        f0 = i * fstep
        cols_blk = bins_ref[pl.ds(f0, fstep), :].astype(jnp.int32)
        for k in range(fstep // group):
            cols = cols_blk[k * group:(k + 1) * group]           # (g, R)
            colrep = jnp.repeat(cols, b, axis=0)                 # (g*B, R)
            onehot = (colrep == iota_gb).astype(jnp.bfloat16)
            part = jax.lax.dot_general(
                onehot, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)              # (g*B, C)
            out_ref[pl.ds((f0 + k * group) * b, group * b)] += part
        return carry

    jax.lax.fori_loop(0, num_features // fstep, do, 0)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_block", "interpret",
                                    "kr"))
def _build_histogram_pallas_bs(bins_t: jnp.ndarray, grad: jnp.ndarray,
                               hess: jnp.ndarray, mask: jnp.ndarray, *,
                               num_bins: int,
                               row_block: int = DEFAULT_ROW_BLOCK,
                               interpret: bool = False,
                               kr: int = 0) -> jnp.ndarray:
    """Implicit-pipeline (BlockSpec-fetched) form of the single-leaf
    histogram kernel — the v1 layout, kept for A/B re-probing."""
    f, n = bins_t.shape
    # Pad bins to a multiple of 64 and pack `group` features per contraction
    # so the stacked one-hot M dim (group*b) fills whole 128-row MXU tiles:
    # at max_bin=63 (the reference's accelerator-recommended setting,
    # docs/GPU-Performance.rst) this doubles throughput vs padding to 128.
    b = _round_up(num_bins, 64)
    group = next((g for g in (2, 4, 8) if (g * b) % 128 == 0), 1)
    while group * 2 <= f and group * 2 * b <= 512:
        group *= 2  # bigger stacked matmuls pipeline better, bounded by VMEM
    if group > f or (group * b) % 128 != 0:
        b = _round_up(num_bins, 128)
        group = 1

    gm = grad * mask
    hm = hess * mask
    g_hi, g_lo = _split_hi_lo(gm)
    h_hi, h_lo = _split_hi_lo(hm)
    z = jnp.zeros_like(g_hi)
    w8 = jnp.stack([g_hi, g_lo, h_hi, h_lo, mask.astype(jnp.bfloat16),
                    z, z, z], axis=-1)  # (N, C) — one fused interleave

    # Feature tiling keeps the VMEM-resident accumulator block bounded no
    # matter how wide the dataset is (wide-sparse/EFB datasets sweep
    # multiple feature tiles over the same rows).  Empirical Mosaic limit:
    # output blocks beyond 8192 sublanes fail scoped-vmem allocation, so
    # cap ft*b at 8192.  The kernel's internal row block is 1024 — measured
    # ~1.8x faster than 4096 at Higgs scale (10.5M x 28, B=256) — while the
    # caller-facing padding contract stays ``row_block``.
    fstep = max(group, 8)  # group is a power of two -> lcm(group, 8)
    ft_cap = max(fstep, 8192 // b // fstep * fstep)
    ft = min(_round_up(f, fstep), ft_cap)
    f_pad = _round_up(f, ft)  # also a multiple of ``fstep`` and ``group``
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    # narrow inputs (the 1-feature leaf-refit pass) want larger row blocks:
    # per-grid-step overhead dominates their tiny per-block compute
    kr = kr or math.gcd(row_block, 1024)

    grid = (f_pad // ft, n // kr)  # row dim innermost
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_features=ft, num_bins=b,
                          group=group, fstep=fstep),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, _C), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, _C), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, _C), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * _C,
            bytes_accessed=f_pad * n + n * _C * 2 + f_pad * b * _C * 4,
            transcendentals=0),
        interpret=interpret,
    )(bins_t, w8)

    out = out.reshape(f_pad, b, _C)
    hist = jnp.stack([out[:, :, 0] + out[:, :, 1],
                      out[:, :, 2] + out[:, :, 3],
                      out[:, :, 4]], axis=-1)
    return hist[:f, :num_bins, :]


def _hist_kernel_dma(bins_hbm, w_hbm, out_ref, *, num_features: int,
                     num_bins: int, group: int, fstep: int, kr: int,
                     nsteps: int, packed: bool):
    """DMA-pipelined form: bins and weight row blocks stream HBM->VMEM
    through two explicitly double-buffered async copies; the copy of
    chunk j+1 is in flight while chunk j feeds the MXU contraction.  The
    whole row sweep lives inside ONE grid step per feature tile, so the
    f32 accumulator block is VMEM-resident start to finish.

    ``packed`` consumes nibble-packed bins (two rows per byte): the
    chunk unpacks in VMEM and contracts each nibble half against its
    half of the pre-split weights — half the streamed bin bytes for the
    same per-(feature, bin) sums."""
    out_ref[...] = jnp.zeros_like(out_ref)
    ft = num_features
    b = num_bins
    f0 = pl.program_id(0) * ft
    kb = kr // 2 if packed else kr            # bin BYTES per chunk lane
    iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, kb), 0) % b

    def body(bbuf, wbuf, bsem, wsem):
        def bins_dma(slot, j):
            return pltpu.make_async_copy(
                bins_hbm.at[pl.ds(f0, ft), pl.ds(j * kb, kb)],
                bbuf.at[slot], bsem.at[slot])

        def w_dma(slot, j):
            if packed:
                return pltpu.make_async_copy(
                    w_hbm.at[:, pl.ds(j * kb, kb), :], wbuf.at[slot],
                    wsem.at[slot])
            return pltpu.make_async_copy(
                w_hbm.at[pl.ds(j * kr, kr), :], wbuf.at[slot],
                wsem.at[slot])

        bins_dma(0, 0).start()
        w_dma(0, 0).start()

        def step(j, carry):
            slot = j % 2

            @pl.when(j + 1 < nsteps)
            def _():
                bins_dma((j + 1) % 2, j + 1).start()
                w_dma((j + 1) % 2, j + 1).start()

            bins_dma(slot, j).wait()
            w_dma(slot, j).wait()
            blk = bbuf[slot]                         # (ft, kb) bin bytes
            if packed:
                w_halves = (wbuf[slot, 0], wbuf[slot, 1])   # (kb, C) each
            else:
                w_halves = (wbuf[slot],)                    # (kr, C)

            def do(i, c):
                fi = i * fstep
                cols_blk = jax.lax.dynamic_slice_in_dim(
                    blk, fi, fstep, 0).astype(jnp.int32)
                nibs = (cols_blk & 0xF, cols_blk >> 4) if packed \
                    else (cols_blk,)
                for k in range(fstep // group):
                    part = None
                    for nib, wh in zip(nibs, w_halves):
                        cols = nib[k * group:(k + 1) * group]    # (g, kb)
                        colrep = jnp.repeat(cols, b, axis=0)     # (g*B, kb)
                        onehot = (colrep == iota_gb).astype(jnp.bfloat16)
                        p = jax.lax.dot_general(
                            onehot, wh, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (g*B, C)
                        part = p if part is None else part + p
                    out_ref[pl.ds((fi + k * group) * b, group * b)] += part
                return c

            jax.lax.fori_loop(0, num_features // fstep, do, 0)
            return carry

        jax.lax.fori_loop(0, nsteps, step, 0)

    wshape = (2, 2, kb, _C) if packed else (2, kr, _C)
    pl.run_scoped(body,
                  pltpu.VMEM((2, ft, kb), bins_hbm.dtype),
                  pltpu.VMEM(wshape, jnp.bfloat16),
                  pltpu.SemaphoreType.DMA((2,)),
                  pltpu.SemaphoreType.DMA((2,)))


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_block", "interpret",
                                    "kr", "packed"))
def _build_histogram_pallas_dma(bins_t: jnp.ndarray, grad: jnp.ndarray,
                                hess: jnp.ndarray, mask: jnp.ndarray, *,
                                num_bins: int, row_block: int,
                                interpret: bool, kr: int,
                                packed: bool) -> jnp.ndarray:
    f = bins_t.shape[0]
    n = bins_t.shape[1] * (2 if packed else 1)
    b, group = _tile_params(num_bins, f, 512)

    gm = grad * mask
    hm = hess * mask
    g_hi, g_lo = _split_hi_lo(gm)
    h_hi, h_lo = _split_hi_lo(hm)
    z = jnp.zeros_like(g_hi)
    w8 = jnp.stack([g_hi, g_lo, h_hi, h_lo, mask.astype(jnp.bfloat16),
                    z, z, z], axis=-1)                     # (N, C)
    if packed:
        # pre-split weight halves pair each nibble with its own rows, so
        # the kernel never lane-interleaves (Mosaic-unfriendly): half 0
        # carries even rows (low nibbles), half 1 odd rows (high nibbles)
        w8 = jnp.stack([w8[0::2], w8[1::2]])               # (2, N/2, C)

    fstep = max(group, 8)
    ft_cap = max(fstep, 8192 // b // fstep * fstep)
    ft = min(_round_up(f, fstep), ft_cap)
    f_pad = _round_up(f, ft)
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    kr = kr or math.gcd(row_block, 1024)

    out = pl.pallas_call(
        functools.partial(_hist_kernel_dma, num_features=ft, num_bins=b,
                          group=group, fstep=fstep, kr=kr, nsteps=n // kr,
                          packed=packed),
        grid=(f_pad // ft,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((ft * b, _C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, _C), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * _C,
            bytes_accessed=f_pad * (n // 2 if packed else n) +
            n * _C * 2 + f_pad * b * _C * 4,
            transcendentals=0),
        interpret=interpret,
    )(bins_t, w8)

    out = out.reshape(f_pad, b, _C)
    hist = jnp.stack([out[:, :, 0] + out[:, :, 1],
                      out[:, :, 2] + out[:, :, 3],
                      out[:, :, 4]], axis=-1)
    return hist[:f, :num_bins, :]


def build_histogram_pallas(bins_t: jnp.ndarray, grad: jnp.ndarray,
                           hess: jnp.ndarray, mask: jnp.ndarray, *,
                           num_bins: int,
                           row_block: int = DEFAULT_ROW_BLOCK,
                           interpret: bool = None,
                           kr: int = 0, pipeline: str = None,
                           bins_packed: bool = False) -> jnp.ndarray:
    """(F, B, 3) histogram over masked rows from feature-major bin codes.

    Args:
      bins_t: (F, N) integer bin codes — or, with ``bins_packed``, the
        (F, N//2) nibble-packed bytes from :func:`pack_bins4`.  N must be
        a multiple of ``row_block`` (use :func:`pad_rows`).
      grad, hess, mask: (N,) f32; mask is 0.0 for out-of-leaf / padded
        rows.
      num_bins: static global bin count B (padded to a lane-friendly size
        internally; trailing bins stay zero).
      interpret: None = auto (interpret off TPU).
      pipeline: "dma" (explicit double-buffered HBM->VMEM streaming,
        default) or "blockspec" (v1 implicit fetch); None = module
        default.
      bins_packed: bins_t holds two 4-bit codes per byte (requires
        ``num_bins <= PACK4_MAX_BINS``; DMA pipeline only).
    """
    f, np_ = bins_t.shape
    n = np_ * 2 if bins_packed else np_
    _check_rows(n, row_block, "build_histogram_pallas")
    _check_same_rows("build_histogram_pallas", n, grad=grad.shape[0],
                     hess=hess.shape[0], mask=mask.shape[0])
    pipeline = resolve_pipeline(pipeline)
    interpret = resolve_interpret(interpret)
    if bins_packed:
        if num_bins > PACK4_MAX_BINS:
            raise ValueError(f"bins_packed requires num_bins <= "
                             f"{PACK4_MAX_BINS}, got {num_bins}")
        pipeline = "dma"  # the packed layout exists only on the DMA path
    _note_kernel(f"ops/hist_kernel/single/{pipeline}"
                 + ("/packed4" if bins_packed else ""),
                 f * np_ * bins_t.dtype.itemsize + n * _C * 2 +
                 f * num_bins * 3 * 4)
    if pipeline == "dma":
        return _build_histogram_pallas_dma(
            bins_t, grad, hess, mask, num_bins=num_bins,
            row_block=row_block, interpret=interpret, kr=kr,
            packed=bins_packed)
    return _build_histogram_pallas_bs(
        bins_t, grad, hess, mask, num_bins=num_bins, row_block=row_block,
        interpret=interpret, kr=kr)


# ---------------------------------------------------------------------------
# Leaf-channel batched kernel: 25 leaf histograms per pass.
#
# The single-leaf kernel above uses only 5 of the MXU's 128 output lanes
# (the one-hot contraction's N dimension); the systolic array computes the
# other 123 for free.  This variant packs LEAF_CHANNELS=25 leaves x 5 weight
# channels (g_hi, g_lo, h_hi, h_lo, count — nothing wasted) into the lane
# dimension: each row carries a leaf-channel id ``ch`` in [0, 25) (or -1 =
# inactive), the kernel expands the row's weight vector into the 5 lanes of
# its leaf's lane-block, and ONE contraction per row block accumulates all
# 25 histograms.  A tree grower that batches up to 25 splits per wave
# (learner/wave.py) gets its smaller-child histograms for the price of one
# full pass — which removes the need to physically partition rows at all
# (PERF.md round-3 analysis: row movement was 55-60%% of tree time).
# ---------------------------------------------------------------------------


@jax.jit
def pack_weights8(grad: jnp.ndarray, hess: jnp.ndarray,
                  mask: jnp.ndarray) -> jnp.ndarray:
    """(8, N) bf16 FEATURE-MAJOR weight rows [g_hi, g_lo, h_hi, h_lo,
    count, 0, 0, 0].

    Precompute once per tree: gradients do not change across waves, only
    the per-row leaf channel does.  ``mask`` may carry bagging weights
    (GOSS amplification) — they scale grad/hess, while the count channel
    is strictly 0/1 row membership (reference counts rows, not weights).
    """
    gm = grad * mask
    hm = hess * mask
    g_hi, g_lo = _split_hi_lo(gm)
    h_hi, h_lo = _split_hi_lo(hm)
    z = jnp.zeros_like(g_hi)
    return jnp.stack([g_hi, g_lo, h_hi, h_lo,
                      (mask > 0).astype(jnp.bfloat16), z, z, z], axis=0)


def _hist_leaves_kernel(bins_ref, w_ref, ch_ref, out_ref, *,
                        num_features: int, num_bins: int, group: int,
                        fstep: int):
    """Accumulate (F*B, 128) lane-packed leaf histograms over one row
    block (25 leaves x 5 channels in the 128-lane dimension).

    Same feature-major rhs-transposed form as the q8 kernel (the dot
    contracts dim 1 of BOTH operands) — measured 120 ms vs 165 ms for
    the row-major lhs-major form at 10.5M x 28 x 256."""
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    w = w_ref[...]                      # (8, R) bf16 feature-major
    ch = ch_ref[...]                    # (1, R) int32
    r = w.shape[1]
    b = num_bins

    # Expand (8, R) weights into (128, R): sublane l carries weight
    # channel l%_CB iff the row's leaf channel == l//_CB.  All arithmetic
    # — Mosaic cannot relayout i1 masks between replicated operand
    # orientations, so the equality select is ``relu(1 - |ch - leaf|)``
    # (exactly 1.0 on match for integer distances); channel tiling is a
    # sublane concatenate sliced to 128 (the last 3 sublanes select leaf
    # 25 which no row carries -> zero).  Pure VPU work, no gather.
    subl = jax.lax.broadcasted_iota(jnp.int32, (128, r), 0)
    leaf_of_subl = subl // _CB
    d = (ch - leaf_of_subl).astype(jnp.float32)     # (128, R) broadcast
    sel = jnp.maximum(0.0, 1.0 - jnp.abs(d)).astype(jnp.bfloat16)
    w5 = w[:_CB, :]
    wtile = jnp.concatenate([w5] * (128 // _CB + 1), axis=0)[:128]
    w128t = wtile * sel                              # (128, R)

    iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b

    def do(i, carry):
        f0 = i * fstep
        cols_blk = bins_ref[pl.ds(f0, fstep), :].astype(jnp.int32)
        for k in range(fstep // group):
            cols = cols_blk[k * group:(k + 1) * group]           # (g, R)
            colrep = jnp.repeat(cols, b, axis=0)                 # (g*B, R)
            onehot = (colrep == iota_gb).astype(jnp.bfloat16)
            part = jax.lax.dot_general(
                onehot, w128t, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)              # (g*B, 128)
            out_ref[pl.ds((f0 + k * group) * b, group * b)] += part
        return carry

    jax.lax.fori_loop(0, num_features // fstep, do, 0)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_block", "interpret"))
def _build_histogram_pallas_leaves_bs(bins_t: jnp.ndarray, w8: jnp.ndarray,
                                      ch: jnp.ndarray, *, num_bins: int,
                                      row_block: int = DEFAULT_ROW_BLOCK,
                                      interpret: bool = False
                                      ) -> jnp.ndarray:
    """Implicit-pipeline (BlockSpec-fetched) 25-leaf kernel (v1 layout)."""
    f, n = bins_t.shape
    b = _round_up(num_bins, 64)
    group = next((g for g in (2, 4, 8) if (g * b) % 128 == 0), 1)
    while group * 2 <= f and group * 2 * b <= 1024:
        group *= 2
    if group > f or (group * b) % 128 != 0:
        b = _round_up(num_bins, 128)
        group = 1

    ch2 = ch.astype(jnp.int32).reshape(1, n)               # (1, N)

    # The (ft*b, 128) f32 accumulator must stay well inside VMEM next to
    # the bins / weight blocks (cap 8192 sublanes); kr=4096 + M<=1024
    # measured best for the bf16 form at Higgs scale (proto_bf16_fm.py:
    # 120 ms vs 165 ms for the old row-major kr=1024 layout).
    fstep = max(group, 8)
    ft_cap = max(fstep, 8192 // b // fstep * fstep)
    ft = min(_round_up(f, fstep), ft_cap)
    f_pad = _round_up(f, ft)
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    kr = math.gcd(row_block, 4096)

    grid = (f_pad // ft, n // kr)
    out = pl.pallas_call(
        functools.partial(_hist_leaves_kernel, num_features=ft, num_bins=b,
                          group=group, fstep=fstep),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_C, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, 128), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * 128,
            bytes_accessed=f_pad * n + n * (_C * 2 + 4) + f_pad * b * 512,
            transcendentals=0),
        interpret=interpret,
    )(bins_t, w8, ch2)

    out = out[:, :LEAF_CHANNELS * _CB].reshape(f_pad, b, LEAF_CHANNELS, _CB)
    hist = jnp.stack([out[..., 0] + out[..., 1],
                      out[..., 2] + out[..., 3],
                      out[..., 4]], axis=-1)              # (F, B, 25, 3)
    return jnp.transpose(hist, (2, 0, 1, 3))[:, :f, :num_bins, :]


def _leaves_dma_common(bins_hbm, w_hbm, ch_hbm, out_ref, *, num_features,
                       num_bins, group, fstep, kr, nsteps, packed,
                       make_w128, onehot_dtype, acc_dtype):
    """Shared DMA pipeline of the two leaf-batched kernels: bins,
    feature-major weights and the leaf-channel row stream HBM->VMEM via
    double-buffered async copies overlapping the contraction.
    ``make_w128(w_chunk, ch_chunk)`` expands the (8, r) weights into the
    lane-packed (128, r) right operand (bf16 hi/lo or int8 form)."""
    out_ref[...] = jnp.zeros_like(out_ref)
    ft = num_features
    b = num_bins
    f0 = pl.program_id(0) * ft
    kb = kr // 2 if packed else kr
    iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, kb), 0) % b

    def body(bbuf, wbuf, cbuf, bsem, wsem, csem):
        def bins_dma(slot, j):
            return pltpu.make_async_copy(
                bins_hbm.at[pl.ds(f0, ft), pl.ds(j * kb, kb)],
                bbuf.at[slot], bsem.at[slot])

        def w_dma(slot, j):
            if packed:
                return pltpu.make_async_copy(
                    w_hbm.at[:, :, pl.ds(j * kb, kb)], wbuf.at[slot],
                    wsem.at[slot])
            return pltpu.make_async_copy(
                w_hbm.at[:, pl.ds(j * kr, kr)], wbuf.at[slot],
                wsem.at[slot])

        def ch_dma(slot, j):
            if packed:
                return pltpu.make_async_copy(
                    ch_hbm.at[:, :, pl.ds(j * kb, kb)], cbuf.at[slot],
                    csem.at[slot])
            return pltpu.make_async_copy(
                ch_hbm.at[:, pl.ds(j * kr, kr)], cbuf.at[slot],
                csem.at[slot])

        def start(slot, j):
            bins_dma(slot, j).start()
            w_dma(slot, j).start()
            ch_dma(slot, j).start()

        start(0, 0)

        def step(j, carry):
            slot = j % 2

            @pl.when(j + 1 < nsteps)
            def _():
                start((j + 1) % 2, j + 1)

            bins_dma(slot, j).wait()
            w_dma(slot, j).wait()
            ch_dma(slot, j).wait()
            blk = bbuf[slot]
            if packed:
                w128s = (make_w128(wbuf[slot, 0], cbuf[slot, 0]),
                         make_w128(wbuf[slot, 1], cbuf[slot, 1]))
            else:
                w128s = (make_w128(wbuf[slot], cbuf[slot]),)

            def do(i, c):
                fi = i * fstep
                cols_blk = jax.lax.dynamic_slice_in_dim(
                    blk, fi, fstep, 0).astype(jnp.int32)
                nibs = (cols_blk & 0xF, cols_blk >> 4) if packed \
                    else (cols_blk,)
                for k in range(fstep // group):
                    part = None
                    for nib, w128t in zip(nibs, w128s):
                        cols = nib[k * group:(k + 1) * group]
                        colrep = jnp.repeat(cols, b, axis=0)
                        onehot = (colrep == iota_gb).astype(onehot_dtype)
                        p = jax.lax.dot_general(
                            onehot, w128t, (((1,), (1,)), ((), ())),
                            preferred_element_type=acc_dtype)  # (g*B, 128)
                        part = p if part is None else part + p
                    out_ref[pl.ds((fi + k * group) * b, group * b)] += part
                return c

            jax.lax.fori_loop(0, num_features // fstep, do, 0)
            return carry

        jax.lax.fori_loop(0, nsteps, step, 0)

    if packed:
        wshape, cshape = (2, 2, _C, kb), (2, 2, 1, kb)
    else:
        wshape, cshape = (2, _C, kr), (2, 1, kr)
    pl.run_scoped(body,
                  pltpu.VMEM((2, ft, kb), bins_hbm.dtype),
                  pltpu.VMEM(wshape, w_hbm.dtype),
                  pltpu.VMEM(cshape, ch_hbm.dtype),
                  pltpu.SemaphoreType.DMA((2,)),
                  pltpu.SemaphoreType.DMA((2,)),
                  pltpu.SemaphoreType.DMA((2,)))


def _make_w128_bf16(w, ch):
    """(8, r) bf16 weights + (1, r) i32 channels -> (128, r) lane-packed
    right operand (same arithmetic as _hist_leaves_kernel)."""
    r = w.shape[1]
    subl = jax.lax.broadcasted_iota(jnp.int32, (128, r), 0)
    d = (ch.astype(jnp.int32) - subl // _CB).astype(jnp.float32)
    sel = jnp.maximum(0.0, 1.0 - jnp.abs(d)).astype(jnp.bfloat16)
    wtile = jnp.concatenate([w[:_CB]] * (128 // _CB + 1), axis=0)[:128]
    return wtile * sel


def _make_w128_q8(w, ch):
    """(8, r) i8 weights + (1, r) i8 channels -> (128, r) int8 operand
    (same arithmetic as _hist_leaves_q8_kernel: 32-bit build, i8 pack)."""
    r = w.shape[1]
    subl = jax.lax.broadcasted_iota(jnp.int32, (128, r), 0)
    sel = (ch.astype(jnp.int32) == subl // _QCB).astype(jnp.int32)
    w3 = w[:_QCB].astype(jnp.int32)
    wtile = jnp.concatenate([w3] * (128 // _QCB + 1), axis=0)[:128]
    return (wtile * sel).astype(jnp.int8)


def _leaves_dma_call(bins_t, w, ch2, *, num_bins, interpret, packed,
                     m_cap, kr0, make_w128, onehot_dtype, acc_dtype,
                     out_dtype, row_block):
    """Shared wrapper plumbing of the two DMA leaf-kernel builders."""
    f = bins_t.shape[0]
    n = bins_t.shape[1] * (2 if packed else 1)
    b, group = _tile_params(num_bins, f, m_cap)
    if packed:
        w = jnp.stack([w[:, 0::2], w[:, 1::2]])       # (2, 8, N/2)
        ch2 = jnp.stack([ch2[:, 0::2], ch2[:, 1::2]])  # (2, 1, N/2)
    fstep = max(group, 8)
    ft_cap = max(fstep, 8192 // b // fstep * fstep)
    ft = min(_round_up(f, fstep), ft_cap)
    f_pad = _round_up(f, ft)
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    kr = math.gcd(row_block, kr0)
    out = pl.pallas_call(
        functools.partial(_leaves_dma_common, num_features=ft, num_bins=b,
                          group=group, fstep=fstep, kr=kr, nsteps=n // kr,
                          packed=packed, make_w128=make_w128,
                          onehot_dtype=onehot_dtype, acc_dtype=acc_dtype),
        grid=(f_pad // ft,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, 128), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * 128,
            bytes_accessed=f_pad * (n // 2 if packed else n) +
            n * (_C * 2 + 4) + f_pad * b * 512,
            transcendentals=0),
        interpret=interpret,
    )(bins_t, w, ch2)
    return out, f_pad


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_block", "interpret",
                                    "packed"))
def _build_histogram_pallas_leaves_dma(bins_t, w8, ch, *, num_bins,
                                       row_block, interpret, packed):
    n = w8.shape[1]
    ch2 = ch.astype(jnp.int32).reshape(1, n)
    out, f_pad = _leaves_dma_call(
        bins_t, w8, ch2, num_bins=num_bins, interpret=interpret,
        packed=packed, m_cap=1024, kr0=4096, make_w128=_make_w128_bf16,
        onehot_dtype=jnp.bfloat16, acc_dtype=jnp.float32,
        out_dtype=jnp.float32, row_block=row_block)
    f = bins_t.shape[0]
    b = out.shape[0] // f_pad
    out = out[:, :LEAF_CHANNELS * _CB].reshape(f_pad, b, LEAF_CHANNELS, _CB)
    hist = jnp.stack([out[..., 0] + out[..., 1],
                      out[..., 2] + out[..., 3],
                      out[..., 4]], axis=-1)
    return jnp.transpose(hist, (2, 0, 1, 3))[:, :f, :num_bins, :]


def build_histogram_pallas_leaves(bins_t: jnp.ndarray, w8: jnp.ndarray,
                                  ch: jnp.ndarray, *, num_bins: int,
                                  row_block: int = DEFAULT_ROW_BLOCK,
                                  interpret: bool = None,
                                  pipeline: str = None,
                                  bins_packed: bool = False) -> jnp.ndarray:
    """(LEAF_CHANNELS, F, B, 3) histograms of 25 leaf channels in one pass.

    Args:
      bins_t: (F, N) integer bin codes — or, with ``bins_packed``, the
        (F, N//2) nibble-packed bytes from :func:`pack_bins4`.  N must be
        a multiple of ``row_block``.
      w8: (8, N) bf16 FEATURE-MAJOR weight rows from :func:`pack_weights8`.
      ch: (N,) integer leaf channel in [0, LEAF_CHANNELS), or -1 for rows
        that belong to no batched leaf (they contribute nothing).
      num_bins: static global bin count B.
      interpret / pipeline / bins_packed: as :func:`build_histogram_pallas`.
    """
    f, np_ = bins_t.shape
    n = np_ * 2 if bins_packed else np_
    _check_rows(n, row_block, "build_histogram_pallas_leaves")
    _check_same_rows("build_histogram_pallas_leaves", n, w8=w8.shape[1],
                     ch=ch.shape[0])
    pipeline = resolve_pipeline(pipeline)
    interpret = resolve_interpret(interpret)
    if bins_packed:
        if num_bins > PACK4_MAX_BINS:
            raise ValueError(f"bins_packed requires num_bins <= "
                             f"{PACK4_MAX_BINS}, got {num_bins}")
        pipeline = "dma"
    _note_kernel(f"ops/hist_kernel/leaves/{pipeline}"
                 + ("/packed4" if bins_packed else ""),
                 f * np_ * bins_t.dtype.itemsize + n * (_C * 2 + 4) +
                 LEAF_CHANNELS * f * num_bins * 3 * 4)
    if pipeline == "dma":
        return _build_histogram_pallas_leaves_dma(
            bins_t, w8, ch, num_bins=num_bins, row_block=row_block,
            interpret=interpret, packed=bins_packed)
    return _build_histogram_pallas_leaves_bs(
        bins_t, w8, ch, num_bins=num_bins, row_block=row_block,
        interpret=interpret)


# ---------------------------------------------------------------------------
# Quantized-gradient kernel: int8 x int8 -> int32 on the MXU, 42 leaves/pass.
#
# The TPU analog of LightGBM 4.x gradient quantization (reference:
# src/treelearner/gradient_discretizer.cpp DiscretizeGradients — int8
# stochastic-rounded gradients feeding integer histograms).  Quantized
# gradients need only THREE lanes per leaf (g_q, h_q, count — no hi/lo
# exactness pairs: integer sums in the int32 MXU accumulator are exact by
# construction), so 42 leaves share one pass vs the bf16 kernel's 25, and
# the i8 MXU path runs at twice the bf16 MAC rate on v5e.  Histogram
# subtraction (parent - child) is exact integer arithmetic — strictly
# better conditioned than the reference's f64 CPU path.  Exactness bounds
# per int32 accumulator bin: the count channel (weight 1) is exact to 2^31
# rows/shard; the g_q/h_q channels (weights up to gq_max/hq_max) are exact
# to 2^31/gq_max rows landing in ONE bin per shard (~16.9M rows at 127
# levels — gbdt.py warns past the bound).  The bf16 kernel's f32 counts
# cap at 2^24 (ops/histogram.py).
#
# Mosaic constraints probed on v5e (scripts/proto_q8_*.py): 8-bit compares
# and 8-bit elementwise multiplies are NOT supported — the one-hot and the
# lane-expanded weights are built with 32-bit arithmetic and packed to i8
# right before the dot.  Best measured layout (proto_q8_round2.py at
# 10.5M x 28 x 256): FEATURE-MAJOR (8, N) weights consumed as a
# (128, R) right operand with the dot contracting dim 1 of both sides —
# 72 ms/pass vs 108 ms for the row-major (R, 128) form and 164 ms for
# the bf16 25-leaf kernel; group=8 features per contraction (M=2048),
# kr=4096 row blocks.  The feature-major layout also makes the per-wave
# leaf-channel update a contiguous (N,) row write instead of a strided
# lane update.
# ---------------------------------------------------------------------------


def _hist_leaves_q8_kernel(bins_ref, wch_ref, ch_ref, out_ref, *,
                           num_features: int, num_bins: int, group: int):
    """Accumulate (F*B, 128) lane-packed int32 leaf histograms over one
    row block (42 leaves x 3 int8 channels in the 128-lane dimension)."""
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    wch = wch_ref[...]                   # (8, R) i8: g_q, h_q, cnt, 0*5
    r = wch.shape[1]
    b = num_bins
    ch = ch_ref[...].astype(jnp.int32)   # (1, R); -1 = inactive
    subl = jax.lax.broadcasted_iota(jnp.int32, (128, r), 0)
    sel = (ch == subl // _QCB).astype(jnp.int32)
    w3 = wch[:_QCB, :].astype(jnp.int32)           # (3, R)
    wtile = jnp.concatenate([w3] * (128 // _QCB + 1), axis=0)[:128]
    w128t = (wtile * sel).astype(jnp.int8)         # (128, R)
    iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b

    for k in range(num_features // group):
        cols = bins_ref[k * group:(k + 1) * group, :].astype(jnp.int32)
        colrep = jnp.repeat(cols, b, axis=0)                 # (g*B, R)
        onehot = (colrep == iota_gb).astype(jnp.int8)
        part = jax.lax.dot_general(
            onehot, w128t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)                # (g*B, 128)
        out_ref[k * group * b:(k + 1) * group * b] += part


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_block", "interpret"))
def _build_histogram_pallas_leaves_q8_bs(bins_t: jnp.ndarray,
                                         wch: jnp.ndarray,
                                         ch: jnp.ndarray, *, num_bins: int,
                                         row_block: int = DEFAULT_ROW_BLOCK,
                                         interpret: bool = False
                                         ) -> jnp.ndarray:
    """Implicit-pipeline (BlockSpec-fetched) 42-leaf q8 kernel (v1)."""
    _, n = wch.shape
    f = bins_t.shape[0]
    b = _round_up(num_bins, 64)
    # largest power-of-two feature group with (g*b) % 128 == 0 and the
    # stacked one-hot M dim capped at 2048 (measured best at B=256)
    group = 1
    while (group * 2 * b <= 2048 and (group * 2 * b) % 128 == 0
           and group * 2 <= max(f, 1)) or (group * b) % 128 != 0:
        group *= 2
        if group > 128:
            raise ValueError(f"num_bins={num_bins} unsupported")
    ft_cap = max(group, 8192 // b // group * group)
    ft = min(_round_up(f, group), ft_cap)
    f_pad = _round_up(f, ft)
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    kr = math.gcd(row_block, 4096)

    grid = (f_pad // ft, n // kr)
    out = pl.pallas_call(
        functools.partial(_hist_leaves_q8_kernel, num_features=ft,
                          num_bins=b, group=group),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * 128,
            bytes_accessed=f_pad * n + n * 9 + f_pad * b * 512,
            transcendentals=0),
        interpret=interpret,
    )(bins_t, wch, ch.astype(jnp.int8).reshape(1, n))

    out = out[:, :Q_LEAF_CHANNELS * _QCB].reshape(f_pad, b,
                                                  Q_LEAF_CHANNELS, _QCB)
    return jnp.transpose(out, (2, 0, 1, 3))[:, :f, :num_bins, :]


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_block", "interpret",
                                    "packed"))
def _build_histogram_pallas_leaves_q8_dma(bins_t, wch, ch, *, num_bins,
                                          row_block, interpret, packed):
    n = wch.shape[1]
    ch2 = ch.astype(jnp.int8).reshape(1, n)
    out, f_pad = _leaves_dma_call(
        bins_t, wch, ch2, num_bins=num_bins, interpret=interpret,
        packed=packed, m_cap=2048, kr0=4096, make_w128=_make_w128_q8,
        onehot_dtype=jnp.int8, acc_dtype=jnp.int32,
        out_dtype=jnp.int32, row_block=row_block)
    f = bins_t.shape[0]
    b = out.shape[0] // f_pad
    out = out[:, :Q_LEAF_CHANNELS * _QCB].reshape(f_pad, b,
                                                  Q_LEAF_CHANNELS, _QCB)
    return jnp.transpose(out, (2, 0, 1, 3))[:, :f, :num_bins, :]


def build_histogram_pallas_leaves_q8(bins_t: jnp.ndarray, wch: jnp.ndarray,
                                     ch: jnp.ndarray, *, num_bins: int,
                                     row_block: int = DEFAULT_ROW_BLOCK,
                                     interpret: bool = None,
                                     pipeline: str = None,
                                     bins_packed: bool = False
                                     ) -> jnp.ndarray:
    """(Q_LEAF_CHANNELS, F, B, 3) int32 histograms of 42 leaf channels.

    Args:
      bins_t: (F, N) uint8 bin codes — or, with ``bins_packed``, the
        (F, N//2) nibble-packed bytes from :func:`pack_bins4`.  N must be
        a multiple of ``row_block``.
      wch: (8, N) int8 FEATURE-MAJOR rows [g_q, h_q, count, 0*5] —
        static per tree (quantize once; no per-wave rewrite).
      ch: (N,) int8 leaf channel in [0, Q_LEAF_CHANNELS), or -1 for
        inactive rows (they contribute nothing regardless of their
        weight lanes).
      num_bins: static global bin count B (<= 256).
      interpret / pipeline / bins_packed: as :func:`build_histogram_pallas`.
    Returns:
      (42, F, B, 3) int32: channel sums (sum g_q, sum h_q, count) —
      exact integer sums, so every pipeline/packing variant is
      bit-for-bit identical.
    """
    f, np_ = bins_t.shape
    n = np_ * 2 if bins_packed else np_
    _check_rows(n, row_block, "build_histogram_pallas_leaves_q8")
    _check_same_rows("build_histogram_pallas_leaves_q8", n,
                     wch=wch.shape[1], ch=ch.shape[0])
    pipeline = resolve_pipeline(pipeline)
    interpret = resolve_interpret(interpret)
    if bins_packed:
        if num_bins > PACK4_MAX_BINS:
            raise ValueError(f"bins_packed requires num_bins <= "
                             f"{PACK4_MAX_BINS}, got {num_bins}")
        pipeline = "dma"
    _note_kernel(f"ops/hist_kernel/leaves_q8/{pipeline}"
                 + ("/packed4" if bins_packed else ""),
                 f * np_ * bins_t.dtype.itemsize + n * 9 +
                 Q_LEAF_CHANNELS * f * num_bins * 3 * 4)
    if pipeline == "dma":
        return _build_histogram_pallas_leaves_q8_dma(
            bins_t, wch, ch, num_bins=num_bins, row_block=row_block,
            interpret=interpret, packed=bins_packed)
    return _build_histogram_pallas_leaves_q8_bs(
        bins_t, wch, ch, num_bins=num_bins, row_block=row_block,
        interpret=interpret)


# ---------------------------------------------------------------------------
# Wave row update: one fused pass assigning rows to their post-wave leaf
# and leaf channel.  The XLA form (learner/wave.py's W sequential masked
# wheres) launches ~W fused loop nests over N rows — per-nest overhead
# alone costs ~30 ms/wave at 10.5M rows.  Here the W winning feature
# columns are gathered once (a cheap major-axis take) and ONE kernel
# sweeps the rows, keeping rl/ch blocks VMEM-resident across the W
# per-split updates.  Numeric splits only — the categorical membership
# lookup is a per-row gather Mosaic cannot express; wave.py keeps the XLA
# path when categorical features or EFB bundles are present.
# ---------------------------------------------------------------------------


def _row_update_kernel(cols_ref, rl_ref, tab_ref, rl_out, ch_out, *,
                       w: int):
    rl = rl_ref[...].astype(jnp.int32)            # (8, KRD)
    ch = jnp.full_like(rl, -1)
    for j in range(w):
        col = cols_ref[j].astype(jnp.int32)       # (8, KRD)
        thr = tab_ref[0, j]
        nanb = tab_ref[1, j]
        dlft = tab_ref[2, j]
        small = tab_ref[3, j]
        selj = tab_ref[4, j]
        newid = tab_ref[5, j]
        act = tab_ref[6, j]
        # integer-valued go_left: Mosaic cannot broadcast a scalar bool
        # through a packed vector (i8->i1 trunci), so the select stays in
        # int32 land and the flags compare as integers
        go_left = jnp.where(col == nanb, dlft,
                            (col <= thr).astype(jnp.int32))
        upd = (rl == selj) & (act > 0)
        ch = jnp.where(upd & (go_left == small), j, ch)
        rl = jnp.where(upd & (go_left == 0), newid, rl)
    rl_out[...] = rl
    ch_out[...] = ch.astype(jnp.int8)


def _row_update_kernel_dma(cols_hbm, rl_hbm, tab_ref, rl_out, ch_out, *,
                           w: int, krd: int, nsteps: int):
    """Fully manual DMA pipeline of the wave row update: the W winning
    feature columns and the row->leaf vector stream in through
    double-buffered async copies, the updated rl/ch blocks stream back
    out, and the copy of block j+1 overlaps block j's W-split sweep —
    the kernel is pure VPU work, so it is bandwidth-bound end to end."""

    def body(cbuf, ibuf, robuf, cobuf, csem, isem, rosem, cosem):
        def cols_dma(slot, j):
            return pltpu.make_async_copy(
                cols_hbm.at[:, :, pl.ds(j * krd, krd)], cbuf.at[slot],
                csem.at[slot])

        def rl_dma(slot, j):
            return pltpu.make_async_copy(
                rl_hbm.at[:, pl.ds(j * krd, krd)], ibuf.at[slot],
                isem.at[slot])

        def ro_dma(slot, j):
            return pltpu.make_async_copy(
                robuf.at[slot], rl_out.at[:, pl.ds(j * krd, krd)],
                rosem.at[slot])

        def co_dma(slot, j):
            return pltpu.make_async_copy(
                cobuf.at[slot], ch_out.at[:, pl.ds(j * krd, krd)],
                cosem.at[slot])

        cols_dma(0, 0).start()
        rl_dma(0, 0).start()

        def step(j, carry):
            slot = j % 2

            @pl.when(j + 1 < nsteps)
            def _():
                cols_dma((j + 1) % 2, j + 1).start()
                rl_dma((j + 1) % 2, j + 1).start()

            cols_dma(slot, j).wait()
            rl_dma(slot, j).wait()
            rl = ibuf[slot].astype(jnp.int32)            # (8, KRD)
            ch = jnp.full_like(rl, -1)
            for jj in range(w):
                col = cbuf[slot, jj].astype(jnp.int32)   # (8, KRD)
                thr = tab_ref[0, jj]
                nanb = tab_ref[1, jj]
                dlft = tab_ref[2, jj]
                small = tab_ref[3, jj]
                selj = tab_ref[4, jj]
                newid = tab_ref[5, jj]
                act = tab_ref[6, jj]
                go_left = jnp.where(col == nanb, dlft,
                                    (col <= thr).astype(jnp.int32))
                upd = (rl == selj) & (act > 0)
                ch = jnp.where(upd & (go_left == small), jj, ch)
                rl = jnp.where(upd & (go_left == 0), newid, rl)

            # the out buffers double-buffer too: wait this slot's
            # previous write-back before overwriting it
            @pl.when(j >= 2)
            def _():
                ro_dma(slot, j - 2).wait()
                co_dma(slot, j - 2).wait()

            robuf[slot] = rl
            cobuf[slot] = ch.astype(jnp.int8)
            ro_dma(slot, j).start()
            co_dma(slot, j).start()
            return carry

        jax.lax.fori_loop(0, nsteps, step, 0)
        # drain the last two in-flight write-backs
        if nsteps >= 2:
            ro_dma((nsteps - 2) % 2, nsteps - 2).wait()
            co_dma((nsteps - 2) % 2, nsteps - 2).wait()
        ro_dma((nsteps - 1) % 2, nsteps - 1).wait()
        co_dma((nsteps - 1) % 2, nsteps - 1).wait()

    pl.run_scoped(body,
                  pltpu.VMEM((2, w, 8, krd), cols_hbm.dtype),
                  pltpu.VMEM((2, 8, krd), rl_hbm.dtype),
                  pltpu.VMEM((2, 8, krd), jnp.int32),
                  pltpu.VMEM((2, 8, krd), jnp.int8),
                  pltpu.SemaphoreType.DMA((2,)),
                  pltpu.SemaphoreType.DMA((2,)),
                  pltpu.SemaphoreType.DMA((2,)),
                  pltpu.SemaphoreType.DMA((2,)))


@functools.partial(jax.jit, static_argnames=("row_block", "interpret"))
def _wave_row_update_dma(cols_w: jnp.ndarray, rl: jnp.ndarray,
                         tab: jnp.ndarray, *,
                         row_block: int = DEFAULT_ROW_BLOCK,
                         interpret: bool = False):
    w, n = cols_w.shape
    kr = math.gcd(row_block, 4096)
    krd = kr // 8
    nd = n // 8
    cols3 = cols_w.reshape(w, 8, nd)
    rl2 = rl.astype(jnp.int32).reshape(8, nd)
    rl_new, ch = pl.pallas_call(
        functools.partial(_row_update_kernel_dma, w=w, krd=krd,
                          nsteps=n // kr),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, nd), jnp.int32),
            jax.ShapeDtypeStruct((8, nd), jnp.int8),
        ],
        interpret=interpret,
    )(cols3, rl2, tab)
    return rl_new.reshape(n), ch.reshape(n)


@functools.partial(jax.jit, static_argnames=("row_block", "interpret"))
def _wave_row_update_bs(cols_w: jnp.ndarray, rl: jnp.ndarray,
                        tab: jnp.ndarray, *,
                        row_block: int = DEFAULT_ROW_BLOCK,
                        interpret: bool = False):
    """Implicit-pipeline (BlockSpec-fetched) row update (v1 layout)."""
    w, n = cols_w.shape
    kr = math.gcd(row_block, 4096)
    krd = kr // 8
    nd = n // 8
    cols3 = cols_w.reshape(w, 8, nd)
    rl2 = rl.astype(jnp.int32).reshape(8, nd)

    grid = (n // kr,)
    rl_new, ch = pl.pallas_call(
        functools.partial(_row_update_kernel, w=w),
        grid=grid,
        in_specs=[
            pl.BlockSpec((w, 8, krd), lambda i: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, krd), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((8, krd), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, krd), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, nd), jnp.int32),
            jax.ShapeDtypeStruct((8, nd), jnp.int8),
        ],
        interpret=interpret,
    )(cols3, rl2, tab)
    return rl_new.reshape(n), ch.reshape(n)


def wave_row_update_pallas(cols_w: jnp.ndarray, rl: jnp.ndarray,
                           tab: jnp.ndarray, *,
                           row_block: int = DEFAULT_ROW_BLOCK,
                           interpret: bool = None, pipeline: str = None):
    """Apply a wave's W numeric splits to every row in one fused pass.

    Args:
      cols_w: (W, N) uint8 — the wave's winning feature columns
        (``jnp.take(X_T, feat, axis=0)``), N a multiple of ``row_block``.
      rl: (N,) integer row->leaf vector (any integer dtype).
      tab: (8, W) int32 per-split table: rows are [threshold_bin,
        nan_bin (-1 = none), default_left, left_is_smaller, split_leaf,
        new_right_id, active, unused].
      interpret / pipeline: as :func:`build_histogram_pallas` ("dma"
        streams the column blocks AND the rl/ch write-backs through
        double-buffered async copies).
    Returns:
      (rl_new int32 (N,), ch int8 (N,)) — post-wave leaf ids and the
      smaller-child channel (-1 = row not in any split's smaller child).
    """
    w, n = cols_w.shape
    _check_rows(n, row_block, "wave_row_update_pallas")
    _check_same_rows("wave_row_update_pallas", n, rl=rl.shape[0])
    pipeline = resolve_pipeline(pipeline)
    interpret = resolve_interpret(interpret)
    _note_kernel(f"ops/hist_kernel/row_update/{pipeline}",
                 w * n * cols_w.dtype.itemsize + n * 4 + n * 5)
    if pipeline == "dma":
        return _wave_row_update_dma(cols_w, rl, tab, row_block=row_block,
                                    interpret=interpret)
    return _wave_row_update_bs(cols_w, rl, tab, row_block=row_block,
                               interpret=interpret)


def wave_trial_channels_pallas(cols_w: jnp.ndarray, rl: jnp.ndarray,
                               sel_leaves: jnp.ndarray, thr: jnp.ndarray,
                               nan_bin: jnp.ndarray, default_left: jnp.ndarray,
                               left_smaller: jnp.ndarray, active: jnp.ndarray,
                               *, row_block: int = DEFAULT_ROW_BLOCK,
                               interpret: bool = None,
                               pipeline: str = None) -> jnp.ndarray:
    """TRIAL leaf-channel assignment for W *candidate* splits.

    Same fused kernel as :func:`wave_row_update_pallas`, but the splits are
    NOT committed: each candidate's ``new_right_id`` is set to its own
    split leaf, so ``rl`` is provably unchanged and only the smaller-child
    channel vector comes back.  The wave grower's exact endgame uses this
    to precompute the frontier candidates' smaller-child histograms in one
    batched pass before the sequential best-first selection commits any of
    them (learner/wave.py).

    Returns ``ch`` int8 (N,): the candidate slot whose smaller side the
    row would take, or -1.
    """
    tab = jnp.stack([thr, nan_bin, default_left.astype(jnp.int32),
                     left_smaller.astype(jnp.int32), sel_leaves, sel_leaves,
                     active.astype(jnp.int32), jnp.zeros_like(thr)])
    _, ch = wave_row_update_pallas(cols_w, rl, tab, row_block=row_block,
                                   interpret=interpret, pipeline=pipeline)
    return ch
