"""Histogram construction: the hot op of histogram-based GBDT.

TPU-native replacement for the reference's histogram kernels
(reference: src/io/dense_bin.hpp:18 templated ``ConstructHistogram`` inner
loops — the hottest CPU code; src/treelearner/ocl/histogram256.cl and
src/treelearner/kernels/histogram_16_64_256.cu — the GPU equivalents with
local-memory float atomics).

TPUs have no fast global atomics, so scatter-add is reformulated:

* ``onehot`` — one-hot expansion of bin codes contracted against the
  (grad, hess, count) rows on the MXU: ``(3, N) @ (N, F*B)``.  This is the
  TPU-idiomatic formulation — the histogram becomes a matmul, chunked over
  rows via ``lax.scan`` to bound memory (the one-hot tile lives only inside
  one chunk).  The Pallas kernel in ``histogram_pallas.py`` fuses the one-hot
  materialization into VMEM.
* ``segment`` — flat ``scatter-add`` (XLA lowers to sorted segment sums);
  portable reference path used on CPU and in tests.
* ``packed4`` — joint-nibble scatter for ``max_bin <= 16`` data: a
  feature PAIR shares one byte (two 4-bit codes, the reference
  dense_bin.hpp 4-bit layout), one scatter builds the pair's joint
  256-bin histogram and both 16-bin marginals fall out as cheap sums —
  half the scatter volume, ~2x on the scatter-bound CPU backend
  (PERF.md round 10).  The device analog is the Pallas kernels'
  ``bins_packed`` path (histogram_pallas.pack_bins4).

All accumulation is float32 (like the reference GPU learner's single-precision
``gpu_hist_t``, gpu_tree_learner.h:79; the reference CPU path uses float64 —
``tpu_double_precision_gain`` upgrades gain math, mirroring ``gpu_use_dp``).
Counts ride in channel 2 as float32, exact up to 2^24 rows per chunk.

Layout: histograms are ``(F, B, 3)`` with channels (sum_grad, sum_hess,
count).  The reference's (grad, hess) interleaved layout is bin.h:32
``hist_t``; count is implicit there via hessian when unweighted, explicit
here because TPU f32 hessian sums are not exact counts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["build_histogram", "build_histogram_leaves", "histogram_subtract",
           "split_hi_lo"]


def split_hi_lo(v: jnp.ndarray):
    """Split f32 v into (hi, lo) with v == hi + lo and hi exactly
    representable in bf16.  TPU matmuls round f32 operands to bf16 at
    DEFAULT precision; carrying (hi, lo) channels keeps the contraction
    f32-exact at bf16 speed (same trick as the Pallas kernel).  The mask is
    integer ops because XLA folds a bf16 round-trip to zero under jit."""
    bits = jax.lax.bitcast_convert_type(v, jnp.uint32)
    hi = jax.lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000),
                                      jnp.float32)
    return hi, v - hi


def _hist_onehot_chunk(bins_chunk: jnp.ndarray, w_chunk: jnp.ndarray,
                       num_bins: int) -> jnp.ndarray:
    """One chunk's histogram via MXU matmul.

    bins_chunk: (n, F) integer codes; w_chunk: (n, 3) f32 weights.
    Returns (F, B, 3) f32.
    """
    n, f = bins_chunk.shape
    onehot = (bins_chunk[:, :, None] ==
              jnp.arange(num_bins, dtype=bins_chunk.dtype)[None, None, :])
    onehot = onehot.reshape(n, f * num_bins).astype(jnp.float32)
    # bf16-exact hi/lo weight channels: the one-hot operand is exact 0/1,
    # so splitting the weights recovers f32-exact sums on the TPU MXU
    g_hi, g_lo = split_hi_lo(w_chunk[:, 0])
    h_hi, h_lo = split_hi_lo(w_chunk[:, 1])
    w6 = jnp.stack([g_hi, g_lo, h_hi, h_lo, w_chunk[:, 2]], axis=0)  # (5, n)
    # (5, n) @ (n, F*B) -> (5, F*B): contraction over rows rides the MXU
    flat = jax.lax.dot_general(
        w6, onehot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    flat3 = jnp.stack([flat[0] + flat[1], flat[2] + flat[3], flat[4]], axis=0)
    return flat3.T.reshape(f, num_bins, 3)


def _hist_segment_chunk(bins_chunk: jnp.ndarray, w_chunk: jnp.ndarray,
                        num_bins: int) -> jnp.ndarray:
    """Scatter-add formulation (portable; CPU-friendly)."""
    n, f = bins_chunk.shape
    ids = bins_chunk.astype(jnp.int32) + (jnp.arange(f, dtype=jnp.int32) *
                                          num_bins)[None, :]
    flat = jnp.zeros((f * num_bins, 3), dtype=jnp.float32)
    updates = jnp.broadcast_to(w_chunk[:, None, :], (n, f, 3)).reshape(-1, 3)
    flat = flat.at[ids.reshape(-1)].add(updates, mode="drop")
    return flat.reshape(f, num_bins, 3)


def _hist_packed4_chunk(bins_chunk: jnp.ndarray, w_chunk: jnp.ndarray,
                        num_bins: int) -> jnp.ndarray:
    """Joint-nibble scatter formulation for max_bin<=16 data (the XLA
    analog of the reference's 4-bit dense_bin.hpp bins and of the Pallas
    kernels' packed layout).  Feature pairs (2j, 2j+1) share one byte
    (lo | hi<<4); ONE scatter of n*ceil(F/2) updates builds the pairs'
    JOINT 256-bin histograms, and both marginals fall out as cheap
    16-way sums — half the scatter volume of the ``segment`` path, which
    is what the scatter-bound CPU backend pays for."""
    n, f = bins_chunk.shape
    fp = (f + 1) // 2
    lo = bins_chunk[:, 0::2].astype(jnp.int32)
    hi = bins_chunk[:, 1::2].astype(jnp.int32)
    if f % 2:
        # odd F: the last feature pairs with a virtual all-zeros column
        # whose marginal is discarded below
        hi = jnp.concatenate([hi, jnp.zeros((n, 1), jnp.int32)], axis=1)
    ids = (lo | (hi << 4)) + (jnp.arange(fp, dtype=jnp.int32) * 256)[None, :]
    flat = jnp.zeros((fp * 256, 3), dtype=jnp.float32)
    upd = jnp.broadcast_to(w_chunk[:, None, :], (n, fp, 3)).reshape(-1, 3)
    joint = flat.at[ids.reshape(-1)].add(upd, mode="drop")
    joint = joint.reshape(fp, 16, 16, 3)          # [pair, hi bin, lo bin]
    lo_h = joint.sum(axis=1)                      # (fp, 16, 3) even feats
    hi_h = joint.sum(axis=2)                      # (fp, 16, 3) odd feats
    out = jnp.stack([lo_h, hi_h], axis=1).reshape(fp * 2, 16, 3)
    return out[:f, :num_bins, :]


def _auto_impl() -> str:
    # route through the probing wrapper: a broken TPU plugin raises
    # RuntimeError from the raw jax.default_backend() before any CPU
    # fallback can engage (utils/backend.py)
    from ..utils.backend import default_backend
    return "onehot" if default_backend() == "tpu" else "segment"


@functools.partial(jax.jit, static_argnames=("num_bins", "impl", "rows_per_chunk"))
def build_histogram(bins: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                    mask: jnp.ndarray, *, num_bins: int,
                    impl: str = "auto", rows_per_chunk: int = 0) -> jnp.ndarray:
    """Build per-feature (grad, hess, count) histograms over masked rows.

    Replaces Dataset::ConstructHistograms (src/io/dataset.cpp:1111) +
    Bin::ConstructHistogram (dense_bin.hpp).  ``mask`` is 1.0 for rows in the
    target leaf (and in-bag), 0.0 otherwise — leaf membership masking replaces
    the reference's DataPartition row-index gather, keeping shapes static
    under jit.

    Args:
      bins: (N, F) integer bin codes (uint8/uint16/int32).
      grad, hess: (N,) float32 gradients/hessians.
      mask: (N,) float32 row mask.
      num_bins: static global bin count B.
    Returns:
      (F, B, 3) float32 histogram.
    """
    if impl == "auto":
        impl = _auto_impl()
    n, f = bins.shape
    w = jnp.stack([grad * mask, hess * mask, mask], axis=-1)  # (N, 3)

    if impl == "packed4":
        if num_bins > 16:
            raise ValueError("impl='packed4' requires num_bins <= 16 "
                             f"(got {num_bins}); use segment/onehot")
        chunk_fn = _hist_packed4_chunk
    elif impl == "onehot":
        chunk_fn = _hist_onehot_chunk
    else:
        chunk_fn = _hist_segment_chunk

    if rows_per_chunk <= 0:
        # bound the one-hot tile to ~64 MB f32
        rows_per_chunk = max(256, int((64 << 20) / 4 / max(1, f * num_bins)))
    if n <= rows_per_chunk:
        return chunk_fn(bins, w, num_bins)

    num_chunks = -(-n // rows_per_chunk)
    pad = num_chunks * rows_per_chunk - n
    bins_p = jnp.pad(bins, ((0, pad), (0, 0)))
    w_p = jnp.pad(w, ((0, pad), (0, 0)))  # padded rows have mask 0
    bins_c = bins_p.reshape(num_chunks, rows_per_chunk, f)
    w_c = w_p.reshape(num_chunks, rows_per_chunk, 3)

    def scan_body(acc, chunk):
        b, ww = chunk
        return acc + chunk_fn(b, ww, num_bins), None

    init = jnp.zeros((f, num_bins, 3), dtype=jnp.float32)
    hist, _ = jax.lax.scan(scan_body, init, (bins_c, w_c))
    return hist


@functools.partial(jax.jit, static_argnames=("num_channels", "num_bins",
                                             "impl"))
def build_histogram_leaves(bins: jnp.ndarray, grad: jnp.ndarray,
                           hess: jnp.ndarray, mask: jnp.ndarray,
                           ch: jnp.ndarray, *, num_channels: int,
                           num_bins: int, impl: str = "auto") -> jnp.ndarray:
    """(K, F, B, 3) histograms of K leaf channels in one logical pass.

    Portable counterpart of ``build_histogram_pallas_leaves``: rows carry a
    leaf-channel id ``ch`` in [0, K) (or -1 = skip).  The ``segment`` path
    folds the channel into the scatter index; the ``onehot`` path loops the
    K channels (still one XLA program).  Used by the wave grower
    (learner/wave.py) off-TPU and in tests.
    """
    if impl == "auto":
        impl = _auto_impl()
    if impl == "packed4":
        impl = "segment"  # the joint-nibble trick has no leaf-channel form
    n, f = bins.shape
    k = num_channels
    w = jnp.stack([grad * mask, hess * mask, mask], axis=-1)      # (N, 3)
    if impl == "segment":
        def chunk_hist(bins_c, w_c, ch_c):
            m = bins_c.shape[0]
            ids = (ch_c.astype(jnp.int32)[:, None] * f +
                   jnp.arange(f, dtype=jnp.int32)[None, :]) * num_bins + \
                bins_c.astype(jnp.int32)
            ids = jnp.where(ch_c[:, None] >= 0, ids, k * f * num_bins)
            flat = jnp.zeros((k * f * num_bins, 3), dtype=jnp.float32)
            upd = jnp.broadcast_to(w_c[:, None, :], (m, f, 3)).reshape(-1, 3)
            return flat.at[ids.reshape(-1)].add(
                upd, mode="drop").reshape(k, f, num_bins, 3)

        # bound the (rows, F, 3) updates tensor like build_histogram does
        rows_per_chunk = max(256, int((64 << 20) / 12 / max(1, f)))
        if n <= rows_per_chunk:
            return chunk_hist(bins, w, ch)
        num_chunks = -(-n // rows_per_chunk)
        pad = num_chunks * rows_per_chunk - n
        bins_p = jnp.pad(bins, ((0, pad), (0, 0)))
        w_p = jnp.pad(w, ((0, pad), (0, 0)))
        ch_p = jnp.pad(ch, (0, pad), constant_values=-1)

        def scan_body(acc, c):
            b_, w_, c_ = c
            return acc + chunk_hist(b_, w_, c_), None

        init = jnp.zeros((k, f, num_bins, 3), dtype=jnp.float32)
        hist, _ = jax.lax.scan(
            scan_body, init,
            (bins_p.reshape(num_chunks, rows_per_chunk, f),
             w_p.reshape(num_chunks, rows_per_chunk, 3),
             ch_p.reshape(num_chunks, rows_per_chunk)))
        return hist

    def one(c):
        m = mask * (ch == c).astype(jnp.float32)
        return build_histogram(bins, grad, hess, m, num_bins=num_bins,
                               impl=impl)

    return jnp.stack([one(c) for c in range(k)])


def histogram_subtract(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """The histogram subtraction trick: sibling = parent - child
    (reference serial_tree_learner.cpp:311-320, FeatureHistogram::Subtract)."""
    return parent - child
