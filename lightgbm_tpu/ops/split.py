"""Vectorized split finding over histogram bins.

TPU-native replacement for FeatureHistogram's sequential threshold scan
(reference: src/treelearner/feature_histogram.hpp
``FindBestThresholdSequentially`` — a per-bin loop in two directions — and
``FindBestThresholdCategoricalInner``).  On TPU the scan becomes
bidirectional ``cumsum`` over the bin axis, all features at once; the
missing-direction double scan becomes two masked gain tensors; the argmax
replaces the reference's SplitInfo comparison ladder.

Gain / leaf-output closed forms follow feature_histogram.hpp:
  ThresholdL1(G, l1) = sign(G) * max(|G| - l1, 0)
  leaf_gain(G, H)    = ThresholdL1(G)^2 / (H + l2)
  output(G, H)       = -ThresholdL1(G) / (H + l2)   (clipped by max_delta_step)

Histograms arrive as (F, B, 3) float32 with channels (sum_grad, sum_hess,
count); our histograms keep every bin (no most-frequent-bin elision), so the
reference's ``Dataset::FixHistogram`` restore step is unnecessary.

Layout: internally the scan runs CHANNEL-SPLIT — three (F, B) planes with
the bin axis in the TPU lane dimension — because a trailing size-3 axis
would tile at 3/128 lane occupancy and make every cumsum/compare ~40x
slower than the arithmetic warrants.  The (F, B, 3) interface stays (it is
the histogram pool's storage layout); the transpose happens once at entry.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = ["SplitParams", "FeatureSplits", "best_split_per_feature",
           "leaf_output", "leaf_output_smoothed",
           "monotone_penalty_factor", "BIG"]

NEG_INF = -1e30


class SplitParams(NamedTuple):
    """Static split-finding hyperparameters (subset of Config)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    path_smooth: float = 0.0
    use_monotone: bool = False     # any monotone_constraints nonzero
    monotone_penalty: float = 0.0
    # categorical split search (feature_histogram.hpp
    # FindBestThresholdCategoricalInner)
    max_cat_to_onehot: int = 4
    max_cat_threshold: int = 32
    min_data_per_group: int = 100
    use_cat_subset: bool = False   # any categorical feature needs the
                                   # sorted-subset search (num_bin > onehot)
    cat_idx: tuple = ()            # STATIC positions of categorical
                                   # features — the sorted-subset search
                                   # (argsort per candidate) runs on this
                                   # slice only, not all F features
    # cost-effective gradient boosting (cost_effective_gradient_boosting
    # .hpp DeltaGain — upstream spells the method ``DetlaGain``):
    # gain -= tradeoff*(penalty_split*leaf_count +
    # coupled feature penalty when the feature is not yet used)
    use_cegb: bool = False
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    feature_fraction_bynode: float = 1.0  # ColSampler by-node sampling
    extra_trees: bool = False  # one random threshold per feature per node
    any_cat: bool = True       # trace the categorical split search at all

BIG = 1e30  # "unbounded" leaf-output constraint sentinel

# SplitParams fields that MAY arrive as traced jax scalars instead of
# Python numbers: the multi-model trainer (lightgbm_tpu/multitrain/)
# sweeps them along a vmapped model axis, so one compiled program serves
# every hyperparameter variant.  They only ever flow through jnp
# arithmetic/comparisons below — never Python control flow — which keeps
# the traced and the constant-folded programs value-identical.
TRACEABLE_PARAMS = ("lambda_l1", "lambda_l2", "min_sum_hessian_in_leaf",
                    "min_data_in_leaf", "min_gain_to_split")


def params_are_static(params: "SplitParams") -> bool:
    """True when every traceable field is a plain Python number (the
    jit-with-static-params fast path); False when any is a jax value."""
    return not any(isinstance(getattr(params, k), (jax.Array, jax.core.Tracer))
                   for k in TRACEABLE_PARAMS)


class FeatureSplits(NamedTuple):
    """Per-feature best split (the vectorized SplitInfo,
    reference src/treelearner/split_info.hpp)."""
    gain: jnp.ndarray          # (F,) relative gain, NEG_INF when invalid
    threshold_bin: jnp.ndarray  # (F,) int32 bin threshold (or category bin)
    default_left: jnp.ndarray  # (F,) bool — direction for missing values
    left_sum: jnp.ndarray      # (F, 3)
    right_sum: jnp.ndarray     # (F, 3)
    cat_member: jnp.ndarray    # (F, B) bool — categorical LEFT-side bins


def _threshold_l1(g: jnp.ndarray, l1: float) -> jnp.ndarray:
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_gain(g: jnp.ndarray, h: jnp.ndarray, l1: float, l2: float) -> jnp.ndarray:
    t = _threshold_l1(g, l1)
    return jnp.where(h + l2 > 0, t * t / (h + l2), 0.0)


def leaf_output(g: jnp.ndarray, h: jnp.ndarray, params: SplitParams) -> jnp.ndarray:
    """Closed-form leaf value (feature_histogram.hpp
    ``CalculateSplittedLeafOutput``)."""
    t = _threshold_l1(g, params.lambda_l1)
    out = jnp.where(h + params.lambda_l2 > 0, -t / (h + params.lambda_l2), 0.0)
    if params.max_delta_step > 0.0:
        out = jnp.clip(out, -params.max_delta_step, params.max_delta_step)
    return out


def leaf_output_smoothed(g, h, cnt, parent_out, params: SplitParams):
    """Leaf value with path smoothing (feature_histogram.hpp
    ``CalculateSplittedLeafOutput`` USE_SMOOTHING branch): the raw output
    shrinks toward the parent leaf's output by smooth/(n + smooth)."""
    t = _threshold_l1(g, params.lambda_l1)
    out = jnp.where(h + params.lambda_l2 > 0, -t / (h + params.lambda_l2), 0.0)
    # the reference clips the RAW output to +-max_delta_step first and
    # blends with the parent after (CalculateSplittedLeafOutput applies the
    # clip before the USE_SMOOTHING mix) — order matters when both are set
    if params.max_delta_step > 0.0:
        out = jnp.clip(out, -params.max_delta_step, params.max_delta_step)
    if params.path_smooth > 0.0:
        f = cnt / (cnt + params.path_smooth)
        out = out * f + parent_out * (1.0 - f)
    return out


def _gain_given_output(g, h, out, l1: float, l2: float):
    """Objective improvement of a leaf FORCED to value ``out`` (reference
    feature_histogram.hpp ``GetLeafGainGivenOutput``) — equals the standard
    closed-form gain when ``out`` is the unconstrained optimum."""
    t = _threshold_l1(g, l1)
    return -(2.0 * t * out + (h + l2) * out * out)


def monotone_penalty_factor(depth, penalty: float):
    """Gain multiplier for splits on monotone features
    (reference monotone_constraints.hpp:355
    ``ComputeMonotoneSplitGainPenalty``)."""
    eps = 1e-15
    d = depth.astype(jnp.float32)
    return jnp.where(penalty >= d + 1.0, eps,
                     jnp.where(penalty <= 1.0,
                               1.0 - penalty / jnp.exp2(d) + eps,
                               1.0 - jnp.exp2(penalty - 1.0 - d) + eps))


def best_split_per_feature(hist: jnp.ndarray, parent_sum: jnp.ndarray,
                           num_bins: jnp.ndarray, is_cat: jnp.ndarray,
                           has_nan: jnp.ndarray,
                           params: SplitParams,
                           monotone: Optional[jnp.ndarray] = None,
                           bound: Optional[jnp.ndarray] = None,
                           depth: Optional[jnp.ndarray] = None,
                           cegb_penalty: Optional[jnp.ndarray] = None,
                           gain_scale: Optional[jnp.ndarray] = None,
                           parent_out: Optional[jnp.ndarray] = None,
                           rand_bins: Optional[jnp.ndarray] = None
                           ) -> FeatureSplits:
    """Dispatch wrapper: static params take the jitted fast path (params
    hashable -> jit static arg); traced params (TRACEABLE_PARAMS carrying
    jax scalars, see multitrain) inline into the caller's trace."""
    if params_are_static(params):
        return _best_split_jit(hist, parent_sum, num_bins, is_cat, has_nan,
                               params, monotone, bound, depth, cegb_penalty,
                               gain_scale, parent_out, rand_bins)
    return _best_split_impl(hist, parent_sum, num_bins, is_cat, has_nan,
                            params, monotone, bound, depth, cegb_penalty,
                            gain_scale, parent_out, rand_bins)


def _best_split_impl(hist: jnp.ndarray, parent_sum: jnp.ndarray,
                     num_bins: jnp.ndarray, is_cat: jnp.ndarray,
                     has_nan: jnp.ndarray,
                     params: SplitParams,
                     monotone: Optional[jnp.ndarray] = None,
                     bound: Optional[jnp.ndarray] = None,
                     depth: Optional[jnp.ndarray] = None,
                     cegb_penalty: Optional[jnp.ndarray] = None,
                     gain_scale: Optional[jnp.ndarray] = None,
                     parent_out: Optional[jnp.ndarray] = None,
                     rand_bins: Optional[jnp.ndarray] = None
                     ) -> FeatureSplits:
    """Best split per feature from one leaf's histograms.

    Args:
      hist: (F, B, 3) float32 (grad, hess, count) histogram of the leaf.
      parent_sum: (3,) leaf totals (grad, hess, count).
      num_bins: (F,) int32 — actual bin count per feature (<= B), including
        the trailing NaN bin when has_nan.
      is_cat: (F,) bool — categorical features use one-vs-rest splits.
      has_nan: (F,) bool — feature's last bin holds NaN values.
      params: static hyperparameters.
      monotone/bound/depth: only read when ``params.use_monotone`` —
        per-feature ±1 constraint directions (F,), the leaf's (min, max)
        output bounds (2,), and the leaf's depth (for monotone_penalty).
    Returns:
      FeatureSplits with per-feature best candidates.

    Feature sub-range scans: F here may be any contiguous SLICE of the
    dataset's feature space — every per-feature operand (hist, num_bins,
    is_cat, has_nan, monotone, cegb_penalty, gain_scale, rand_bins) is
    indexed positionally, so shard-sliced scans (feature-parallel,
    voting, the DP reduce-scatter wave path) pass their block and remap
    the returned LOCAL indices to global feature space themselves.  The
    one exception is ``params.cat_idx``: those STATIC categorical
    positions index full feature space, so slice-scanned callers must
    leave it empty (the sorted-subset search then falls back to scanning
    all F slice columns) or avoid the sliced path for categorical shapes.
    """
    f, b, _ = hist.shape
    l1, l2 = params.lambda_l1, params.lambda_l2
    min_h = params.min_sum_hessian_in_leaf
    mdl = params.min_data_in_leaf
    min_cnt = (mdl.astype(jnp.float32)
               if isinstance(mdl, (jax.Array, jax.core.Tracer))
               else float(mdl))
    use_mc = params.use_monotone
    use_sm = params.path_smooth > 0.0
    use_out = use_mc or use_sm   # gains via explicit (possibly
    #                              constrained/smoothed) outputs
    if use_mc:
        mn, mx = bound[0], bound[1]
        mono = jnp.where(is_cat, 0, monotone)[:, None]           # (F, 1)

    if use_sm:
        # the leaf's own (smoothed) output is the smoothing target of its
        # children and defines the gain shift (GetLeafGain USE_SMOOTHING)
        parent_gain = _gain_given_output(parent_sum[0], parent_sum[1],
                                         parent_out, l1, l2)
    else:
        parent_gain = _leaf_gain(parent_sum[0], parent_sum[1], l1, l2)
    min_gain_shift = parent_gain + params.min_gain_to_split

    bins_r = jnp.arange(b, dtype=jnp.int32)[None, :]            # (1, B)
    nan_bin = (num_bins - 1)[:, None]                            # (F, 1)
    # per-(f,b) validity of a threshold: real-value bins only, and at least
    # one bin must remain on the right
    real_bin = jnp.where(has_nan[:, None], bins_r < nan_bin, bins_r < num_bins[:, None])
    thr_valid = jnp.where(has_nan[:, None],
                          bins_r < nan_bin,             # b in [0, nan_bin-1]
                          bins_r < num_bins[:, None] - 1)
    use_et = params.extra_trees and rand_bins is not None
    if use_et:
        # ExtraTrees (feature_histogram.hpp USE_RAND): evaluate ONE random
        # threshold per feature per node instead of the full bin scan
        thr_valid = thr_valid & (bins_r == rand_bins[:, None])

    # channel-split planes (F, B) — bins ride the lane dimension
    hg, hh, hc = hist[..., 0], hist[..., 1], hist[..., 2]
    # zero out bins beyond each feature's true range so cumsums are clean
    hg_m = jnp.where(real_bin, hg, 0.0)
    hh_m = jnp.where(real_bin, hh, 0.0)
    hc_m = jnp.where(real_bin, hc, 0.0)

    def at_bin(a, idx):
        """(F,) gather of one bin per feature from an (F, B) plane."""
        return jnp.take_along_axis(a, idx[:, None], 1)[:, 0]

    hn_f = has_nan[:, None]                                      # (F, 1)
    nan_g = jnp.where(hn_f, jnp.take_along_axis(hg, nan_bin, 1), 0.0)
    nan_h = jnp.where(hn_f, jnp.take_along_axis(hh, nan_bin, 1), 0.0)
    nan_c = jnp.where(hn_f, jnp.take_along_axis(hc, nan_bin, 1), 0.0)

    cum_g = jnp.cumsum(hg_m, axis=1)                             # (F, B)
    cum_h = jnp.cumsum(hh_m, axis=1)
    cum_c = jnp.cumsum(hc_m, axis=1)
    tot_g, tot_h, tot_c = parent_sum[0], parent_sum[1], parent_sum[2]

    def clamped_out(sg, sh, sc, l2_eff):
        """Split-child output with smoothing and/or constraint clamping
        (CalculateSplittedLeafOutput USE_SMOOTHING / USE_MC)."""
        t = _threshold_l1(sg, l1)
        h_ = sh + l2_eff
        out = jnp.where(h_ > 0, -t / h_, 0.0)
        # clip the raw output BEFORE the smoothing blend (the reference's
        # CalculateSplittedLeafOutput order); monotone clamping stays last
        if params.max_delta_step > 0.0:
            out = jnp.clip(out, -params.max_delta_step, params.max_delta_step)
        if use_sm:
            fac = sc / (sc + params.path_smooth)
            out = out * fac + parent_out * (1.0 - fac)
        return jnp.clip(out, mn, mx) if use_mc else out

    def dir_gain(lg, lh, lc):
        rg, rh, rc = tot_g - lg, tot_h - lh, tot_c - lc
        ok = ((lc >= min_cnt) & (rc >= min_cnt) &
              (lh >= min_h) & (rh >= min_h) & thr_valid)
        if use_out:
            # constrained/smoothed outputs (GetSplitGains USE_MC /
            # USE_SMOOTHING branches, feature_histogram.hpp): gain is
            # evaluated at the actually-deliverable output
            out_l = clamped_out(lg, lh, lc, l2)
            out_r = clamped_out(rg, rh, rc, l2)
            gl = _gain_given_output(lg, lh, out_l, l1, l2)
            gr = _gain_given_output(rg, rh, out_r, l1, l2)
            if use_mc:
                viol = (((mono > 0) & (out_l > out_r)) |
                        ((mono < 0) & (out_l < out_r)))
                ok = ok & jnp.logical_not(viol)
        else:
            gl = _leaf_gain(lg, lh, l1, l2)
            gr = _leaf_gain(rg, rh, l1, l2)
        g = gl + gr - min_gain_shift
        if use_mc and params.monotone_penalty > 0.0:
            pen = monotone_penalty_factor(depth, params.monotone_penalty)
            g = jnp.where(mono != 0, g * pen, g)
        return jnp.where(ok & (g > 0), g, NEG_INF)

    # numerical, missing->right (left = cum of real bins up to b)
    gain_r = dir_gain(cum_g, cum_h, cum_c)
    # numerical, missing->left (NaN bin joins the left side)
    gain_l = dir_gain(cum_g + nan_g, cum_h + nan_h, cum_c + nan_c)
    gain_l = jnp.where(hn_f, gain_l, NEG_INF)

    if params.any_cat:
        # ---- categorical one-vs-rest: category bin b goes left, rest right
        # (feature_histogram.hpp FindBestThresholdCategoricalInner
        # one-hot branch; cat_l2 regularizes)
        cat_l2 = l2 + params.cat_l2
        crg, crh, crc = tot_g - hg_m, tot_h - hh_m, tot_c - hc_m
        if use_out:  # clamp/smooth outputs (no direction check for cats)
            c_out_l = clamped_out(hg_m, hh_m, hc_m, cat_l2)
            c_out_r = clamped_out(crg, crh, crc, cat_l2)
            cgl = _gain_given_output(hg_m, hh_m, c_out_l, l1, cat_l2)
            cgr = _gain_given_output(crg, crh, c_out_r, l1, cat_l2)
        else:
            cgl = _leaf_gain(hg_m, hh_m, l1, cat_l2)
            cgr = _leaf_gain(crg, crh, l1, cat_l2)
        cat_ok = ((hc_m >= min_cnt) & (crc >= min_cnt) &
                  (hh_m >= min_h) & (crh >= min_h) & real_bin)
        if use_et:  # one random category per node (USE_RAND one-hot branch)
            cat_ok = cat_ok & (bins_r == rand_bins[:, None])
        cat_gain = cgl + cgr - min_gain_shift
        cat_gain = jnp.where(cat_ok & (cat_gain > 0), cat_gain, NEG_INF)
        oh_bin = jnp.argmax(cat_gain, axis=1)
        oh_gain = at_bin(cat_gain, oh_bin)
        oh_member = jax.nn.one_hot(oh_bin, b, dtype=jnp.bool_)
        oh_left = jnp.stack([at_bin(hg_m, oh_bin), at_bin(hh_m, oh_bin),
                             at_bin(hc_m, oh_bin)], axis=-1)

        # ---- categorical sorted-subset search (feature_histogram.hpp
        # non-onehot branch): categories ordered by sum_grad/(sum_hess +
        # cat_smooth); prefix subsets scanned from BOTH ends, up to
        # max_cat_threshold categories; the LEFT child takes the subset.
        # The argsort/rank machinery is the single most expensive part of
        # a categorical scan, so it runs ONLY on the static cat columns
        # (params.cat_idx) and scatters back — numeric features never pay
        # for it.
        if params.use_cat_subset:
            ci = jnp.asarray(params.cat_idx, jnp.int32) \
                if params.cat_idx else jnp.arange(f, dtype=jnp.int32)
            nc = len(params.cat_idx) or f
            hgc, hhc, hcc = hg_m[ci], hh_m[ci], hc_m[ci]
            real_bin_c = real_bin[ci]
            rand_bins_c = rand_bins[ci] if use_et else None
            mdpg = float(params.min_data_per_group)
            # candidate categories: count >= cat_smooth (the reference
            # reuses cat_smooth as the per-category min count filter)
            cat_valid = real_bin_c & (hcc >= params.cat_smooth)
            ratio = jnp.where(cat_valid,
                              hgc / (hhc + params.cat_smooth), BIG)
            order = jnp.argsort(ratio, axis=1, stable=True)      # (nc, B)
            rank = jnp.zeros((nc, b), jnp.int32).at[
                jnp.arange(nc)[:, None], order].set(
                jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[None, :],
                                 (nc, b)))
            used = jnp.sum(cat_valid, axis=1).astype(jnp.int32)  # (nc,)
            pos = jnp.arange(b, dtype=jnp.int32)[None, :]        # (1, B)
            pos_used = pos < used[:, None]

            def fwd_bwd(plane):
                """Forward/backward ratio-ordered prefix cumsums of one
                channel plane."""
                sh = jnp.take_along_axis(plane, order, axis=1)
                sh = jnp.where(pos_used, sh, 0.0)
                cumf = jnp.cumsum(sh, axis=1)                    # (F, B)
                total_used = cumf[:, -1:]
                # prefix of the (i+1) LARGEST ratios =
                #   total_used - cumf[used-2-i]
                bidx = used[:, None] - 2 - pos                   # (F, B)
                tb = jnp.take_along_axis(cumf, jnp.clip(bidx, 0, b - 1), 1)
                cumb = total_used - jnp.where(bidx >= 0, tb, 0.0)
                return cumf, cumb

            cumf_g, cumb_g = fwd_bwd(hgc)
            cumf_h, cumb_h = fwd_bwd(hhc)
            cumf_c, cumb_c = fwd_bwd(hcc)

            max_pos = jnp.minimum(jnp.minimum(params.max_cat_threshold,
                                              (used[:, None] + 1) // 2),
                                  used[:, None])                 # (F, 1)
            pos_ok = pos < max_pos
            if use_et:  # one random subset size per node (USE_RAND)
                pos_ok = pos_ok & (pos == rand_bins_c[:, None] %
                                   jnp.maximum(max_pos, 1))

            def subset_gain(lg, lh, lc):
                rg, rh, rc = tot_g - lg, tot_h - lh, tot_c - lc
                # group spacing: the reference only evaluates a position
                # once >= min_data_per_group rows accumulated since the
                # last evaluated one; approximated here as crossing a
                # multiple of min_data_per_group in the prefix count
                gcross = jnp.floor(lc / mdpg)
                gprev = jnp.concatenate([jnp.full((nc, 1), -1.0),
                                         gcross[:, :-1]], axis=1)
                ok = (pos_ok & (lc >= min_cnt) & (lh >= min_h) &
                      (rc >= jnp.maximum(min_cnt, mdpg)) &
                      (rh >= min_h) & (gcross > gprev))
                if use_out:
                    o_l = clamped_out(lg, lh, lc, cat_l2)
                    o_r = clamped_out(rg, rh, rc, cat_l2)
                    gl_ = _gain_given_output(lg, lh, o_l, l1, cat_l2)
                    gr_ = _gain_given_output(rg, rh, o_r, l1, cat_l2)
                else:
                    gl_ = _leaf_gain(lg, lh, l1, cat_l2)
                    gr_ = _leaf_gain(rg, rh, l1, cat_l2)
                g = gl_ + gr_ - min_gain_shift
                return jnp.where(ok & (g > 0), g, NEG_INF)

            gain_f = subset_gain(cumf_g, cumf_h, cumf_c)
            gain_bk = subset_gain(cumb_g, cumb_h, cumb_c)
            f_pos = jnp.argmax(gain_f, axis=1)
            f_best = at_bin(gain_f, f_pos)
            b_pos = jnp.argmax(gain_bk, axis=1)
            b_best = at_bin(gain_bk, b_pos)
            use_bk = b_best > f_best
            sub_gain = jnp.where(use_bk, b_best, f_best)
            sub_pos = jnp.where(use_bk, b_pos, f_pos)
            sub_left = jnp.where(
                use_bk[:, None],
                jnp.stack([at_bin(cumb_g, b_pos), at_bin(cumb_h, b_pos),
                           at_bin(cumb_c, b_pos)], axis=-1),
                jnp.stack([at_bin(cumf_g, f_pos), at_bin(cumf_h, f_pos),
                           at_bin(cumf_c, f_pos)], axis=-1))
            # membership: forward -> ranks [0, pos]; backward -> the top
            # (pos+1) ranks of the used range
            sub_member = jnp.where(
                use_bk[:, None],
                (rank >= used[:, None] - 1 - sub_pos[:, None]) &
                (rank < used[:, None]),
                rank <= sub_pos[:, None])

            # scatter the nc-sliced results back into F-space
            sub_gain = jnp.full((f,), NEG_INF, hist.dtype).at[ci].set(
                sub_gain, mode="drop")
            sub_left = jnp.zeros((f, 3), hist.dtype).at[ci].set(
                sub_left, mode="drop")
            sub_member = jnp.zeros((f, b), jnp.bool_).at[ci].set(
                sub_member, mode="drop")

            use_subset = is_cat & (num_bins > params.max_cat_to_onehot)
            cat_best_gain = jnp.where(use_subset, sub_gain, oh_gain)
            cat_member = jnp.where(use_subset[:, None], sub_member, oh_member)
            cat_left_sum = jnp.where(use_subset[:, None], sub_left, oh_left)
        else:
            cat_best_gain = oh_gain
            cat_member = oh_member
            cat_left_sum = oh_left
    else:
        # no categorical features in the dataset: the scan skips the
        # one-vs-rest/subset machinery entirely (is_cat is all-False, so
        # these dummies are never selected)
        cat_best_gain = jnp.full((f,), NEG_INF, hist.dtype)
        cat_member = jnp.zeros((f, b), jnp.bool_)
        cat_left_sum = jnp.zeros((f, 3), hist.dtype)

    # ---- numerical best over (bin, direction); categorical by mode ----
    best_r_bin = jnp.argmax(gain_r, axis=1)
    best_r_gain = at_bin(gain_r, best_r_bin)
    best_l_bin = jnp.argmax(gain_l, axis=1)
    best_l_gain = at_bin(gain_l, best_l_bin)

    use_left = best_l_gain > best_r_gain
    num_gain = jnp.where(use_left, best_l_gain, best_r_gain)
    num_thr = jnp.where(use_left, best_l_bin, best_r_bin).astype(jnp.int32)

    num_bin_pick = jnp.where(use_left, best_l_bin, best_r_bin)
    left_num = jnp.stack([at_bin(cum_g, num_bin_pick),
                          at_bin(cum_h, num_bin_pick),
                          at_bin(cum_c, num_bin_pick)], axis=-1)
    left_num = left_num + jnp.where(
        use_left[:, None],
        jnp.concatenate([nan_g, nan_h, nan_c], axis=1), 0.0)

    is_cat_b = is_cat[:, None]
    gain = jnp.where(is_cat, cat_best_gain, num_gain)
    if params.use_cegb:
        # constant per-feature penalty commutes with the per-bin argmax, so
        # it is applied to each feature's best (DeltaGain subtracted from
        # SplitInfo.gain in ComputeBestSplitForFeature)
        delta = (params.cegb_tradeoff * params.cegb_penalty_split *
                 parent_sum[2] +
                 (cegb_penalty if cegb_penalty is not None else 0.0))
        gain = jnp.where(gain > NEG_INF / 2, gain - delta, gain)
    if gain_scale is not None:
        # per-feature gain penalty (feature_contri; feature_histogram.hpp:94
        # ``output->gain *= meta_->penalty``)
        gain = jnp.where(gain > NEG_INF / 2, gain * gain_scale, gain)
    if params.any_cat:
        cat_member = cat_member & is_cat_b & (gain > NEG_INF / 2)[:, None]
        # cat threshold_bin kept as the first member bin (display/compat;
        # the partition decision uses the membership vector)
        cat_thr = jnp.argmax(cat_member, axis=1).astype(jnp.int32)
    else:
        # cat_member is the all-False constant here; running the argmax
        # anyway hands XLA a constant-foldable variadic (pred, iota)
        # reduce that costs >2s of compile time per vmapped scan on
        # multichip programs (MULTICHIP_r05's %reduce.227 stall) — skip
        # the reduce instead of folding it
        cat_thr = jnp.zeros((f,), jnp.int32)
    thr = jnp.where(is_cat, cat_thr, num_thr)
    left_sum = jnp.where(is_cat_b, cat_left_sum, left_num)
    right_sum = parent_sum[None, :] - left_sum

    return FeatureSplits(
        gain=gain,
        threshold_bin=thr,
        default_left=use_left & has_nan & jnp.logical_not(is_cat),
        left_sum=left_sum,
        right_sum=right_sum,
        cat_member=cat_member,
    )


_best_split_jit = functools.partial(jax.jit, static_argnames=("params",))(
    _best_split_impl)
