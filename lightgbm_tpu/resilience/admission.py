"""Serving admission control: typed errors + telemetry for load
shedding, per-request deadlines and shutdown draining.

The micro-batcher (``serve/batcher.py``) enforces the policy; the HTTP
layer (``serve/server.py``) maps the errors to wire semantics:

  :class:`QueueFullError`     -> 503 + ``Retry-After`` (load shed: the
                                 bounded queue is over its row budget;
                                 admitting more would only grow latency
                                 for everyone already queued)
  :class:`DeadlineExceeded`   -> 504 (the request's deadline passed
                                 before a device slot freed up; the
                                 handler thread returns instead of
                                 hanging on the future)
  :class:`ServerClosed`       -> request failed because the batcher was
                                 shut down; queued work is drained and
                                 failed promptly, never left blocking
                                 its caller until a client timeout

Counters (process-wide registry, labeled ``model=<name>``):
``requests_shed_total`` and ``deadline_exceeded_total`` — both exported
through ``GET /metrics`` and consulted by the degraded-mode ``/healthz``.
"""

from __future__ import annotations

from ..telemetry.metrics import Counter, MetricsRegistry, default_registry
from ..telemetry.slo import register_metric_ensurer, slo

__all__ = ["QueueFullError", "DeadlineExceeded", "ServerClosed",
           "shed_counter", "deadline_counter"]

# Shed-budget objective, declared next to the counter it reads: at most
# 1% of client predict calls may be refused by admission control.  A
# sustained higher shed rate means the tier is undersized for its
# traffic, not protecting itself from a blip.
slo("serve/shed_rate", metric="requests_shed_total",
    total_metric="serve_requests_total", kind="ratio", target=0.99,
    min_events=50,
    note="load-shed (503) budget over client predict calls")


class QueueFullError(RuntimeError):
    """Request rejected by admission control; ``retry_after`` is the
    suggested client backoff in seconds (drives ``Retry-After``)."""

    def __init__(self, backlog_rows: int, limit_rows: int,
                 retry_after: float) -> None:
        super().__init__(
            f"request queue is full ({backlog_rows} rows queued, limit "
            f"{limit_rows}); retry in {retry_after:.2f}s")
        self.backlog_rows = int(backlog_rows)
        self.limit_rows = int(limit_rows)
        self.retry_after = float(retry_after)


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before its batch ran (or before
    its result was collected)."""


class ServerClosed(RuntimeError):
    """The batcher/server shut down while the request was queued."""


def shed_counter() -> Counter:
    return default_registry().counter(
        "requests_shed_total",
        "requests rejected by admission control (503 load shed)",
        labels=("model",))


def deadline_counter() -> Counter:
    return default_registry().counter(
        "deadline_exceeded_total",
        "requests failed by per-request deadline (504)",
        labels=("model",))


@register_metric_ensurer
def _ensure_admission_metrics(reg: MetricsRegistry) -> None:
    """SLO-coverage ensurer: the admission counter families exist in a
    registry before any traffic (or shed) does."""
    reg.counter("requests_shed_total",
                "requests rejected by admission control (503 load shed)",
                labels=("model",))
    reg.counter("deadline_exceeded_total",
                "requests failed by per-request deadline (504)",
                labels=("model",))
