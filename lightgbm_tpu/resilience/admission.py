"""Serving admission control: typed errors + telemetry for load
shedding, per-request deadlines and shutdown draining.

The micro-batcher (``serve/batcher.py``) enforces the policy; the HTTP
layer (``serve/server.py``) maps the errors to wire semantics:

  :class:`QueueFullError`     -> 503 + ``Retry-After`` (load shed: the
                                 bounded queue is over its row budget;
                                 admitting more would only grow latency
                                 for everyone already queued)
  :class:`DeadlineExceeded`   -> 504 (the request's deadline passed
                                 before a device slot freed up; the
                                 handler thread returns instead of
                                 hanging on the future)
  :class:`ServerClosed`       -> request failed because the batcher was
                                 shut down; queued work is drained and
                                 failed promptly, never left blocking
                                 its caller until a client timeout

Counters (process-wide registry, labeled ``model=<name>``):
``requests_shed_total`` and ``deadline_exceeded_total`` — both exported
through ``GET /metrics`` and consulted by the degraded-mode ``/healthz``.
"""

from __future__ import annotations

from ..telemetry.metrics import Counter, default_registry

__all__ = ["QueueFullError", "DeadlineExceeded", "ServerClosed",
           "shed_counter", "deadline_counter"]


class QueueFullError(RuntimeError):
    """Request rejected by admission control; ``retry_after`` is the
    suggested client backoff in seconds (drives ``Retry-After``)."""

    def __init__(self, backlog_rows: int, limit_rows: int,
                 retry_after: float) -> None:
        super().__init__(
            f"request queue is full ({backlog_rows} rows queued, limit "
            f"{limit_rows}); retry in {retry_after:.2f}s")
        self.backlog_rows = int(backlog_rows)
        self.limit_rows = int(limit_rows)
        self.retry_after = float(retry_after)


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before its batch ran (or before
    its result was collected)."""


class ServerClosed(RuntimeError):
    """The batcher/server shut down while the request was queued."""


def shed_counter() -> Counter:
    return default_registry().counter(
        "requests_shed_total",
        "requests rejected by admission control (503 load shed)",
        labels=("model",))


def deadline_counter() -> Counter:
    return default_registry().counter(
        "deadline_exceeded_total",
        "requests failed by per-request deadline (504)",
        labels=("model",))
