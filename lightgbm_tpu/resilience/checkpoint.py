"""Crash-safe checkpoint/resume of the full boosting state.

The reference snapshots mid-train by dumping model text every
``snapshot_freq`` iterations (gbdt.cpp:277-281); that is not enough to
CONTINUE a run bit-identically — the objective/bagging RNG position,
early-stopping bookkeeping and the exact f32 score bits are all part of
the training state.  A :class:`Checkpoint` bundles everything
``train()`` needs:

  * model text (reference v3 format — round-trips doubles via %.17g),
  * completed-iteration count,
  * the train score and every valid-set score as EXACT f32 arrays
    (rebuilding scores from trees re-rounds in a different order and
    can drift the last ulp, which would fork the remaining boosting
    trajectory),
  * RNG seed state (``utils/random.py`` streams are pure functions of
    (seed, iteration), so seeds + iteration IS the generator state —
    validated on restore so a changed seed fails instead of silently
    diverging),
  * early-stopping tracker state and the eval-history dict,
  * CEGB coupled-penalty used-feature set, lagged stump bookkeeping,
  * a dataset fingerprint (binning hash + shape + binned-data crc)
    checked on restore so resuming against the wrong binned matrix
    fails loudly.

On disk a checkpoint is ONE ``.npz`` file written via
``io_utils.atomic_write_bytes`` (temp + fsync + atomic rename): a crash
mid-write can never leave a truncated bundle.  :class:`CheckpointManager`
keeps a bounded ring of the newest ``keep`` snapshots plus a ``LATEST``
pointer file.
"""

from __future__ import annotations

import io
import json
import os
import re
import signal
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..io_utils import atomic_write_bytes, atomic_write_text
from ..telemetry.metrics import default_registry
from ..utils.log import log_warning

__all__ = ["Checkpoint", "CheckpointError", "CheckpointManager",
           "TrainingPreempted", "load_checkpoint", "resolve_checkpoint",
           "PreemptionGuard", "reject_checkpointing"]

FORMAT_VERSION = 1
LATEST = "LATEST"
_CKPT_RE = re.compile(r"^ckpt_iter(\d+)\.npz$")

# params recorded into every bundle and compared on restore.  Structural
# drift makes the continuation nonsense -> validate_config raises; soft
# drift only breaks bit-identity -> warns.  engine.py records exactly
# STRUCTURAL + SOFT, so adding a key here is the whole change.
CKPT_STRUCTURAL_KEYS = ("objective", "num_class")
CKPT_SOFT_KEYS = ("num_leaves", "learning_rate", "bagging_fraction",
                  "bagging_freq", "feature_fraction", "use_quantized_grad",
                  "tree_learner")


class CheckpointError(ValueError):
    """A checkpoint could not be written, read, or safely restored."""


def reject_checkpointing(cfg, context: str) -> None:
    """Raise a typed :class:`CheckpointError` when checkpoint/resume
    params are set in a training mode that cannot honor them.

    The multi-model trainer (``train_many``) stacks M boosters' state
    along a vmapped model axis — a shape the per-model bundle format
    cannot capture yet — so a checkpoint written there would resume
    wrong.  The contract is "checkpoint correctly or fail loudly":
    never train silently without the fault tolerance the params asked
    for (covered by the chaos-marked multitrain test)."""
    offending = [k for k, v in (
        ("checkpoint_dir", str(cfg.checkpoint_dir or "")),
        ("snapshot_freq", int(cfg.snapshot_freq) > 0 and
         str(cfg.snapshot_freq)),
        ("resume", str(cfg.resume or "").strip()),
    ) if v]
    if offending:
        raise CheckpointError(
            f"checkpointing/resume ({', '.join(offending)}) is unsupported "
            f"in {context}: the stacked multi-model state cannot be "
            f"captured as per-model bundles yet; drop those params or "
            f"train the models individually via train()")


class TrainingPreempted(RuntimeError):
    """Training was interrupted by SIGTERM/SIGINT after a final
    checkpoint flush.  ``booster`` is the partial model; ``checkpoint``
    the path of the flushed bundle (None when checkpointing was off)."""

    def __init__(self, signum: int, booster=None,
                 checkpoint: Optional[str] = None) -> None:
        name = signal.Signals(signum).name
        super().__init__(
            f"training preempted by {name}"
            + (f"; state checkpointed to {checkpoint}" if checkpoint
               else "; no checkpoint configured"))
        self.signum = signum
        self.booster = booster
        self.checkpoint = checkpoint


@dataclass
class Checkpoint:
    """In-memory form of one snapshot (see module docstring for what each
    field buys).  ``score`` is (N,) or (N,K) float32; ``valid_scores``
    parallel ``valid_names``."""

    iteration: int
    model_text: str
    score: np.ndarray
    valid_names: List[str] = field(default_factory=list)
    valid_scores: List[np.ndarray] = field(default_factory=list)
    eval_history: Dict[str, Dict[str, List[float]]] = field(
        default_factory=dict)
    early_stop: List[Dict[str, Any]] = field(default_factory=list)
    rng_state: Dict[str, int] = field(default_factory=dict)
    fingerprint: Dict[str, Any] = field(default_factory=dict)
    params: Dict[str, Any] = field(default_factory=dict)
    cegb_used: Optional[np.ndarray] = None
    prev_iter_leaves: Optional[List[int]] = None

    # -- serialization -------------------------------------------------------
    def to_bytes(self) -> bytes:
        state = {
            "format": FORMAT_VERSION,
            "iteration": int(self.iteration),
            "valid_names": list(self.valid_names),
            "eval_history": self.eval_history,
            "early_stop": self.early_stop,
            "rng_state": {k: int(v) for k, v in self.rng_state.items()},
            "fingerprint": self.fingerprint,
            "params": self.params,
            "prev_iter_leaves": self.prev_iter_leaves,
        }
        arrays = {
            "state_json": np.frombuffer(
                json.dumps(state).encode("utf-8"), np.uint8),
            "model_text": np.frombuffer(
                self.model_text.encode("utf-8"), np.uint8),
            "score": np.ascontiguousarray(self.score, np.float32),
        }
        for i, vs in enumerate(self.valid_scores):
            arrays[f"valid_score_{i}"] = np.ascontiguousarray(vs, np.float32)
        if self.cegb_used is not None:
            arrays["cegb_used"] = np.ascontiguousarray(self.cegb_used, bool)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes, source: str = "<bytes>") -> "Checkpoint":
        try:
            z = np.load(io.BytesIO(data), allow_pickle=False)
            state = json.loads(bytes(z["state_json"]).decode("utf-8"))
            if int(state.get("format", -1)) > FORMAT_VERSION:
                raise CheckpointError(
                    f"{source}: checkpoint format {state['format']} is "
                    f"newer than this build understands ({FORMAT_VERSION})")
            valid_names = list(state.get("valid_names", []))
            valid_scores = [np.asarray(z[f"valid_score_{i}"])
                            for i in range(len(valid_names))]
            return cls(
                iteration=int(state["iteration"]),
                model_text=bytes(z["model_text"]).decode("utf-8"),
                score=np.asarray(z["score"]),
                valid_names=valid_names,
                valid_scores=valid_scores,
                eval_history=state.get("eval_history", {}),
                early_stop=state.get("early_stop", []),
                rng_state=state.get("rng_state", {}),
                fingerprint=state.get("fingerprint", {}),
                params=state.get("params", {}),
                cegb_used=(np.asarray(z["cegb_used"])
                           if "cegb_used" in z.files else None),
                prev_iter_leaves=state.get("prev_iter_leaves"),
            )
        except CheckpointError:
            raise
        except Exception as exc:
            raise CheckpointError(
                f"{source}: not a readable checkpoint bundle "
                f"({type(exc).__name__}: {exc})") from exc

    # -- restore-time validation --------------------------------------------
    def validate_dataset(self, train_set) -> None:
        """Fail loudly when the resume dataset's binned matrix differs
        from the one this checkpoint was trained on."""
        if not self.fingerprint:
            return
        got = train_set.fingerprint()
        diffs = [f"{k}: checkpoint={self.fingerprint[k]!r} dataset={got[k]!r}"
                 for k in self.fingerprint
                 if k in got and got[k] != self.fingerprint[k]]
        if diffs:
            raise CheckpointError(
                "resume dataset does not match the checkpoint's training "
                "data (a resume against a different binned matrix cannot "
                "be bit-identical): " + "; ".join(diffs))

    def validate_config(self, cfg) -> None:
        """Structural params must match for the continuation to make
        sense (objective/num_class) or to stay bit-identical (seeds,
        sampling params) — the former fail, the latter warn."""
        p = self.params
        if not p:
            return
        for key in CKPT_STRUCTURAL_KEYS:
            if key in p and str(getattr(cfg, key)) != str(p[key]):
                raise CheckpointError(
                    f"cannot resume: checkpoint was trained with "
                    f"{key}={p[key]!r}, this run has "
                    f"{key}={getattr(cfg, key)!r}")
        from ..utils.random import rng_checkpoint_state
        now = rng_checkpoint_state(cfg)
        for key, val in self.rng_state.items():
            if key in now and int(now[key]) != int(val):
                raise CheckpointError(
                    f"cannot resume bit-identically: RNG seed {key} was "
                    f"{val} at checkpoint time but is {now[key]} now "
                    f"(utils/random.py streams are keyed on (seed, "
                    f"iteration); change the seed and the sampling "
                    f"trajectory forks)")
        drift = [f"{k}={p[k]!r}->{getattr(cfg, k)!r}" for k in CKPT_SOFT_KEYS
                 if k in p and str(getattr(cfg, k)) != str(p[k])]
        if drift:
            log_warning("resume config drifts from the checkpoint's "
                        "(continuation will not be bit-identical to an "
                        "uninterrupted run): " + ", ".join(drift))


def _ckpt_name(iteration: int) -> str:
    return f"ckpt_iter{iteration:08d}.npz"


class CheckpointManager:
    """Bounded ring of atomic snapshots in one directory.

    ``save()`` writes ``ckpt_iterNNNNNNNN.npz`` atomically, repoints
    ``LATEST``, then prunes beyond ``keep`` — in that order, so a crash
    between any two steps still leaves a loadable latest checkpoint.
    Thread-safe: the SIGTERM flush may race a periodic save."""

    def __init__(self, directory: str, keep: int = 3) -> None:
        self.directory = os.fspath(directory)
        self.keep = max(1, int(keep))
        self._lock = threading.Lock()
        self._write_seconds = default_registry().histogram(
            "checkpoint_write_seconds",
            "wall seconds per checkpoint bundle write")

    def save(self, ckpt: Checkpoint) -> str:
        import time
        t0 = time.perf_counter()
        with self._lock:
            os.makedirs(self.directory, exist_ok=True)
            name = _ckpt_name(ckpt.iteration)
            path = os.path.join(self.directory, name)
            atomic_write_bytes(path, ckpt.to_bytes())
            atomic_write_text(os.path.join(self.directory, LATEST), name)
            self._prune()
        self._write_seconds.observe(time.perf_counter() - t0)
        return path

    def _prune(self) -> None:
        for name, _ in self.list()[:-self.keep]:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass

    def list(self) -> List[tuple]:
        """(filename, iteration) pairs, oldest first."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        out = []
        for name in entries:
            m = _CKPT_RE.match(name)
            if m:
                out.append((name, int(m.group(1))))
        out.sort(key=lambda t: t[1])
        return out

    def latest_path(self) -> Optional[str]:
        """Resolve the newest loadable snapshot: the ``LATEST`` pointer
        when it names an existing file, else the highest-numbered ring
        entry (covers a crash between bundle write and repoint)."""
        try:
            with open(os.path.join(self.directory, LATEST)) as fh:
                name = fh.read().strip()
            if name and os.path.exists(os.path.join(self.directory, name)):
                return os.path.join(self.directory, name)
        except OSError:
            pass
        entries = self.list()
        if entries:
            return os.path.join(self.directory, entries[-1][0])
        return None


def load_checkpoint(path: str) -> Checkpoint:
    """Read one checkpoint bundle (a ``.npz`` file or a checkpoint
    directory, in which case the newest snapshot is used)."""
    resolved = resolve_checkpoint(path)
    if resolved is None:
        raise CheckpointError(f"no checkpoint found at {path!r}")
    try:
        with open(resolved, "rb") as fh:
            data = fh.read()
    except OSError as exc:
        raise CheckpointError(f"cannot read checkpoint {resolved}: {exc}") \
            from exc
    return Checkpoint.from_bytes(data, source=resolved)


def resolve_checkpoint(path: str) -> Optional[str]:
    """Map a user-supplied resume target (bundle file or checkpoint
    directory) to a concrete bundle path, or None."""
    path = os.fspath(path)
    if os.path.isdir(path):
        return CheckpointManager(path).latest_path()
    return path if os.path.exists(path) else None


# -- preemption handling -----------------------------------------------------
class PreemptionGuard:
    """SIGTERM/SIGINT handler installed for the duration of a training
    run (TPU preemption notices arrive as SIGTERM): the handler only
    sets a flag; the boosting loop drains the in-flight iteration,
    flushes one final checkpoint, and exits via
    :class:`TrainingPreempted`.  On ``__exit__`` the previous handlers
    are restored.  Off the main thread (where ``signal.signal`` is
    illegal) the guard is inert."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, enabled: bool = True) -> None:
        self._enabled = enabled and \
            threading.current_thread() is threading.main_thread()
        self._previous: Dict[int, Any] = {}
        self.fired: Optional[int] = None

    def __enter__(self) -> "PreemptionGuard":
        if not self._enabled:
            return self

        def _handler(signum, frame):
            if self.fired is not None:
                # second signal: the sender insists — restore the
                # previous dispositions and let this one take effect
                # immediately instead of waiting out a long iteration
                self.__exit__()
                os.kill(os.getpid(), signum)
                return
            log_warning(f"received {signal.Signals(signum).name}: "
                        "draining the current iteration, then "
                        "flushing a final checkpoint (repeat to abort "
                        "without the flush)")
            self.fired = signum

        for sig in self.SIGNALS:
            try:
                self._previous[sig] = signal.signal(sig, _handler)
            except (ValueError, OSError):  # non-main thread race / platform
                pass
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._previous.clear()
