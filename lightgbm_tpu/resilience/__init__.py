"""lightgbm_tpu.resilience — fault tolerance for training and serving.

Three pieces:

  * :mod:`.checkpoint` — crash-safe checkpoint/resume of the FULL
    boosting state: model text, iteration, exact f32 train/valid score
    bits, early-stopping bookkeeping, eval history, RNG seed state and
    a dataset fingerprint validated on restore.  Snapshots are written
    atomically (temp + fsync + rename), kept in a bounded ring with a
    ``LATEST`` pointer.  ``train(..., resume_from=...)`` continues
    bit-identically to an uninterrupted run.
  * :mod:`.faults` — chaos injection points (crash/kill at iteration k,
    simulated device loss) driven by ``LGBM_TPU_FAULTS`` or
    :func:`faults.configure`; the recovery test suite uses them to
    PROVE resume rather than assume it.
  * :mod:`.admission` — serving admission control: typed errors for a
    bounded request queue (503 + Retry-After load shedding), per-request
    deadlines (504), and batcher shutdown (``ServerClosed``), with the
    shed/deadline counters in the telemetry registry.
"""

from .admission import (DeadlineExceeded, QueueFullError, ServerClosed,
                        deadline_counter, shed_counter)
from .checkpoint import (Checkpoint, CheckpointError, CheckpointManager,
                         TrainingPreempted, load_checkpoint,
                         resolve_checkpoint)
from .faults import InjectedFault, faults

__all__ = [
    "Checkpoint", "CheckpointError", "CheckpointManager",
    "TrainingPreempted", "load_checkpoint", "resolve_checkpoint",
    "InjectedFault", "faults",
    "DeadlineExceeded", "QueueFullError", "ServerClosed",
    "deadline_counter", "shed_counter",
]
