"""Chaos-injection layer: named fault points the recovery test suite
uses to PROVE crash/resume behavior instead of assuming it.

A fault plan is a comma-separated ``key=value`` spec, configured either
through the ``LGBM_TPU_FAULTS`` environment variable (read once per
:func:`FaultPlan.configure` / process start, so subprocess tests can
arm a child) or programmatically via ``faults.configure(...)``:

  crash_at_iter=K    raise :class:`InjectedFault` entering iteration K
                     (simulates an uncaught training error)
  kill_at_iter=K     hard-kill the process (``os._exit(137)``) entering
                     iteration K — no flush, no atexit: the closest
                     host-side analogue to a preempted/OOM-killed
                     worker dying mid-allreduce
  kill_rank=R        restrict kill_at_iter to distributed process R
                     (multi-process chaos: one worker of a collective
                     dies; the others hit a collective timeout)
  device_loss=1      make the accelerator-backend probe
                     (``utils/backend.default_backend``) report the
                     device as lost, driving the CPU-fallback path

Serve-side chaos (the fleet-resilience suite kills and wedges worker
processes deterministically WHILE the load generator drives traffic;
``serve/server.py`` calls :meth:`FaultPlan.check_serve_request` at the
top of every HTTP handler):

  serve_crash_after_n=N  hard-kill the worker (``os._exit(137)``) on the
                     first ``/predict`` request AFTER N have been
                     admitted — the in-flight client sees a connection
                     reset, the supervisor sees a dead process
  serve_hang_ms=T    sleep T ms in EVERY handler (including
                     ``/healthz`` — a wedged process wedges its health
                     probe too, which is exactly what the fleet
                     watchdog keys on)
  serve_drop_conn=K  sever every K-th ``/predict`` connection without a
                     response (simulates a mid-request network reset;
                     the dispatcher's bounded retry path)

Every trigger increments ``faults_injected_total{fault=...}`` in the
telemetry registry (kill_at_iter / serve_crash_after_n necessarily
excepted — the process is gone before any export).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from ..telemetry.metrics import default_registry
from ..utils.log import log_warning

__all__ = ["InjectedFault", "FaultPlan", "faults"]

ENV_VAR = "LGBM_TPU_FAULTS"


class InjectedFault(RuntimeError):
    """Raised by an armed ``crash_at_iter`` fault point."""


def _parse_spec(spec: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise ValueError(f"bad fault spec token {tok!r} "
                             f"(want key=value)")
        key, val = tok.split("=", 1)
        out[key.strip()] = int(val)
    return out


class FaultPlan:
    """Process-wide armed faults; thread-safe, cleared between tests."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._plan: Dict[str, int] = {}
        self._serve_predicts = 0  # /predict requests seen (serve chaos)
        self._counter = default_registry().counter(
            "faults_injected_total", "chaos-layer faults triggered",
            labels=("fault",))
        env = os.environ.get(ENV_VAR, "")
        if env:
            try:
                self._plan = _parse_spec(env)
            except ValueError as exc:
                log_warning(f"ignoring {ENV_VAR}={env!r}: {exc}")

    def configure(self, spec) -> "FaultPlan":
        """Arm a plan from a spec string or dict (replaces the current
        plan)."""
        plan = dict(spec) if isinstance(spec, dict) else _parse_spec(spec)
        with self._lock:
            self._plan = {k: int(v) for k, v in plan.items()}
        return self

    def clear(self) -> None:
        with self._lock:
            self._plan = {}
            self._serve_predicts = 0

    def get(self, key: str) -> Optional[int]:
        with self._lock:
            return self._plan.get(key)

    def is_active(self, key: str) -> bool:
        return self.get(key) not in (None, 0)

    def fire(self, name: str) -> None:
        self._counter.inc(1, fault=name)

    # -- fault points --------------------------------------------------------
    def check_train_iter(self, iteration: int) -> None:
        """Called by the boosting loop entering iteration ``iteration``."""
        kill_at = self.get("kill_at_iter")
        if kill_at is not None and iteration == kill_at and \
                self._rank_matches():
            log_warning(f"fault injection: hard-killing the process at "
                        f"iteration {iteration} (no flush)")
            os._exit(137)
        crash_at = self.get("crash_at_iter")
        if crash_at is not None and iteration == crash_at:
            self.fire("crash_at_iter")
            raise InjectedFault(
                f"injected crash entering iteration {iteration}")

    def _rank_matches(self) -> bool:
        rank = self.get("kill_rank")
        if rank is None:
            return True
        try:
            import jax
            return int(jax.process_index()) == rank
        except Exception:
            return rank == 0

    def check_serve_request(self, path: str) -> Optional[str]:
        """Called by the HTTP serving layer at the top of every handler.

        Returns ``"drop"`` when the armed plan wants this connection
        severed without a response (the handler closes the socket), or
        ``None`` to proceed.  ``serve_crash_after_n`` never returns —
        the process is gone.
        """
        # production fast path: with nothing armed this is one
        # unlocked dict-emptiness read per request, not four lock
        # acquisitions (faults are armed before traffic starts; a
        # racy read here only delays an injection by one request)
        if not self._plan:
            return None
        hang_ms = self.get("serve_hang_ms")
        if hang_ms:
            # wedge, don't die: EVERY handler (healthz probes included)
            # stalls, which is what distinguishes a hung worker from a
            # crashed one to the supervisor's watchdog
            self.fire("serve_hang_ms")
            import time
            time.sleep(hang_ms / 1e3)
        if path != "/predict":
            return None
        with self._lock:
            self._serve_predicts += 1
            n_seen = self._serve_predicts
        crash_after = self.get("serve_crash_after_n")
        if crash_after is not None and n_seen > crash_after:
            log_warning(f"fault injection: hard-killing the serving "
                        f"process after {crash_after} /predict requests")
            os._exit(137)
        drop_every = self.get("serve_drop_conn")
        if drop_every and n_seen % drop_every == 0:
            self.fire("serve_drop_conn")
            return "drop"
        return None

    def check_device_probe(self) -> None:
        """Called by the backend probe; an armed ``device_loss`` makes it
        take the CPU-fallback path."""
        if self.is_active("device_loss"):
            self.fire("device_loss")
            raise RuntimeError(
                "injected fault: accelerator device lost (device_loss)")


faults = FaultPlan()
