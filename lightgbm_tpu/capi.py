"""The ``LGBM_*`` C-API surface (reference: include/LightGBM/c_api.h,
src/c_api.cpp — the stable handle-based ABI behind the Python/R/SWIG
bindings).

In this framework the boosting driver is in-process Python, so the ABI's
raw-pointer marshalling collapses: handles are integers in a registry,
matrices are numpy arrays, and every function keeps the reference's NAME,
argument order, and 0/-1 + ``LGBM_GetLastError`` error contract.  Code
written against the reference's ctypes surface ports by swapping
``_LIB.LGBM_x(...)`` for ``capi.LGBM_x(...)``; a future native embedding
can re-export these symbols unchanged.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster
from .config import Config
from .dataset import Dataset
from .utils.log import log_warning

__all__ = [n for n in dir() if n.startswith("LGBM_")]

_lock = threading.Lock()
_handles: Dict[int, Any] = {}
_next_handle = [1]
_last_error = [""]

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3


def _register(obj: Any) -> int:
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(handle: int):
    try:
        return _handles[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}")


def _api(fn):
    """Error contract: 0 on success, -1 + LGBM_GetLastError on failure
    (reference c_api.cpp API_BEGIN/API_END)."""
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — the ABI swallows into -1
            _last_error[0] = f"{type(e).__name__}: {e}"
            return -1, None
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def LGBM_GetLastError() -> str:
    """reference c_api.h:46."""
    return _last_error[0]


def _parse_params(parameters: Optional[str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for tok in (parameters or "").replace("\n", " ").split(" "):
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# ---- Dataset surface (c_api.h:66-398) -----------------------------------

@_api
def LGBM_DatasetCreateFromMat(data, parameters: str = "",
                              label=None, reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(data), label=label, reference=ref, params=params)
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateFromCSR(csr, parameters: str = "", label=None,
                              reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(csr, label=label, reference=ref, params=params)
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateFromFile(filename: str, parameters: str = "",
                               reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(filename, reference=ref, params=params)
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetFree(handle: int):
    with _lock:
        _handles.pop(handle, None)
    return 0, None


@_api
def LGBM_DatasetGetNumData(handle: int):
    return 0, _get(handle).num_data()


@_api
def LGBM_DatasetGetNumFeature(handle: int):
    return 0, _get(handle).num_feature()


@_api
def LGBM_DatasetSetField(handle: int, field_name: str, field_data):
    ds = _get(handle)
    if field_name == "label":
        ds.set_label(field_data)
    elif field_name == "weight":
        ds.set_weight(field_data)
    elif field_name in ("group", "query"):
        ds.set_group(field_data)
    elif field_name == "init_score":
        ds.set_init_score(field_data)
    else:
        raise ValueError(f"unknown field {field_name}")
    return 0, None


@_api
def LGBM_DatasetGetField(handle: int, field_name: str):
    ds = _get(handle)
    md = ds.metadata
    val = {"label": md.label, "weight": md.weight, "group": md.group,
           "query": md.group, "init_score": md.init_score}.get(field_name)
    if val is None and field_name not in ("label", "weight", "group",
                                          "query", "init_score"):
        raise ValueError(f"unknown field {field_name}")
    return 0, val


@_api
def LGBM_DatasetSaveBinary(handle: int, filename: str):
    _get(handle).save_binary(filename)
    return 0, None


# ---- Booster surface (c_api.h:418-1263) ---------------------------------

@_api
def LGBM_BoosterCreate(train_data: int, parameters: str = ""):
    ds = _get(train_data)
    bst = Booster(params=_parse_params(parameters), train_set=ds)
    return 0, _register(bst)


@_api
def LGBM_BoosterCreateFromModelfile(filename: str):
    bst = Booster(model_file=filename)
    return 0, _register(bst)


@_api
def LGBM_BoosterLoadModelFromString(model_str: str):
    bst = Booster(model_str=model_str)
    return 0, _register(bst)


@_api
def LGBM_BoosterFree(handle: int):
    with _lock:
        _handles.pop(handle, None)
    return 0, None


@_api
def LGBM_BoosterAddValidData(handle: int, valid_data: int):
    bst = _get(handle)
    bst.add_valid(_get(valid_data), f"valid_{len(bst._gbdt.valid_sets)}")
    return 0, None


@_api
def LGBM_BoosterUpdateOneIter(handle: int):
    finished = _get(handle).update()
    return 0, 1 if finished else 0


@_api
def LGBM_BoosterUpdateOneIterCustom(handle: int, grad, hess):
    bst = _get(handle)
    finished = bst._gbdt.train_one_iter(np.asarray(grad, np.float32),
                                        np.asarray(hess, np.float32))
    return 0, 1 if finished else 0


@_api
def LGBM_BoosterRollbackOneIter(handle: int):
    _get(handle).rollback_one_iter()
    return 0, None


@_api
def LGBM_BoosterGetCurrentIteration(handle: int):
    return 0, _get(handle).current_iteration


@_api
def LGBM_BoosterNumModelPerIteration(handle: int):
    return 0, _get(handle).num_model_per_iteration()


@_api
def LGBM_BoosterNumberOfTotalModel(handle: int):
    return 0, _get(handle).num_trees()


@_api
def LGBM_BoosterGetNumClasses(handle: int):
    return 0, _get(handle)._gbdt.config.num_class


@_api
def LGBM_BoosterGetNumFeature(handle: int):
    return 0, _get(handle).num_feature()


@_api
def LGBM_BoosterGetFeatureNames(handle: int):
    return 0, _get(handle).feature_name()


@_api
def LGBM_BoosterGetEval(handle: int, data_idx: int):
    """data_idx 0 = training, i+1 = i-th validation set (c_api.h:648)."""
    bst = _get(handle)
    res = bst.eval_train() if data_idx == 0 else bst.eval_valid()
    if data_idx > 0:
        names = [n for n, _ in bst._gbdt.valid_sets]
        want = names[data_idx - 1]
        res = [r for r in res if r[0] == want]
    return 0, [(name, val) for _, name, val, _ in res]


@_api
def LGBM_BoosterSaveModel(handle: int, filename: str,
                          start_iteration: int = 0,
                          num_iteration: int = -1):
    _get(handle).save_model(filename,
                            None if num_iteration < 0 else num_iteration,
                            start_iteration)
    return 0, None


@_api
def LGBM_BoosterSaveModelToString(handle: int, start_iteration: int = 0,
                                  num_iteration: int = -1):
    return 0, _get(handle).model_to_string(
        None if num_iteration < 0 else num_iteration, start_iteration)


@_api
def LGBM_BoosterDumpModel(handle: int, start_iteration: int = 0,
                          num_iteration: int = -1):
    return 0, _get(handle).dump_model(
        None if num_iteration < 0 else num_iteration, start_iteration)


@_api
def LGBM_BoosterPredictForMat(handle: int, data, predict_type: int = 0,
                              start_iteration: int = 0,
                              num_iteration: int = -1,
                              parameter: str = ""):
    bst = _get(handle)
    out = bst.predict(np.asarray(data),
                      start_iteration=start_iteration,
                      num_iteration=None if num_iteration < 0 else
                      num_iteration,
                      raw_score=predict_type == C_API_PREDICT_RAW_SCORE,
                      pred_leaf=predict_type == C_API_PREDICT_LEAF_INDEX,
                      pred_contrib=predict_type == C_API_PREDICT_CONTRIB)
    return 0, out


@_api
def LGBM_BoosterPredictForCSR(handle: int, csr, predict_type: int = 0,
                              start_iteration: int = 0,
                              num_iteration: int = -1,
                              parameter: str = ""):
    bst = _get(handle)
    out = bst.predict(np.asarray(csr.todense()),
                      start_iteration=start_iteration,
                      num_iteration=None if num_iteration < 0 else
                      num_iteration,
                      raw_score=predict_type == C_API_PREDICT_RAW_SCORE,
                      pred_leaf=predict_type == C_API_PREDICT_LEAF_INDEX,
                      pred_contrib=predict_type == C_API_PREDICT_CONTRIB)
    return 0, out


@_api
def LGBM_BoosterFeatureImportance(handle: int, num_iteration: int = -1,
                                  importance_type: int = 0):
    kind = "split" if importance_type == 0 else "gain"
    return 0, _get(handle).feature_importance(kind)


@_api
def LGBM_BoosterRefit(handle: int, data, label, decay_rate: float = 0.9):
    new_bst = _get(handle).refit(np.asarray(data), np.asarray(label),
                                 decay_rate)
    return 0, _register(new_bst)


@_api
def LGBM_BoosterResetParameter(handle: int, parameters: str):
    _get(handle).reset_parameter(_parse_params(parameters))
    return 0, None


# ---- network (c_api.h:1274) ---------------------------------------------

@_api
def LGBM_NetworkInit(machines: str, local_listen_port: int,
                     listen_time_out: int, num_machines: int):
    if num_machines > 1:
        raise NotImplementedError(
            "socket meshes are replaced by the JAX runtime: call "
            "lightgbm_tpu.distributed.init(...) per process instead")
    log_warning("LGBM_NetworkInit with one machine is a no-op")
    return 0, None


@_api
def LGBM_NetworkFree():
    return 0, None


__all__ = sorted(n for n in dir() if n.startswith("LGBM_"))
