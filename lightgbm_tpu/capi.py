"""The ``LGBM_*`` C-API surface (reference: include/LightGBM/c_api.h,
src/c_api.cpp — the stable handle-based ABI behind the Python/R/SWIG
bindings).

In this framework the boosting driver is in-process Python, so the ABI's
raw-pointer marshalling collapses: handles are integers in a registry,
matrices are numpy arrays, and every function keeps the reference's NAME,
argument order, and 0/-1 + ``LGBM_GetLastError`` error contract.  Code
written against the reference's ctypes surface ports by swapping
``_LIB.LGBM_x(...)`` for ``capi.LGBM_x(...)``; a future native embedding
can re-export these symbols unchanged.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster
from .config import Config
from .dataset import Dataset
from .utils.log import log_warning

__all__ = [n for n in dir() if n.startswith("LGBM_")]

_lock = threading.Lock()
_handles: Dict[int, Any] = {}
_next_handle = [1]
_last_error = [""]

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3


def _register(obj: Any) -> int:
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(handle: int):
    try:
        return _handles[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}")


_abi_errors = [False]


def strict_abi(enable: bool = True) -> None:
    """Select the error mode.  Default (False): exceptions PROPAGATE —
    in-process Python callers get real stack traces (the reference's own
    Python wrapper raises on nonzero codes, basic.py _safe_call).
    ``strict_abi(True)`` restores the raw ABI contract: -1 +
    ``LGBM_GetLastError`` (c_api.cpp API_BEGIN/API_END), for code that
    ports the ctypes call pattern verbatim."""
    _abi_errors[0] = bool(enable)


def _api(fn):
    """0 on success; failures raise (default) or return -1 under
    ``strict_abi(True)`` — see :func:`strict_abi`."""
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — the ABI swallows into -1
            _last_error[0] = f"{type(e).__name__}: {e}"
            if _abi_errors[0]:
                return -1, None
            raise
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def LGBM_GetLastError() -> str:
    """reference c_api.h:46."""
    return _last_error[0]


def _check_stream_complete(ds) -> None:
    """A streaming dataset must be fully pushed before first use —
    training on the zero-filled allocation would be silently wrong."""
    filled = getattr(ds, "_stream_filled", None)
    if filled is not None and not ds.constructed and \
            filled < len(ds.data):
        raise ValueError(
            f"streaming dataset incomplete: {filled} of {len(ds.data)} "
            "rows pushed (LGBM_DatasetPushRows*)")


def _parse_params(parameters: Optional[str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for tok in (parameters or "").replace("\n", " ").split(" "):
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# ---- Dataset surface (c_api.h:66-398) -----------------------------------

@_api
def LGBM_DatasetCreateFromMat(data, parameters: str = "",
                              label=None, reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(data), label=label, reference=ref, params=params)
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateFromCSR(csr, parameters: str = "", label=None,
                              reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(csr, label=label, reference=ref, params=params)
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateFromFile(filename: str, parameters: str = "",
                               reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(filename, reference=ref, params=params)
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateByReference(reference: int, num_total_row: int):
    """Streaming ingestion step 1 (c_api.h:232 DatasetCreateByReference):
    allocate an empty dataset aligned to a constructed reference; fill it
    with LGBM_DatasetPushRows* and it bins lazily on first use."""
    ref = _get(reference)
    if not ref.constructed:
        ref.construct(Config(ref.params))
    buf = np.zeros((int(num_total_row), ref.num_total_features), np.float64)
    ds = Dataset(buf, reference=ref, params=dict(ref.params),
                 free_raw_data=False)
    ds._stream_filled = 0
    return 0, _register(ds)


@_api
def LGBM_DatasetPushRows(dataset: int, data, start_row: int):
    """Streaming ingestion step 2 (c_api.h:66 DatasetPushRows): copy a
    dense row block into [start_row, start_row+nrow)."""
    ds = _get(dataset)
    if ds.constructed:
        raise ValueError("cannot push rows into a dataset already used "
                         "for training/validation")
    rows = np.asarray(data, np.float64)
    if rows.ndim == 1:
        rows = rows.reshape(1, -1)
    ds.data[int(start_row):int(start_row) + len(rows)] = rows
    ds._stream_filled = max(getattr(ds, "_stream_filled", 0),
                            int(start_row) + len(rows))
    return 0, None


@_api
def LGBM_DatasetPushRowsByCSR(dataset: int, csr_block, start_row: int):
    """Streaming ingestion of one sparse row block (c_api.h:105
    DatasetPushRowsByCSR); only the pushed block densifies."""
    ds = _get(dataset)
    if ds.constructed:
        raise ValueError("cannot push rows into a dataset already used "
                         "for training/validation")
    block = np.asarray(csr_block.todense()
                       if hasattr(csr_block, "todense") else csr_block,
                       np.float64)
    ds.data[int(start_row):int(start_row) + len(block),
            :block.shape[1]] = block
    ds._stream_filled = max(getattr(ds, "_stream_filled", 0),
                            int(start_row) + len(block))
    return 0, None


@_api
def LGBM_DatasetGetSubset(handle: int, used_row_indices,
                          parameters: str = ""):
    """Row subset sharing the parent's bin mappers (c_api.h:286)."""
    ds = _get(handle)
    _check_stream_complete(ds)
    if not ds.constructed:
        ds.construct(Config(ds.params))
    idx = np.asarray(used_row_indices, np.int64)
    return 0, _register(ds.subset(idx))


@_api
def LGBM_DatasetFree(handle: int):
    with _lock:
        _handles.pop(handle, None)
    return 0, None


@_api
def LGBM_DatasetGetNumData(handle: int):
    return 0, _get(handle).num_data()


@_api
def LGBM_DatasetGetNumFeature(handle: int):
    return 0, _get(handle).num_feature()


@_api
def LGBM_DatasetSetField(handle: int, field_name: str, field_data):
    ds = _get(handle)
    if field_name == "label":
        ds.set_label(field_data)
    elif field_name == "weight":
        ds.set_weight(field_data)
    elif field_name in ("group", "query"):
        ds.set_group(field_data)
    elif field_name == "init_score":
        ds.set_init_score(field_data)
    else:
        raise ValueError(f"unknown field {field_name}")
    return 0, None


@_api
def LGBM_DatasetGetField(handle: int, field_name: str):
    ds = _get(handle)
    md = ds.metadata
    val = {"label": md.label, "weight": md.weight, "group": md.group,
           "query": md.group, "init_score": md.init_score}.get(field_name)
    if val is None and field_name not in ("label", "weight", "group",
                                          "query", "init_score"):
        raise ValueError(f"unknown field {field_name}")
    return 0, val


@_api
def LGBM_DatasetSaveBinary(handle: int, filename: str):
    _get(handle).save_binary(filename)
    return 0, None


# ---- Booster surface (c_api.h:418-1263) ---------------------------------

@_api
def LGBM_BoosterCreate(train_data: int, parameters: str = ""):
    _check_stream_complete(_get(train_data))
    ds = _get(train_data)
    bst = Booster(params=_parse_params(parameters), train_set=ds)
    return 0, _register(bst)


@_api
def LGBM_BoosterCreateFromModelfile(filename: str):
    bst = Booster(model_file=filename)
    return 0, _register(bst)


@_api
def LGBM_BoosterLoadModelFromString(model_str: str):
    bst = Booster(model_str=model_str)
    return 0, _register(bst)


@_api
def LGBM_BoosterFree(handle: int):
    with _lock:
        _handles.pop(handle, None)
    return 0, None


@_api
def LGBM_BoosterAddValidData(handle: int, valid_data: int):
    _check_stream_complete(_get(valid_data))
    bst = _get(handle)
    bst.add_valid(_get(valid_data), f"valid_{len(bst._gbdt.valid_sets)}")
    return 0, None


@_api
def LGBM_BoosterUpdateOneIter(handle: int):
    finished = _get(handle).update()
    return 0, 1 if finished else 0


@_api
def LGBM_BoosterUpdateOneIterCustom(handle: int, grad, hess):
    bst = _get(handle)
    finished = bst._gbdt.train_one_iter(np.asarray(grad, np.float32),
                                        np.asarray(hess, np.float32))
    return 0, 1 if finished else 0


@_api
def LGBM_BoosterRollbackOneIter(handle: int):
    _get(handle).rollback_one_iter()
    return 0, None


@_api
def LGBM_BoosterGetCurrentIteration(handle: int):
    return 0, _get(handle).current_iteration


@_api
def LGBM_BoosterNumModelPerIteration(handle: int):
    return 0, _get(handle).num_model_per_iteration()


@_api
def LGBM_BoosterNumberOfTotalModel(handle: int):
    return 0, _get(handle).num_trees()


@_api
def LGBM_BoosterGetNumClasses(handle: int):
    return 0, _get(handle)._gbdt.config.num_class


@_api
def LGBM_BoosterGetNumFeature(handle: int):
    return 0, _get(handle).num_feature()


@_api
def LGBM_BoosterGetFeatureNames(handle: int):
    return 0, _get(handle).feature_name()


@_api
def LGBM_BoosterGetEval(handle: int, data_idx: int):
    """data_idx 0 = training, i+1 = i-th validation set (c_api.h:648)."""
    bst = _get(handle)
    res = bst.eval_train() if data_idx == 0 else bst.eval_valid()
    if data_idx > 0:
        names = [n for n, _ in bst._gbdt.valid_sets]
        want = names[data_idx - 1]
        res = [r for r in res if r[0] == want]
    return 0, [(name, val) for _, name, val, _ in res]


@_api
def LGBM_BoosterSaveModel(handle: int, filename: str,
                          start_iteration: int = 0,
                          num_iteration: int = -1):
    _get(handle).save_model(filename,
                            None if num_iteration < 0 else num_iteration,
                            start_iteration)
    return 0, None


@_api
def LGBM_BoosterSaveModelToString(handle: int, start_iteration: int = 0,
                                  num_iteration: int = -1):
    return 0, _get(handle).model_to_string(
        None if num_iteration < 0 else num_iteration, start_iteration)


@_api
def LGBM_BoosterDumpModel(handle: int, start_iteration: int = 0,
                          num_iteration: int = -1):
    return 0, _get(handle).dump_model(
        None if num_iteration < 0 else num_iteration, start_iteration)


@_api
def LGBM_BoosterPredictForMat(handle: int, data, predict_type: int = 0,
                              start_iteration: int = 0,
                              num_iteration: int = -1,
                              parameter: str = ""):
    bst = _get(handle)
    out = bst.predict(np.asarray(data),
                      start_iteration=start_iteration,
                      num_iteration=None if num_iteration < 0 else
                      num_iteration,
                      raw_score=predict_type == C_API_PREDICT_RAW_SCORE,
                      pred_leaf=predict_type == C_API_PREDICT_LEAF_INDEX,
                      pred_contrib=predict_type == C_API_PREDICT_CONTRIB)
    return 0, out


def _predict_kwargs(predict_type, start_iteration, num_iteration):
    return dict(
        start_iteration=start_iteration,
        num_iteration=None if num_iteration < 0 else num_iteration,
        raw_score=predict_type == C_API_PREDICT_RAW_SCORE,
        pred_leaf=predict_type == C_API_PREDICT_LEAF_INDEX,
        pred_contrib=predict_type == C_API_PREDICT_CONTRIB)


@_api
def LGBM_BoosterPredictForCSR(handle: int, csr, predict_type: int = 0,
                              start_iteration: int = 0,
                              num_iteration: int = -1,
                              parameter: str = ""):
    """Sparse prediction.  Rows densify in bounded chunks only — a
    Bosch-shaped CSR never materializes as one dense matrix
    (c_api.h:896 PredictForCSR)."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    n, f = csr.shape
    step = max(1024, (1 << 24) // max(1, f))
    if n <= step:
        return 0, bst.predict(np.asarray(csr.todense()), **kw)
    parts = [bst.predict(np.asarray(csr[lo:lo + step].todense()), **kw)
             for lo in range(0, n, step)]
    return 0, np.concatenate(parts, axis=0)


@_api
def LGBM_BoosterPredictForMatSingleRow(handle: int, row,
                                       predict_type: int = 0,
                                       start_iteration: int = 0,
                                       num_iteration: int = -1,
                                       parameter: str = ""):
    """Single-row fast path (c_api.h:1018 PredictForMatSingleRow)."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    out = bst.predict(np.asarray(row, np.float64).reshape(1, -1), **kw)
    return 0, np.asarray(out)[0]


@_api
def LGBM_BoosterPredictForCSRSingleRow(handle: int, csr_row,
                                       predict_type: int = 0,
                                       start_iteration: int = 0,
                                       num_iteration: int = -1,
                                       parameter: str = ""):
    """Single sparse row (c_api.h:961 PredictForCSRSingleRow)."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    dense = np.asarray(csr_row.todense()).reshape(1, -1)
    return 0, np.asarray(bst.predict(dense, **kw))[0]


@_api
def LGBM_BoosterPredictForFile(handle: int, data_filename: str,
                               data_has_header: bool = False,
                               predict_type: int = 0,
                               start_iteration: int = 0,
                               num_iteration: int = -1,
                               parameter: str = "",
                               result_filename: str =
                               "LightGBM_predict_result.txt"):
    """File -> prediction file (c_api.h:858 PredictForFile; the CLI's
    task=predict body, application.cpp Predict)."""
    bst = _get(handle)
    params = _parse_params(parameter)
    if data_has_header:
        params.setdefault("header", True)
    from .io_utils import load_data_file
    X, _, _ = load_data_file(data_filename, params)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    out = np.atleast_1d(np.asarray(bst.predict(np.asarray(X), **kw)))
    with open(result_filename, "w") as fh:
        if out.ndim == 1:
            fh.write("\n".join(f"{v:.18g}" for v in out) + "\n")
        else:
            for r in out:
                fh.write("\t".join(f"{v:.18g}" for v in r) + "\n")
    return 0, None


@_api
def LGBM_BoosterFeatureImportance(handle: int, num_iteration: int = -1,
                                  importance_type: int = 0):
    kind = "split" if importance_type == 0 else "gain"
    return 0, _get(handle).feature_importance(kind)


@_api
def LGBM_BoosterRefit(handle: int, data, label, decay_rate: float = 0.9):
    new_bst = _get(handle).refit(np.asarray(data), np.asarray(label),
                                 decay_rate)
    return 0, _register(new_bst)


@_api
def LGBM_BoosterResetParameter(handle: int, parameters: str):
    _get(handle).reset_parameter(_parse_params(parameters))
    return 0, None


# ---- network (c_api.h:1274) ---------------------------------------------

@_api
def LGBM_NetworkInit(machines: str, local_listen_port: int,
                     listen_time_out: int, num_machines: int):
    if num_machines > 1:
        raise NotImplementedError(
            "socket meshes are replaced by the JAX runtime: call "
            "lightgbm_tpu.distributed.init(...) per process instead")
    log_warning("LGBM_NetworkInit with one machine is a no-op")
    return 0, None


@_api
def LGBM_NetworkFree():
    return 0, None


__all__ = sorted(n for n in dir() if n.startswith("LGBM_"))
