"""The ``LGBM_*`` C-API surface (reference: include/LightGBM/c_api.h,
src/c_api.cpp — the stable handle-based ABI behind the Python/R/SWIG
bindings).

In this framework the boosting driver is in-process Python, so the ABI's
raw-pointer marshalling collapses: handles are integers in a registry,
matrices are numpy arrays, and every function keeps the reference's NAME,
argument order, and 0/-1 + ``LGBM_GetLastError`` error contract.  Code
written against the reference's ctypes surface ports by swapping
``_LIB.LGBM_x(...)`` for ``capi.LGBM_x(...)``; a future native embedding
can re-export these symbols unchanged.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster
from .config import Config
from .dataset import Dataset
from .utils.log import log_warning

__all__ = [n for n in dir() if n.startswith("LGBM_")]

_lock = threading.Lock()
_handles: Dict[int, Any] = {}
_next_handle = [1]
_last_error = [""]

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3


def _register(obj: Any) -> int:
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(handle: int):
    try:
        return _handles[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}")


_abi_errors = [False]


def strict_abi(enable: bool = True) -> None:
    """Select the error mode.  Default (False): exceptions PROPAGATE —
    in-process Python callers get real stack traces (the reference's own
    Python wrapper raises on nonzero codes, basic.py _safe_call).
    ``strict_abi(True)`` restores the raw ABI contract: -1 +
    ``LGBM_GetLastError`` (c_api.cpp API_BEGIN/API_END), for code that
    ports the ctypes call pattern verbatim."""
    _abi_errors[0] = bool(enable)


def _api(fn):
    """0 on success; failures raise (default) or return -1 under
    ``strict_abi(True)`` — see :func:`strict_abi`."""
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — the ABI swallows into -1
            _last_error[0] = f"{type(e).__name__}: {e}"
            if _abi_errors[0]:
                return -1, None
            raise
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def LGBM_GetLastError() -> str:
    """reference c_api.h:46."""
    return _last_error[0]


def _check_stream_complete(ds) -> None:
    """A streaming dataset must be fully pushed before first use —
    training on the zero-filled allocation would be silently wrong."""
    filled = getattr(ds, "_stream_filled", None)
    if filled is not None and not ds.constructed and \
            filled < len(ds.data):
        raise ValueError(
            f"streaming dataset incomplete: {filled} of {len(ds.data)} "
            "rows pushed (LGBM_DatasetPushRows*)")


def _free_raw(params: Dict[str, Any]) -> bool:
    """C-API datasets drop raw data after binning by default (the
    reference keeps only binned features); pass free_raw_data=false in
    the parameters string to retain it (needed by AddFeaturesFrom)."""
    return str(params.get("free_raw_data", "true")).lower() not in (
        "false", "0")


def _parse_params(parameters: Optional[str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for tok in (parameters or "").replace("\n", " ").split(" "):
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# ---- Dataset surface (c_api.h:66-398) -----------------------------------

@_api
def LGBM_DatasetCreateFromMat(data, parameters: str = "",
                              label=None, reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(data), label=label, reference=ref,
                 params=params, free_raw_data=_free_raw(params))
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateFromCSR(csr, parameters: str = "", label=None,
                              reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(csr, label=label, reference=ref, params=params,
                 free_raw_data=_free_raw(params))
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateFromFile(filename: str, parameters: str = "",
                               reference: Optional[int] = None):
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(filename, reference=ref, params=params)
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateByReference(reference: int, num_total_row: int):
    """Streaming ingestion step 1 (c_api.h:232 DatasetCreateByReference):
    allocate an empty dataset aligned to a constructed reference; fill it
    with LGBM_DatasetPushRows* and it bins lazily on first use."""
    ref = _get(reference)
    if not ref.constructed:
        ref.construct(Config(ref.params))
    buf = np.zeros((int(num_total_row), ref.num_total_features), np.float64)
    ds = Dataset(buf, reference=ref, params=dict(ref.params),
                 free_raw_data=False)
    ds._stream_filled = 0
    return 0, _register(ds)


@_api
def LGBM_DatasetPushRows(dataset: int, data, start_row: int):
    """Streaming ingestion step 2 (c_api.h:66 DatasetPushRows): copy a
    dense row block into [start_row, start_row+nrow)."""
    ds = _get(dataset)
    if ds.constructed:
        raise ValueError("cannot push rows into a dataset already used "
                         "for training/validation")
    rows = np.asarray(data, np.float64)
    if rows.ndim == 1:
        rows = rows.reshape(1, -1)
    ds.data[int(start_row):int(start_row) + len(rows)] = rows
    ds._stream_filled = max(getattr(ds, "_stream_filled", 0),
                            int(start_row) + len(rows))
    return 0, None


@_api
def LGBM_DatasetPushRowsByCSR(dataset: int, csr_block, start_row: int):
    """Streaming ingestion of one sparse row block (c_api.h:105
    DatasetPushRowsByCSR); only the pushed block densifies."""
    ds = _get(dataset)
    if ds.constructed:
        raise ValueError("cannot push rows into a dataset already used "
                         "for training/validation")
    block = np.asarray(csr_block.todense()
                       if hasattr(csr_block, "todense") else csr_block,
                       np.float64)
    ds.data[int(start_row):int(start_row) + len(block),
            :block.shape[1]] = block
    ds._stream_filled = max(getattr(ds, "_stream_filled", 0),
                            int(start_row) + len(block))
    return 0, None


@_api
def LGBM_DatasetGetSubset(handle: int, used_row_indices,
                          parameters: str = ""):
    """Row subset sharing the parent's bin mappers (c_api.h:286)."""
    ds = _get(handle)
    _check_stream_complete(ds)
    if not ds.constructed:
        ds.construct(Config(ds.params))
    idx = np.asarray(used_row_indices, np.int64)
    return 0, _register(ds.subset(idx))


@_api
def LGBM_DatasetFree(handle: int):
    with _lock:
        _handles.pop(handle, None)
    return 0, None


@_api
def LGBM_DatasetGetNumData(handle: int):
    return 0, _get(handle).num_data()


@_api
def LGBM_DatasetGetNumFeature(handle: int):
    return 0, _get(handle).num_feature()


@_api
def LGBM_DatasetSetField(handle: int, field_name: str, field_data):
    ds = _get(handle)
    if field_name == "label":
        ds.set_label(field_data)
    elif field_name == "weight":
        ds.set_weight(field_data)
    elif field_name in ("group", "query"):
        ds.set_group(field_data)
    elif field_name == "init_score":
        ds.set_init_score(field_data)
    else:
        raise ValueError(f"unknown field {field_name}")
    return 0, None


@_api
def LGBM_DatasetGetField(handle: int, field_name: str):
    ds = _get(handle)
    md = ds.metadata
    val = {"label": md.label, "weight": md.weight, "group": md.group,
           "query": md.group, "init_score": md.init_score}.get(field_name)
    if val is None and field_name not in ("label", "weight", "group",
                                          "query", "init_score"):
        raise ValueError(f"unknown field {field_name}")
    return 0, val


@_api
def LGBM_DatasetSaveBinary(handle: int, filename: str):
    _get(handle).save_binary(filename)
    return 0, None


# ---- Booster surface (c_api.h:418-1263) ---------------------------------

@_api
def LGBM_BoosterCreate(train_data: int, parameters: str = ""):
    _check_stream_complete(_get(train_data))
    ds = _get(train_data)
    bst = Booster(params=_parse_params(parameters), train_set=ds)
    return 0, _register(bst)


@_api
def LGBM_BoosterCreateFromModelfile(filename: str):
    bst = Booster(model_file=filename)
    return 0, _register(bst)


@_api
def LGBM_BoosterLoadModelFromString(model_str: str):
    bst = Booster(model_str=model_str)
    return 0, _register(bst)


@_api
def LGBM_BoosterFree(handle: int):
    with _lock:
        _handles.pop(handle, None)
    return 0, None


@_api
def LGBM_BoosterAddValidData(handle: int, valid_data: int):
    _check_stream_complete(_get(valid_data))
    bst = _get(handle)
    bst.add_valid(_get(valid_data), f"valid_{len(bst._gbdt.valid_sets)}")
    return 0, None


@_api
def LGBM_BoosterUpdateOneIter(handle: int):
    finished = _get(handle).update()
    return 0, 1 if finished else 0


@_api
def LGBM_BoosterUpdateOneIterCustom(handle: int, grad, hess):
    bst = _get(handle)
    finished = bst._gbdt.train_one_iter(np.asarray(grad, np.float32),
                                        np.asarray(hess, np.float32))
    return 0, 1 if finished else 0


@_api
def LGBM_BoosterRollbackOneIter(handle: int):
    _get(handle).rollback_one_iter()
    return 0, None


@_api
def LGBM_BoosterGetCurrentIteration(handle: int):
    return 0, _get(handle).current_iteration


@_api
def LGBM_BoosterNumModelPerIteration(handle: int):
    return 0, _get(handle).num_model_per_iteration()


@_api
def LGBM_BoosterNumberOfTotalModel(handle: int):
    return 0, _get(handle).num_trees()


@_api
def LGBM_BoosterGetNumClasses(handle: int):
    return 0, _get(handle)._gbdt.config.num_class


@_api
def LGBM_BoosterGetNumFeature(handle: int):
    return 0, _get(handle).num_feature()


@_api
def LGBM_BoosterGetFeatureNames(handle: int):
    return 0, _get(handle).feature_name()


@_api
def LGBM_BoosterGetEval(handle: int, data_idx: int):
    """data_idx 0 = training, i+1 = i-th validation set (c_api.h:648).
    The reference's Booster always creates training metrics from the
    metric config (c_api.cpp CreateObjectiveAndMetrics), so data_idx=0
    works without is_provide_training_metric — lazily instantiate."""
    bst = _get(handle)
    if data_idx == 0:
        _eval_metrics(handle)
    res = bst.eval_train() if data_idx == 0 else bst.eval_valid()
    if data_idx > 0:
        names = [n for n, _ in bst._gbdt.valid_sets]
        want = names[data_idx - 1]
        res = [r for r in res if r[0] == want]
    return 0, [(name, val) for _, name, val, _ in res]


@_api
def LGBM_BoosterSaveModel(handle: int, filename: str,
                          start_iteration: int = 0,
                          num_iteration: int = -1):
    _get(handle).save_model(filename,
                            None if num_iteration < 0 else num_iteration,
                            start_iteration)
    return 0, None


@_api
def LGBM_BoosterSaveModelToString(handle: int, start_iteration: int = 0,
                                  num_iteration: int = -1):
    return 0, _get(handle).model_to_string(
        None if num_iteration < 0 else num_iteration, start_iteration)


@_api
def LGBM_BoosterDumpModel(handle: int, start_iteration: int = 0,
                          num_iteration: int = -1):
    return 0, _get(handle).dump_model(
        None if num_iteration < 0 else num_iteration, start_iteration)


@_api
def LGBM_BoosterPredictForMat(handle: int, data, predict_type: int = 0,
                              start_iteration: int = 0,
                              num_iteration: int = -1,
                              parameter: str = ""):
    bst = _get(handle)
    out = bst.predict(np.asarray(data),
                      start_iteration=start_iteration,
                      num_iteration=None if num_iteration < 0 else
                      num_iteration,
                      raw_score=predict_type == C_API_PREDICT_RAW_SCORE,
                      pred_leaf=predict_type == C_API_PREDICT_LEAF_INDEX,
                      pred_contrib=predict_type == C_API_PREDICT_CONTRIB)
    return 0, out


def _predict_kwargs(predict_type, start_iteration, num_iteration):
    return dict(
        start_iteration=start_iteration,
        num_iteration=None if num_iteration < 0 else num_iteration,
        raw_score=predict_type == C_API_PREDICT_RAW_SCORE,
        pred_leaf=predict_type == C_API_PREDICT_LEAF_INDEX,
        pred_contrib=predict_type == C_API_PREDICT_CONTRIB)


@_api
def LGBM_BoosterPredictForCSR(handle: int, csr, predict_type: int = 0,
                              start_iteration: int = 0,
                              num_iteration: int = -1,
                              parameter: str = ""):
    """Sparse prediction.  Rows densify in bounded chunks only — a
    Bosch-shaped CSR never materializes as one dense matrix
    (c_api.h:896 PredictForCSR)."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    n, f = csr.shape
    step = max(1024, (1 << 24) // max(1, f))
    if n <= step:
        return 0, bst.predict(np.asarray(csr.todense()), **kw)
    parts = [bst.predict(np.asarray(csr[lo:lo + step].todense()), **kw)
             for lo in range(0, n, step)]
    return 0, np.concatenate(parts, axis=0)


@_api
def LGBM_BoosterPredictForMatSingleRow(handle: int, row,
                                       predict_type: int = 0,
                                       start_iteration: int = 0,
                                       num_iteration: int = -1,
                                       parameter: str = ""):
    """Single-row fast path (c_api.h:1018 PredictForMatSingleRow)."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    out = bst.predict(np.asarray(row, np.float64).reshape(1, -1), **kw)
    return 0, np.asarray(out)[0]


@_api
def LGBM_BoosterPredictForCSRSingleRow(handle: int, csr_row,
                                       predict_type: int = 0,
                                       start_iteration: int = 0,
                                       num_iteration: int = -1,
                                       parameter: str = ""):
    """Single sparse row (c_api.h:961 PredictForCSRSingleRow)."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    dense = np.asarray(csr_row.todense()).reshape(1, -1)
    return 0, np.asarray(bst.predict(dense, **kw))[0]


@_api
def LGBM_BoosterPredictForFile(handle: int, data_filename: str,
                               data_has_header: bool = False,
                               predict_type: int = 0,
                               start_iteration: int = 0,
                               num_iteration: int = -1,
                               parameter: str = "",
                               result_filename: str =
                               "LightGBM_predict_result.txt"):
    """File -> prediction file (c_api.h:858 PredictForFile; the CLI's
    task=predict body, application.cpp Predict)."""
    bst = _get(handle)
    params = _parse_params(parameter)
    if data_has_header:
        params.setdefault("header", True)
    from .io_utils import load_data_file
    X, _, _ = load_data_file(data_filename, params)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    out = np.atleast_1d(np.asarray(bst.predict(np.asarray(X), **kw)))
    with open(result_filename, "w") as fh:
        if out.ndim == 1:
            fh.write("\n".join(f"{v:.18g}" for v in out) + "\n")
        else:
            for r in out:
                fh.write("\t".join(f"{v:.18g}" for v in r) + "\n")
    return 0, None


@_api
def LGBM_BoosterFeatureImportance(handle: int, num_iteration: int = -1,
                                  importance_type: int = 0):
    kind = "split" if importance_type == 0 else "gain"
    return 0, _get(handle).feature_importance(kind)


@_api
def LGBM_BoosterRefit(handle: int, data, label, decay_rate: float = 0.9):
    new_bst = _get(handle).refit(np.asarray(data), np.asarray(label),
                                 decay_rate)
    return 0, _register(new_bst)


@_api
def LGBM_BoosterResetParameter(handle: int, parameters: str):
    _get(handle).reset_parameter(_parse_params(parameters))
    return 0, None


def LGBM_SetLastError(msg: str):
    """reference c_api.h:54 (the reverse direction of GetLastError)."""
    _last_error[0] = str(msg)
    return 0


@_api
def LGBM_RegisterLogCallback(callback):
    """Route every log line through ``callback(str)``
    (c_api.h:62 LGBM_RegisterLogCallback; None restores stdout)."""
    from .utils.log import register_log_callback
    register_log_callback(callback)
    return 0, None


# ---- Dataset surface, part 2 --------------------------------------------

@_api
def LGBM_DatasetCreateFromCSC(csc, parameters: str = "", label=None,
                              reference: Optional[int] = None):
    """Column-sparse create (c_api.h:160 DatasetCreateFromCSC) — the
    column-major layout feeds the EFB sparse bundler directly."""
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(csc.tocsc() if hasattr(csc, "tocsc") else csc,
                 label=label, reference=ref, params=params,
                 free_raw_data=_free_raw(params))
    ds.construct(Config(params) if ref is None else None)
    return 0, _register(ds)


@_api
def LGBM_DatasetCreateFromMats(mats, parameters: str = "", label=None,
                               reference: Optional[int] = None):
    """Multiple dense row blocks -> one dataset (c_api.h:137
    DatasetCreateFromMats)."""
    data = np.vstack([np.asarray(m, np.float64) for m in mats])
    return LGBM_DatasetCreateFromMat(data, parameters, label, reference)


@_api
def LGBM_DatasetCreateFromCSRFunc(get_row_fun, num_rows: int,
                                  num_col: int, parameters: str = "",
                                  label=None,
                                  reference: Optional[int] = None):
    """Row-callback create (c_api.h:121 DatasetCreateFromCSRFunc): the C
    ABI pulls rows through a function pointer; here ``get_row_fun(i)``
    returns ``(indices, values)`` for row i."""
    import scipy.sparse as _sp
    indptr = [0]
    indices: List[int] = []
    values: List[float] = []
    for i in range(int(num_rows)):
        idx, val = get_row_fun(i)
        indices.extend(int(j) for j in idx)
        values.extend(float(v) for v in val)
        indptr.append(len(indices))
    csr = _sp.csr_matrix(
        (np.asarray(values), np.asarray(indices, np.int32),
         np.asarray(indptr, np.int64)),
        shape=(int(num_rows), int(num_col)))
    return LGBM_DatasetCreateFromCSR(csr, parameters, label, reference)


@_api
def LGBM_DatasetCreateFromSampledColumn(sample_data, sample_indices,
                                        num_total_row: int,
                                        parameters: str = "",
                                        num_sample_row: int = 0):
    """Streaming ingestion step 0 (c_api.h:210): bin mappers are fitted
    from per-column SAMPLES, then an empty dataset of ``num_total_row``
    rows awaits LGBM_DatasetPushRows*.  ``sample_data[j]`` /
    ``sample_indices[j]`` are column j's sampled values / row indices
    within the ``num_sample_row``-row sample — unsampled cells are zero,
    so the zero fraction matches the reference's FindBin contract
    (dataset_loader.cpp:666: zeros = total_sample_size - num_per_col)."""
    params = _parse_params(parameters)
    ncol = len(sample_data)
    if not num_sample_row:
        num_sample_row = max(
            (int(np.max(np.atleast_1d(ix))) + 1 if len(np.atleast_1d(ix))
             else 0 for ix in (sample_indices or [])),
            default=0) or max(
            (len(np.atleast_1d(s)) for s in sample_data), default=0)
    samp = np.zeros((int(num_sample_row), ncol), np.float64)
    for j in range(ncol):
        vals = np.atleast_1d(sample_data[j])
        idx = np.asarray(sample_indices[j], np.int64) \
            if sample_indices is not None else np.arange(len(vals))
        samp[idx, j] = vals
    ref = Dataset(samp, params=params)
    ref.construct(Config(params))
    buf = np.zeros((int(num_total_row), ncol), np.float64)
    ds = Dataset(buf, reference=ref, params=dict(params),
                 free_raw_data=False)
    ds._stream_filled = 0
    return 0, _register(ds)


@_api
def LGBM_DatasetSetFeatureNames(handle: int, feature_names):
    ds = _get(handle)
    names = [str(n) for n in feature_names]
    ds.feature_name = names
    if getattr(ds, "feature_names_", None) is not None:
        # capi datasets construct at creation: propagate into the frozen
        # post-construct names so boosters/saved models see them too
        if len(names) != len(ds.feature_names_):
            raise ValueError(f"expected {len(ds.feature_names_)} names, "
                             f"got {len(names)}")
        ds.feature_names_ = list(names)
    return 0, None


@_api
def LGBM_DatasetGetFeatureNames(handle: int):
    ds = _get(handle)
    names = getattr(ds, "feature_name", None) or "auto"
    if names == "auto":
        names = [f"Column_{i}" for i in range(ds.num_total_features)]
    return 0, [str(n) for n in names]


@_api
def LGBM_DatasetAddFeaturesFrom(target: int, source: int):
    """Append ``source``'s features to ``target`` (c_api.h:317
    DatasetAddFeaturesFrom); both must hold raw data and equal rows."""
    tgt, src = _get(target), _get(source)
    if tgt.data is None or src.data is None:
        raise ValueError("AddFeaturesFrom needs datasets that still hold "
                         "their raw data (free_raw_data=False)")
    td = np.asarray(tgt.data.todense()
                    if hasattr(tgt.data, "todense") else tgt.data)
    sd = np.asarray(src.data.todense()
                    if hasattr(src.data, "todense") else src.data)
    if len(td) != len(sd):
        raise ValueError(f"row mismatch: {len(td)} vs {len(sd)}")

    def _names(ds, width):
        n = getattr(ds, "feature_name", None) or "auto"
        return list(n) if n != "auto" else \
            [f"Column_{i}" for i in range(width)]

    merged = Dataset(np.hstack([td, sd]),
                     label=tgt.metadata.label if tgt.constructed
                     else getattr(tgt, "_label_arg", None),
                     params=dict(tgt.params), free_raw_data=False)
    merged.feature_name = _names(tgt, td.shape[1]) + _names(src, sd.shape[1])
    if tgt.constructed:
        merged.construct(Config(tgt.params))
        # the reference mutates the target in place and keeps its
        # Metadata — weight/group/init_score must survive the merge
        md = tgt.metadata
        if md.weight is not None:
            merged.set_weight(md.weight)
        if md.group is not None:
            merged.set_group(md.group)
        if md.init_score is not None:
            merged.set_init_score(md.init_score)
    # the merged dataset replaces the target IN PLACE so the caller's
    # handle stays valid (the reference mutates the target Dataset too)
    tgt.__dict__.clear()
    tgt.__dict__.update(merged.__dict__)
    return 0, None


@_api
def LGBM_DatasetDumpText(handle: int, filename: str):
    """Dump the BINNED dataset as text (c_api.h:372 DatasetDumpText;
    reference dataset.cpp DumpTextFile) — a debugging surface."""
    ds = _get(handle)
    _check_stream_complete(ds)
    if not ds.constructed:
        ds.construct(Config(ds.params))
    with open(filename, "w") as fh:
        fh.write(f"num_data: {ds.num_data()}\n")
        fh.write(f"num_features: {ds.num_feature()}\n")
        names = LGBM_DatasetGetFeatureNames(handle)[1]
        fh.write("feature_names: " + "\t".join(names) + "\n")
        xb = ds.X_binned
        if ds.efb is not None:
            # device columns are EFB bundles, not per-feature bins —
            # label the rows honestly so the dump stays self-consistent
            fh.write(f"num_device_columns: {xb.shape[1]} "
                     "(EFB bundle-space bin codes follow)\n")
        for i in range(min(len(xb), ds.num_data())):
            fh.write("\t".join(str(int(v)) for v in xb[i]) + "\n")
    return 0, None


@_api
def LGBM_DatasetUpdateParamChecking(old_parameters: str,
                                    new_parameters: str):
    """Validate that changed params do not alter the binned data
    (c_api.h:351; reference Dataset::ValidateSampleCount /
    config.cpp CheckParamConflict)."""
    frozen = ("max_bin", "min_data_in_bin", "bin_construct_sample_cnt",
              "enable_bundle", "use_missing", "zero_as_missing",
              "categorical_feature", "feature_pre_filter",
              "forcedbins_filename", "data_random_seed", "two_round",
              "pre_partition", "header", "label_column", "weight_column",
              "group_column", "ignore_column", "is_enable_sparse",
              "linear_tree", "precise_float_parser")
    old = _parse_params(old_parameters)
    new = _parse_params(new_parameters)
    for k in frozen:
        if old.get(k) != new.get(k):
            raise ValueError(
                f"cannot change {k} after the Dataset was constructed "
                f"({old.get(k)!r} -> {new.get(k)!r}); build a new Dataset")
    return 0, None


# ---- Booster surface, part 2 --------------------------------------------

@_api
def LGBM_BoosterMerge(handle: int, other_handle: int):
    """Append ``other``'s trees to ``handle``'s model (c_api.h:489)."""
    bst, other = _get(handle), _get(other_handle)
    bst._gbdt.merge_from(other._gbdt)
    return 0, None


@_api
def LGBM_BoosterResetTrainingData(handle: int, train_data: int):
    """Swap the training dataset, keeping the model (c_api.h:478;
    reference GBDT::ResetTrainingData) — continued training resumes on
    the new rows with scores rebuilt from the existing trees."""
    _check_stream_complete(_get(train_data))
    _get(handle).reset_train_data(_get(train_data))
    return 0, None


@_api
def LGBM_BoosterShuffleModels(handle: int, start_iter: int, end_iter: int):
    """Shuffle tree order in [start_iter, end_iter) (c_api.h:497)."""
    _get(handle)._gbdt.shuffle_models(int(start_iter), int(end_iter))
    return 0, None


def _eval_metrics(handle: int):
    """Training metrics, lazily created + CACHED on the booster (the
    reference's Booster always builds them from the metric config,
    c_api.cpp CreateObjectiveAndMetrics)."""
    g = _get(handle)._gbdt
    if not g.train_metrics and g.train_set is not None:
        from .metric import create_metrics
        ms = create_metrics(g.config)
        for m in ms:
            m.init(g.train_set.metadata, g.num_data)
        g.train_metrics = ms
    return g.train_metrics


@_api
def LGBM_BoosterGetEvalCounts(handle: int):
    """Number of eval VALUES per GetEval call — multi-position metrics
    (ndcg/map with eval_at) count one per position, matching the
    reference's sum over Metric::GetName() sizes (c_api.cpp:772)."""
    return 0, sum(len(m.eval_names) for m in _eval_metrics(handle))


@_api
def LGBM_BoosterGetEvalNames(handle: int):
    return 0, [n for m in _eval_metrics(handle) for n in m.eval_names]


@_api
def LGBM_BoosterGetNumPredict(handle: int, data_idx: int):
    """Length of the inner prediction buffer for train (0) / valid i
    (c_api.h:724)."""
    g = _get(handle)._gbdt
    score = g.score if data_idx == 0 else g.valid_scores[data_idx - 1]
    return 0, int(np.asarray(score).size)


@_api
def LGBM_BoosterGetPredict(handle: int, data_idx: int):
    """Inner predictions (objective-transformed scores) of the training
    (0) or i-th validation data (c_api.h:736; c_api.cpp GetPredictAt)."""
    g = _get(handle)._gbdt
    score = g.score if data_idx == 0 else g.valid_scores[data_idx - 1]
    out = np.asarray(g.objective.convert_output(score))
    return 0, out.reshape(-1) if out.ndim == 1 else out


@_api
def LGBM_BoosterGetLeafValue(handle: int, tree_idx: int, leaf_idx: int):
    t = _get(handle)._gbdt.models[int(tree_idx)]
    return 0, float(t.leaf_value[int(leaf_idx)])


@_api
def LGBM_BoosterSetLeafValue(handle: int, tree_idx: int, leaf_idx: int,
                             val: float):
    g = _get(handle)._gbdt
    # mutates the host-side model only (like the reference's
    # Tree::SetLeafOutput): predictions read host trees per call, while
    # training scores keep their pre-edit values, same as the reference
    g.models[int(tree_idx)].leaf_value[int(leaf_idx)] = float(val)
    return 0, None


@_api
def LGBM_BoosterGetLinear(handle: int):
    g = _get(handle)._gbdt
    return 0, int(any(getattr(t, "is_linear", False) for t in g.models))


@_api
def LGBM_BoosterGetLowerBoundValue(handle: int):
    """Sum over trees of each tree's minimum leaf value (c_api.h:565)."""
    g = _get(handle)._gbdt
    return 0, float(sum(
        float(np.min(t.leaf_value[:t.num_leaves])) for t in g.models))


@_api
def LGBM_BoosterGetUpperBoundValue(handle: int):
    g = _get(handle)._gbdt
    return 0, float(sum(
        float(np.max(t.leaf_value[:t.num_leaves])) for t in g.models))


@_api
def LGBM_BoosterCalcNumPredict(handle: int, num_row: int,
                               predict_type: int = 0,
                               start_iteration: int = 0,
                               num_iteration: int = -1):
    """Output length of a predict call (c_api.h:771 CalcNumPredict)."""
    g = _get(handle)._gbdt
    k = g.num_tree_per_iteration
    total_iter = len(g.models) // max(k, 1)
    ni = max(0, total_iter - start_iteration if num_iteration < 0 else
             min(num_iteration, total_iter - start_iteration))
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        per_row = ni * k
    elif predict_type == C_API_PREDICT_CONTRIB:
        per_row = (g.num_features + 1) * k
    else:
        per_row = k
    return 0, int(num_row) * per_row


@_api
def LGBM_BoosterPredictForCSC(handle: int, csc, predict_type: int = 0,
                              start_iteration: int = 0,
                              num_iteration: int = -1,
                              parameter: str = ""):
    """Column-sparse prediction (c_api.h:1003 PredictForCSC): converted
    to row-sparse once, then the bounded-chunk CSR path."""
    return LGBM_BoosterPredictForCSR(handle, csc.tocsr(), predict_type,
                                     start_iteration, num_iteration,
                                     parameter)


@_api
def LGBM_BoosterPredictForMats(handle: int, mats, predict_type: int = 0,
                               start_iteration: int = 0,
                               num_iteration: int = -1,
                               parameter: str = ""):
    """Predict rows given as a list of single-row arrays (c_api.h:1097
    PredictForMats)."""
    data = np.vstack([np.asarray(m, np.float64).reshape(1, -1)
                      for m in mats])
    return LGBM_BoosterPredictForMat(handle, data, predict_type,
                                     start_iteration, num_iteration,
                                     parameter)


@_api
def LGBM_BoosterPredictSparseOutput(handle: int, csr, predict_type: int = 3,
                                    start_iteration: int = 0,
                                    num_iteration: int = -1,
                                    matrix_type: int = 0,
                                    parameter: str = ""):
    """SHAP contributions as a sparse matrix (c_api.h:920
    PredictSparseOutput; matrix_type 0 = CSR, 1 = CSC).  Zero
    contributions are squeezed out, like the reference's sparse
    contrib path."""
    import scipy.sparse as _sp
    if predict_type != C_API_PREDICT_CONTRIB:
        raise ValueError("sparse output is defined for contrib "
                         "predictions (predict_type=3)")
    rc, dense = LGBM_BoosterPredictForCSR(
        handle, csr, predict_type, start_iteration, num_iteration,
        parameter)
    dense = np.asarray(dense)
    if dense.ndim == 3:   # multiclass: (n, k, f+1) -> stacked rows
        dense = dense.reshape(dense.shape[0] * dense.shape[1], -1)
    out = _sp.csr_matrix(dense)
    return 0, out.tocsc() if matrix_type == 1 else out


@_api
def LGBM_BoosterFreePredictSparse(handle_or_matrix=None):
    """No-op here: sparse predict results are garbage-collected Python
    objects, not C allocations (c_api.h:950 FreePredictSparse)."""
    return 0, None


# ---- fast single-row predict (c_api.h:1018-1140) -------------------------

class _FastConfig:
    __slots__ = ("booster", "kwargs", "ncol", "dtype")

    def __init__(self, booster, kwargs, ncol, dtype=1):
        self.booster = booster
        self.kwargs = kwargs
        self.ncol = ncol
        self.dtype = dtype


@_api
def LGBM_BoosterPredictForMatSingleRowFastInit(handle: int,
                                               predict_type: int = 0,
                                               start_iteration: int = 0,
                                               num_iteration: int = -1,
                                               data_type: int = 1,
                                               ncol: int = -1,
                                               parameter: str = ""):
    """Bind predict configuration once (c_api.h:1060 SingleRowFastInit);
    per-call overhead then drops to the row marshalling alone."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    return 0, _register(_FastConfig(bst, kw, int(ncol), int(data_type)))


@_api
def LGBM_BoosterPredictForMatSingleRowFast(fast_config: int, row):
    """Predict one dense row against a bound config (c_api.h:1090)."""
    fc = _get(fast_config)
    r = np.asarray(row, np.float64).reshape(1, -1)
    return 0, np.asarray(fc.booster.predict(r, **fc.kwargs))[0]


@_api
def LGBM_BoosterPredictForCSRSingleRowFastInit(handle: int,
                                               predict_type: int = 0,
                                               start_iteration: int = 0,
                                               num_iteration: int = -1,
                                               data_type: int = 1,
                                               num_col: int = -1,
                                               parameter: str = ""):
    """c_api.h:1018 CSRSingleRowFastInit."""
    bst = _get(handle)
    kw = _predict_kwargs(predict_type, start_iteration, num_iteration)
    return 0, _register(_FastConfig(bst, kw, int(num_col), int(data_type)))


@_api
def LGBM_BoosterPredictForCSRSingleRowFast(fast_config: int, csr_row):
    """c_api.h:1043 CSRSingleRowFast."""
    fc = _get(fast_config)
    if hasattr(csr_row, "todense"):
        dense = np.asarray(csr_row.todense(), np.float64).reshape(1, -1)
    else:  # (indices, values) pair against the bound ncol
        idx, val = csr_row
        dense = np.zeros((1, fc.ncol), np.float64)
        dense[0, np.asarray(idx, np.int64)] = np.asarray(val, np.float64)
    return 0, np.asarray(fc.booster.predict(dense, **fc.kwargs))[0]


@_api
def LGBM_FastConfigFree(fast_config: int):
    with _lock:
        _handles.pop(fast_config, None)
    return 0, None


# ---- network (c_api.h:1274) ---------------------------------------------

@_api
def LGBM_NetworkInit(machines: str, local_listen_port: int,
                     listen_time_out: int, num_machines: int):
    if num_machines > 1:
        raise NotImplementedError(
            "socket meshes are replaced by the JAX runtime: call "
            "lightgbm_tpu.distributed.init(...) per process instead")
    log_warning("LGBM_NetworkInit with one machine is a no-op")
    return 0, None


@_api
def LGBM_NetworkFree():
    return 0, None


@_api
def LGBM_NetworkInitWithFunctions(num_machines: int, rank: int,
                                  reduce_scatter_ext_fun=None,
                                  allgather_ext_fun=None):
    """External-collective bootstrap (c_api.h:1293).  The reference lets
    MPI-like runtimes inject reduce-scatter/allgather function pointers;
    here collectives are XLA's own — multi-process setups must use
    lightgbm_tpu.distributed.init, which wires the SAME degrees of
    freedom (rank, world size) into the JAX runtime."""
    if num_machines > 1:
        raise NotImplementedError(
            "external collective functions are replaced by XLA "
            "collectives: call lightgbm_tpu.distributed.init(...) per "
            "process instead")
    return 0, None


__all__ = sorted(n for n in dir() if n.startswith("LGBM_"))
