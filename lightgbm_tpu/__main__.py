"""``python -m lightgbm_tpu`` — the CLI entry point (reference
src/main.cpp:11)."""

import sys

from .cli import main  # the package __init__ honors JAX_PLATFORMS

if __name__ == "__main__":
    sys.exit(main())
