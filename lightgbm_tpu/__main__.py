"""``python -m lightgbm_tpu`` — the CLI entry point (reference
src/main.cpp:11).  Tasks: train / predict / refit / convert_model via
``key=value`` args, plus the serving verb
``python -m lightgbm_tpu serve model.txt [port=8080 ...]``, the fleet
verb ``python -m lightgbm_tpu serve-fleet model.txt [workers=4 ...]``
(N supervised worker processes behind a crash-tolerant dispatcher), the
profiling verb ``python -m lightgbm_tpu profile config=train.conf``
(jax.profiler capture + telemetry dump) and the trace-lint verb
``python -m lightgbm_tpu lint-trace [configs=...] [out=report.json]``
(static analysis of the traced program matrix against the declared
collective/dtype/retrace/donation contracts; exits nonzero on any
violation)."""

import sys

from .cli import main  # the package __init__ honors JAX_PLATFORMS

if __name__ == "__main__":
    sys.exit(main())
