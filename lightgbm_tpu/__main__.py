"""``python -m lightgbm_tpu`` — the CLI entry point (reference
src/main.cpp:11).  Tasks: train / predict / refit / convert_model via
``key=value`` args, plus the serving verb
``python -m lightgbm_tpu serve model.txt [port=8080 ...]``."""

import sys

from .cli import main  # the package __init__ honors JAX_PLATFORMS

if __name__ == "__main__":
    sys.exit(main())
