"""Training callbacks (reference python-package/lightgbm/callback.py:
``print_evaluation``:52, ``record_evaluation``:78, ``reset_parameter``:106,
``early_stopping``:147-242 raising EarlyStopException)."""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List

from .utils.log import log_info, log_warning

__all__ = ["EarlyStopException", "CallbackEnv", "print_evaluation",
           "log_evaluation", "record_evaluation", "reset_parameter",
           "early_stopping"]


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score) -> None:
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _fmt_eval(res) -> str:
    data_name, eval_name, value, _ = res
    return f"{data_name}'s {eval_name}: {value:g}"


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(_fmt_eval(x) for x in env.evaluation_result_list)
            log_info(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    return _callback


log_evaluation = print_evaluation


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result must be a dict")
    eval_result.clear()

    def _callback(env: CallbackEnv) -> None:
        for data_name, eval_name, value, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    """Reset parameters on a schedule: value is a list (per iteration) or a
    function iteration -> value (reference callback.py:106)."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"list length of {key} must match "
                                     "num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("reset_parameter values must be list or callable")
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """Early stopping on validation metrics (reference callback.py:147)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            log_warning("Early stopping is not available in dart mode"
                        if env.params.get("boosting") == "dart"
                        else "For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
            return
        if verbose:
            log_info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds")
        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for res in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if res[3]:  # higher is better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, res in enumerate(env.evaluation_result_list):
            data_name, eval_name, score, _ = res
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if data_name == "training":
                continue  # training metric never triggers stopping
            if first_metric_only and eval_name.split(" ")[-1] != first_metric[0]:
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log_info(f"Early stopping, best iteration is:\n"
                             f"[{best_iter[i] + 1}]\t" + "\t".join(
                                 _fmt_eval(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    log_info(f"Did not meet early stopping. Best iteration is:"
                             f"\n[{best_iter[i] + 1}]\t" + "\t".join(
                                 _fmt_eval(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    return _callback
