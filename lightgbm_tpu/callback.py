"""Training callbacks (reference python-package/lightgbm/callback.py:
``print_evaluation``:52, ``record_evaluation``:78, ``reset_parameter``:106,
``early_stopping``:147-242 raising EarlyStopException)."""

from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from .utils.log import log_info, log_warning

__all__ = ["EarlyStopException", "CallbackEnv", "print_evaluation",
           "log_evaluation", "record_evaluation", "reset_parameter",
           "early_stopping"]


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score) -> None:
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _fmt_eval(res) -> str:
    data_name, eval_name, value, _ = res
    return f"{data_name}'s {eval_name}: {value:g}"


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(_fmt_eval(x) for x in env.evaluation_result_list)
            log_info(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    return _callback


log_evaluation = print_evaluation


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result must be a dict")
    eval_result.clear()

    def _callback(env: CallbackEnv) -> None:
        for data_name, eval_name, value, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(value)
    _callback.order = 20
    # resume support: train(resume_from=...) refills this dict with the
    # checkpointed eval history so the user's record survives preemption
    _callback.eval_result = eval_result
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    """Reset parameters on a schedule: value is a list (per iteration) or a
    function iteration -> value (reference callback.py:106)."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"list length of {key} must match "
                                     "num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("reset_parameter values must be list or callable")
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def _metric_tag(name: str) -> str:
    """Trailing token of a (possibly composite) metric name."""
    return name.rsplit(" ", 1)[-1]


@dataclass
class _MetricTracker:
    """Best-so-far state of one (dataset, metric) evaluation stream."""
    higher_better: bool
    best_score: float = None
    best_iter: int = 0
    snapshot: Any = None  # full eval list at the best iteration

    def observe(self, score: float, iteration: int, results) -> None:
        if self.snapshot is None or (
                score > self.best_score if self.higher_better
                else score < self.best_score):
            self.best_score = score
            self.best_iter = iteration
            self.snapshot = results


class _EarlyStopper:
    """Stateful early-stopping callback: stop when no validation metric
    improved for ``rounds`` consecutive iterations (the contract of the
    reference's early_stopping callback, callback.py:147).

    One :class:`_MetricTracker` per evaluation stream; training-set
    streams update their tracker (so best_score reports them) but never
    drive the stop decision, and ``first_metric_only`` restricts the
    decision to streams whose metric name matches the first stream's.
    """

    order = 30

    def __init__(self, rounds: int, first_metric_only: bool,
                 verbose: bool) -> None:
        self.rounds = int(rounds)
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.trackers: Optional[List[_MetricTracker]] = None
        self.active = True
        self.first_metric_name = ""

    def _start(self, env: CallbackEnv) -> None:
        results = env.evaluation_result_list
        self.active = bool(results)
        if not self.active:
            if env.params.get("boosting") == "dart":
                log_warning("Early stopping is not available in dart mode")
            else:
                log_warning("For early stopping, at least one dataset and "
                            "eval metric is required for evaluation")
            return
        if self.verbose:
            log_info(f"Training until validation scores don't improve for "
                     f"{self.rounds} rounds")
        # custom fevals may produce composite "prefix metric" names;
        # streams are matched on the trailing token like the reference
        self.first_metric_name = _metric_tag(results[0][1])
        self.trackers = [_MetricTracker(higher_better=hb)
                         for (_, _, _, hb) in results]

    def _stop(self, tracker: _MetricTracker, reached_end: bool) -> None:
        if self.verbose:
            head = ("Did not meet early stopping. Best iteration is:"
                    if reached_end else "Early stopping, best iteration is:")
            body = "\t".join(_fmt_eval(x) for x in tracker.snapshot)
            log_info(f"{head}\n[{tracker.best_iter + 1}]\t{body}")
        raise EarlyStopException(tracker.best_iter, tracker.snapshot)

    # -- checkpoint support --------------------------------------------------
    def state_dict(self) -> dict:
        """JSON-ready best-so-far bookkeeping, captured mid-train by the
        resilience checkpoint so a resumed run keeps counting patience
        from the same best iteration instead of restarting it."""
        return {
            "rounds": self.rounds,
            "first_metric_only": self.first_metric_only,
            "first_metric_name": self.first_metric_name,
            "trackers": None if self.trackers is None else [
                {"higher_better": t.higher_better,
                 "best_score": t.best_score,
                 "best_iter": t.best_iter,
                 "snapshot": None if t.snapshot is None else
                 [list(row) for row in t.snapshot]}
                for t in self.trackers],
        }

    def load_state_dict(self, state: dict) -> None:
        self.first_metric_name = state.get("first_metric_name", "")
        trackers = state.get("trackers")
        if trackers is None:
            self.trackers = None
            return
        self.trackers = []
        self.active = True
        for t in trackers:
            tr = _MetricTracker(higher_better=bool(t["higher_better"]),
                                best_score=t["best_score"],
                                best_iter=int(t["best_iter"]))
            tr.snapshot = None if t["snapshot"] is None else \
                [(r[0], r[1], float(r[2]), bool(r[3]))
                 for r in t["snapshot"]]
            self.trackers.append(tr)
        if not self.trackers:
            self.trackers = None

    def __call__(self, env: CallbackEnv) -> None:
        if self.trackers is None and self.active:
            self._start(env)
        if not self.active:
            return
        results = env.evaluation_result_list
        last_iter = env.iteration == env.end_iteration - 1
        for tracker, (data_name, metric_name, score, _) in zip(
                self.trackers, results):
            tracker.observe(score, env.iteration, results)
            if data_name == "training":
                continue  # training metrics never trigger stopping
            if self.first_metric_only and \
                    _metric_tag(metric_name) != self.first_metric_name:
                continue
            if env.iteration - tracker.best_iter >= self.rounds or \
                    last_iter:
                self._stop(tracker, reached_end=last_iter and
                           env.iteration - tracker.best_iter < self.rounds)


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """Early stopping on validation metrics (reference callback.py:147's
    surface; implementation is the tracker-based :class:`_EarlyStopper`)."""
    return _EarlyStopper(stopping_rounds, first_metric_only, verbose)
