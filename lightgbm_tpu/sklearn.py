"""scikit-learn API wrappers (reference python-package/lightgbm/sklearn.py:
``LGBMModel`` + Classifier/Regressor/Ranker, 981 LoC — estimator params map
to Config names, fit/predict with eval sets, custom objective adapters)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster
from .callback import early_stopping as early_stopping_cb
from .dataset import Dataset
from .engine import train as engine_train

__all__ = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]


class LGBMModel:
    """Base sklearn-style estimator (reference sklearn.py LGBMModel)."""

    _objective_default: Optional[str] = None

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight: Optional[Union[Dict, str]] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs: Any) -> None:
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._n_features = -1
        self._classes = None

    # sklearn plumbing ------------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {k: getattr(self, k) for k in (
            "boosting_type", "num_leaves", "max_depth", "learning_rate",
            "n_estimators", "subsample_for_bin", "objective", "class_weight",
            "min_split_gain", "min_child_weight", "min_child_samples",
            "subsample", "subsample_freq", "colsample_bytree", "reg_alpha",
            "reg_lambda", "random_state", "n_jobs", "importance_type")}
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _make_params(self) -> Dict[str, Any]:
        p = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1,
        }
        if self.random_state is not None:
            p["seed"] = int(self.random_state)
        obj = self.objective or self._objective_default
        if obj is not None and not callable(obj):
            p["objective"] = obj
        p.update(self._other_params)
        return p

    # fitting ----------------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            early_stopping_rounds=None, feature_name="auto",
            categorical_feature="auto", callbacks=None) -> "LGBMModel":
        params = self._make_params()
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric

        fobj = None
        if callable(self.objective):
            fobj = _wrap_sklearn_objective(self.objective)
            params["objective"] = "none"

        y_arr = np.asarray(y).ravel()
        y_fit, extra = self._process_label(y_arr, params)
        params.update(extra)
        if self.class_weight is not None and "is_unbalance" not in params:
            if self.class_weight == "balanced":
                params["is_unbalance"] = True
            elif isinstance(self.class_weight, dict):
                cw = np.asarray([self.class_weight.get(int(c), 1.0)
                                 for c in y_fit.astype(int)])
                sample_weight = (cw if sample_weight is None
                                 else np.asarray(sample_weight) * cw)

        train_set = Dataset(X, label=y_fit, weight=sample_weight,
                            group=group, init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vy_arr = self._transform_eval_label(np.asarray(vy).ravel())
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(train_set.create_valid(
                    vx, label=vy_arr, weight=vw, group=vg, init_score=vi))
                valid_names.append(eval_names[i] if eval_names else f"valid_{i}")

        callbacks = list(callbacks or [])
        if early_stopping_rounds is not None and early_stopping_rounds > 0:
            callbacks.append(early_stopping_cb(early_stopping_rounds))

        feval = _wrap_sklearn_metric(eval_metric) if callable(eval_metric) else None
        self._evals_result = {}
        from .callback import record_evaluation
        callbacks.append(record_evaluation(self._evals_result))

        self._Booster = engine_train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None,
            fobj=fobj, feval=feval, callbacks=callbacks)
        self._n_features = self._Booster.num_feature()
        return self

    def _process_label(self, y, params):
        return y, {}

    def _transform_eval_label(self, y):
        return y

    # prediction -------------------------------------------------------------
    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        self._check_fitted()
        return self._Booster.predict(X, raw_score=raw_score,
                                     start_iteration=start_iteration,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    def _check_fitted(self):
        if self._Booster is None:
            raise RuntimeError("Estimator not fitted, call fit first")

    # attributes -------------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._Booster.best_iteration

    @property
    def best_score_(self) -> Dict:
        self._check_fitted()
        return self._Booster.best_score

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()


class LGBMRegressor(LGBMModel):
    _objective_default = "regression"

    def fit(self, X, y, **kwargs) -> "LGBMRegressor":
        super().fit(X, y, **kwargs)
        return self


class LGBMClassifier(LGBMModel):
    _objective_default = "binary"

    def _process_label(self, y, params):
        self._classes, y_enc = np.unique(y, return_inverse=True)
        n_classes = len(self._classes)
        extra = {}
        if n_classes > 2:
            obj = self.objective or "multiclass"
            if not callable(obj):
                extra["objective"] = obj if obj in ("multiclass", "multiclassova") \
                    else "multiclass"
            extra["num_class"] = n_classes
        return y_enc.astype(np.float64), extra

    def _transform_eval_label(self, y):
        if self._classes is not None:
            lookup = {c: i for i, c in enumerate(self._classes)}
            return np.asarray([lookup[v] for v in y], np.float64)
        return y

    @property
    def classes_(self):
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return len(self._classes)

    def predict(self, X, raw_score=False, start_iteration=0,
                num_iteration=None, pred_leaf=False, pred_contrib=False,
                **kwargs):
        result = self.predict_proba(X, raw_score=raw_score,
                                    start_iteration=start_iteration,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            idx = (result > 0.5).astype(int)
        else:
            idx = np.argmax(result, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score=False, start_iteration=0,
                      num_iteration=None, pred_leaf=False, pred_contrib=False,
                      **kwargs):
        self._check_fitted()
        res = self._Booster.predict(X, raw_score=raw_score,
                                    start_iteration=start_iteration,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return res
        if res.ndim == 1:
            return np.stack([1.0 - res, res], axis=1) if not raw_score else res
        return res


class LGBMRanker(LGBMModel):
    _objective_default = "lambdarank"

    def fit(self, X, y, group=None, **kwargs) -> "LGBMRanker":
        if group is None:
            raise ValueError("LGBMRanker.fit requires group")
        super().fit(X, y, group=group, **kwargs)
        return self


def _wrap_sklearn_objective(func):
    """sklearn custom objective (y_true, y_pred) -> engine fobj(preds, ds)."""
    def fobj(preds, dataset):
        label = dataset.get_label()
        out = func(label, preds)
        return out
    return fobj


def _wrap_sklearn_metric(func):
    def feval(preds, dataset):
        label = dataset.get_label()
        return func(label, preds)
    return feval
