"""Config / parameter system.

TPU-native re-implementation of the reference parameter surface
(reference: include/LightGBM/config.h:34 ``struct Config``, alias table in
src/io/config_auto.cpp:10 ``Config::alias_table``).  The reference drives its
parsing code off doc-comments via helpers/parameter_generator.py; here the
single source of truth is the ``_PARAMS`` schema table below, from which
parsing, alias resolution, validation and docs are all derived.

Every parameter keeps the reference's canonical name, aliases, default and
constraint so user params written for the reference work unmodified
(``device_type='tpu'`` is the only new value).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Config", "ParamSpec", "PARAM_ALIASES", "resolve_param_aliases"]


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    name: str
    type: type
    default: Any
    aliases: Tuple[str, ...] = ()
    check: Optional[str] = None  # human-readable constraint, e.g. ">=0.0"


def _p(name, typ, default, aliases=(), check=None):
    return ParamSpec(name, typ, default, tuple(aliases), check)


# Schema mirroring reference include/LightGBM/config.h declarations (line refs
# there).  Types: bool/int/float/str and list[...] for vector params.
_PARAMS: List[ParamSpec] = [
    # --- core (config.h:93-268) ---
    _p("config", str, "", ("config_file",)),
    _p("task", str, "train", ("task_type",)),
    _p("objective", str, "regression", ("objective_type", "app", "application")),
    _p("boosting", str, "gbdt", ("boosting_type", "boost")),
    _p("linear_tree", bool, False),
    _p("data", str, "", ("train", "train_data", "train_data_file", "data_filename")),
    _p("valid", str, "", ("test", "valid_data", "valid_data_file", "test_data",
                          "test_data_file", "valid_filenames")),
    _p("num_iterations", int, 100,
       ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
        "num_rounds", "num_boost_round", "n_estimators"), check=">=0"),
    _p("learning_rate", float, 0.1, ("shrinkage_rate", "eta"), check=">0.0"),
    _p("num_leaves", int, 31, ("num_leaf", "max_leaves", "max_leaf"), check="1<v<=131072"),
    _p("tree_learner", str, "serial", ("tree", "tree_type", "tree_learner_type")),
    _p("num_threads", int, 0, ("num_thread", "nthread", "nthreads", "n_jobs")),
    _p("device_type", str, "tpu", ("device",)),
    _p("seed", int, 0, ("random_seed", "random_state")),
    _p("deterministic", bool, False),
    _p("force_col_wise", bool, False),
    _p("force_row_wise", bool, False),
    _p("histogram_pool_size", float, -1.0, ("hist_pool_size",)),
    _p("max_depth", int, -1),
    _p("min_data_in_leaf", int, 20, ("min_data_per_leaf", "min_data", "min_child_samples"),
       check=">=0"),
    _p("min_sum_hessian_in_leaf", float, 1e-3,
       ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian", "min_child_weight"),
       check=">=0.0"),
    # --- learning control (config.h:292-546) ---
    _p("bagging_fraction", float, 1.0, ("sub_row", "subsample", "bagging"),
       check="0.0<v<=1.0"),
    _p("pos_bagging_fraction", float, 1.0, ("pos_sub_row", "pos_subsample", "pos_bagging"),
       check="0.0<v<=1.0"),
    _p("neg_bagging_fraction", float, 1.0, ("neg_sub_row", "neg_subsample", "neg_bagging"),
       check="0.0<v<=1.0"),
    _p("bagging_freq", int, 0, ("subsample_freq",)),
    _p("bagging_seed", int, 3, ("bagging_fraction_seed",)),
    _p("feature_fraction", float, 1.0, ("sub_feature", "colsample_bytree"),
       check="0.0<v<=1.0"),
    _p("feature_fraction_bynode", float, 1.0, ("sub_feature_bynode", "colsample_bynode"),
       check="0.0<v<=1.0"),
    _p("feature_fraction_seed", int, 2),
    _p("extra_trees", bool, False),
    _p("extra_seed", int, 6),
    _p("early_stopping_round", int, 0,
       ("early_stopping_rounds", "early_stopping", "n_iter_no_change")),
    _p("first_metric_only", bool, False),
    _p("max_delta_step", float, 0.0, ("max_tree_output", "max_leaf_output")),
    _p("lambda_l1", float, 0.0, ("reg_alpha",), check=">=0.0"),
    _p("lambda_l2", float, 0.0, ("reg_lambda", "lambda"), check=">=0.0"),
    _p("linear_lambda", float, 0.0, check=">=0.0"),
    _p("min_gain_to_split", float, 0.0, ("min_split_gain",), check=">=0.0"),
    _p("drop_rate", float, 0.1, ("rate_drop",), check="0.0<=v<=1.0"),
    _p("max_drop", int, 50),
    _p("skip_drop", float, 0.5, check="0.0<=v<=1.0"),
    _p("xgboost_dart_mode", bool, False),
    _p("uniform_drop", bool, False),
    _p("drop_seed", int, 4),
    _p("top_rate", float, 0.2, check="0.0<=v<=1.0"),
    _p("other_rate", float, 0.1, check="0.0<=v<=1.0"),
    _p("min_data_per_group", int, 100, check=">0"),
    _p("max_cat_threshold", int, 32, check=">0"),
    _p("cat_l2", float, 10.0, check=">=0.0"),
    _p("cat_smooth", float, 10.0, check=">=0.0"),
    _p("max_cat_to_onehot", int, 4, check=">0"),
    _p("top_k", int, 20, ("topk",), check=">0"),
    _p("monotone_constraints", list, None, ("mc", "monotone_constraint")),
    _p("monotone_constraints_method", str, "basic",
       ("monotone_constraining_method", "mc_method")),
    _p("monotone_penalty", float, 0.0, ("monotone_splits_penalty", "ms_penalty", "mc_penalty"),
       check=">=0.0"),
    _p("feature_contri", list, None, ("feature_contrib", "fc", "fp", "feature_penalty")),
    _p("forcedsplits_filename", str, "",
       ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits")),
    _p("refit_decay_rate", float, 0.9, check="0.0<=v<=1.0"),
    _p("cegb_tradeoff", float, 1.0, check=">=0.0"),
    _p("cegb_penalty_split", float, 0.0, check=">=0.0"),
    _p("cegb_penalty_feature_lazy", list, None),
    _p("cegb_penalty_feature_coupled", list, None),
    _p("path_smooth", float, 0.0, check=">=0.0"),
    _p("interaction_constraints", str, ""),
    _p("verbosity", int, 1, ("verbose",)),
    # --- IO / model (config.h:559-711) ---
    _p("input_model", str, "", ("model_input", "model_in")),
    _p("output_model", str, "LightGBM_model.txt", ("model_output", "model_out")),
    _p("saved_feature_importance_type", int, 0),
    _p("snapshot_freq", int, -1, ("save_period",)),
    # fault tolerance (lightgbm_tpu/resilience/): full-state checkpoint
    # bundles next to the reference's model-text snapshots.  checkpoint_dir
    # defaults to "<output_model>.ckpt" when snapshot_freq > 0; setting it
    # explicitly enables checkpointing even without snapshot_freq (then
    # every iteration).  resume: "" (off), "latest"/"auto" (newest bundle
    # in checkpoint_dir; cold-start friendly), or a bundle/directory path.
    _p("checkpoint_dir", str, "", ("checkpoint_directory",)),
    _p("checkpoint_keep", int, 3, ("checkpoint_ring",), check=">0"),
    _p("resume", str, "", ("resume_from",)),
    # training flight recorder (telemetry/flight.py): bounded ring of
    # per-iteration structured events, dumped to JSONL by the
    # PreemptionGuard/crash path (into flight_dir, defaulting to the
    # checkpoint dir).  Observation-only run directives like resume/
    # checkpoint_dir: excluded from the model-text params dump so
    # recorder-on and recorder-off models match byte for byte.
    _p("flight_recorder", bool, True),
    _p("flight_events", int, 1024, check=">0"),
    _p("flight_dir", str, ""),
    _p("max_bin", int, 255, check="1<v<=65535"),
    _p("min_data_in_bin", int, 3, check=">0"),
    _p("bin_construct_sample_cnt", int, 200000, ("subsample_for_bin",), check=">0"),
    _p("data_random_seed", int, 1, ("data_seed",)),
    _p("is_enable_sparse", bool, True, ("is_sparse", "enable_sparse", "sparse")),
    _p("enable_bundle", bool, True, ("is_enable_bundle", "bundle")),
    _p("use_missing", bool, True),
    _p("zero_as_missing", bool, False),
    _p("feature_pre_filter", bool, True),
    _p("pre_partition", bool, False, ("is_pre_partition",)),
    _p("two_round", bool, False, ("two_round_loading", "use_two_round_loading")),
    _p("header", bool, False, ("has_header",)),
    _p("label_column", str, "", ("label",)),
    _p("weight_column", str, "", ("weight",)),
    _p("group_column", str, "",
       ("group", "group_id", "query_column", "query", "query_id")),
    _p("ignore_column", str, "", ("ignore_feature", "blacklist")),
    _p("categorical_feature", str, "", ("cat_feature", "categorical_column", "cat_column")),
    _p("forcedbins_filename", str, ""),
    _p("save_binary", bool, False, ("is_save_binary", "is_save_binary_file")),
    # --- predict (config.h:721-779) ---
    _p("start_iteration_predict", int, 0),
    _p("num_iteration_predict", int, -1),
    _p("predict_raw_score", bool, False,
       ("is_predict_raw_score", "predict_rawscore", "raw_score")),
    _p("predict_leaf_index", bool, False, ("is_predict_leaf_index", "leaf_index")),
    _p("predict_contrib", bool, False, ("is_predict_contrib", "contrib")),
    _p("predict_disable_shape_check", bool, False),
    _p("pred_early_stop", bool, False),
    _p("pred_early_stop_freq", int, 10),
    _p("pred_early_stop_margin", float, 10.0),
    _p("output_result", str, "LightGBM_predict_result.txt",
       ("predict_result", "prediction_result", "predict_name", "prediction_name",
        "pred_name", "name_pred")),
    # --- convert (config.h:790-797) ---
    _p("convert_model_language", str, ""),
    _p("convert_model", str, "gbdt_prediction.cpp", ("convert_model_file",)),
    # --- objective (config.h:807-874) ---
    _p("objective_seed", int, 5),
    _p("num_class", int, 1, ("num_classes",), check=">0"),
    _p("is_unbalance", bool, False, ("unbalance", "unbalanced_sets")),
    _p("scale_pos_weight", float, 1.0, check=">0.0"),
    _p("sigmoid", float, 1.0, check=">0.0"),
    _p("boost_from_average", bool, True),
    _p("reg_sqrt", bool, False),
    _p("alpha", float, 0.9, check=">0.0"),
    _p("fair_c", float, 1.0, check=">0.0"),
    _p("poisson_max_delta_step", float, 0.7, check=">0.0"),
    _p("tweedie_variance_power", float, 1.5, check="1.0<=v<2.0"),
    _p("lambdarank_truncation_level", int, 30, check=">0"),
    _p("lambdarank_norm", bool, True),
    _p("label_gain", list, None),
    # --- metric (config.h:925-946) ---
    _p("metric", list, None, ("metrics", "metric_types")),
    _p("metric_freq", int, 1, ("output_freq",), check=">0"),
    _p("is_provide_training_metric", bool, False,
       ("training_metric", "is_training_metric", "train_metric")),
    _p("eval_at", list, None, ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")),
    _p("multi_error_top_k", int, 1, check=">0"),
    _p("auc_mu_weights", list, None),
    # --- network (config.h:965-984) ---
    _p("num_machines", int, 1, ("num_machine",), check=">0"),
    _p("local_listen_port", int, 12400, ("local_port", "port"), check=">0"),
    _p("time_out", int, 120, check=">0"),
    _p("machine_list_filename", str, "", ("machine_list_file", "machine_list", "mlist")),
    _p("machines", str, "", ("workers", "nodes")),
    # --- device (config.h:993-1006; TPU additions) ---
    _p("gpu_platform_id", int, -1),
    _p("gpu_device_id", int, -1),
    _p("gpu_use_dp", bool, False),
    _p("num_gpu", int, 1, check=">0"),
    # TPU-specific knobs (new in this framework)
    # auto | segment | onehot | pallas | packed4 ("packed4" = the XLA
    # joint-nibble scatter formulation for max_bin<=16 data — two 4-bit
    # codes share one byte and one scatter builds BOTH features'
    # histograms; the CPU analog of the Pallas kernels' packed layout)
    _p("tpu_histogram_impl", str, "auto"),
    # Pallas histogram kernel pipeline: auto (= dma on TPU, blockspec
    # under off-TPU interpretation) | dma (explicit
    # double-buffered HBM->VMEM async-copy streaming overlapping the MXU
    # contraction) | blockspec (the v1 implicit per-grid-step fetch,
    # kept for A/B re-probing per PERF.md's measured-dead-ends rule)
    _p("tpu_pallas_pipeline", str, "auto"),
    # 4-bit bin packing (reference src/io/dense_bin.hpp 4-bit bins):
    # when every feature fits a nibble (max_bin <= 16) the wave grower's
    # device bin matrix stores two bin codes per int8 lane and the
    # Pallas kernels unpack in VMEM — half the streamed/held bin bytes
    _p("tpu_hist_pack4", bool, True),
    _p("tpu_rows_per_chunk", int, 0),        # 0 = auto-tune
    _p("tpu_double_precision_gain", bool, False),  # like gpu_use_dp for split gains
    # tree_grow_mode: auto | wave | partition.  "wave" = leaf-wise growth
    # with MXU leaf-batched histograms and no row movement (learner/wave.py,
    # up to tpu_wave_size splits committed per wave); "partition" = exact
    # sequential leaf-wise with leaf-contiguous packed rows
    # (learner/partitioned.py).  "auto" picks wave on TPU when no
    # wave-incompatible feature (forced splits / interaction constraints /
    # bynode sampling) is active.
    _p("tree_grow_mode", str, "auto"),
    # 0 = the kernel maximum (25 leaves/pass exact bf16, 42 quantized i8)
    _p("tpu_wave_size", int, 0, check=">=0"),
    # speculative ramp (learner/wave.py): grow a provisional subtree on a
    # row subsample, verify it against ONE full-data multi-channel
    # histogram pass, and commit every provisional split whose exact gain
    # is within tpu_spec_tolerance of that node's exact best — the
    # frontier ramp (1 -> 2 -> 4 ... leaves) collapses from ~log2(W)
    # full-data passes into one.  Exactness: every committed split's
    # gain/sums are computed from full data; the subsample only GUESSES
    # which splits to precompute.  Applies on the serial Pallas wave path
    # for numeric-only datasets with num_leaves >= 3*wave_size.
    _p("tpu_speculative_ramp", bool, True),
    _p("tpu_spec_tolerance", float, 0.3, check=">=0.0"),
    # exact device-side endgame (learner/wave.py + learner/endgame.py):
    # once the remaining leaf budget drops below 2*wave_size, ONE batched
    # kernel pass precomputes the frontier candidates' smaller-child
    # histograms (larger siblings via subtraction) and the remaining
    # splits are selected by the TRUE sequential best-first order in an
    # on-device while loop over the cached histogram bank — no more
    # full-data passes per taper wave.  Replaces the wave-halving taper
    # on numeric non-EFB shapes; reproduces the exact leaf-wise order.
    _p("tpu_exact_endgame", bool, True),
    # feature-sliced reduce-scatter histogram merging on the DP wave path
    # (learner/wave.py + parallel/data_parallel.py): each wave's histogram
    # batch is psum_scatter'd over a static feature-block axis so every
    # chip materializes only its F/k slice of the merged histogram, scans
    # that slice, and a tiny O(W*k) winner exchange picks the global best
    # split per frontier leaf — the reference DP learner's ReduceScatter
    # refinement (data_parallel_tree_learner.cpp:155-173) applied to the
    # wave path: ~1/k the ICI bytes and 1/k the scan FLOPs per pass.
    # False = the former full-histogram allreduce (one psum per wave).
    # Falls back to allreduce automatically for categorical/EFB/forced-
    # split/lazy-CEGB configurations; results are identical either way.
    _p("tpu_dp_hist_scatter", bool, True),
    _p("num_devices", int, 0),               # 0 = all visible devices
    # --- gradient quantization (config.h use_quantized_grad block;
    # gradient_discretizer.cpp) — int8 histogram training on the MXU
    # (ops/histogram_pallas.py build_histogram_pallas_leaves_q8).  Levels
    # beyond the reference's default 4 are free on the int8 lanes, up to
    # 254 (clamped to the int8 payload).
    _p("use_quantized_grad", bool, False),
    _p("num_grad_quant_bins", int, 4, check=">1"),
    _p("quant_train_renew_leaf", bool, False),
    _p("stochastic_rounding", bool, True),
    # --- one-program multi-model training (lightgbm_tpu/multitrain/) ---
    # tpu_cv_many: route engine.cv() through the vmapped train_many fast
    # path (folds = models with held-out sample masks sharing ONE binned
    # dataset and ONE compiled program) whenever the configuration
    # supports it; False = the per-fold boosting loop.
    _p("tpu_cv_many", bool, True),
    # cap on models trained in one compiled batch; larger variant sets
    # are chunked (HBM for stacked scores/histograms scales with M)
    _p("tpu_multitrain_batch", int, 256, check=">0"),
    # shard the model axis over local devices (pmap of the vmapped
    # grower) when the batch width divides the device count — every
    # chip grows M/k models concurrently; False = single-device vmap
    _p("tpu_multitrain_shard", bool, True),
    # out-of-core ingest (lightgbm_tpu/ingest/): how a StreamedDataset
    # trains.  "hbm" = upload the streamed binned cache to HBM once and
    # run the normal growers (bit-identical to in-core training on every
    # path); "chunked" = chunk-accumulated wave histograms with a
    # rows-independent HBM budget (the 10^8-10^9-row regime; envelope
    # checked by ingest/train.py).  An execution-strategy directive like
    # resume/checkpoint_dir: it never changes the model (quantized path)
    # and is excluded from the model-text params dump.
    _p("tpu_ingest_mode", str, "hbm"),
    # --- inference compiler (lightgbm_tpu/serve/compiler.py) ---
    # dense = force the fused dense MXU program (one loop-free jitted
    # program per row bucket: one-hot threshold compares, categorical
    # bitset-membership contraction, quantized leaf tables); walk = the
    # sequential per-tree walk; auto = dense whenever the ensemble
    # lowers AND the backend profits (always on TPU; on CPU a host cost
    # model keeps the walk where it measures faster and RECORDS the
    # fallback reason in the serve_compiler_fallback counter).
    _p("tpu_predict_compiler", str, "auto"),
    # leaf-table quantization for the dense program: 0 = exact f32
    # leaves, 8/16 = i8/i16 leaf codes + per-tree f32 scale dequantized
    # in the final contraction (abs error <= sum of per-tree scales / 2)
    _p("tpu_predict_leaf_bits", int, 0),
    # pjit-shard the dense program's tree axis over this many devices
    # (0/1 = single device); partial scores merge in ONE psum per
    # request (collective contract serve/dense_predict/score_psum)
    _p("tpu_predict_shard", int, 0, check=">=0"),
    # --- explanation compiler (lightgbm_tpu/explain/) ---
    # dense = force the loop-free dense TreeSHAP program (per-leaf
    # root-path slot tensors contracted with the PR-13 condition
    # matrix; exact f32 leaf values, never quantized); walk = the host
    # TreeSHAP recursion (models/shap.py); auto = dense whenever the
    # ensemble lowers — no CPU cost model: the host walk is Python-
    # recursive, so the vectorized program wins on every backend — with
    # any lowering fallback (depth/table budget) RECORDED in the
    # serve_explain_fallback counter, never silent
    _p("tpu_explain_compiler", str, "auto"),
    # --- continuous-learning lane (lightgbm_tpu/publish/) ---
    # publish_dir: when set, the trainer appends a per-round model delta
    # journal there (publish/delta.py) every publish_every rounds (0 =
    # every round) plus a forced publish on the preemption drain path
    # and at completion.  Run directives like checkpoint_dir: excluded
    # from the model-text params dump so publishing runs serialize byte-
    # identically to non-publishing ones.
    _p("publish_dir", str, ""),
    _p("publish_every", int, 0, check=">=0"),
]

PARAM_SCHEMA: Dict[str, ParamSpec] = {p.name: p for p in _PARAMS}

# alias -> canonical name (reference src/io/config_auto.cpp:10-168)
PARAM_ALIASES: Dict[str, str] = {}
for _spec in _PARAMS:
    for _a in _spec.aliases:
        PARAM_ALIASES[_a] = _spec.name

_OBJECTIVE_ALIASES = {
    # regression family (config.h:113-121)
    "regression_l2": "regression", "l2": "regression", "mean_squared_error": "regression",
    "mse": "regression", "l2_root": "regression", "root_mean_squared_error": "regression",
    "rmse": "regression",
    "l1": "regression_l1", "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "mean_absolute_percentage_error": "mape",
    # classification
    "softmax": "multiclass", "multiclass_ova": "multiclassova", "ova": "multiclassova",
    "ovr": "multiclassova",
    # cross-entropy
    "xentropy": "cross_entropy", "xentlambda": "cross_entropy_lambda",
    # ranking
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg", "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

_BOOSTING_ALIASES = {"gbrt": "gbdt", "random_forest": "rf"}

_TREE_LEARNER_ALIASES = {
    "feature_parallel": "feature", "data_parallel": "data", "voting_parallel": "voting",
}

_TASK_ALIASES = {"training": "train", "prediction": "predict", "test": "predict",
                 "refit_tree": "refit"}


def resolve_param_aliases(params: Dict[str, Any]) -> Dict[str, Any]:
    """Map alias keys to canonical keys (first writer wins, like
    ParameterAlias::KeyAliasTransform in the reference's config_auto.cpp)."""
    out: Dict[str, Any] = {}
    for k, v in params.items():
        canonical = PARAM_ALIASES.get(k, k)
        if canonical in out and out[canonical] != v:
            # canonical name beats alias; earlier alias beats later alias
            if k == canonical:
                out[canonical] = v
        else:
            out[canonical] = v
    return out


def _coerce(spec: ParamSpec, value: Any) -> Any:
    if value is None:
        return None
    if spec.type is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "+", "t")
        return bool(value)
    if spec.type is int:
        return int(value)
    if spec.type is float:
        return float(value)
    if spec.type is list:
        if isinstance(value, str):
            if not value.strip():
                return None
            return [_maybe_num(s) for s in value.replace(" ", "").split(",")]
        if isinstance(value, (list, tuple)):
            return list(value)
        return [value]
    return str(value)


def _maybe_num(s: str) -> Any:
    try:
        return int(s)
    except ValueError:
        try:
            return float(s)
        except ValueError:
            return s


class Config:
    """Typed parameter container (reference config.h:34).

    Construct from a dict of user params (aliases allowed); unknown keys are
    kept in ``extra`` so custom objective params pass through.
    """

    def __init__(self, params: Optional[Dict[str, Any]] = None, **kw: Any) -> None:
        merged = dict(params or {})
        merged.update(kw)
        merged = resolve_param_aliases(merged)
        self.extra: Dict[str, Any] = {}
        for spec in _PARAMS:
            object.__setattr__(self, spec.name, spec.default)
        for key, value in merged.items():
            if key in PARAM_SCHEMA:
                setattr(self, key, _coerce(PARAM_SCHEMA[key], value))
            else:
                self.extra[key] = value
        self._post_process()
        self._validate()

    def _post_process(self) -> None:
        self.objective = _OBJECTIVE_ALIASES.get(self.objective, self.objective)
        self.boosting = _BOOSTING_ALIASES.get(self.boosting, self.boosting)
        self.tree_learner = _TREE_LEARNER_ALIASES.get(self.tree_learner, self.tree_learner)
        self.task = _TASK_ALIASES.get(self.task, self.task)
        if self.eval_at is None:
            self.eval_at = [1, 2, 3, 4, 5]
        if self.label_gain is None:
            # reference config.cpp: default label_gain = 2^i - 1
            self.label_gain = [float((1 << i) - 1) for i in range(31)]
        # reference config.cpp:216-232: seed cascades to sub-seeds when set
        if self.seed != 0:
            import random as _random
            rng = _random.Random(self.seed)
            for sub in ("data_random_seed", "bagging_seed", "drop_seed",
                        "feature_fraction_seed", "extra_seed", "objective_seed"):
                setattr(self, sub, rng.randint(0, 2 ** 31 - 1))

    def _validate(self) -> None:
        checks = [
            (self.num_leaves >= 2, "num_leaves must be >=2"),
            (1 < self.max_bin <= 65535, "max_bin must be in (1, 65535]"),
            (0.0 < self.bagging_fraction <= 1.0, "bagging_fraction in (0,1]"),
            (0.0 < self.feature_fraction <= 1.0, "feature_fraction in (0,1]"),
            (self.lambda_l1 >= 0.0, "lambda_l1 must be >=0"),
            (self.lambda_l2 >= 0.0, "lambda_l2 must be >=0"),
            (self.min_data_in_leaf >= 0, "min_data_in_leaf must be >=0"),
            (self.num_class >= 1, "num_class must be >=1"),
            (self.top_rate + self.other_rate <= 1.0,
             "top_rate + other_rate must be <=1 (GOSS)"),
            (not (self.force_col_wise and self.force_row_wise),
             "cannot set both force_col_wise and force_row_wise"),
            (self.tree_grow_mode in ("auto", "wave", "partition"),
             "tree_grow_mode must be one of auto|wave|partition"),
            (self.tpu_histogram_impl in ("auto", "segment", "onehot",
                                         "pallas", "packed4"),
             "tpu_histogram_impl must be auto|segment|onehot|pallas|"
             "packed4"),
            (self.tpu_pallas_pipeline in ("auto", "dma", "blockspec"),
             "tpu_pallas_pipeline must be auto|dma|blockspec"),
            (self.tpu_ingest_mode in ("hbm", "chunked"),
             "tpu_ingest_mode must be hbm|chunked"),
            (self.tpu_predict_compiler in ("auto", "dense", "walk"),
             "tpu_predict_compiler must be auto|dense|walk"),
            (self.tpu_predict_leaf_bits in (0, 8, 16),
             "tpu_predict_leaf_bits must be 0|8|16"),
            (self.tpu_explain_compiler in ("auto", "dense", "walk"),
             "tpu_explain_compiler must be auto|dense|walk"),
        ]
        for ok, msg in checks:
            if not ok:
                raise ValueError(f"Invalid parameter: {msg}")
        if self.objective in ("multiclass", "multiclassova") and self.num_class <= 1:
            raise ValueError("num_class must be >1 for multiclass objectives")

    # -- helpers -------------------------------------------------------------
    @property
    def num_model_per_iteration(self) -> int:
        """Trees per boosting iteration (reference multiclass_objective.hpp
        NumModelPerIteration): num_class for softmax/OVA, else 1."""
        if self.objective in ("multiclass", "multiclassova"):
            return self.num_class
        return 1

    @property
    def is_parallel(self) -> bool:
        return self.tree_learner != "serial"

    def to_dict(self) -> Dict[str, Any]:
        d = {p.name: getattr(self, p.name) for p in _PARAMS}
        d.update(self.extra)
        return d

    def update(self, params: Dict[str, Any]) -> "Config":
        merged = self.to_dict()
        merged.update(params)
        return Config(merged)

    def __repr__(self) -> str:
        diffs = {p.name: getattr(self, p.name) for p in _PARAMS
                 if getattr(self, p.name) != p.default}
        return f"Config({diffs})"


# Parameters that are parsed (for reference-config compatibility) but whose
# behavior is not implemented yet.  Training warns LOUDLY when one is set to
# a non-default value — a silent no-op would hand users a different model
# than the same params produce on the reference (VERDICT r2 "what's weak" #5).
# Entries are removed as features land; tests assert this list shrinks only.
# `deterministic` is intentionally absent: training is deterministic by
# construction (fixed seeds, static schedules, no atomics), which satisfies
# the flag's contract without a switch.
_UNIMPLEMENTED_PARAMS: Tuple[str, ...] = ()


def warn_unimplemented_params(config: "Config") -> None:
    """Warn about accepted-but-inert parameters set away from defaults
    (called at training setup; loading/prediction stays quiet)."""
    from .utils.log import log_warning
    for name in _UNIMPLEMENTED_PARAMS:
        spec = PARAM_SCHEMA.get(name)
        if spec is None:
            continue
        if getattr(config, name) != spec.default:
            log_warning(
                f"parameter '{name}' is accepted for config compatibility "
                f"but NOT implemented yet in lightgbm_tpu — it has no "
                f"effect on this training run")


def parse_config_file(path: str) -> Dict[str, Any]:
    """Parse a reference-style ``key = value`` CLI config file
    (reference src/application/application.cpp:52 + common.h KV parsing)."""
    params: Dict[str, Any] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, value = line.split("=", 1)
            params[key.strip()] = value.strip()
    return params
