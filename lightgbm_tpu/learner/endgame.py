"""Shared sequential split-commit selector.

The TRUE leaf-wise order (serial_tree_learner.cpp:158-209) commits one
split at a time: pick the global best candidate, write the node records,
patch the parent's child pointer at the committed node, renumber leaves
(left child keeps the split leaf's id, right child takes the next fresh
id — Tree::Split).  Three growers share this selector:

* the partitioned grower's per-split ``fori_loop`` body
  (learner/partitioned.py) — the exact sequential reference path;
* the wave grower's **exact device-side endgame** (learner/wave.py): once
  the remaining budget drops below ``2*wave_size``, one batched kernel
  pass precomputes the frontier candidates' smaller-child histograms and
  the remaining splits are committed in the exact sequential order by a
  ``lax.while_loop`` over the cached bank — zero further full-data
  passes in the common case.  Under the DP reduce-scatter merge
  (``tpu_dp_hist_scatter``) the cached bank and every per-commit
  2-child rescan operate on this shard's feature slice, with one winner
  exchange per commit recombining the block-local bests;
* degenerately, every ``wave_size=1`` wave.

Leaves are encoded in child slots as ``-(leaf+1)``; at any moment exactly
one node slot holds a given leaf's code (its parent's — earlier holders
were patched when the leaf was created), so a full-array compare-and-set
replaces the reference's parent-index bookkeeping.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["patch_child_pointers", "write_split_records"]


def patch_child_pointers(left_child, right_child, leaf, node, active=None):
    """Point the split leaf's parent slot at the newly committed node.

    ``active`` (scalar bool) masks the patch for fori-loop growers whose
    iteration may be a no-op; the wave endgame always commits.
    """
    enc = -(leaf + 1)
    hit_l = left_child == enc
    hit_r = right_child == enc
    if active is not None:
        hit_l = hit_l & active
        hit_r = hit_r & active
    return (jnp.where(hit_l, node, left_child),
            jnp.where(hit_r, node, right_child))


def write_split_records(out, *, node, leaf, new_id, feat, thr, f_nan_bin,
                        dt_bits, gain, internal_value, internal_weight,
                        internal_count, left_child, right_child,
                        member=None, active=None):
    """Write one committed split's node records into the state dict.

    ``out`` must hold the standard node arrays (split_feature,
    threshold_bin, nan_bin, decision_type, split_gain, internal_value/
    weight/count, and cat_member when ``member`` is given).
    ``left_child``/``right_child`` arrive pre-patched
    (:func:`patch_child_pointers`); the node's own slots are written here,
    encoding the children as leaves ``-(leaf+1)`` / ``-(new_id+1)``.
    ``active=False`` turns every write into a dropped no-op.
    """
    idx = node if active is None else jnp.where(
        active, node, out["split_feature"].shape[0])

    def w(name, val):
        out[name] = out[name].at[idx].set(val, mode="drop")

    w("split_feature", feat)
    w("threshold_bin", thr)
    w("nan_bin", f_nan_bin)
    if member is not None:
        w("cat_member", member)
    w("decision_type", dt_bits)
    w("split_gain", gain)
    w("internal_value", internal_value)
    w("internal_weight", internal_weight)
    w("internal_count", internal_count)
    out["left_child"] = left_child.at[idx].set(-(leaf + 1), mode="drop")
    out["right_child"] = right_child.at[idx].set(-(new_id + 1), mode="drop")
    return out
