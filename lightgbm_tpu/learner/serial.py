"""Leaf-wise tree grower + serial (single-device) learner.

TPU-native re-implementation of the reference SerialTreeLearner
(reference: src/treelearner/serial_tree_learner.cpp:158 ``Train`` — best-first
growth to num_leaves with per-leaf histograms, the histogram subtraction trick
at :311-320, split finding at :374, partition update at :564).

Design (SURVEY.md §7): the whole tree grows inside ONE jitted function with a
``lax.fori_loop`` over the num_leaves-1 splits — no host round-trips per
split.  Static shapes throughout:

* leaf membership is a per-row ``row_leaf`` int32 vector (replaces the
  reference's DataPartition index shuffling, data_partition.hpp:170) — the
  partition update after a split is a masked ``where``;
* per-leaf histograms live in a (num_leaves, F, B, 3) pool when it fits the
  memory budget, enabling the parent-minus-sibling subtraction trick; with
  many features the learner switches to recompute mode (two masked passes per
  split, no pool) — the analog of the reference's bounded HistogramPool
  (feature_histogram.hpp:1095);
* split finding is the vectorized bin scan in ops/split.py;
* the best-leaf argmax replaces serial_tree_learner.cpp:194's ArgMax over
  best_split_per_leaf_.

After a split, the left child keeps the parent's leaf id and the right child
takes the next fresh id (matching the reference Tree::Split leaf numbering).

The grower is parameterized by a **communication strategy** — the TPU analog
of the reference templating its parallel learners over the device learner
(parallel_tree_learner.h:54 ``DataParallelTreeLearner<TREELEARNER_T>``):
the serial strategy is all-identity; data-/feature-/voting-parallel
strategies (lightgbm_tpu/parallel/) insert ``jax.lax`` collectives at the
same points the reference calls its Network layer.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..ops.histogram import build_histogram
from ..ops.split import (BIG, NEG_INF, FeatureSplits, SplitParams,
                         best_split_per_feature, leaf_output)
from ..models.tree import CAT_MASK, DEFAULT_LEFT_MASK, MISSING_NAN

__all__ = ["SerialTreeLearner", "GrownTree", "make_grow_fn", "CommStrategy",
           "local_best_candidate"]


class GrownTree(NamedTuple):
    """Device-side result of growing one tree."""
    split_feature: jnp.ndarray     # (L-1,) int32 (global feature indices)
    threshold_bin: jnp.ndarray     # (L-1,) int32
    nan_bin: jnp.ndarray           # (L-1,) int32
    cat_member: jnp.ndarray        # (L-1, B) bool — categorical LEFT bins
    decision_type: jnp.ndarray     # (L-1,) int32
    left_child: jnp.ndarray        # (L-1,) int32
    right_child: jnp.ndarray       # (L-1,) int32
    split_gain: jnp.ndarray        # (L-1,) float32
    internal_value: jnp.ndarray    # (L-1,) float32
    internal_weight: jnp.ndarray   # (L-1,) float32
    internal_count: jnp.ndarray    # (L-1,) float32
    leaf_value: jnp.ndarray        # (L,) float32
    leaf_weight: jnp.ndarray       # (L,) float32
    leaf_count: jnp.ndarray        # (L,) float32
    num_leaves: jnp.ndarray        # () int32 — actual leaves grown
    row_leaf: jnp.ndarray          # (N,) int32 — final leaf of every row
    hist_passes: jnp.ndarray       # () int32 — full-data histogram passes
    #                                spent growing this tree (wave grower;
    #                                0 = untracked: the partitioned/masked
    #                                growers' per-split builds scale with
    #                                the split leaf's size, not with N)


def local_best_candidate(hist, leaf_sum, num_bins, is_cat, has_nan,
                         feature_mask, params, monotone=None, bound=None,
                         depth=None, cegb=None, contri=None,
                         parent_out=None, rand_bins=None
                         ) -> Tuple[jnp.ndarray, ...]:
    """Best split over (local) features for one leaf -> scalar candidate
    tuple (gain, feat, bin, default_left, left_sum, right_sum)."""
    fs: FeatureSplits = best_split_per_feature(hist, leaf_sum, num_bins,
                                               is_cat, has_nan, params,
                                               monotone, bound, depth, cegb,
                                               contri, parent_out, rand_bins)
    gain = jnp.where(feature_mask, fs.gain, NEG_INF)
    f = jnp.argmax(gain)
    return (gain[f], f.astype(jnp.int32), fs.threshold_bin[f],
            fs.default_left[f], fs.left_sum[f], fs.right_sum[f],
            fs.cat_member[f])


class CommStrategy:
    """Serial (no-comm) strategy; parallel learners override the hooks.

    Hook contract inside the jitted grower:
      * ``reduce_sum(v)`` — reduce per-shard scalars/vectors over row shards
        (root grad/hess/count sums; DP/voting: ``psum``).
      * ``leaf_candidates(hist_local, leaf_sum, feature_mask, params)`` —
        best split for one leaf from the (possibly shard-local) histogram;
        must return a candidate with a GLOBAL feature index, identical on
        every device.
      * ``get_column(X_local, global_feat)`` — fetch the winning feature's
        bin column for the partition update (FP: owner broadcast).
      * ``local_meta(...)`` — slice per-feature descriptors to this shard's
        histogram width.
    """

    def __init__(self, num_bins, is_cat, has_nan, monotone=None):
        self.num_bins_full = num_bins
        self.is_cat_full = is_cat
        self.has_nan_full = has_nan
        self.monotone_full = monotone

    def reduce_sum(self, v):
        return v

    def reduce_max(self, v):
        """Cross-shard max (quantization scales; DP: pmax)."""
        return v

    def shard_key(self, key):
        """Decorrelate the stochastic-rounding PRNG stream per row shard
        (DP: fold in the axis index)."""
        return key

    def reduce_hist(self, hist):
        """Reduce a freshly built histogram across row shards (DP: psum —
        the analog of data_parallel_tree_learner.cpp:155's ReduceScatter+
        Allgather; voting keeps local histograms and reduces only the
        voted features inside leaf_candidates)."""
        return hist

    def local_meta(self, feature_mask):
        return (self.num_bins_full, self.is_cat_full, self.has_nan_full,
                feature_mask)

    def leaf_candidates(self, hist, leaf_sum, feature_mask, params,
                        bound=None, depth=None, parent_out=None,
                        rand_bins=None):
        nb, ic, hn, fm = self.local_meta(feature_mask)
        return local_best_candidate(hist, leaf_sum, nb, ic, hn, fm, params,
                                    self.monotone_full, bound, depth,
                                    getattr(self, "cegb_full", None),
                                    getattr(self, "contri_full", None),
                                    parent_out, rand_bins)

    def pair_candidates(self, hist_l, hist_r, lsum, rsum, feature_mask,
                        params, bound_l, bound_r, depth, fm_l=None,
                        fm_r=None, po_l=None, po_r=None, rb_l=None,
                        rb_r=None):
        """Both children's candidates in ONE vmapped scan (halves the
        per-split fixed cost of the dozens of small ops in the bin scan).
        fm_l/fm_r are optional per-child feature masks (bynode sampling);
        po_l/po_r the children's own smoothed outputs (path_smooth).
        Parallel strategies override with two sequential calls — their
        collectives are not vmap-batched."""
        hists = jnp.stack([hist_l, hist_r])
        sums = jnp.stack([lsum, rsum])
        nb, ic, hn, fm = self.local_meta(feature_mask)
        fms = jnp.stack([fm if fm_l is None else fm_l,
                         fm if fm_r is None else fm_r])
        if bound_l is None:
            bounds = jnp.zeros((2, 2), jnp.float32)
        else:
            bounds = jnp.stack([bound_l, bound_r])
        pos = jnp.zeros((2,), jnp.float32) if po_l is None \
            else jnp.stack([po_l, po_r])
        cegb = getattr(self, "cegb_full", None)
        contri = getattr(self, "contri_full", None)

        if rb_l is not None:
            rbs = jnp.stack([rb_l, rb_r])

            def one(h, s, b, f_m, po, rb):
                return local_best_candidate(h, s, nb, ic, hn, f_m, params,
                                            self.monotone_full, b, depth,
                                            cegb, contri, po, rb)

            out = jax.vmap(one)(hists, sums, bounds, fms, pos, rbs)
        else:
            def one(h, s, b, f_m, po):
                return local_best_candidate(h, s, nb, ic, hn, f_m, params,
                                            self.monotone_full, b, depth,
                                            cegb, contri, po)

            out = jax.vmap(one)(hists, sums, bounds, fms, pos)
        cl = tuple(o[0] for o in out)
        cr = tuple(o[1] for o in out)
        return cl, cr

    def get_column(self, X, feat):
        return jnp.take(X, feat, axis=1).astype(jnp.int32)


def make_grow_fn(*, num_leaves: int, max_bins: int, max_depth: int,
                 split_params: SplitParams, hist_impl: str,
                 rows_per_chunk: int, use_hist_pool: bool,
                 strategy: Optional[CommStrategy] = None, jit: bool = True):
    """Build the single-tree grower for a fixed configuration.

    The returned function signature is
    ``grow(X, X_T, grad, hess, sample_mask, num_bins, is_cat, has_nan,
    feature_mask) -> GrownTree`` where X may be the full binned matrix
    (serial), a row shard (data/voting parallel) or a feature shard
    (feature parallel) depending on the strategy.  ``X_T`` is the
    feature-major ``(F, N)`` copy used by the Pallas histogram kernel
    (None for the other impls); N must be padded to the kernel's row block.
    """

    hist_kwargs = dict(num_bins=max_bins, impl=hist_impl,
                       rows_per_chunk=rows_per_chunk)
    L = num_leaves
    if split_params.extra_trees:
        from ..utils.log import log_warning
        log_warning("extra_trees is not applied on this grower (pool-less "
                    "fallback / parallel learners); growing full scans")
    pallas = hist_impl == "pallas"
    if pallas:
        from ..ops.histogram_pallas import (DEFAULT_ROW_BLOCK,
                                            build_histogram_pallas)

    def _build_hist(X, X_T, g, h, m):
        if pallas:
            return build_histogram_pallas(X_T, g, h, m, num_bins=max_bins)
        return build_histogram(X, g, h, m, **hist_kwargs)

    use_mc = split_params.use_monotone
    use_sm = split_params.path_smooth > 0.0

    def _child_out(s3, parent_out):
        """Child leaf value: smoothed toward the parent when path_smooth
        is active (feature_histogram.hpp USE_SMOOTHING)."""
        if use_sm:
            from ..ops.split import leaf_output_smoothed
            return leaf_output_smoothed(s3[0], s3[1], s3[2], parent_out,
                                        split_params)
        return leaf_output(s3[0], s3[1], split_params)

    def grow(X: jnp.ndarray, X_T, grad: jnp.ndarray, hess: jnp.ndarray,
             sample_mask: jnp.ndarray, num_bins: jnp.ndarray,
             is_cat: jnp.ndarray, has_nan: jnp.ndarray,
             monotone: jnp.ndarray, feature_mask: jnp.ndarray) -> GrownTree:
        strat = strategy if strategy is not None else CommStrategy(
            num_bins, is_cat, has_nan, monotone)
        if strategy is not None:
            strat.monotone_full = monotone
        n, f_local = X.shape

        root_hist = strat.reduce_hist(
            _build_hist(X, X_T, grad, hess, sample_mask))
        root_sum = strat.reduce_sum(jnp.stack([
            jnp.sum(grad * sample_mask),
            jnp.sum(hess * sample_mask),
            jnp.sum(sample_mask)]))

        root_bound = jnp.asarray([-BIG, BIG], jnp.float32)
        root_out = _child_out(root_sum, jnp.asarray(0.0, jnp.float32))
        cand = strat.leaf_candidates(root_hist, root_sum, feature_mask,
                                     split_params, root_bound,
                                     jnp.asarray(0, jnp.int32), root_out)

        # Per-split child-row compaction buckets: the smaller child's rows
        # are gathered into the smallest adequate fixed-size buffer (a
        # power-of-4 ladder), so histogram work scales with the child's
        # size.  The leaf membership itself stays a per-row row_leaf vector
        # (DataPartition analog, data_partition.hpp:170) updated with masked
        # wheres — sequential full-N passes with a tiny constant beat
        # index-permutation bookkeeping on TPU, where random gather/scatter
        # is the expensive primitive.
        rows_sharded = getattr(strat, "rows_sharded", False)
        hist_buckets = []
        _size = (n // 2 + 1) if not rows_sharded else n
        if pallas:  # bucket sizes must be row-block multiples for the kernel
            _rb = DEFAULT_ROW_BLOCK
            _size = -(-_size // _rb) * _rb
            _top = _size
            while _size >= _rb and len(hist_buckets) < 4:
                hist_buckets.append(_size)
                _size = -(-(_size // 4) // _rb) * _rb
                if hist_buckets[-1] == _size:
                    break
        else:
            _top = _size
            while _size >= 4096 and len(hist_buckets) < 4:
                hist_buckets.append(_size)
                _size //= 4
        if not hist_buckets:
            hist_buckets = [_top]

        state = {
            "row_leaf": jnp.zeros((n,), jnp.int32),
            "leaf_sum": jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum),
            "leaf_depth": jnp.zeros((L,), jnp.int32),
            "leaf_parent": jnp.full((L,), -1, jnp.int32),
            "cand_gain": jnp.full((L,), NEG_INF, jnp.float32).at[0].set(cand[0]),
            "cand_feat": jnp.zeros((L,), jnp.int32).at[0].set(cand[1]),
            "cand_bin": jnp.zeros((L,), jnp.int32).at[0].set(cand[2]),
            "cand_dleft": jnp.zeros((L,), jnp.bool_).at[0].set(cand[3]),
            "cand_lsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[4]),
            "cand_rsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[5]),
            "cand_member": jnp.zeros((L, max_bins), jnp.bool_).at[0].set(
                cand[6]),
            "split_feature": jnp.full((L - 1,), -1, jnp.int32),
            "threshold_bin": jnp.zeros((L - 1,), jnp.int32),
            "nan_bin": jnp.full((L - 1,), -1, jnp.int32),
            "cat_member": jnp.zeros((L - 1, max_bins), jnp.bool_),
            "decision_type": jnp.zeros((L - 1,), jnp.int32),
            "left_child": jnp.zeros((L - 1,), jnp.int32),
            "right_child": jnp.zeros((L - 1,), jnp.int32),
            "split_gain": jnp.zeros((L - 1,), jnp.float32),
            "internal_value": jnp.zeros((L - 1,), jnp.float32),
            "internal_weight": jnp.zeros((L - 1,), jnp.float32),
            "internal_count": jnp.zeros((L - 1,), jnp.float32),
            "leaf_value": jnp.zeros((L,), jnp.float32).at[0].set(root_out),
            "leaf_weight": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[1]),
            "leaf_count": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[2]),
            "num_leaves": jnp.asarray(1, jnp.int32),
            "done": jnp.asarray(False),
        }
        if use_hist_pool:
            state["hists"] = jnp.zeros((L, f_local, max_bins, 3),
                                       jnp.float32).at[0].set(root_hist)
        if use_mc:
            state["leaf_mn"] = jnp.full((L,), -BIG, jnp.float32)
            state["leaf_mx"] = jnp.full((L,), BIG, jnp.float32)

        nb_full = strat.num_bins_full
        ic_full = strat.is_cat_full
        hn_full = strat.has_nan_full

        def body(t, s):
            best_leaf = jnp.argmax(s["cand_gain"]).astype(jnp.int32)
            bgain = s["cand_gain"][best_leaf]
            do = jnp.logical_and(jnp.logical_not(s["done"]), bgain > 0)
            dof = do.astype(jnp.float32)

            feat = s["cand_feat"][best_leaf]          # GLOBAL feature index
            thr = s["cand_bin"][best_leaf]
            dleft = s["cand_dleft"][best_leaf]
            lsum = s["cand_lsum"][best_leaf]
            rsum = s["cand_rsum"][best_leaf]
            member = s["cand_member"][best_leaf]      # (B,) categorical set
            psum_ = s["leaf_sum"][best_leaf]
            new_id = (t + 1).astype(jnp.int32)

            # ---- partition update (DataPartition::Split analog) ----
            col = strat.get_column(X, feat)
            fcat = ic_full[feat]
            fnan = hn_full[feat]
            f_nan_bin = jnp.where(fnan, nb_full[feat] - 1, -1)
            in_leaf = s["row_leaf"] == best_leaf
            is_nanbin = col == f_nan_bin
            go_left = jnp.where(fcat, member[col],
                                jnp.where(is_nanbin, dleft, col <= thr))
            row_leaf = jnp.where(do & in_leaf & jnp.logical_not(go_left),
                                 new_id, s["row_leaf"])
            # smaller side chosen by GLOBAL counts so every shard agrees
            # (GetGlobalDataCountInLeaf parity, parallel_tree_learner.h:67)
            left_smaller = lsum[2] <= rsum[2]

            if use_hist_pool:
                # one histogram pass over the SMALLER child + subtraction
                # (serial_tree_learner.cpp:311-320).  The child's rows are
                # compacted via cumsum + vectorized binary search (gather
                # only — jnp.nonzero's scatter is ~6x slower on TPU) into
                # the smallest adequate bucket.  The f32 running count is
                # exact up to 2^24 rows per shard; larger shards would need
                # a f64 cumsum here.
                small_id = jnp.where(left_smaller, best_leaf, new_id)
                small_mask = (row_leaf == small_id).astype(jnp.float32) * \
                    sample_mask * dof
                cs = jnp.cumsum(small_mask)
                small_cnt = cs[-1]

                def hist_branch(size):
                    def fn(cs_in):
                        q = jnp.arange(1, size + 1, dtype=jnp.float32)
                        idx = jnp.searchsorted(cs_in, q, side="left")
                        idx = jnp.where(q <= small_cnt, idx, n)
                        bsub = jnp.take(X, idx, axis=0, mode="fill",
                                        fill_value=0)
                        gsub = jnp.take(grad, idx, mode="fill", fill_value=0.0)
                        hsub = jnp.take(hess, idx, mode="fill", fill_value=0.0)
                        msub = jnp.take(small_mask, idx, mode="fill",
                                        fill_value=0.0)
                        return _build_hist(bsub, bsub.T if pallas else None,
                                           gsub, hsub, msub)
                    return fn

                if len(hist_buckets) == 1:
                    hist_small = hist_branch(hist_buckets[0])(cs)
                else:
                    sel = sum((small_cnt <= b).astype(jnp.int32)
                              for b in hist_buckets[1:])
                    hist_small = jax.lax.switch(
                        sel, [hist_branch(b) for b in hist_buckets], cs)
                hist_small = strat.reduce_hist(hist_small)
                parent_hist = s["hists"][best_leaf]
                hist_big = parent_hist - hist_small
                hist_left = jnp.where(left_smaller, hist_small, hist_big)
                hist_right = jnp.where(left_smaller, hist_big, hist_small)
            else:
                # no histogram pool (huge feature count): masked full passes
                left_mask = (row_leaf == best_leaf).astype(jnp.float32) * \
                    sample_mask * dof
                right_mask = (row_leaf == new_id).astype(jnp.float32) * \
                    sample_mask * dof
                hist_left = strat.reduce_hist(_build_hist(
                    X, X_T, grad, hess, left_mask))
                hist_right = strat.reduce_hist(_build_hist(
                    X, X_T, grad, hess, right_mask))

            # ---- monotone bounds for the children (BasicLeafConstraints::
            # Update, monotone_constraints.hpp:487-501: split outputs are
            # clamped to the leaf's bounds; the mid-point partitions the
            # output range between the children) ----
            parent_lv = s["leaf_value"][best_leaf]
            out_l = _child_out(lsum, parent_lv)
            out_r = _child_out(rsum, parent_lv)
            if use_mc:
                p_mn = s["leaf_mn"][best_leaf]
                p_mx = s["leaf_mx"][best_leaf]
                out_l = jnp.clip(out_l, p_mn, p_mx)
                out_r = jnp.clip(out_r, p_mn, p_mx)
                m = jnp.where(fcat, 0, monotone[feat])
                mid = (out_l + out_r) / 2.0
                mn_l = jnp.where(m < 0, jnp.maximum(p_mn, mid), p_mn)
                mx_l = jnp.where(m > 0, jnp.minimum(p_mx, mid), p_mx)
                mn_r = jnp.where(m > 0, jnp.maximum(p_mn, mid), p_mn)
                mx_r = jnp.where(m < 0, jnp.minimum(p_mx, mid), p_mx)
                bound_l = jnp.stack([mn_l, mx_l])
                bound_r = jnp.stack([mn_r, mx_r])
            else:
                bound_l = bound_r = None

            # ---- children candidates ----
            child_depth = s["leaf_depth"][best_leaf] + 1
            depth_ok = jnp.logical_or(max_depth <= 0, child_depth < max_depth)
            cl, cr = strat.pair_candidates(hist_left, hist_right, lsum, rsum,
                                           feature_mask, split_params,
                                           bound_l, bound_r, child_depth,
                                           po_l=out_l, po_r=out_r)
            gl = jnp.where(depth_ok, cl[0], NEG_INF)
            gr = jnp.where(depth_ok, cr[0], NEG_INF)

            # ---- tree arrays for node t ----
            node = t
            # categorical NaN rows live in bin 0 (most frequent category);
            # record default_left so raw-feature inference routes NaN the
            # same way the binned training partition did
            dleft = jnp.where(fcat, member[0], dleft)
            dt_bits = (jnp.where(fcat, CAT_MASK, 0) |
                       jnp.where(dleft, DEFAULT_LEFT_MASK, 0) |
                       jnp.where(fnan & jnp.logical_not(fcat), MISSING_NAN, 0)
                       ).astype(jnp.int32)
            parent_node = s["leaf_parent"][best_leaf]
            enc_best = -(best_leaf + 1)    # ~best_leaf
            node_idx = jnp.arange(L - 1, dtype=jnp.int32)
            patch_l = (node_idx == parent_node) & (s["left_child"] == enc_best) & do
            patch_r = (node_idx == parent_node) & (s["right_child"] == enc_best) & do
            left_child = jnp.where(patch_l, node, s["left_child"])
            right_child = jnp.where(patch_r, node, s["right_child"])

            def upd(arr, idx, val):
                return arr.at[idx].set(jnp.where(do, val, arr[idx]))

            out = dict(s)
            out["row_leaf"] = row_leaf
            if use_hist_pool:
                hists = s["hists"]
                hists = hists.at[best_leaf].set(
                    jnp.where(do, hist_left, hists[best_leaf]))
                hists = hists.at[new_id].set(
                    jnp.where(do, hist_right, hists[new_id]))
                out["hists"] = hists
            out["leaf_sum"] = upd(upd(s["leaf_sum"], best_leaf, lsum),
                                  new_id, rsum)
            out["leaf_depth"] = upd(upd(s["leaf_depth"], best_leaf, child_depth),
                                    new_id, child_depth)
            out["leaf_parent"] = upd(upd(s["leaf_parent"], best_leaf, node),
                                     new_id, node)
            out["cand_gain"] = upd(upd(s["cand_gain"], best_leaf, gl), new_id, gr)
            out["cand_feat"] = upd(upd(s["cand_feat"], best_leaf, cl[1]), new_id, cr[1])
            out["cand_bin"] = upd(upd(s["cand_bin"], best_leaf, cl[2]), new_id, cr[2])
            out["cand_dleft"] = upd(upd(s["cand_dleft"], best_leaf, cl[3]),
                                    new_id, cr[3])
            out["cand_lsum"] = upd(upd(s["cand_lsum"], best_leaf, cl[4]), new_id, cr[4])
            out["cand_rsum"] = upd(upd(s["cand_rsum"], best_leaf, cl[5]), new_id, cr[5])
            out["cand_member"] = upd(upd(s["cand_member"], best_leaf, cl[6]),
                                     new_id, cr[6])
            out["split_feature"] = upd(s["split_feature"], node, feat)
            out["threshold_bin"] = upd(s["threshold_bin"], node, thr)
            out["nan_bin"] = upd(s["nan_bin"], node, f_nan_bin)
            out["cat_member"] = upd(s["cat_member"], node, member)
            out["decision_type"] = upd(s["decision_type"], node, dt_bits)
            out["left_child"] = upd(left_child, node, enc_best)
            out["right_child"] = upd(right_child, node, -(new_id + 1))
            out["split_gain"] = upd(s["split_gain"], node, bgain)
            out["internal_value"] = upd(s["internal_value"], node,
                                        leaf_output(psum_[0], psum_[1],
                                                    split_params))
            out["internal_weight"] = upd(s["internal_weight"], node, psum_[1])
            out["internal_count"] = upd(s["internal_count"], node, psum_[2])
            if use_mc:
                out["leaf_mn"] = upd(upd(s["leaf_mn"], best_leaf, mn_l),
                                     new_id, mn_r)
                out["leaf_mx"] = upd(upd(s["leaf_mx"], best_leaf, mx_l),
                                     new_id, mx_r)
            lv = upd(s["leaf_value"], best_leaf, out_l)
            out["leaf_value"] = upd(lv, new_id, out_r)
            lw = upd(s["leaf_weight"], best_leaf, lsum[1])
            out["leaf_weight"] = upd(lw, new_id, rsum[1])
            lc = upd(s["leaf_count"], best_leaf, lsum[2])
            out["leaf_count"] = upd(lc, new_id, rsum[2])
            out["num_leaves"] = s["num_leaves"] + do.astype(jnp.int32)
            out["done"] = jnp.logical_not(do)
            return out

        s = jax.lax.fori_loop(0, L - 1, body, state)
        return GrownTree(
            split_feature=s["split_feature"], threshold_bin=s["threshold_bin"],
            nan_bin=s["nan_bin"], cat_member=s["cat_member"],
            decision_type=s["decision_type"],
            left_child=s["left_child"], right_child=s["right_child"],
            split_gain=s["split_gain"], internal_value=s["internal_value"],
            internal_weight=s["internal_weight"],
            internal_count=s["internal_count"], leaf_value=s["leaf_value"],
            leaf_weight=s["leaf_weight"], leaf_count=s["leaf_count"],
            num_leaves=s["num_leaves"], row_leaf=s["row_leaf"],
            hist_passes=jnp.asarray(0, jnp.int32))

    return jax.jit(grow) if jit else grow


def resolve_hist_impl(config: Config, parallel: bool = False,
                      wave: bool = False, max_bins: int = 0) -> str:
    """Pick the histogram implementation (the analog of the reference's
    col-wise/row-wise autotune, dataset.cpp:659-670, collapsed to a static
    choice: the Pallas MXU kernel on TPU, scatter-add elsewhere).

    The SEQUENTIAL ``parallel`` growers (masked grower under shard_map)
    use the XLA onehot formulation on TPU — their per-split compaction
    path has no feature-major layout.  The WAVE grower keeps the Pallas
    leaf-batched kernel in both serial and shard_map form (``wave=True``;
    it owns the (F, N) layout natively)."""
    from ..utils.backend import default_backend
    impl = config.tpu_histogram_impl
    if impl == "auto":
        if default_backend() == "tpu":
            impl = "onehot" if (parallel and not wave) else "pallas"
        else:
            impl = "segment"
    elif impl == "pallas" and parallel and not wave:
        impl = "onehot"
    if impl == "packed4" and max_bins > 16:
        from ..utils.log import log_warning
        log_warning(f"tpu_histogram_impl=packed4 requires max_bin<=16 "
                    f"(got {max_bins}); using the segment path")
        impl = "segment"
    if impl == "pallas" and max_bins > 256:
        from ..utils.log import log_warning
        log_warning(f"max_bin={max_bins} exceeds the Pallas kernels' uint8 "
                    "bin range (256); using the XLA onehot histogram path "
                    "(uint16 bins) — set max_bin<=255 for peak TPU "
                    "throughput")
        impl = "onehot"
    return impl


def split_params_from_config(config: Config,
                             num_bins: Optional[np.ndarray] = None,
                             is_cat: Optional[np.ndarray] = None
                             ) -> SplitParams:
    mc = config.monotone_constraints or []
    use_mc = any(int(v) != 0 for v in mc)
    # monotone_constraints_method is a GROWER-level choice: the wave
    # growers implement 'intermediate' (region-box contiguity propagation,
    # learner/wave.py); other growers warn and use 'basic' — the warnings
    # are emitted where the grower is picked.
    # the sorted-subset categorical search is traced in only when some
    # categorical feature exceeds the one-hot threshold
    use_cat_subset = bool(
        num_bins is not None and is_cat is not None and
        np.any(np.asarray(is_cat) &
               (np.asarray(num_bins) > int(config.max_cat_to_onehot))))
    use_cegb = bool(config.cegb_penalty_split > 0.0 or
                    config.cegb_penalty_feature_coupled or
                    config.cegb_penalty_feature_lazy)
    return SplitParams(
        lambda_l1=float(config.lambda_l1),
        lambda_l2=float(config.lambda_l2),
        min_data_in_leaf=int(config.min_data_in_leaf),
        min_sum_hessian_in_leaf=float(config.min_sum_hessian_in_leaf),
        min_gain_to_split=float(config.min_gain_to_split),
        max_delta_step=float(config.max_delta_step),
        cat_l2=float(config.cat_l2),
        cat_smooth=float(config.cat_smooth),
        path_smooth=float(config.path_smooth),
        use_monotone=use_mc,
        monotone_penalty=float(config.monotone_penalty),
        max_cat_to_onehot=int(config.max_cat_to_onehot),
        max_cat_threshold=int(config.max_cat_threshold),
        min_data_per_group=int(config.min_data_per_group),
        use_cat_subset=use_cat_subset,
        use_cegb=use_cegb,
        cegb_tradeoff=float(config.cegb_tradeoff),
        cegb_penalty_split=float(config.cegb_penalty_split),
        feature_fraction_bynode=float(config.feature_fraction_bynode),
        extra_trees=bool(config.extra_trees),
        any_cat=bool(is_cat is None or np.any(np.asarray(is_cat))))
    # NOTE: cat_idx (the static cat-column positions that bound the
    # sorted-subset search) is NOT set here — scans that operate on
    # per-shard feature BLOCKS (feature-parallel, voting, DP
    # psum_scatter) index a sliced feature space where global positions
    # would be wrong.  Full-feature-space learners attach it via
    # ``sp._replace(cat_idx=...)``.


def resolve_monotone_method(config: Config, use_mc: bool,
                            wave: bool) -> bool:
    """Pick the intermediate-constraint flag for a grower and warn about
    downgrades (reference monotone_constraints.hpp:514/:856 — 'advanced'
    falls back to 'intermediate' on the wave growers; non-wave growers
    fall back to 'basic')."""
    method = str(config.monotone_constraints_method)
    if not use_mc or method == "basic":
        return False
    from ..utils.log import log_warning
    if not wave:
        log_warning(f"monotone_constraints_method='{method}' requires the "
                    "wave grower; falling back to 'basic' (safe but more "
                    "conservative bounds)")
        return False
    if method == "advanced":
        log_warning("monotone_constraints_method='advanced' is not "
                    "implemented; using 'intermediate' (less constraining "
                    "than basic, more than advanced)")
    return True


def hist_pool_fits(config: Config, num_features: int, max_bins: int) -> bool:
    """Keep per-leaf histograms when they fit the budget (reference
    histogram_pool_size, default -1 = a 1 GiB cap here to stay inside HBM
    alongside the data)."""
    pool_bytes = config.num_leaves * num_features * max_bins * 3 * 4
    budget = (float(config.histogram_pool_size) * (1 << 20)
              if config.histogram_pool_size > 0 else (1 << 30))
    return pool_bytes <= budget


# jitted growers cached by their full static configuration so repeated
# train() calls (tests, cv folds, sklearn fits) reuse compiled code.
# Bounded LRU: every live compiled executable holds process memory
# mappings and XLA:CPU segfaults when a process exhausts vm.max_map_count,
# so the cache drops the least-recently-used growers.  (This bounds the
# CACHE's contribution only — growers still referenced by live learners
# keep their executables mapped until those learners are released.)
_GROW_FN_CACHE: dict = {}
_GROW_FN_CACHE_MAX = 48


def _cache_put(key, fn):
    if len(_GROW_FN_CACHE) >= _GROW_FN_CACHE_MAX:
        _GROW_FN_CACHE.pop(next(iter(_GROW_FN_CACHE)))
    _GROW_FN_CACHE[key] = fn
    return fn


def _cache_hit(key):
    """LRU touch: move the hit entry to the back so cycling workloads
    (grid search over many configs) do not evict their hottest growers."""
    fn = _GROW_FN_CACHE.pop(key)
    _GROW_FN_CACHE[key] = fn
    return fn


class SerialTreeLearner:
    """Host-side wrapper: owns the jitted grower and the dataset's static
    feature descriptors (reference tree_learner.h:27 ``TreeLearner``)."""

    def __init__(self, config: Config, num_features: int, max_bins: int,
                 num_bins: np.ndarray, is_cat: np.ndarray, has_nan: np.ndarray,
                 monotone: Optional[np.ndarray] = None,
                 forced_splits: tuple = (), efb=None,
                 interaction_groups: tuple = (),
                 feature_contri: tuple = (), cegb_lazy: tuple = ()):
        self.config = config
        self.efb = efb
        if efb is not None:
            self._efb_args = (jnp.asarray(efb.exp_map),
                              jnp.asarray(efb.f_bundle),
                              jnp.asarray(efb.f_offset),
                              jnp.asarray(efb.f_default),
                              jnp.asarray(efb.f_nbins),
                              jnp.asarray(efb.f_single))
            self._efb_dims = (int(efb.n_bundles), int(efb.bundle_bins))
        else:
            self._efb_args = ()
            self._efb_dims = None
        self.max_bins = int(max_bins)
        self.num_bins = jnp.asarray(num_bins, jnp.int32)
        self.is_cat = jnp.asarray(is_cat, jnp.bool_)
        self.has_nan = jnp.asarray(has_nan, jnp.bool_)
        self.monotone = jnp.asarray(
            monotone if monotone is not None else np.zeros(num_features),
            jnp.int32)
        self.num_features = num_features
        self.split_params = split_params_from_config(config, num_bins, is_cat)
        if np.any(np.asarray(is_cat)):
            # serial scans + the wave row update run in FULL feature
            # space: record the static cat-column positions (bounds the
            # subset search's argsort and enables the embedding-style
            # membership lookup)
            self.split_params = self.split_params._replace(
                cat_idx=tuple(int(j) for j in
                              np.where(np.asarray(is_cat))[0]))
        pool_f, pool_b = (self._efb_dims if self._efb_dims is not None
                          else (num_features, self.max_bins))
        self.use_hist_pool = hist_pool_fits(config, pool_f, pool_b)
        if efb is not None and not self.use_hist_pool:
            raise ValueError("EFB requires the partitioned grower; raise "
                             "histogram_pool_size or disable enable_bundle")
        impl = resolve_hist_impl(config, max_bins=self.max_bins)
        if impl == "packed4" and efb is not None:
            # EFB histograms run in BUNDLE space whose bin count can
            # exceed the 4-bit range even when every feature fits it
            impl = "segment"
        if not self.use_hist_pool and impl == "pallas":
            # the pool-less fallback grower takes no transposed X and no row
            # padding — downgrade to the XLA onehot formulation (same MXU
            # math, without the VMEM layout contract)
            impl = "onehot"
        self.pallas = impl == "pallas"
        self._x_src = None
        # The partition-ordered grower (learner/partitioned.py) is the
        # exact sequential serial path — no full-N work per split.  The
        # wave grower (learner/wave.py) trades row movement for MXU
        # leaf-batched histogram passes and wins on TPU.  The masked
        # grower below remains for the pool-less huge-feature fallback and
        # as the shared body of the parallel strategies.
        self.partitioned = self.use_hist_pool
        forced_splits = tuple(tuple(f) for f in forced_splits)
        interaction_groups = tuple(tuple(g) for g in interaction_groups)
        feature_contri = tuple(float(v) for v in feature_contri)
        cegb_lazy = tuple(float(v) for v in cegb_lazy)
        wave_ok = (self.use_hist_pool and int(config.num_leaves) > 2)
        mode = str(config.tree_grow_mode)
        if mode == "wave" and not wave_ok:
            from ..utils.log import log_warning
            log_warning("tree_grow_mode=wave is incompatible with "
                        "num_leaves<=2 / pool-less growth; "
                        "falling back to the partitioned grower")
            mode = "partition"
        elif mode == "auto":
            mode = "wave" if (wave_ok and impl == "pallas") else "partition"
        self.grow_mode = mode if self.use_hist_pool else "masked"
        if self.grow_mode != "wave":
            resolve_monotone_method(config, self.split_params.use_monotone,
                                    wave=False)
        self._use_lazy = bool(cegb_lazy) and self.grow_mode == "wave"
        self._lazy_used = None
        if cegb_lazy and self.grow_mode != "wave":
            from ..utils.log import log_warning
            log_warning("cegb_penalty_feature_lazy is applied by the wave "
                        "grower only; this grower ignores it")
        self.quantized = bool(config.use_quantized_grad) and \
            self.grow_mode == "wave"
        if config.use_quantized_grad and not self.quantized:
            from ..utils.log import log_warning
            log_warning("use_quantized_grad requires the wave grower "
                        "(tree_grow_mode=wave/auto on TPU); training "
                        "with exact gradients instead")
        # kernel-v2 knobs: the DMA/blockspec pipeline choice and the
        # 4-bit packed bin layout (two codes per int8 lane when every
        # feature fits a nibble — reference dense_bin.hpp's 4-bit bins)
        from ..ops.histogram_pallas import PACK4_MAX_BINS
        self.pallas_pipeline = (None if config.tpu_pallas_pipeline == "auto"
                                else str(config.tpu_pallas_pipeline))
        self.pack4 = False
        if self.grow_mode == "wave":
            from ..ops.quantize import quant_levels
            wave_size = int(config.tpu_wave_size)
            any_cat = bool(np.any(np.asarray(is_cat)))
            # pack4 exists only on the DMA pipeline: an explicit
            # blockspec request (the measured-dead-ends A/B knob) must
            # actually run the v1 layout, so it disables packing
            self.pack4 = bool(
                config.tpu_hist_pack4 and impl == "pallas" and
                self.max_bins <= PACK4_MAX_BINS and not any_cat and
                efb is None and self.pallas_pipeline != "blockspec")
            gq_max, hq_max = quant_levels(int(config.num_grad_quant_bins))
            # in exact mode the quant params don't affect the traced fn —
            # collapse the cache key so sweeps over them don't recompile
            qtuple = (self.quantized, gq_max, hq_max,
                      bool(config.quant_train_renew_leaf),
                      bool(config.stochastic_rounding)) \
                if self.quantized else (False,)
            spec_ramp = bool(config.tpu_speculative_ramp)
            spec_tol = float(config.tpu_spec_tolerance)
            endg = bool(config.tpu_exact_endgame)
            mc_inter = resolve_monotone_method(
                config, self.split_params.use_monotone, wave=True)
            key = ("wave", int(config.num_leaves), num_features,
                   self.max_bins, int(config.max_depth), self.split_params,
                   impl, any_cat, wave_size, self._efb_dims, feature_contri,
                   qtuple, interaction_groups, cegb_lazy, spec_ramp,
                   spec_tol, forced_splits, mc_inter, endg,
                   self.pack4, self.pallas_pipeline)
            from .wave import make_wave_grow_fn
            self._grow_factory = make_wave_grow_fn
            self._grow_kwargs = dict(
                num_leaves=int(config.num_leaves),
                num_features=num_features, max_bins=self.max_bins,
                max_depth=int(config.max_depth),
                split_params=self.split_params, hist_impl=impl,
                any_cat=any_cat, wave_size=wave_size,
                pack4=self.pack4, pipeline=self.pallas_pipeline,
                efb_dims=self._efb_dims, feature_contri=feature_contri,
                quantized=self.quantized, gq_max=gq_max, hq_max=hq_max,
                renew_leaf=bool(config.quant_train_renew_leaf),
                stochastic=bool(config.stochastic_rounding),
                interaction_groups=interaction_groups,
                cegb_lazy=cegb_lazy, spec_ramp=spec_ramp,
                spec_tol=spec_tol, forced_splits=forced_splits,
                mc_inter=mc_inter, exact_endgame=endg)
            if key not in _GROW_FN_CACHE:
                _cache_put(key, self.build_grow_fn())
        elif self.partitioned:
            key = ("part", int(config.num_leaves), num_features,
                   self.max_bins, int(config.max_depth), self.split_params,
                   impl, forced_splits, self._efb_dims,
                   interaction_groups, feature_contri,
                   self.pallas_pipeline)
            from .partitioned import make_partitioned_grow_fn
            self._grow_factory = make_partitioned_grow_fn
            self._grow_kwargs = dict(
                num_leaves=int(config.num_leaves),
                num_features=num_features, max_bins=self.max_bins,
                max_depth=int(config.max_depth),
                split_params=self.split_params, hist_impl=impl,
                pipeline=self.pallas_pipeline,
                forced_splits=forced_splits, efb_dims=self._efb_dims,
                interaction_groups=interaction_groups,
                feature_contri=feature_contri)
            if key not in _GROW_FN_CACHE:
                _cache_put(key, self.build_grow_fn())
        else:
            key = ("serial", int(config.num_leaves), self.max_bins,
                   int(config.max_depth), self.split_params, impl,
                   int(config.tpu_rows_per_chunk), self.use_hist_pool)
            self._grow_factory = make_grow_fn
            self._grow_kwargs = dict(
                num_leaves=int(config.num_leaves), max_bins=self.max_bins,
                max_depth=int(config.max_depth),
                split_params=self.split_params, hist_impl=impl,
                rows_per_chunk=int(config.tpu_rows_per_chunk),
                use_hist_pool=self.use_hist_pool)
            if key not in _GROW_FN_CACHE:
                _cache_put(key, self.build_grow_fn())
        self._grow = _cache_hit(key)

    def build_grow_fn(self, split_params=None, jit: bool = True):
        """(Re)build this learner's grower from its recorded factory
        configuration.  ``split_params`` overrides the static SplitParams —
        the multi-model trainer (lightgbm_tpu/multitrain/) passes a
        variant carrying traced per-model scalars (ops/split.py
        TRACEABLE_PARAMS) and ``jit=False`` so it can vmap the raw grower
        over the model axis inside its own jitted step."""
        kw = dict(self._grow_kwargs)
        if split_params is not None:
            kw["split_params"] = split_params
        return self._grow_factory(jit=jit, **kw)

    supports_extras = True  # cegb_penalty / node_key keyword args

    def train(self, X_dev: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              sample_mask: jnp.ndarray,
              feature_mask: Optional[jnp.ndarray] = None,
              cegb_penalty: Optional[jnp.ndarray] = None,
              node_key: Optional[jnp.ndarray] = None,
              quant_key: Optional[jnp.ndarray] = None) -> GrownTree:
        if feature_mask is None:
            feature_mask = jnp.ones((self.num_features,), jnp.bool_)
        if cegb_penalty is None:
            cegb_penalty = jnp.zeros((self.num_features,), jnp.float32)
        if node_key is None:
            node_key = jnp.zeros((2, 2), jnp.uint32)
        if not self.partitioned:
            if self.split_params.use_cegb or \
                    self.split_params.feature_fraction_bynode < 1.0:
                from ..utils.log import log_warning
                log_warning("cegb / feature_fraction_bynode are not applied "
                            "on the pool-less fallback grower")
            return self._grow(X_dev, None, grad, hess, sample_mask,
                              self.num_bins, self.is_cat, self.has_nan,
                              self.monotone, feature_mask)
        n = X_dev.shape[0]
        if self.pallas:  # pad rows to the Pallas kernel's block
            from ..ops.histogram_pallas import pad_rows
            n_pad = pad_rows(n)
        else:
            n_pad = n
        if self._x_src is not X_dev:  # strong ref: ids can be recycled
            self._lazy_used = None  # fresh data -> fresh used bitmap
            Xp = jnp.pad(X_dev, ((0, n_pad - n), (0, 0))) \
                if n_pad != n else X_dev
            if self.grow_mode == "wave":
                # only the feature-major copy is consumed; do not keep the
                # padded row-major matrix alive next to it in HBM — and
                # under pack4 only the nibble-packed HALF-width matrix
                # (two 4-bit codes per int8 lane) lives on device
                xpt = jnp.asarray(jnp.swapaxes(Xp, 0, 1))
                if self.pack4:
                    from ..ops.histogram_pallas import pack_bins4
                    xpt = pack_bins4(xpt.astype(jnp.uint8))
                self._XpT = xpt
                self._Xp = None
            else:
                self._Xp = Xp
            self._x_src = X_dev
        pad = n_pad - n
        if pad:
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            sample_mask = jnp.pad(sample_mask, (0, pad))
        if self.grow_mode == "wave":
            kw = {}
            if self.quantized:
                if quant_key is None:
                    # per-call stream so direct callers (no gbdt driver
                    # threading a per-tree key) still decorrelate the
                    # stochastic rounding across trees
                    self._quant_calls = getattr(self, "_quant_calls", 0) + 1
                    quant_key = jax.random.PRNGKey(self._quant_calls)
                kw["quant_key"] = quant_key
            if self.split_params.feature_fraction_bynode < 1.0 or \
                    self.split_params.extra_trees:
                kw["node_key"] = node_key
            if self._use_lazy:
                # the used-feature bitmap persists across trees (the
                # reference's feature_used_in_data_ lives for the whole
                # training run)
                from .wave import LAZY_PACK, lazy_bitmap_init
                bitpack = n_pad % LAZY_PACK == 0  # pallas pads to 4096
                width = n_pad // LAZY_PACK if bitpack else n_pad
                if self._lazy_used is None or \
                        self._lazy_used.shape[1] != width:
                    self._lazy_used = lazy_bitmap_init(
                        self.num_features, n_pad, bitpack)
                kw["lazy_used"] = self._lazy_used
            out = self._grow(self._XpT, grad, hess, sample_mask,
                             self.num_bins, self.is_cat, self.has_nan,
                             self.monotone, cegb_penalty,
                             self._efb_args, feature_mask, **kw)
            if self._use_lazy:
                grown, self._lazy_used = out
            else:
                grown = out
        else:
            grown = self._grow(self._Xp, grad, hess, sample_mask,
                               self.num_bins, self.is_cat, self.has_nan,
                               self.monotone, cegb_penalty, node_key,
                               self._efb_args, feature_mask)
        if pad:
            grown = grown._replace(row_leaf=grown.row_leaf[:n])
        return grown
