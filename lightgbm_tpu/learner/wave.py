"""Wave grower — leaf-wise growth with NO physical row movement.

The partitioned grower (learner/partitioned.py) keeps rows leaf-contiguous
so per-split histogram work scales with the split leaf's size; the price is
moving every row once per level it participates in (~37 ns/row via the
1-bit-sort partition — 55-60%% of tree time at Higgs scale, PERF.md).  This
grower removes that cost entirely by exploiting the MXU's lane dimension
instead: the leaf-batched Pallas kernel
(ops/histogram_pallas.py ``build_histogram_pallas_leaves``) computes
**LEAF_CHANNELS=25 leaf histograms in one full-data pass** for the cost of
one — the single-leaf kernel wastes 123 of the 128 output lanes of its
one-hot contraction, so 25 leaves x 5 weight channels (125 lanes) fill
them instead.

Growth proceeds in *waves*: each wave splits the top-``wave_size`` leaves
by candidate gain (best-first, like the reference's leaf-wise ArgMax over
best_split_per_leaf_, serial_tree_learner.cpp:194), updates the per-row
``row_leaf`` vector with masked wheres (streaming, no gather/scatter), and
builds the wave's SMALLER children's histograms in one kernel pass — the
larger siblings come from the subtraction trick
(serial_tree_learner.cpp:311-320).  Total histogram passes per tree ≈
ceil((L-1)/25) + frontier ramp-up, independent of data size beyond the
pass cost itself.

Semantics vs the exact sequential leaf-wise order: identical while fewer
than ``num_leaves`` leaves exist and all wave candidates have positive
gain, EXCEPT that a wave commits its top-k splits before the children of
those splits can compete for the budget.  With ``wave_size=1`` the grower
reproduces the sequential order exactly (tests cross-check this).  Near
budget exhaustion (remaining budget < 2*wave_size) the **exact
device-side endgame** (``tpu_exact_endgame``, learner/endgame.py) takes
over on numeric non-EFB shapes: one batched kernel pass precomputes the
frontier candidates' smaller-child histograms and the remaining splits
are committed in the TRUE sequential best-first order by an on-device
while loop over the cached bank — typically zero further full-data
passes where the former wave-halving taper spent 3-4, and exact where
the taper was approximate.  Configurations outside the endgame gate keep
the taper; quality parity is asserted by tests on held-out loss.  The
``hist_passes`` field of the returned GrownTree counts full-data
histogram passes (root/mega + one per wave + one per endgame pass).

Forced splits (serial_tree_learner.cpp:450 ForceSplits) are applied as
pre-committed waves before gain-driven growth.  EFB, monotone
constraints, CEGB, categorical splits, interaction constraints, by-node
feature sampling, ExtraTrees random thresholds and quantized-gradient
histograms are fully supported (the latter four batched per wave with the
sequential node-id RNG streams, so wave_size=1 reproduces the partitioned
grower's sampling exactly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..analysis.contracts import collective_contract, memory_budget
from ..models.tree import CAT_MASK, DEFAULT_LEFT_MASK, MISSING_NAN
from ..ops.histogram import build_histogram_leaves, histogram_subtract
from ..ops.quantize import dequant_scales, quantize_wch
from ..ops.split import (BIG, NEG_INF, _leaf_gain, best_split_per_feature,
                         leaf_output,
                         leaf_output_smoothed)
from .endgame import patch_child_pointers, write_split_records
from .serial import CommStrategy, GrownTree, local_best_candidate

__all__ = ["make_wave_grow_fn", "WAVE_SIZE", "Q_WAVE_SIZE",
           "lazy_bitmap_init", "LAZY_PACK", "wave_taper_k"]


def wave_taper_k(budget, W: int):
    """Endgame-taper wave width: commit min(W, budget) splits while the
    budget is ample, halve the wave once budget < 2W (with a W//4 floor
    capping the halving cascade) so freshly-created children get to
    compete near exhaustion.  Shared by the traced in-core wave body and
    the chunked streamed grower (ingest/grower.py), which must select
    identically for the streamed-vs-in-core bit-identity contract."""
    taper = jnp.maximum(budget // 2, jnp.minimum(W // 4, budget))
    return jnp.minimum(W, jnp.maximum(
        1, jnp.where(budget >= 2 * W, budget, taper)))

# Lazy-CEGB persistent bitmap layout: one bit per (feature, row), packed
# LSB-first into uint8 bytes — 8x less HBM than the former bool layout
# for wide lazy-penalized datasets.  The bool layout remains available
# behind ``lazy_bitpack=False`` (tests cross-check equality).
LAZY_PACK = 8


def lazy_bitmap_init(num_features: int, n_pad: int, bitpack: bool = True):
    """Fresh persistent 'feature computed for row' bitmap (the reference's
    feature_used_in_data_ bitset; allocated once per training run)."""
    if bitpack:
        return jnp.zeros((num_features, n_pad // LAZY_PACK), jnp.uint8)
    return jnp.zeros((num_features, n_pad), jnp.bool_)


def _pack_bits(m: jnp.ndarray) -> jnp.ndarray:
    """(N,) bool -> (N//8,) uint8, LSB-first."""
    b = m.reshape(-1, LAZY_PACK).astype(jnp.uint8)
    out = b[:, 0]
    for k in range(1, LAZY_PACK):
        out = out | (b[:, k] << k)
    return out


def _unpack_bits(p: jnp.ndarray) -> jnp.ndarray:
    """(..., N8) uint8 -> (..., N8*8) bool, LSB-first."""
    sh = jnp.arange(LAZY_PACK, dtype=jnp.uint8)
    bits = (p[..., None] >> sh) & jnp.uint8(1)
    return bits.reshape(*p.shape[:-1], -1).astype(jnp.bool_)

from ..ops.histogram_pallas import LEAF_CHANNELS as WAVE_SIZE  # 25/pass
from ..ops.histogram_pallas import Q_LEAF_CHANNELS as Q_WAVE_SIZE  # 42/pass


# ---------------------------------------------------------------------------
# Program contracts for the DP-wave collective sites (lint-trace enforced;
# site names match the WaveDPStrategy note_collective tallies so the
# contract, the telemetry tally and the collective call cannot drift).
# ---------------------------------------------------------------------------

# Histogram-MERGE sites per traced wave-tree program: the root pass, the
# wave-body pass inside the while loop (traced once), and the endgame
# bank pass.  tests/test_wave_scatter.py asserts this exact count on the
# scatter path.
WAVE_MERGE_SITES = 3


def _wave_merge_budget(ctx):
    """Merge collectives per traced tree: the three merge sites, plus —
    spec ramp on — ceil(log2 W) provisional-pass merges and the
    verification mega-pass (which replaces the root pass, hence the +1
    net; the budget tests/test_specramp.py counts on the jaxpr)."""
    import math
    if not ctx.get("spec_ramp"):
        return WAVE_MERGE_SITES
    w = max(2, int(ctx.get("wave_size", 2)))
    return WAVE_MERGE_SITES + math.ceil(math.log2(w)) + 1


def _hist_batch_bytes(ctx):
    """Full merged histogram batch: (W, F, B, 3) x itemsize."""
    return (int(ctx.get("wave_size", WAVE_SIZE)) * int(ctx["features"]) *
            int(ctx["bins"]) * 3 * int(ctx.get("itemsize", 4)))


def _hist_slice_bytes(ctx):
    """Feature-sliced reduce-scatter payload: each shard RECEIVES only
    its ceil(F/k) feature block of the merged batch — the 1/k budget
    the round-8 optimisation claims (PERF.md).  ``k`` is the mesh world
    size, so the same declaration checks W=4, W=8 and the trace-only
    W=64 pod mesh."""
    from ..analysis.contracts import world_size
    k = world_size(ctx)
    f_blk = -(-int(ctx["features"]) // k)
    return (int(ctx.get("wave_size", WAVE_SIZE)) * f_blk *
            int(ctx["bins"]) * 3 * int(ctx.get("itemsize", 4)))


def _exchange_payload_bytes(ctx):
    """O(W*k) winner exchange: per scan site a (W,) gain pmax, a (W,)
    feature pmin and one (W, 8) packed payload psum — never a histogram
    (the exchange_cap tests/test_wave_scatter.py bounds)."""
    w = int(ctx.get("wave_size", WAVE_SIZE))
    return 16 * max(2 * w, int(ctx.get("leaves", 2 * w))) * \
        int(ctx.get("itemsize", 4))


def _dcn_of(limit):
    """DCN ceiling derived from a per-op payload curve: the modeled
    cross-host share — dcn_fraction(ctx), (H-1)/H on a host-major axis —
    of that payload.  Declared explicitly per site so lint-trace bounds
    the pod (DCN) bytes separately from the per-op (ICI) bytes."""
    def dcn_bytes(ctx):
        from ..analysis.contracts import dcn_fraction
        return limit(ctx) * dcn_fraction(ctx)
    return dcn_bytes


collective_contract(
    "data_parallel/wave/hist_psum", "psum",
    max_count=_wave_merge_budget, max_bytes_per_op=_hist_batch_bytes,
    max_dcn_bytes_per_op=_dcn_of(_hist_batch_bytes),
    note="one full-batch histogram psum per merge site")
collective_contract(
    "data_parallel/wave/hist_reduce_scatter", "psum_scatter",
    max_count=_wave_merge_budget, max_bytes_per_op=_hist_slice_bytes,
    max_dcn_bytes_per_op=_dcn_of(_hist_slice_bytes),
    note="one reduce_scatter per merge site, 1/k received payload")
collective_contract(
    "data_parallel/wave/winner_exchange", ("pmax", "pmin", "psum"),
    max_count=lambda ctx: 3 * _wave_merge_budget(ctx),
    max_bytes_per_op=_exchange_payload_bytes,
    max_dcn_bytes_per_op=_dcn_of(_exchange_payload_bytes),
    note="pmax/pmin/psum triple per candidate-scan site, O(W*k) bytes")
collective_contract(
    "data_parallel/wave/scalar_sum", "psum",
    max_count=8, max_bytes_per_op=_exchange_payload_bytes,
    max_dcn_bytes_per_op=_dcn_of(_exchange_payload_bytes),
    note="leaf totals / root sums — small vectors only")
collective_contract(
    "data_parallel/wave/quant_scale", "pmax",
    max_count=2, max_bytes_per_op=8, max_dcn_bytes_per_op=8,
    note="global gradient/hessian quantization scales (two scalars)")


# ---------------------------------------------------------------------------
# Memory budget for the wave grower program family (lint-mem enforced).
# The footprint is histogram-channel dominated: the per-leaf bank
# (L,F,B,3), the kernel's channel batch (the quantized kernel always
# builds Q_WAVE_SIZE=42 channels, the f32 one 2*wave trial channels) and
# the wave loop's subtraction/scan temporaries — measured ~5 channel
# layers of working set per batch layer at the lint geometry; the curve
# budgets 6 for headroom.  Row arrays: bins (F,N) uint8 + grad/hess/
# mask/row_leaf/quantized lanes, ~24 B/row beyond the bin matrix.
# ---------------------------------------------------------------------------

def wave_grow_hbm_bytes(ctx):
    """Per-device HBM curve of one wave-grower tree program, as a
    function of (rows, features, bins, wave_size, leaves, world_size) —
    the statically answerable half of "will 10^8 rows fit at W=64?"."""
    from ..analysis.contracts import world_size
    f = int(ctx["features"])
    b = int(ctx["bins"])
    it = int(ctx.get("itemsize", 4))
    r = -(-int(ctx["rows"]) // world_size(ctx))
    wave = int(ctx.get("wave_size", WAVE_SIZE))
    kernel_ch = Q_WAVE_SIZE if ctx.get("quantized") else WAVE_SIZE
    layers = int(ctx.get("leaves", 2)) + 6 * max(2 * wave, kernel_ch)
    hist = layers * f * b * 3 * it
    rows = r * (f + 24)
    return hist + rows + (1 << 20)


memory_budget(
    "wave/grow", ("serial", "wave"), wave_grow_hbm_bytes,
    note="per-leaf bank + 6 channel layers of wave batches + row arrays")


def make_wave_grow_fn(*, num_leaves: int, num_features: int, max_bins: int,
                      max_depth: int, split_params, hist_impl: str,
                      any_cat: bool = True, interpret: bool = None,
                      pack4: bool = False, pipeline: str = None,
                      jit: bool = True, wave_size: int = 0,
                      efb_dims=None, feature_contri: tuple = (),
                      strategy=None, quantized: bool = False,
                      gq_max: int = 127, hq_max: int = 127,
                      renew_leaf: bool = False, stochastic: bool = True,
                      interaction_groups: tuple = (),
                      cegb_lazy: tuple = (), spec_ramp: bool = False,
                      spec_tol: float = 0.3,
                      spec_subsample: int = 1 << 19,
                      forced_splits: tuple = (),
                      mc_inter: bool = False,
                      exact_endgame: bool = True,
                      lazy_bitpack: bool = True):
    """Build the wave single-tree grower.

    Returned signature matches the partitioned grower:
    ``grow(X_T, grad, hess, bag_mask, num_bins, is_cat, has_nan, monotone,
    cegb_penalty, efb_arrays, feature_mask) -> GrownTree`` with X_T the
    FEATURE-MAJOR (G, N) bin matrix (bundle-space under EFB), N a multiple
    of the Pallas row block when hist_impl == 'pallas'.

    ``strategy`` hooks the data-parallel mesh in: under shard_map with
    row-sharded X_T/grad/hess, each wave's (W, G, Bb, 3) histogram batch
    is merged with ONE collective (instead of the per-split
    reduce-scatter of the sequential DP learner,
    data_parallel_tree_learner.cpp:155-173's pattern amortized over up
    to 25 splits), in one of two modes:

    * ``strategy.reduce_hist`` (psum) — every shard holds the full
      merged batch and the candidate scans run replicated with no
      further communication;
    * ``strategy.hist_scatter`` — ``reduce_hist_scatter`` psum_scatters
      the batch over a padded feature-block axis: each shard keeps only
      its G/k block, scans that slice (per-feature operands sliced to
      match), and an O(W*k) winner exchange (``exchange_collectives``)
      recombines the block-local bests into the global per-leaf winners
      — 1/k the wire residency and scan FLOPs, identical results.
    """
    L = num_leaves
    F = num_features
    ch_cap = Q_WAVE_SIZE if quantized else WAVE_SIZE
    W = max(1, min(int(wave_size) or ch_cap, ch_cap, L - 1))
    use_efb = efb_dims is not None
    G, Bb = efb_dims if use_efb else (F, max_bins)
    pallas = hist_impl == "pallas"
    if pallas:
        from ..ops.histogram_pallas import (
            build_histogram_pallas, build_histogram_pallas_leaves,
            build_histogram_pallas_leaves_q8, pack_weights8,
            unpack_bins4, wave_row_update_pallas)
    if pack4 and not pallas:
        raise ValueError("pack4 bins require hist_impl='pallas'")
    if pack4 and (efb_dims is not None or max_bins > 16 or any_cat):
        raise ValueError("pack4 bins require numeric non-EFB data with "
                         "max_bins <= 16")

    sp = split_params
    use_mc = split_params.use_monotone
    use_sm = split_params.path_smooth > 0.0
    # per-node feature sampling / random thresholds / interaction
    # constraints, traced per wave (the partitioned grower's node_mask /
    # node_rand / allowed_features, learner/partitioned.py:96-128, batched
    # over the wave's 2W children).  Node ids mirror the sequential
    # numbering (2t, 2t+1 for node t's children; 2L for the root) so
    # wave_size=1 reproduces the partitioned grower's streams exactly.
    use_bynode = sp.feature_fraction_bynode < 1.0
    use_et = sp.extra_trees
    use_ic = len(interaction_groups) > 0
    # CEGB lazy feature costs (cost_effective_gradient_boosting.hpp
    # CalculateOndemandCosts): penalty[f] per row in the candidate leaf
    # whose feature f has not yet been computed (used by any split on the
    # row's path).  The wave grower keeps rows in original order, so the
    # per-(feature, child) unused counts are small matvecs against the
    # (F, N) used bitmap.  ``cegb_lazy`` arrives pre-scaled by
    # cegb_tradeoff (like the coupled penalties).
    use_lazy = len(cegb_lazy) > 0
    if use_lazy:
        lazy_pen = jnp.asarray(cegb_lazy, jnp.float32)       # (F,)
    # Speculative ramp eligibility (all static).  The frontier ramp
    # (1 -> 2 -> 4 -> ... leaves) costs ~log2(W) full-data histogram
    # passes with most lanes idle; when eligible, grow() instead grows a
    # provisional <=W-leaf subtree on a row subsample, verifies it with
    # ONE full-data W-channel pass, and commits every provisional split
    # whose EXACT full-data gain is within ``spec_tol`` of that node's
    # exact best split.  Exactness: committed gains/sums/hists all come
    # from the full-data channel sums — the subsample only chooses which
    # histograms to precompute; a bad guess costs a skipped commit, never
    # a wrong number.  Gated to the Pallas numeric path (the shapes the
    # flagship benchmark runs) — SERIAL or row-sharded DATA-PARALLEL: a
    # WaveDPStrategy advertises ``spec_ok`` and the provisional subsample
    # waves psum their histograms over ICI exactly like committed waves
    # (one collective per provisional pass), so every shard grows the
    # same provisional tree and verifies it against the full sharded
    # data.  Every other configuration keeps the plain ramp.
    spec_dp_ok = strategy is None or getattr(strategy, "spec_ok", False)
    spec_shards = int(getattr(strategy, "nshards", 1) or 1)
    use_spec = (spec_ramp and hist_impl == "pallas" and not any_cat and
                not use_efb and max_bins <= 255 and not use_mc and
                not use_sm and not use_ic and not use_bynode and
                not use_et and not use_lazy and not sp.use_cegb and
                spec_dp_ok and max_depth <= 0 and
                not feature_contri and W >= 2 and L >= 3 * W and
                not forced_splits)
    # Narrow-dtype fast path (shared by the row updates and the endgame):
    # bin codes stay uint8 (255 reserved as the no-NaN sentinel) and leaf
    # ids uint8 when the tree fits — 4x less HBM traffic than int32.
    small_bins = (not use_efb) and max_bins <= 255
    # Exact device-side endgame eligibility (all static).  Once the
    # remaining budget drops below 2W the halving taper is replaced by
    # ONE batched kernel pass over the frontier candidates' smaller
    # children plus a true sequential best-first selection over the
    # cached histogram bank (learner/endgame.py docnotes).  Gated off the
    # per-wave-stateful features (monotone bounds, interaction paths,
    # per-node RNG streams, lazy-CEGB bitmap upkeep) and categorical/EFB
    # shapes; works on the serial AND row-sharded DP paths (the batched
    # pass rides the same one-psum-per-pass reduction as committed
    # waves), quantized or exact, any hist impl.
    use_endgame = (exact_endgame and not any_cat and not use_efb and
                   small_bins and not use_mc and not use_ic and
                   not use_bynode and not use_et and not use_lazy and
                   L > 2)
    # Forced splits (serial_tree_learner.cpp:450 ForceSplits): the
    # BFS-ordered (leaf, inner feature, threshold bin) triples are applied
    # as PRE-COMMITTED waves before gain-driven growth — statically
    # grouped so no wave splits a leaf created (or already split) in the
    # same wave, which keeps the sequential right-child numbering
    # identical to the triples' BFS next_id assignment.  Child sums come
    # from the parent's pooled histogram, so forced waves reuse the exact
    # per-wave machinery (row update, one kernel pass, subtraction,
    # children scans) with only split SELECTION overridden.
    forced_waves: list = []
    if forced_splits:
        nf = min(len(forced_splits), L - 1)
        cur: list = []
        blocked: set = set()
        nl_sim = 1
        for (leaf_, f_, b_) in forced_splits[:nf]:
            if leaf_ in blocked or len(cur) == W:
                forced_waves.append(cur)
                cur, blocked = [], set()
            cur.append((leaf_, f_, b_))
            blocked.add(leaf_)     # split once per wave
            blocked.add(nl_sim)    # fresh right child: next wave only
            nl_sim += 1
        if cur:
            forced_waves.append(cur)
    # Feature-sliced reduce-scatter histogram merge (all static): under a
    # row-sharded WaveDPStrategy with ``hist_scatter``, each wave's
    # (W, G, Bb, 3) batch is psum_scatter'd over a padded feature-block
    # axis — every shard materializes only its G/k slice of the merged
    # histogram, runs the candidate scan on that slice, and an O(W*k)
    # winner exchange (pmax gain / pmin global feature / psum'd payload)
    # picks the global best split per frontier leaf.  This is the
    # reference DP learner's ReduceScatter refinement
    # (data_parallel_tree_learner.cpp:155-173, network.h:164) amortized
    # over the wave's channels: 1/k the ICI residency of the full-batch
    # psum and 1/k the scan FLOPs, with bit-identical results (the
    # scattered block equals the same slice of the psum'd batch).  Gated
    # off categorical shapes (the sorted-subset search's static cat_idx
    # positions index full feature space), EFB (bundle->feature expansion
    # needs the whole bundle axis), forced splits (child sums are read
    # from the parent's pooled histogram at an arbitrary global feature)
    # and lazy CEGB (its per-(feature, child) unused counts would add a
    # full-F psum per wave) — those configs keep the full-batch psum.
    k_sc = int(getattr(strategy, "nshards", 1) or 1)
    use_scatter = (bool(getattr(strategy, "hist_scatter", False)) and
                   k_sc > 1 and not any_cat and not use_efb and
                   not use_lazy and not forced_waves)
    if use_scatter:
        FP_SC = -(-G // k_sc) * k_sc   # feature axis padded to k blocks
        FB_SC = FP_SC // k_sc          # features owned per shard
        F_PAD_SC = FP_SC - G
    # PV-Tree voting histogram merge (arXiv:1611.01276) on the wave batch
    # (all static): under a row-sharded strategy with ``hist_voting``, the
    # per-leaf histogram POOL stays shard-LOCAL (so the subtraction trick
    # still holds shard-by-shard) and only the voted top-2k features'
    # slices of each scan batch are psum'd — per-leaf cross-shard wire
    # volume drops from F*B to 2k*B.  Quantized batches merge as exact
    # int32 and dequantize after the psum, so at 2k >= F the voted path
    # is bit-identical to the full-batch DP merge.  Gated off the same
    # shapes as scatter (cats / EFB / lazy CEGB / forced splits need
    # full-feature merged histograms); those configs fall back to the
    # strategy's full reduce_hist.  Mutually exclusive with scatter: a
    # strategy declares one merge mode.
    use_voting = (bool(getattr(strategy, "hist_voting", False)) and
                  k_sc > 1 and not use_scatter and not any_cat and
                  not use_efb and not use_lazy and not forced_waves)
    if use_voting:
        TOPK_V = max(1, min(int(getattr(strategy, "top_k", 10)), F))
        SEL_V = min(2 * TOPK_V, F)     # voted features aggregated per leaf
    G_loc = FB_SC if use_scatter else G   # this shard's histogram width
    if use_bynode:
        import math as _math
        kcnt = max(1, int(_math.ceil(F * sp.feature_fraction_bynode)))
    if use_ic:
        import numpy as _np
        _g = _np.zeros((len(interaction_groups), F), bool)
        for gi, feats in enumerate(interaction_groups):
            for ff in feats:
                if 0 <= ff < F:
                    _g[gi, ff] = True
        ic_groups = jnp.asarray(_g)

        def allowed_features(path):
            """Union of constraint sets containing every feature already
            used on the branch (col_sampler.hpp GetByNode)."""
            compat = jnp.logical_not(
                jnp.any(path[None, :] & jnp.logical_not(ic_groups), axis=1))
            return jnp.any(ic_groups & compat[:, None], axis=0)

    def _child_out(g, h, cnt, parent_out):
        if use_sm:
            return leaf_output_smoothed(g, h, cnt, parent_out, sp)
        return leaf_output(g, h, sp)

    def grow(X_T: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
             bag_mask: jnp.ndarray, num_bins: jnp.ndarray,
             is_cat: jnp.ndarray, has_nan: jnp.ndarray,
             monotone: jnp.ndarray, cegb_penalty: jnp.ndarray,
             efb_arrays: tuple, feature_mask: jnp.ndarray,
             quant_key: jnp.ndarray = None,
             node_key: jnp.ndarray = None,
             lazy_used: jnp.ndarray = None):
        # Under ``pack4`` X_T is the nibble-packed (G, N//2) byte matrix
        # (ops/histogram_pallas.pack_bins4): the histogram kernels
        # consume it directly (half the streamed bin bytes) and the few
        # per-wave winning-feature column fetches unpack on the fly.
        n = X_T.shape[1] * 2 if pack4 else X_T.shape[1]

        def take_cols(feats):
            """(k, N) UNPACKED bin columns of the given features."""
            cols = jnp.take(X_T, feats, axis=0)
            return unpack_bins4(cols) if pack4 else cols
        if strategy is not None:
            # shallow per-trace copy: traced array attributes must not
            # outlive the trace on the learner's long-lived strategy object
            import copy
            strat = copy.copy(strategy)
            strat.num_bins_full = num_bins
            strat.is_cat_full = is_cat
            strat.has_nan_full = has_nan
            strat.monotone_full = monotone
        else:
            strat = CommStrategy(num_bins, is_cat, has_nan, monotone)
        strat.cegb_full = cegb_penalty if sp.use_cegb else None
        if feature_contri:
            strat.contri_full = jnp.asarray(feature_contri, jnp.float32)
        nb_full, ic_full, hn_full = num_bins, is_cat, has_nan

        if use_scatter:
            # this shard's feature block [f_start, f_start + FB_SC): the
            # scan sees sliced per-feature descriptors; winner feature
            # indices are remapped to global space in the exchange
            f_start = (jax.lax.axis_index(strat.axis_name) *
                       FB_SC).astype(jnp.int32)

            def _slf(a, fill):
                """(F,) per-feature array -> this shard's (FB_SC,) block
                (padded features get inert ``fill`` values)."""
                if F_PAD_SC:
                    a = jnp.concatenate(
                        [a, jnp.full((F_PAD_SC,), fill, a.dtype)])
                return jax.lax.dynamic_slice_in_dim(a, f_start, FB_SC, 0)

            def _slf2(a, fill):
                """(..., F) batch -> (..., FB_SC) block slice."""
                if F_PAD_SC:
                    a = jnp.concatenate(
                        [a, jnp.full(a.shape[:-1] + (F_PAD_SC,), fill,
                                     a.dtype)], axis=-1)
                return jax.lax.dynamic_slice_in_dim(a, f_start, FB_SC,
                                                    a.ndim - 1)

            nb_sc = _slf(nb_full, 1)      # 1-bin pads: never splittable
            ic_sc = _slf(ic_full, False)
            hn_sc = _slf(hn_full, False)
            mono_sc = _slf(monotone, 0)
            xmax_sc, xmin_sc, xsum_sc = strat.exchange_collectives()

            def _exchange(cands):
                """Combine per-shard block-local best candidates into the
                global per-leaf winners: pmax of the gain, pmin of the
                global feature index among gain-achieving blocks (the
                same lowest-feature tie-break a full-space argmax
                applies), then one psum of the winner's packed payload
                (bin, default_left, left/right sums) — O(k) floats per
                leaf, the SplitInfo allreduce-max analog.  ``member``
                stays block-local: categorical shapes never take the
                scatter path, so it is identically all-False."""
                g, f_loc, b, dl, ls, rs, member = cands
                gmax = xmax_sc(g)
                f_glob = f_start + f_loc
                cf = jnp.where(g >= gmax, f_glob, jnp.int32(2 ** 30))
                f_win = xmin_sc(cf)
                is_win = (f_glob == f_win) & (g >= gmax)
                pack = jnp.concatenate([
                    b.astype(jnp.float32)[:, None],
                    dl.astype(jnp.float32)[:, None], ls, rs], axis=-1)
                pk = xsum_sc(jnp.where(is_win[:, None], pack, 0.0))
                return (gmax, f_win, pk[:, 0].astype(jnp.int32),
                        pk[:, 1] > 0, pk[:, 2:5], pk[:, 5:8], member)

        from ..efb import make_bundle_decode, make_expand_hist
        expand_hist = make_expand_hist(efb_arrays if use_efb else (),
                                       F, G, Bb)
        bundle_decode = make_bundle_decode(efb_arrays if use_efb else ())
        f_bundle = efb_arrays[1] if use_efb else None

        gm = (grad * bag_mask).astype(jnp.float32)
        hm = (hess * bag_mask).astype(jnp.float32)
        cnt_mask = (bag_mask > 0).astype(jnp.float32)
        if use_lazy:
            # packed vs bool layout of the persistent `used` bitmap: follow
            # whatever the learner threads in (its dtype is static at trace
            # time); fresh bitmaps pack only when the row count allows it
            lp = (lazy_used.dtype == jnp.uint8) if lazy_used is not None \
                else (lazy_bitpack and n % LAZY_PACK == 0)
        if pallas:
            if not quantized:
                w8 = pack_weights8(grad, hess, bag_mask)
            bins_rows = None
        else:
            # row-major copy made ONCE per grow call (outside the wave
            # loop; XLA cannot hoist it out of lax.while itself)
            bins_rows = jnp.swapaxes(X_T, 0, 1)

        if quantized:
            # per-tree linear quantization scales from cross-shard maxima
            # (gradient_discretizer.cpp DiscretizeGradients); every DP
            # shard derives the same scales, so integer histograms psum
            # exactly.
            gmax = strat.reduce_max(jnp.max(jnp.abs(gm)))
            hmax = strat.reduce_max(jnp.max(hm))
            g_scale = jnp.maximum(gmax, jnp.float32(1e-30)) / gq_max
            h_scale = jnp.maximum(hmax, jnp.float32(1e-30)) / hq_max
            qscales = dequant_scales(g_scale, h_scale)
            qk = quant_key if quant_key is not None else \
                jax.random.PRNGKey(0)
            wch0 = quantize_wch(grad, hess, bag_mask, g_scale, h_scale,
                                strat.shard_key(qk), gq_max=gq_max,
                                hq_max=hq_max, stochastic=stochastic)

            def dq(h):
                """int32 channel sums -> f32 (sum_grad, sum_hess, count)."""
                return h.astype(jnp.float32) * qscales

        _dqh = dq if quantized else (lambda h: h)

        def _scan_hists(h, totals):
            """The histogram form the candidate scans consume: the
            dequantized (and, under EFB, feature-expanded) batch
            normally; under voting the RAW shard-local batch — the
            voted merge inside many_candidates dequantizes AFTER its
            exact integer psum of the selected slices."""
            if use_voting:
                return h
            return jax.vmap(expand_hist)(_dqh(h), totals)

        def _reduce_waves(h, k, with_totals=False):
            """Merge a freshly built (c, G, Bb, 3) histogram batch across
            row shards, trimmed to the first ``k`` channels.  Scatter
            mode pads the feature axis to the block quantum and
            reduce-scatters it, so this shard keeps only its fully
            reduced (k, FB_SC, Bb, 3) block.  ``with_totals``
            additionally returns the (k, 3) per-channel leaf totals:
            under scatter they come from a tiny psum of the LOCAL
            pre-merge batch's feature-0 bin sums (each shard's slice
            holds a different feature, whose f32 bin sums agree only up
            to rounding — and pure-pad shards hold no real feature at
            all); otherwise from the merged batch.  Quantized batches
            stay int32 end to end and dequantize AFTER the exact integer
            sum, so totals are identical across shards and across merge
            modes.  Voting mode returns the batch UNMERGED (shard-local):
            the vote-and-psum of the winning feature slices happens
            inside many_candidates; only the (k, 3) leaf totals cross
            the wire here."""
            hk = h[:k]
            if use_voting:
                if not with_totals:
                    return hk
                return hk, _dqh(strat.reduce_sum(hk[:, 0].sum(axis=1)))
            if use_scatter:
                hp = jnp.pad(hk, ((0, 0), (0, F_PAD_SC), (0, 0), (0, 0))) \
                    if F_PAD_SC else hk
                hmg = strat.reduce_hist_scatter(hp)
                if not with_totals:
                    return hmg
                return hmg, _dqh(strat.reduce_sum(hk[:, 0].sum(axis=1)))
            hmg = strat.reduce_hist(hk)
            if not with_totals:
                return hmg
            return hmg, _dqh(hmg[:, 0].sum(axis=1))

        def hist_waves(ch, k=W, with_totals=False):
            """(k, G_loc, Bb, 3) histograms of the wave's leaf channels,
            reduced across row shards (serial: identity; DP scatter mode:
            this shard's feature block of the merged batch).  ``k`` trims
            the cross-shard reduction to the channels actually used (the
            root pass needs only channel 0).  Quantized mode returns
            exact int32 channel sums (dequantize with ``dq``)."""
            if quantized:
                if pallas:
                    h = build_histogram_pallas_leaves_q8(
                        X_T, wch0, ch, num_bins=Bb, interpret=interpret,
                        pipeline=pipeline, bins_packed=pack4)
                else:
                    # off-TPU emulation: f32 sums of integer levels are
                    # exact while |sum| < 2^24 per bin — ample for the
                    # CPU/test shards this path serves (the Pallas path
                    # accumulates true int32 and has no such cap)
                    h = build_histogram_leaves(
                        bins_rows, wch0[0].astype(jnp.float32),
                        wch0[1].astype(jnp.float32),
                        wch0[2].astype(jnp.float32), ch,
                        num_channels=W, num_bins=Bb, impl=hist_impl)
                    h = jnp.round(h).astype(jnp.int32)
            elif pallas:
                h = build_histogram_pallas_leaves(X_T, w8, ch, num_bins=Bb,
                                                  interpret=interpret,
                                                  pipeline=pipeline,
                                                  bins_packed=pack4)
            else:
                h = build_histogram_leaves(
                    bins_rows, gm, hm, cnt_mask, ch,
                    num_channels=W, num_bins=Bb, impl=hist_impl)
            return _reduce_waves(h, k, with_totals)

        def feature_col(feat):
            """FEATURE-space bin codes (N,) of one feature (decoded from
            its bundle column under EFB; efb.make_bundle_decode)."""
            g = f_bundle[feat] if use_efb else feat
            if pack4:
                return unpack_bins4(
                    jax.lax.dynamic_slice(X_T, (g, 0), (1, n // 2)))[0]
            v = jax.lax.dynamic_slice(X_T, (g, 0), (1, n))[0]
            if small_bins:
                return v                                     # uint8
            return bundle_decode(v.astype(jnp.int32), feat)

        def _voting_candidates(hists, sums, bounds, depths, pouts, fms,
                               rbs, cegb2, cegb, contri):
            """PV-Tree voted merge + scan for k leaves (the voting
            counterpart of the scatter exchange).  ``hists`` arrive RAW
            and shard-LOCAL (int32 under quantized): each shard scores
            its local batch with the 1/num_machines-relaxed constraints
            (voting_parallel_tree_learner.cpp:62-63), votes its top-k
            features per leaf, the votes ride one small all_gather, and
            only the global top-2k features' histogram slices are
            psum'd — (k, 2k, B, 3) on the wire instead of (k, F, B, 3).
            The final scan runs on the merged slices with the FULL
            split params and global leaf sums; the winner's slice-local
            feature index maps back through ``selected``.  Every shard
            computes identical votes and identical merged slices, so
            candidates are replicated without any exchange — and with
            2k >= F, ``selected`` (sorted ascending) is the identity
            permutation and the scan is bit-identical to the full-batch
            DP merge."""
            kl = hists.shape[0]
            # 1. local candidate gains, relaxed constraints, local view
            #    (the local leaf totals are exact: any feature's bins sum
            #    to the shard's total — EFB is gated out under voting)
            lp_v = getattr(strat, "local_params", None) or sp
            lsum_loc = _dqh(hists[:, 0].sum(axis=1))

            def one_local(h, s, bd, d, po):
                fs = best_split_per_feature(
                    h, s, nb_full, ic_full, hn_full, lp_v, monotone,
                    bd if use_mc else None, d, parent_out=po)
                return fs.gain
            gains = jax.vmap(one_local)(_dqh(hists), lsum_loc, bounds,
                                        depths, pouts)
            gains = jnp.where(fms, gains, NEG_INF)
            # 2. local top-k vote -> one all_gather of (k, top_k) ids
            _, top_ids = jax.lax.top_k(gains, TOPK_V)
            all_ids = strat.vote_allgather(top_ids)   # (k_sc, kl, TOPK_V)
            # 3. global voting; ties break toward the lower feature index
            #    (GlobalVoting, voting_parallel_tree_learner.cpp:151)
            votes = jnp.zeros((kl, F), jnp.float32).at[
                jnp.arange(kl)[None, :, None], all_ids].add(
                    1.0, mode="drop")
            anti = -jnp.arange(F, dtype=jnp.float32) * 1e-6
            _, selected = jax.lax.top_k(votes + anti[None, :], SEL_V)
            # ascending order: at 2k >= F this is the identity map, and
            # argmax's first-max tie-break matches the full scan's
            selected = jnp.sort(selected, axis=1)
            # 4. merge ONLY the selected slices; dequantize after the
            #    exact integer sum (same ordering contract as scatter)
            sel_raw = jnp.take_along_axis(
                hists, selected[:, :, None, None], axis=1)
            hist_sel = _dqh(strat.reduce_hist_voted(sel_raw))
            # 5. full-constraint scan on the merged slices
            nb_v = nb_full[selected]
            ic_v = ic_full[selected]
            hn_v = hn_full[selected]
            mono_v = monotone[selected]
            fm_v = jnp.take_along_axis(fms, selected, axis=1)
            pen = cegb2 if cegb2 is not None else (
                jnp.broadcast_to(cegb, fms.shape)
                if cegb is not None else None)
            pen_v = None if pen is None else \
                jnp.take_along_axis(pen, selected, axis=1)
            contri_v = None if contri is None else contri[selected]
            rb_v = None if rbs is None else \
                jnp.take_along_axis(rbs, selected, axis=1)

            def one_sel(h, s, nb_, ic_, hn_, fm, mo, bd, d, po, *rest):
                it = iter(rest)
                pr = next(it) if pen_v is not None else None
                ct = next(it) if contri_v is not None else None
                rb = next(it) if rb_v is not None else None
                return local_best_candidate(
                    h, s, nb_, ic_, hn_, fm, sp, mo,
                    bd if use_mc else None, d, pr, ct, po, rb)
            extras = [a for a in (pen_v, contri_v, rb_v) if a is not None]
            g, f_loc, b, dl, ls, rs, member = jax.vmap(one_sel)(
                hist_sel, sums, nb_v, ic_v, hn_v, fm_v, mono_v, bounds,
                depths, pouts, *extras)
            f_glob = jnp.take_along_axis(
                selected, f_loc[:, None], axis=1)[:, 0]
            return (g, f_glob, b, dl, ls, rs, member)

        def many_candidates(hists, sums, bounds, depths, pouts, fms,
                            rbs=None, cegb2=None):
            """Best-split candidates for k leaves in one vmapped scan.
            ``fms`` is the per-child feature mask (k, F); ``rbs`` the
            per-child ExtraTrees random threshold bins (k, F) or None;
            ``cegb2`` an optional per-child (k, F) CEGB penalty vector
            (lazy costs) overriding the shared one.

            Scatter mode: ``hists`` arrive as this shard's feature block
            (k, FB_SC, Bb, 3); every per-feature operand is sliced to the
            same block, the scan runs on 1/k of the features, and the
            winner exchange combines the block-local bests into globally
            consistent candidates (global feature indices)."""
            cegb = getattr(strat, "cegb_full", None)
            contri = getattr(strat, "contri_full", None)
            if use_voting:
                return _voting_candidates(hists, sums, bounds, depths,
                                          pouts, fms, rbs, cegb2, cegb,
                                          contri)
            if use_scatter:
                nb_s, ic_s, hn_s, mono_s = nb_sc, ic_sc, hn_sc, mono_sc
                fms = _slf2(fms, False)
                if rbs is not None:
                    rbs = _slf2(rbs, 0)
                if cegb2 is not None:
                    cegb2 = _slf2(cegb2, 0.0)
                if cegb is not None:
                    cegb = _slf(cegb, 0.0)
                if contri is not None:
                    contri = _slf(contri, 1.0)
            else:
                nb_s, ic_s, hn_s, mono_s = nb_full, ic_full, hn_full, \
                    monotone
            if cegb2 is not None:
                if rbs is None:
                    def one(h, s, bd, d, po, fm, cg):
                        return local_best_candidate(
                            h, s, nb_s, ic_s, hn_s, fm, sp,
                            mono_s, bd if use_mc else None, d, cg,
                            contri, po)
                    out = jax.vmap(one)(hists, sums, bounds, depths,
                                        pouts, fms, cegb2)
                else:
                    def one(h, s, bd, d, po, fm, cg, rb):
                        return local_best_candidate(
                            h, s, nb_s, ic_s, hn_s, fm, sp,
                            mono_s, bd if use_mc else None, d, cg, contri,
                            po, rb)
                    out = jax.vmap(one)(hists, sums, bounds, depths,
                                        pouts, fms, cegb2, rbs)
            elif rbs is None:
                def one(h, s, bd, d, po, fm):
                    return local_best_candidate(
                        h, s, nb_s, ic_s, hn_s, fm, sp,
                        mono_s, bd if use_mc else None, d, cegb, contri,
                        po)
                out = jax.vmap(one)(hists, sums, bounds, depths, pouts,
                                    fms)
            else:
                def one(h, s, bd, d, po, fm, rb):
                    return local_best_candidate(
                        h, s, nb_s, ic_s, hn_s, fm, sp,
                        mono_s, bd if use_mc else None, d, cegb, contri,
                        po, rb)
                out = jax.vmap(one)(hists, sums, bounds, depths, pouts,
                                    fms, rbs)
            return _exchange(out) if use_scatter else out

        # per-node RNG streams (bynode sampling / ExtraTrees thresholds),
        # identical on every DP shard (replicated key, identical node ids)
        if use_bynode or use_et:
            nk = node_key if node_key is not None else \
                jnp.zeros((2, 2), jnp.uint32)
        if use_bynode:
            def node_mask_many(ids):
                def one(i):
                    r = jax.random.uniform(jax.random.fold_in(nk[0], i),
                                           (F,))
                    kth = jax.lax.top_k(r, kcnt)[0][-1]
                    return r >= kth
                return jax.vmap(one)(ids)
        if use_et:
            et_hi = jnp.maximum(
                jnp.where(ic_full, nb_full - 1, nb_full - 2), 0)

            def node_rand_many(ids):
                def one(i):
                    u = jax.random.uniform(jax.random.fold_in(nk[1], i),
                                           (F,))
                    return jnp.minimum(
                        (u * (et_hi + 1).astype(jnp.float32)
                         ).astype(jnp.int32), et_hi)
                return jax.vmap(one)(ids)

        rl_dtype = jnp.uint8 if L <= 256 else jnp.int32
        nonlocal_dbg: dict = {}

        def _spec_state():
            """Speculative-ramp initial state: provisional subtree from a
            row subsample, verified and committed against one full-data
            W-channel histogram pass (see make_wave_grow_fn docnotes).
            Replaces the root pass + the first ~log2(W) ramp waves.

            Data-parallel: each shard strides its LOCAL rows (the global
            subsample budget divides by ``spec_shards``) and every
            provisional pass psums its (W, G, Bb, 3) histogram batch over
            the mesh — exactly one extra collective per provisional pass,
            the same payload shape as a committed wave's — so all shards
            grow one identical provisional tree; the verification pass
            and commit tests then run on psum'd full-data sums."""
            import math as _m
            Kc, K1 = W, W - 1
            # -- statically-strided row subsample (weights carry bagging/
            # GOSS masks, so out-of-bag rows contribute nothing) --
            stride = max(1, n // max(int(spec_subsample) // spec_shards,
                                     4096))
            n_ss = max((n // stride) // 4096 * 4096, 4096)
            w_src = wch0 if quantized else w8
            if pack4:
                # stride over packed BYTES: the subsample keeps adjacent
                # row pairs (one byte each) so the packed kernels consume
                # it directly; weights follow the same pair selection
                X_ss = X_T[:, ::stride][:, :n_ss // 2]
                w_ss = w_src.reshape(w_src.shape[0], -1, 2)[
                    :, ::stride][:, :n_ss // 2].reshape(w_src.shape[0],
                                                        n_ss)
            else:
                X_ss = X_T[:, ::stride][:, :n_ss]
                w_ss = w_src[:, ::stride][:, :n_ss]
            nan_of = jnp.where(hn_full, nb_full - 1, -1)       # (F,)
            fm_k = jnp.broadcast_to(feature_mask, (Kc, F))
            jar = jnp.arange(Kc, dtype=jnp.int32)
            zb_k = jnp.zeros((Kc, 2), jnp.float32)
            zd_k = jnp.zeros((Kc,), jnp.int32)

            def dqh(h):
                return dq(h) if quantized else h

            # -- provisional growth on the subsample: each wave histograms
            # EVERY current prov leaf (rl_ss doubles as the channel id),
            # scans, and splits all positive-gain leaves up to capacity --
            rl_ss = jnp.zeros((n_ss,), jnp.uint8)
            nlp = jnp.asarray(1, jnp.int32)
            pfeat = jnp.zeros((K1,), jnp.int32)
            pthr = jnp.zeros((K1,), jnp.int32)
            pnan = jnp.full((K1,), -1, jnp.int32)
            pdl = jnp.zeros((K1,), jnp.int32)
            pleaf = jnp.zeros((K1,), jnp.int32)
            pact = jnp.zeros((K1,), jnp.bool_)
            ppar = jnp.full((K1,), -1, jnp.int32)
            owner = jnp.full((Kc,), -1, jnp.int32)
            Lm = jnp.zeros((K1, Kc), jnp.bool_)   # left-descendant leaves
            Rm = jnp.zeros((K1, Kc), jnp.bool_)   # right-descendant leaves
            tabs = []
            for _t in range(max(1, int(_m.ceil(_m.log2(Kc))))):
                if quantized:
                    h_ss = build_histogram_pallas_leaves_q8(
                        X_ss, w_ss, rl_ss.astype(jnp.int8), num_bins=Bb,
                        interpret=interpret, pipeline=pipeline,
                        bins_packed=pack4)[:Kc]
                else:
                    h_ss = build_histogram_pallas_leaves(
                        X_ss, w_ss, rl_ss.astype(jnp.int8), num_bins=Bb,
                        interpret=interpret, pipeline=pipeline,
                        bins_packed=pack4)[:Kc]
                # DP: the one histogram collective of this provisional
                # pass — the provisional batches ride the same merge mode
                # as committed waves (psum, or the feature-sliced
                # reduce-scatter), so every shard grows the same
                # provisional tree (serial: identity).  Leaf totals come
                # from _reduce_waves so they are shard-consistent under
                # scatter.
                h_ss, sums_pl = _reduce_waves(h_ss, Kc, with_totals=True)
                lvp = leaf_output(sums_pl[:, 0], sums_pl[:, 1], sp)
                cnds = many_candidates(
                    _scan_hists(h_ss, sums_pl), sums_pl,
                    zb_k, zd_k, lvp, fm_k)
                g = jnp.where(jar < nlp, cnds[0], NEG_INF)
                vals, sel_l = jax.lax.top_k(g, Kc)
                sel = (vals > 0) & (jar < Kc - nlp)
                prefix = jnp.cumsum(sel.astype(jnp.int32))
                newids = nlp + prefix - 1
                nodeids = (nlp - 1) + prefix - 1
                feat_s = cnds[1][sel_l]
                thr_s = cnds[2][sel_l]
                dl_s = cnds[3][sel_l].astype(jnp.int32)
                fnan_s = nan_of[feat_s]
                nidx = jnp.where(sel, nodeids, K1)
                pfeat = pfeat.at[nidx].set(feat_s, mode="drop")
                pthr = pthr.at[nidx].set(thr_s, mode="drop")
                pnan = pnan.at[nidx].set(fnan_s, mode="drop")
                pdl = pdl.at[nidx].set(dl_s, mode="drop")
                pleaf = pleaf.at[nidx].set(sel_l, mode="drop")
                pact = pact.at[nidx].set(sel, mode="drop")
                ppar = ppar.at[nidx].set(owner[sel_l], mode="drop")
                # descendant propagation: nodes holding leaf r gain leaf s
                A = jnp.zeros((Kc, Kc), jnp.int32).at[
                    jnp.where(sel, sel_l, Kc),
                    jnp.where(sel, newids, Kc)].set(1, mode="drop")
                Lm = Lm | (Lm.astype(jnp.int32) @ A > 0)
                Rm = Rm | (Rm.astype(jnp.int32) @ A > 0)
                oh_l = jax.nn.one_hot(sel_l, Kc, dtype=jnp.bool_)
                oh_r = jax.nn.one_hot(newids, Kc, dtype=jnp.bool_)
                Lm = Lm.at[nidx].set(oh_l, mode="drop")
                Rm = Rm.at[nidx].set(oh_r, mode="drop")
                owner = owner.at[jnp.where(sel, sel_l, Kc)].set(
                    nodeids, mode="drop")
                owner = owner.at[jnp.where(sel, newids, Kc)].set(
                    nodeids, mode="drop")
                feats_cl = jnp.clip(feat_s, 0, F - 1)
                tab = jnp.stack([
                    thr_s, fnan_s, dl_s, jnp.ones((Kc,), jnp.int32),
                    sel_l, newids, sel.astype(jnp.int32),
                    jnp.zeros((Kc,), jnp.int32)])
                cols_ss = jnp.take(X_ss, feats_cl, axis=0)
                if pack4:
                    cols_ss = unpack_bins4(cols_ss)
                rl2, _ = wave_row_update_pallas(cols_ss, rl_ss, tab,
                                                interpret=interpret,
                                                pipeline=pipeline)
                rl_ss = rl2.astype(jnp.uint8)
                tabs.append((tab, feats_cl))
                nlp = nlp + prefix[-1]

            # -- route ALL rows through the provisional tree (same
            # per-wave fused kernel the real row update uses, so the
            # partition matches how committed splits will route) --
            rl_full = jnp.zeros((n,), jnp.uint8)
            for tab, feats_cl in tabs:
                cols = take_cols(feats_cl)
                rlf, _ = wave_row_update_pallas(cols, rl_full, tab,
                                                interpret=interpret,
                                                pipeline=pipeline)
                rl_full = rlf.astype(jnp.uint8)

            # -- ONE full-data pass: exact per-prov-leaf channel sums --
            h_ch, leaf_tot = hist_waves(rl_full.astype(jnp.int8), k=Kc,
                                        with_totals=True)     # (Kc, 3)
            # voting: keep the batch RAW and shard-local — the node-sum
            # einsum is exact in int32 and _voting_candidates merges
            # (and dequantizes) only the voted slices
            hf_ch = h_ch if use_voting else dqh(h_ch)

            # -- exact node aggregates + commit tests --
            lt3 = Lm.astype(jnp.float32) @ leaf_tot          # (K1, 3)
            rt3 = Rm.astype(jnp.float32) @ leaf_tot
            pt3 = lt3 + rt3
            Dn = Lm | Rm
            H_node = jnp.einsum("jl,lgbc->jgbc",
                                Dn.astype(hf_ch.dtype), hf_ch)
            lvn = leaf_output(pt3[:, 0], pt3[:, 1], sp)
            bg = many_candidates(
                H_node if use_voting else
                jax.vmap(expand_hist)(H_node, pt3), pt3,
                jnp.zeros((K1, 2), jnp.float32),
                jnp.zeros((K1,), jnp.int32), lvn,
                jnp.broadcast_to(feature_mask, (K1, F)))[0]

            def lg3(s3):
                return _leaf_gain(s3[:, 0], s3[:, 1],
                                  sp.lambda_l1, sp.lambda_l2)

            pg = lg3(lt3) + lg3(rt3) - (lg3(pt3) + sp.min_gain_to_split)
            okc = ((lt3[:, 2] >= sp.min_data_in_leaf) &
                   (rt3[:, 2] >= sp.min_data_in_leaf) &
                   (lt3[:, 1] >= sp.min_sum_hessian_in_leaf) &
                   (rt3[:, 1] >= sp.min_sum_hessian_in_leaf))
            test = (pact & okc & (pg > 0) &
                    (pg >= (1.0 - spec_tol) * jnp.maximum(bg, 0.0)))
            comm = jnp.zeros((K1,), jnp.bool_)
            for j in range(K1):  # parents precede children by construction
                pok = jnp.where(ppar[j] < 0, True,
                                comm[jnp.maximum(ppar[j], 0)])
                comm = comm.at[j].set(pok & test[j])

            # -- replay committed nodes into the wave-state arrays (same
            # leaf/node numbering convention as the wave body: left child
            # keeps the split leaf's id, right child takes the next
            # fresh id; child slots encode leaves as -(leaf+1)) --
            s_map = jnp.zeros((Kc,), jnp.int32)   # prov leaf -> state leaf
            depth_pl = jnp.zeros((Kc,), jnp.int32)
            nl_run = jnp.asarray(1, jnp.int32)
            sf = jnp.full((L - 1,), -1, jnp.int32)
            tb_ = jnp.zeros((L - 1,), jnp.int32)
            nb_ = jnp.full((L - 1,), -1, jnp.int32)
            dt_ = jnp.zeros((L - 1,), jnp.int32)
            lc_ = jnp.zeros((L - 1,), jnp.int32)
            rc_ = jnp.zeros((L - 1,), jnp.int32)
            sg_ = jnp.zeros((L - 1,), jnp.float32)
            iv_ = jnp.zeros((L - 1,), jnp.float32)
            iw_ = jnp.zeros((L - 1,), jnp.float32)
            ic_ = jnp.zeros((L - 1,), jnp.float32)
            for j in range(K1):
                cj = comm[j]
                sl = s_map[pleaf[j]]
                new_leaf = nl_run
                nid = nl_run - 1
                enc = -(sl + 1)
                lc_ = jnp.where(cj & (lc_ == enc), nid, lc_)
                rc_ = jnp.where(cj & (rc_ == enc), nid, rc_)
                nidx = jnp.where(cj, nid, L - 1)
                sf = sf.at[nidx].set(pfeat[j], mode="drop")
                tb_ = tb_.at[nidx].set(pthr[j], mode="drop")
                nb_ = nb_.at[nidx].set(pnan[j], mode="drop")
                dt_ = dt_.at[nidx].set(
                    jnp.where(pdl[j] > 0, DEFAULT_LEFT_MASK, 0) |
                    jnp.where(pnan[j] >= 0, MISSING_NAN, 0), mode="drop")
                lc_ = lc_.at[nidx].set(enc, mode="drop")
                rc_ = rc_.at[nidx].set(-(new_leaf + 1), mode="drop")
                sg_ = sg_.at[nidx].set(pg[j], mode="drop")
                iv_ = iv_.at[nidx].set(
                    leaf_output(pt3[j, 0], pt3[j, 1], sp), mode="drop")
                iw_ = iw_.at[nidx].set(pt3[j, 1], mode="drop")
                ic_ = ic_.at[nidx].set(pt3[j, 2], mode="drop")
                s_map = jnp.where(cj & Rm[j], new_leaf, s_map)
                depth_pl = jnp.where(cj & Dn[j], depth_pl + 1, depth_pl)
                nl_run = nl_run + cj.astype(jnp.int32)

            import os as _os
            if _os.environ.get("LGBM_TPU_SPEC_DEBUG"):
                # debug-only (axon cannot host-callback): smuggle the
                # commit/prov counts out through the last split_gain slot,
                # which a 255-leaf debug tree then exposes to the host
                nonlocal_dbg["spec_counts"] = jnp.stack(
                    [nlp, jnp.sum(comm.astype(jnp.int32))])

            # -- pools + frontier candidates --
            rl0 = jnp.take(s_map, rl_full.astype(jnp.int32))
            hists0 = jnp.zeros(
                (L, G_loc, Bb, 3), h_ch.dtype).at[s_map].add(h_ch[:Kc])
            lsum0 = jnp.zeros((L, 3), jnp.float32).at[s_map].add(leaf_tot)
            ldep0 = jnp.zeros((L,), jnp.int32).at[s_map].set(depth_pl)
            live = jnp.arange(L, dtype=jnp.int32) < nl_run
            lval0 = jnp.where(live, leaf_output(lsum0[:, 0], lsum0[:, 1],
                                                sp), 0.0)
            cnds0 = many_candidates(
                _scan_hists(hists0[:Kc], lsum0[:Kc]),
                lsum0[:Kc], zb_k, ldep0[:Kc], lval0[:Kc], fm_k)
            cg0 = jnp.where(jar < nl_run, cnds0[0], NEG_INF)
            return {
                "row_leaf": rl0.astype(rl_dtype),
                "leaf_sum": lsum0,
                "leaf_depth": ldep0,
                "cand_gain": jnp.full((L,), NEG_INF,
                                      jnp.float32).at[:Kc].set(cg0),
                "cand_feat": jnp.zeros((L,), jnp.int32).at[:Kc].set(
                    cnds0[1]),
                "cand_bin": jnp.zeros((L,), jnp.int32).at[:Kc].set(
                    cnds0[2]),
                "cand_dleft": jnp.zeros((L,), jnp.bool_).at[:Kc].set(
                    cnds0[3]),
                "cand_lsum": jnp.zeros((L, 3), jnp.float32).at[:Kc].set(
                    cnds0[4]),
                "cand_rsum": jnp.zeros((L, 3), jnp.float32).at[:Kc].set(
                    cnds0[5]),
                "cand_member": jnp.zeros((L, max_bins),
                                         jnp.bool_).at[:Kc].set(cnds0[6]),
                "hists": hists0,
                "split_feature": sf, "threshold_bin": tb_, "nan_bin": nb_,
                "cat_member": jnp.zeros((L - 1, max_bins), jnp.bool_),
                "decision_type": dt_, "left_child": lc_, "right_child": rc_,
                "split_gain": sg_, "internal_value": iv_,
                "internal_weight": iw_, "internal_count": ic_,
                "leaf_value": lval0,
                "leaf_weight": jnp.where(live, lsum0[:, 1], 0.0),
                "leaf_count": jnp.where(live, lsum0[:, 2], 0.0),
                "num_leaves": nl_run,
                "done": jnp.asarray(False),
                # full-data histogram passes so far: the one verification
                # mega-pass (the ~log2(W) provisional passes run at
                # subsample scale and are not counted)
                "hist_passes": jnp.asarray(1, jnp.int32),
            }

        if use_spec:
            state = _spec_state()
        else:
            # ---- root ----
            if quantized:
                # derive the root totals from the quantized histogram
                # itself (any bundle's bins sum to the total, and the
                # integer sum is exact BEFORE dequantization — identical
                # for every feature, shard and merge mode) so candidate
                # left+right sums stay consistent with the totals
                # downstream
                rh, rtot = hist_waves(jnp.zeros((n,), jnp.int8), k=1,
                                      with_totals=True)
                root_hist = rh[0]
                root_sum = rtot[0]
            else:
                root_hist = hist_waves(jnp.zeros((n,), jnp.int8), k=1)[0]
                root_sum = strat.reduce_sum(jnp.stack([
                    jnp.sum(gm), jnp.sum(hm), jnp.sum(cnt_mask)]))
            root_hist_f = dq(root_hist) if quantized else root_hist
            root_bound = jnp.asarray([-BIG, BIG], jnp.float32)
            root_out = _child_out(root_sum[0], root_sum[1], root_sum[2],
                                  jnp.asarray(0.0, jnp.float32))
            rid = jnp.asarray([2 * L], jnp.int32)
            fm_root = feature_mask
            if use_ic:
                fm_root = fm_root & allowed_features(
                    jnp.zeros((F,), jnp.bool_))
            if use_bynode:
                fm_root = fm_root & node_mask_many(rid)[0]
            rb_root = node_rand_many(rid)[0] if use_et else None
            if use_lazy:
                # Charge only rows whose feature bit is still unset in the
                # PERSISTENT used bitmap (cost_effective_gradient_boosting.hpp
                # CalculateOndemandCosts): from the second tree on, features
                # already materialized by earlier trees' splits cost nothing
                # for those rows.  used_root[f] = in-bag rows with bit set.
                # Like cnt_group below, the f32-accumulated 0/1 dot is exact
                # to 2^24 counted rows per shard; beyond that the lazy cost
                # degrades gracefully (it only biases split selection).
                base = strat.cegb_full if strat.cegb_full is not None else 0.0
                used0 = lazy_used if lazy_used is not None \
                    else lazy_bitmap_init(F, n, lp)
                used_root = strat.reduce_sum(jax.lax.dot_general(
                    (_unpack_bits(used0) if lp
                     else used0).astype(jnp.bfloat16),
                    (bag_mask > 0).astype(jnp.bfloat16)[None, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)[:, 0])       # (F,)
                strat.cegb_full = base + lazy_pen * jnp.maximum(
                    root_sum[2] - used_root, 0.0)
            if use_scatter or use_voting:
                # the root scan rides the sliced/voted many_candidates
                # path (a 1-channel batch) so it too scans only this
                # shard's block (scatter) or merges only the voted
                # feature slices (voting)
                c1 = many_candidates(
                    _scan_hists(root_hist[None], root_sum[None]),
                    root_sum[None], root_bound[None],
                    jnp.zeros((1,), jnp.int32), root_out[None],
                    fm_root[None],
                    rb_root[None] if rb_root is not None else None)
                cand = tuple(a[0] for a in c1)
            else:
                cand = strat.leaf_candidates(
                    expand_hist(root_hist_f, root_sum), root_sum, fm_root,
                    sp, root_bound, jnp.asarray(0, jnp.int32), root_out,
                    rb_root)

            state = {
                "row_leaf": jnp.zeros((n,), rl_dtype),
                "leaf_sum": jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum),
                "leaf_depth": jnp.zeros((L,), jnp.int32),
                "cand_gain": jnp.full((L,), NEG_INF, jnp.float32).at[0].set(cand[0]),
                "cand_feat": jnp.zeros((L,), jnp.int32).at[0].set(cand[1]),
                "cand_bin": jnp.zeros((L,), jnp.int32).at[0].set(cand[2]),
                "cand_dleft": jnp.zeros((L,), jnp.bool_).at[0].set(cand[3]),
                "cand_lsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[4]),
                "cand_rsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[5]),
                "cand_member": jnp.zeros((L, max_bins), jnp.bool_).at[0].set(
                    cand[6]),
                "hists": jnp.zeros(
                    (L, G_loc, Bb, 3),
                    jnp.int32 if quantized else jnp.float32).at[0].set(
                        root_hist),
                "split_feature": jnp.full((L - 1,), -1, jnp.int32),
                "threshold_bin": jnp.zeros((L - 1,), jnp.int32),
                "nan_bin": jnp.full((L - 1,), -1, jnp.int32),
                "cat_member": jnp.zeros((L - 1, max_bins), jnp.bool_),
                "decision_type": jnp.zeros((L - 1,), jnp.int32),
                "left_child": jnp.zeros((L - 1,), jnp.int32),
                "right_child": jnp.zeros((L - 1,), jnp.int32),
                "split_gain": jnp.zeros((L - 1,), jnp.float32),
                "internal_value": jnp.zeros((L - 1,), jnp.float32),
                "internal_weight": jnp.zeros((L - 1,), jnp.float32),
                "internal_count": jnp.zeros((L - 1,), jnp.float32),
                "leaf_value": jnp.zeros((L,), jnp.float32).at[0].set(root_out),
                "leaf_weight": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[1]),
                "leaf_count": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[2]),
                "num_leaves": jnp.asarray(1, jnp.int32),
                "done": jnp.asarray(False),
                "hist_passes": jnp.asarray(1, jnp.int32),  # the root pass
            }
            if use_mc:
                state["leaf_mn"] = jnp.full((L,), -BIG, jnp.float32)
                state["leaf_mx"] = jnp.full((L,), BIG, jnp.float32)
                if mc_inter:
                    # per-leaf bin-space region boxes for the geometric
                    # contiguity test of the intermediate constraints
                    state["leaf_lo"] = jnp.zeros((L, F), jnp.int32)
                    state["leaf_hi"] = jnp.broadcast_to(
                        (nb_full - 1).astype(jnp.int32)[None, :],
                        (L, F)).copy()
            if use_ic:
                # features used on the path to each leaf (interaction
                # constraints restrict children to compatible groups)
                state["leaf_path"] = jnp.zeros((L, F), jnp.bool_)
            if use_lazy:
                # per-(feature, row) "already computed" bitmap — PERSISTENT
                # across trees like the reference's feature_used_in_data_
                # bitset (it is allocated once per training run and never
                # cleared); the learner threads it through every grow call.
                # Packed to uint8 bitfields (lazy_bitmap_init) — 8x less
                # HBM than the former bool layout; lazy_bitpack=False
                # keeps the bool path (tests cross-check equality).
                state["used"] = lazy_used if lazy_used is not None \
                    else lazy_bitmap_init(F, n, lp)

        jarange = jnp.arange(W, dtype=jnp.int32)

        def body(s, forced=None):
            nl0 = s["num_leaves"]
            if forced is None:
                budget = L - nl0
                # Endgame taper: committing a full wave close to the leaf
                # budget would lock in splits that freshly-created children
                # (whose gains are not yet known) should have outcompeted —
                # the sequential best-first order lets them.  Halving the
                # wave once budget < 2W closes most of the quality gap to
                # the exact order; the W//4 floor caps the halving cascade
                # at ~2-3 extra waves (each wave is a full-data histogram
                # pass — a log2(W)-deep taper costs more wall time than
                # its last few splits are worth).
                k_eff = wave_taper_k(budget, W)
                vals, sel_leaves = jax.lax.top_k(s["cand_gain"], W)
                sel = (vals > 0) & (jarange < k_eff)
                feat = s["cand_feat"][sel_leaves]          # (W,)
                thr = s["cand_bin"][sel_leaves]
                dleft = s["cand_dleft"][sel_leaves]
                lsum = s["cand_lsum"][sel_leaves]          # (W, 3)
                rsum = s["cand_rsum"][sel_leaves]
                member = s["cand_member"][sel_leaves]      # (W, B)
                psum_ = s["leaf_sum"][sel_leaves]
            else:
                # forced wave: fixed (leaf, feature, bin) applied
                # regardless of gain; child sums read from the parent's
                # pooled histogram (the partitioned grower's ForceSplits
                # override, learner/partitioned.py:440, batched)
                import numpy as _np
                k = len(forced)
                pad = [(0, 0, 0)] * (W - k)
                trip = _np.asarray(list(forced) + pad, _np.int32)
                sel_leaves = jnp.asarray(trip[:, 0])
                feat = jnp.asarray(trip[:, 1])
                thr = jnp.asarray(trip[:, 2])
                psum_ = s["leaf_sum"][jnp.asarray(trip[:, 0])]
                # empty forced leaves are skipped like the partitioned
                # grower's `do = leaf_seg > 0` gate (degenerate forcing
                # files route all rows one way; the reference stops
                # forcing such subtrees too)
                sel = jnp.asarray(_np.arange(W) < k) & (psum_[:, 2] > 0)
                dleft = jnp.zeros((W,), jnp.bool_)
                member = jnp.zeros((W, max_bins), jnp.bool_)
                ph = s["hists"][sel_leaves]
                phf = dq(ph) if quantized else ph
                exh = jax.vmap(expand_hist)(phf, psum_)    # (W, F, B, 3)
                fh = exh[jnp.arange(W), feat]              # (W, B, 3)
                csum = jnp.cumsum(fh, axis=1)
                lsum = csum[jnp.arange(W),
                            jnp.clip(thr, 0, max_bins - 1)]
                rsum = psum_ - lsum
                # record the forced split's REAL gain (the reference's
                # ForceSplits computes a full SplitInfo for the forced
                # threshold), on the scan's shifted-gain scale
                vals = (_leaf_gain(lsum[:, 0], lsum[:, 1],
                                   sp.lambda_l1, sp.lambda_l2) +
                        _leaf_gain(rsum[:, 0], rsum[:, 1],
                                   sp.lambda_l1, sp.lambda_l2) -
                        _leaf_gain(psum_[:, 0], psum_[:, 1],
                                   sp.lambda_l1, sp.lambda_l2) -
                        sp.min_gain_to_split)
            prefix = jnp.cumsum(sel.astype(jnp.int32))
            total_new = prefix[-1]
            new_ids = nl0 + prefix - 1                     # valid where sel
            node_ids = (nl0 - 1) + prefix - 1              # node index
            left_smaller = lsum[:, 2] <= rsum[:, 2]        # (W,)
            fcat = ic_full[feat]
            fnan = hn_full[feat]
            f_nan_bin = jnp.where(fnan, nb_full[feat] - 1, -1)

            # ---- row_leaf + wave-channel update ----
            rl = s["row_leaf"]
            rl_old = rl
            if pallas and small_bins and not any_cat:
                # one fused kernel pass instead of W masked XLA sweeps
                # (each sweep's fused-loop launch overhead alone costs
                # ~0.7 ms at 10.5M rows)
                cols_w = take_cols(feat)                      # (W, N) u8
                tab = jnp.stack([
                    thr, f_nan_bin, dleft.astype(jnp.int32),
                    left_smaller.astype(jnp.int32), sel_leaves, new_ids,
                    sel.astype(jnp.int32), jnp.zeros_like(thr)])
                rl_new, ch = wave_row_update_pallas(
                    cols_w, rl, tab, interpret=interpret,
                    pipeline=pipeline)
                rl = rl_new.astype(rl.dtype)
            else:
                # Vectorized XLA fallback (categorical / EFB / wide-bin
                # shapes the fused kernel cannot take).  The former W
                # SEQUENTIAL masked sweeps cost ~0.7-2 ms of fused-loop
                # launch overhead EACH (~50 ms/wave at small N — the
                # dominant cost of the whole benchmark-matrix shapes);
                # one batched (W, N) formulation replaces them: every
                # row belongs to at most one split leaf, so an argmax
                # over the match matrix picks its slot and a single
                # take_along_axis resolves the decision.
                if small_bins:
                    thr_c = thr.astype(jnp.uint8)[:, None]
                    nan_c = jnp.where(f_nan_bin < 0, 255,
                                      f_nan_bin).astype(jnp.uint8)[:, None]
                else:
                    thr_c = thr[:, None]
                    nan_c = f_nan_bin[:, None]
                sel_c = sel_leaves.astype(rl.dtype)
                mi8 = member.astype(jnp.int8).T                # (B, W)
                cat_static = sp.cat_idx if any_cat else ()

                def _upd_block(Xb, rlb):
                    """One row block of the batched update — (W, m)
                    intermediates stay bounded for very large N."""
                    m = Xb.shape[1]

                    def fcol(ff):
                        g = f_bundle[ff] if use_efb else ff
                        v = jax.lax.dynamic_slice(Xb, (g, 0), (1, m))[0]
                        if small_bins:
                            return v
                        return bundle_decode(v.astype(jnp.int32), ff)

                    cols_w = jax.vmap(fcol)(feat)              # (W, m)
                    num_go = jnp.where(cols_w == nan_c, dleft[:, None],
                                       cols_w <= thr_c)
                    if not any_cat:
                        go_w = num_go
                    elif 0 < len(cat_static) <= 8:
                        # per-slot bitset lookup as FEW-INDICES x
                        # WIDE-ROW embedding takes: a (W, N)-indexed
                        # gather from the (W, B) membership table costs
                        # ~45 ms at 145K rows on TPU for every dtype,
                        # while N row-takes from the transposed (B, W)
                        # table cost ~6 ms — loop the STATIC cat
                        # features, combine by split-feature match
                        acc = jnp.zeros((m, W), jnp.int8)
                        for cf in cat_static:
                            colv = fcol(jnp.asarray(cf, jnp.int32))
                            look = jnp.take(mi8, colv.astype(jnp.int32),
                                            axis=0)            # (m, W)
                            acc = acc + look * (feat == cf).astype(
                                jnp.int8)[None, :]
                        go_w = jnp.where(fcat[:, None], acc.T > 0, num_go)
                    else:
                        go_w = jnp.where(
                            fcat[:, None],
                            jnp.take_along_axis(
                                member, cols_w.astype(jnp.int32), axis=1),
                            num_go)
                    match = sel[:, None] & (rlb[None, :] == sel_c[:, None])
                    has = jnp.any(match, axis=0)               # (m,)
                    jhit = jnp.argmax(match, axis=0)
                    go = jnp.take_along_axis(go_w, jhit[None, :],
                                             axis=0)[0]
                    chb = jnp.where(
                        has & (go == left_smaller[jhit]),
                        jhit.astype(jnp.int8), jnp.int8(-1))
                    rlb = jnp.where(has & jnp.logical_not(go),
                                    new_ids[jhit].astype(rlb.dtype), rlb)
                    return rlb, chb

                blk = max(4096, ((1 << 26) // max(W, 1)) // 4096 * 4096)
                if n <= blk:
                    rl, ch = _upd_block(X_T, rl)
                else:
                    parts = [_upd_block(X_T[:, lo:lo + blk],
                                        rl[lo:lo + blk])
                             for lo in range(0, n, blk)]
                    rl = jnp.concatenate([p_[0] for p_ in parts])
                    ch = jnp.concatenate([p_[1] for p_ in parts])

            # ---- one kernel pass: all W smaller-child histograms ----
            hist_small = hist_waves(ch)                    # (W, G, Bb, 3)
            parents = s["hists"][sel_leaves]
            hist_big = parents - hist_small
            ls4 = left_smaller[:, None, None, None]
            hist_l = jnp.where(ls4, hist_small, hist_big)
            hist_r = jnp.where(ls4, hist_big, hist_small)

            # ---- children outputs (smoothed toward the split leaf's own
            # value under path_smooth) + monotone bounds
            # (BasicLeafConstraints::Update) ----
            parent_lv = s["leaf_value"][sel_leaves]
            out_l = _child_out(lsum[:, 0], lsum[:, 1], lsum[:, 2], parent_lv)
            out_r = _child_out(rsum[:, 0], rsum[:, 1], rsum[:, 2], parent_lv)
            if use_mc and mc_inter:
                # Intermediate constraints (monotone_constraints.hpp:514
                # IntermediateLeafConstraints): children are bounded by
                # the SIBLING'S OUTPUT instead of the midpoint, and the
                # new outputs propagate to every geometrically contiguous
                # leaf.  The reference finds contiguous leaves by walking
                # up the tree and filtering thresholds
                # (GoUpToFindLeavesToUpdate / GoDownToFindLeavesToUpdate);
                # here each leaf carries its bin-space region box
                # (leaf_lo/leaf_hi), and contiguity is the EXACT geometric
                # test — regions overlapping in every feature except one
                # monotone feature where they are disjoint and ordered.
                # The wave's W splits are refined sequentially over the
                # SMALL (L,)-sized arrays (one histogram pass still serves
                # the whole wave), so later slots see earlier slots'
                # tightened bounds — within-wave batching stays safe.
                mn_all, mx_all = s["leaf_mn"], s["leaf_mx"]
                lo_all, hi_all = s["leaf_lo"], s["leaf_hi"]
                out_l2 = jnp.zeros((W,), jnp.float32)
                out_r2 = jnp.zeros((W,), jnp.float32)
                bnd_l = jnp.zeros((W, 2), jnp.float32)
                bnd_r = jnp.zeros((W, 2), jnp.float32)
                inc_row = (monotone > 0)[None, :]
                dec_row = (monotone < 0)[None, :]
                for j in range(W):
                    act = sel[j]
                    p = sel_leaves[j]
                    fj = feat[j]
                    mj = jnp.where(fcat[j], 0, monotone[fj])
                    pmn, pmx = mn_all[p], mx_all[p]
                    ol = jnp.clip(out_l[j], pmn, pmx)
                    orr = jnp.clip(out_r[j], pmn, pmx)
                    # bounds tightened by earlier slots can cross a stale
                    # candidate's outputs; collapse to the shared boundary
                    # (monotone-safe, zero-gain degenerate split)
                    cross = ((mj > 0) & (ol > orr)) | ((mj < 0) & (ol < orr))
                    midj = (ol + orr) / 2.0
                    ol = jnp.where(cross, jnp.clip(midj, pmn, pmx), ol)
                    orr = jnp.where(cross, jnp.clip(midj, pmn, pmx), orr)
                    # child entries (UpdateConstraintsWithOutputs)
                    mn_lj = jnp.where(mj < 0, jnp.maximum(pmn, orr), pmn)
                    mx_lj = jnp.where(mj > 0, jnp.minimum(pmx, orr), pmx)
                    mn_rj = jnp.where(mj > 0, jnp.maximum(pmn, ol), pmn)
                    mx_rj = jnp.where(mj < 0, jnp.minimum(pmx, ol), pmx)
                    # child regions (categorical splits keep the parent box
                    # — no feature-order relation between cat children)
                    lo_p, hi_p = lo_all[p], hi_all[p]
                    num_j = jnp.logical_not(fcat[j])
                    hi_l = jnp.where(num_j, hi_p.at[fj].set(thr[j]), hi_p)
                    lo_r = jnp.where(num_j,
                                     lo_p.at[fj].set(thr[j] + 1), lo_p)
                    for c_lo, c_hi, c_out in ((lo_p, hi_l, ol),
                                              (lo_r, hi_p, orr)):
                        inter = (lo_all <= c_hi[None, :]) & \
                            (hi_all >= c_lo[None, :])          # (L, F)
                        nfail = jnp.sum(jnp.logical_not(inter), axis=1)
                        onlyf = (nfail == 1)[:, None] & \
                            jnp.logical_not(inter)
                        below = onlyf & (hi_all < c_lo[None, :])
                        above = onlyf & (lo_all > c_hi[None, :])
                        capmax = jnp.any((below & inc_row) |
                                         (above & dec_row), axis=1)
                        capmin = jnp.any((above & inc_row) |
                                         (below & dec_row), axis=1)
                        mx_all = jnp.where(act & capmax,
                                           jnp.minimum(mx_all, c_out),
                                           mx_all)
                        mn_all = jnp.where(act & capmin,
                                           jnp.maximum(mn_all, c_out),
                                           mn_all)
                    pj = jnp.where(act, p, L)
                    rj = jnp.where(act, new_ids[j], L)
                    mn_all = mn_all.at[pj].set(mn_lj, mode="drop") \
                                   .at[rj].set(mn_rj, mode="drop")
                    mx_all = mx_all.at[pj].set(mx_lj, mode="drop") \
                                   .at[rj].set(mx_rj, mode="drop")
                    hi_all = hi_all.at[pj].set(hi_l, mode="drop") \
                                   .at[rj].set(hi_p, mode="drop")
                    lo_all = lo_all.at[rj].set(lo_r, mode="drop")
                    out_l2 = out_l2.at[j].set(ol)
                    out_r2 = out_r2.at[j].set(orr)
                    bnd_l = bnd_l.at[j].set(jnp.stack([mn_lj, mx_lj]))
                    bnd_r = bnd_r.at[j].set(jnp.stack([mn_rj, mx_rj]))
                out_l, out_r = out_l2, out_r2
                mn_l, mx_l = bnd_l[:, 0], bnd_l[:, 1]
                mn_r, mx_r = bnd_r[:, 0], bnd_r[:, 1]
                bounds2 = jnp.concatenate([bnd_l, bnd_r])   # (2W, 2)
            elif use_mc:
                p_mn = s["leaf_mn"][sel_leaves]
                p_mx = s["leaf_mx"][sel_leaves]
                out_l = jnp.clip(out_l, p_mn, p_mx)
                out_r = jnp.clip(out_r, p_mn, p_mx)
                m = jnp.where(fcat, 0, monotone[feat])
                mid = (out_l + out_r) / 2.0
                mn_l = jnp.where(m < 0, jnp.maximum(p_mn, mid), p_mn)
                mx_l = jnp.where(m > 0, jnp.minimum(p_mx, mid), p_mx)
                mn_r = jnp.where(m > 0, jnp.maximum(p_mn, mid), p_mn)
                mx_r = jnp.where(m < 0, jnp.minimum(p_mx, mid), p_mx)
                bounds2 = jnp.concatenate([
                    jnp.stack([mn_l, mx_l], axis=1),
                    jnp.stack([mn_r, mx_r], axis=1)])       # (2W, 2)
            else:
                bounds2 = jnp.zeros((2 * W, 2), jnp.float32)

            # ---- children candidates: one vmapped scan over 2W ----
            child_depth = s["leaf_depth"][sel_leaves] + 1
            hists2 = jnp.concatenate([hist_l, hist_r])      # (2W, G, Bb, 3)
            sums2 = jnp.concatenate([lsum, rsum])
            totals2 = sums2
            ex2 = _scan_hists(hists2, totals2)
            depth2 = jnp.concatenate([child_depth, child_depth])
            lv2 = jnp.concatenate([out_l, out_r])
            fm2 = jnp.broadcast_to(feature_mask, (2 * W, F))
            if use_ic:
                child_path = s["leaf_path"][sel_leaves] | \
                    (jnp.arange(F, dtype=jnp.int32)[None, :] ==
                     feat[:, None])                          # (W, F)
                path2 = jnp.concatenate([child_path, child_path])
                fm2 = fm2 & jax.vmap(allowed_features)(path2)
            ids2 = jnp.concatenate([2 * node_ids, 2 * node_ids + 1])
            if use_bynode:
                fm2 = fm2 & node_mask_many(ids2)
            rb2 = node_rand_many(ids2) if use_et else None
            cegb2 = None
            if use_lazy:
                # 1) mark the wave's split features as computed for every
                # parent row (the reference marks the split leaf's rows,
                # cost_effective_gradient_boosting.hpp:111-121) BEFORE the
                # children scans, which must see the updated bitmap
                used_b = s["used"]
                slz = sel_leaves.astype(rl_old.dtype)
                in_bag = bag_mask > 0
                for j in range(W):
                    # only in-bag rows: the reference marks via the
                    # bagged DataPartition's GetIndexOnLeaf
                    m = sel[j] & (rl_old == slz[j]) & in_bag
                    used_b = used_b.at[feat[j]].set(
                        used_b[feat[j]] | (_pack_bits(m) if lp
                                           else m))
                # 2) per-(feature, child) unused counts: grouped matvecs
                # against the bitmap (0/1 bf16 products, f32 accumulation
                # — exact to 2^24 counted rows per shard)
                live2 = jnp.concatenate([sel, sel])
                cid2 = jnp.where(live2, jnp.concatenate(
                    [sel_leaves, new_ids]), -2)
                pad_c = (-cid2.shape[0]) % 7
                if pad_c:
                    cid2 = jnp.concatenate(
                        [cid2, jnp.full((pad_c,), -2, cid2.dtype)])
                used_f = (_unpack_bits(used_b) if lp
                          else used_b).astype(jnp.bfloat16)
                # out-of-bag rows are invisible to the counts (sums2
                # totals are bagged counts too)
                rl32 = jnp.where(in_bag, rl.astype(jnp.int32), -9)

                def cnt_group(cids):
                    m = (rl32[None, :] == cids[:, None]).astype(
                        jnp.bfloat16)                         # (7, N)
                    return jax.lax.dot_general(
                        used_f, m, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)   # (F, 7)

                used_cnt = jax.lax.map(cnt_group, cid2.reshape(-1, 7))
                used_cnt = jnp.moveaxis(used_cnt, 0, 1).reshape(
                    F, -1)[:, :2 * W]                         # (F, 2W)
                used_cnt = strat.reduce_sum(used_cnt)
                unused = jnp.maximum(sums2[:, 2][None, :] - used_cnt, 0.0)
                base = cegb_penalty if sp.use_cegb else \
                    jnp.zeros((F,), jnp.float32)
                cegb2 = base[None, :] + (lazy_pen[:, None] * unused).T
            cands = many_candidates(ex2, sums2, bounds2, depth2, lv2, fm2,
                                    rb2, cegb2)
            depth_ok = jnp.logical_or(max_depth <= 0, child_depth < max_depth)
            dok2 = jnp.concatenate([depth_ok, depth_ok])
            cg = jnp.where(dok2 & jnp.concatenate([sel, sel]), cands[0],
                           NEG_INF)

            # ---- scatter state updates (invalid lanes -> dropped) ----
            idx_l = jnp.where(sel, sel_leaves, L)
            idx_r = jnp.where(sel, new_ids, L)
            idx2 = jnp.concatenate([idx_l, idx_r])

            def sc2(arr, val2):
                return arr.at[idx2].set(val2, mode="drop")

            out = dict(s)
            out["row_leaf"] = rl
            out["hists"] = s["hists"].at[idx_l].set(
                hist_l, mode="drop").at[idx_r].set(hist_r, mode="drop")
            out["leaf_sum"] = sc2(s["leaf_sum"], sums2)
            out["leaf_depth"] = sc2(s["leaf_depth"], depth2)
            out["cand_gain"] = sc2(s["cand_gain"], cg)
            out["cand_feat"] = sc2(s["cand_feat"], cands[1])
            out["cand_bin"] = sc2(s["cand_bin"], cands[2])
            out["cand_dleft"] = sc2(s["cand_dleft"], cands[3])
            out["cand_lsum"] = sc2(s["cand_lsum"], cands[4])
            out["cand_rsum"] = sc2(s["cand_rsum"], cands[5])
            out["cand_member"] = sc2(s["cand_member"], cands[6])
            if use_mc and mc_inter:
                # the sequential refinement already wrote child entries
                # AND propagated caps to contiguous leaves
                out["leaf_mn"] = mn_all
                out["leaf_mx"] = mx_all
                out["leaf_lo"] = lo_all
                out["leaf_hi"] = hi_all
            elif use_mc:
                out["leaf_mn"] = sc2(s["leaf_mn"],
                                     jnp.concatenate([mn_l, mn_r]))
                out["leaf_mx"] = sc2(s["leaf_mx"],
                                     jnp.concatenate([mx_l, mx_r]))
            if use_ic:
                out["leaf_path"] = sc2(s["leaf_path"], path2)
            if use_lazy:
                out["used"] = used_b
            out["leaf_value"] = sc2(s["leaf_value"], lv2)
            out["leaf_weight"] = sc2(s["leaf_weight"], sums2[:, 1])
            out["leaf_count"] = sc2(s["leaf_count"], sums2[:, 2])

            # ---- tree node records ----
            nidx = jnp.where(sel, node_ids, L - 1)
            dleft_rec = jnp.where(fcat, member[:, 0], dleft)
            dt_bits = (jnp.where(fcat, CAT_MASK, 0) |
                       jnp.where(dleft_rec, DEFAULT_LEFT_MASK, 0) |
                       jnp.where(fnan & jnp.logical_not(fcat), MISSING_NAN, 0)
                       ).astype(jnp.int32)

            def scn(arr, val):
                return arr.at[nidx].set(val, mode="drop")

            out["split_feature"] = scn(s["split_feature"], feat)
            out["threshold_bin"] = scn(s["threshold_bin"], thr)
            out["nan_bin"] = scn(s["nan_bin"], f_nan_bin)
            out["cat_member"] = scn(s["cat_member"], member)
            out["decision_type"] = scn(s["decision_type"], dt_bits)
            out["split_gain"] = scn(s["split_gain"], vals)
            out["internal_value"] = scn(
                s["internal_value"], leaf_output(psum_[:, 0], psum_[:, 1], sp))
            out["internal_weight"] = scn(s["internal_weight"], psum_[:, 1])
            out["internal_count"] = scn(s["internal_count"], psum_[:, 2])

            # patch parent nodes' child slots pointing at the split leaves
            # (encoded as -(leaf+1)), then write the new nodes' own slots
            enc = -(sel_leaves + 1)
            for name in ("left_child", "right_child"):
                arr = s[name]
                match = (arr[:, None] == enc[None, :]) & sel[None, :]
                has = jnp.any(match, axis=1)
                pick = jnp.argmax(match, axis=1)
                arr = jnp.where(has, node_ids[pick], arr)
                if name == "left_child":
                    arr = arr.at[nidx].set(enc, mode="drop")
                else:
                    arr = arr.at[nidx].set(-(new_ids + 1), mode="drop")
                out[name] = arr

            out["num_leaves"] = nl0 + total_new
            out["done"] = total_new == 0
            out["hist_passes"] = s["hist_passes"] + 1
            return out

        if use_endgame:
            # ---- exact device-side endgame --------------------------
            # The main loop stops once the remaining budget drops below
            # 2W (instead of tapering the wave); the endgame below then
            # commits the rest in the TRUE sequential best-first order.
            # One batched kernel pass precomputes the smaller child of
            # each of the top-W frontier candidates (channel j = slot j's
            # smaller side, via the TRIAL form of the row-update kernel —
            # nothing committed); the selection while-loop then takes the
            # global top-1, writes its node records, derives BOTH
            # children's histograms from the cached bank by subtraction,
            # rescans the two children so they compete, and repeats.
            # Children born in the endgame have no precomputed bank entry
            # for their own candidates' children — when such a leaf
            # becomes the global best, the outer loop flushes the
            # committed row updates and runs ONE more batched pass over
            # the then-current frontier.  Every outer pass commits at
            # least one split (the global best always holds slot 0 of a
            # fresh pass), so the loop terminates; in the flattening-gain
            # endgame typical of deep trees one pass serves the whole
            # remaining budget, vs the taper's 3-4 full passes.
            EG = 2 * W   # pending-commit capacity (budget < 2W at entry)

            def _pend0():
                z = jnp.zeros((EG,), jnp.int32)
                return {"feat": z, "thr": z, "nan": z - 1, "dleft": z,
                        "leaf": z, "newid": z, "act": z}

            def _apply_pending(rl, pend, pcnt):
                """Flush committed endgame splits into row_leaf, in
                commit order (a row rerouted by an earlier entry can be
                caught by a later one — parents precede children)."""
                def flush(rl):
                    if pallas:
                        for c in range(EG // W):
                            sl = slice(c * W, (c + 1) * W)
                            cols = take_cols(pend["feat"][sl])
                            tab = jnp.stack([
                                pend["thr"][sl], pend["nan"][sl],
                                pend["dleft"][sl],
                                jnp.zeros((W,), jnp.int32),
                                pend["leaf"][sl], pend["newid"][sl],
                                pend["act"][sl],
                                jnp.zeros((W,), jnp.int32)])
                            rl2, _ = wave_row_update_pallas(
                                cols, rl, tab, interpret=interpret,
                                pipeline=pipeline)
                            rl = rl2.astype(rl_dtype)
                        return rl

                    def one(k, rl_):
                        colv = feature_col(pend["feat"][k]).astype(
                            jnp.int32)
                        go = jnp.where(colv == pend["nan"][k],
                                       pend["dleft"][k] > 0,
                                       colv <= pend["thr"][k])
                        move = ((pend["act"][k] > 0) &
                                (rl_ == pend["leaf"][k].astype(rl_.dtype))
                                & jnp.logical_not(go))
                        return jnp.where(
                            move, pend["newid"][k].astype(rl_.dtype), rl_)
                    return jax.lax.fori_loop(0, EG, one, rl)
                return jax.lax.cond(pcnt > 0, flush, lambda r: r, rl)

            def _trial_channels(rl, sel, sel_leaves, feat, thr, fnanb,
                                dleft, small):
                """(N,) int8 candidate slot whose SMALLER side each row
                would take (-1 = none) — the splits stay uncommitted."""
                if pallas:
                    from ..ops.histogram_pallas import (
                        wave_trial_channels_pallas)
                    cols = take_cols(feat)
                    return wave_trial_channels_pallas(
                        cols, rl, sel_leaves, thr, fnanb, dleft, small,
                        sel, interpret=interpret, pipeline=pipeline)
                cols = jax.vmap(feature_col)(feat).astype(jnp.int32)
                go = jnp.where(cols == fnanb[:, None], dleft[:, None],
                               cols <= thr[:, None])
                match = sel[:, None] & \
                    (rl[None, :] == sel_leaves.astype(rl.dtype)[:, None])
                has = jnp.any(match, axis=0)
                jhit = jnp.argmax(match, axis=0)
                go_hit = jnp.take_along_axis(go, jhit[None, :], axis=0)[0]
                return jnp.where(has & (go_hit == small[jhit]),
                                 jhit.astype(jnp.int8), jnp.int8(-1))

            def _commit_cond(c):
                s, slot, pend, pcnt = c
                b = jnp.argmax(s["cand_gain"])
                return ((s["num_leaves"] < L) & (s["cand_gain"][b] > 0) &
                        (slot[b] >= 0))

            def _make_commit(bank):
                def _commit(c):
                    s, slot, pend, pcnt = c
                    b = jnp.argmax(s["cand_gain"]).astype(jnp.int32)
                    gain = s["cand_gain"][b]
                    feat = s["cand_feat"][b]
                    thr = s["cand_bin"][b]
                    dleft = s["cand_dleft"][b]
                    lsum = s["cand_lsum"][b]
                    rsum = s["cand_rsum"][b]
                    psum_ = s["leaf_sum"][b]
                    nl0 = s["num_leaves"]
                    new_id = nl0
                    node = nl0 - 1
                    fnan = hn_full[feat]
                    f_nan_bin = jnp.where(fnan, nb_full[feat] - 1, -1)
                    left_smaller = lsum[2] <= rsum[2]
                    hist_small = bank[slot[b]]
                    hist_big = histogram_subtract(s["hists"][b], hist_small)
                    hist_l = jnp.where(left_smaller, hist_small, hist_big)
                    hist_r = jnp.where(left_smaller, hist_big, hist_small)
                    # both children's candidates in one vmapped scan
                    child_depth = s["leaf_depth"][b] + 1
                    parent_lv = s["leaf_value"][b]
                    out_l = _child_out(lsum[0], lsum[1], lsum[2], parent_lv)
                    out_r = _child_out(rsum[0], rsum[1], rsum[2], parent_lv)
                    hists2 = jnp.stack([hist_l, hist_r])
                    sums2 = jnp.stack([lsum, rsum])
                    lv2 = jnp.stack([out_l, out_r])
                    d2 = jnp.full((2,), child_depth, jnp.int32)
                    cnds = many_candidates(
                        _scan_hists(hists2, sums2), sums2,
                        jnp.zeros((2, 2), jnp.float32), d2, lv2,
                        jnp.broadcast_to(feature_mask, (2, F)))
                    depth_ok = jnp.logical_or(max_depth <= 0,
                                              child_depth < max_depth)
                    cg2 = jnp.where(depth_ok, cnds[0], NEG_INF)
                    out = dict(s)
                    idx2 = jnp.stack([b, new_id])

                    def sc2(arr, val2):
                        return arr.at[idx2].set(val2)

                    out["hists"] = s["hists"].at[b].set(hist_l) \
                                             .at[new_id].set(hist_r)
                    out["leaf_sum"] = sc2(s["leaf_sum"], sums2)
                    out["leaf_depth"] = sc2(s["leaf_depth"], d2)
                    out["cand_gain"] = sc2(s["cand_gain"], cg2)
                    out["cand_feat"] = sc2(s["cand_feat"], cnds[1])
                    out["cand_bin"] = sc2(s["cand_bin"], cnds[2])
                    out["cand_dleft"] = sc2(s["cand_dleft"], cnds[3])
                    out["cand_lsum"] = sc2(s["cand_lsum"], cnds[4])
                    out["cand_rsum"] = sc2(s["cand_rsum"], cnds[5])
                    out["cand_member"] = sc2(s["cand_member"], cnds[6])
                    out["leaf_value"] = sc2(s["leaf_value"], lv2)
                    out["leaf_weight"] = sc2(s["leaf_weight"], sums2[:, 1])
                    out["leaf_count"] = sc2(s["leaf_count"], sums2[:, 2])
                    # node records via the shared sequential selector
                    dt_bits = (jnp.where(dleft, DEFAULT_LEFT_MASK, 0) |
                               jnp.where(fnan, MISSING_NAN, 0)
                               ).astype(jnp.int32)
                    lc, rc = patch_child_pointers(
                        s["left_child"], s["right_child"], b, node)
                    write_split_records(
                        out, node=node, leaf=b, new_id=new_id, feat=feat,
                        thr=thr, f_nan_bin=f_nan_bin, dt_bits=dt_bits,
                        gain=gain,
                        internal_value=leaf_output(psum_[0], psum_[1], sp),
                        internal_weight=psum_[1], internal_count=psum_[2],
                        left_child=lc, right_child=rc)
                    out["num_leaves"] = nl0 + 1
                    slot2 = slot.at[b].set(-1).at[new_id].set(-1)
                    pend2 = dict(pend)
                    for k_, v_ in (("feat", feat), ("thr", thr),
                                   ("nan", f_nan_bin),
                                   ("dleft", dleft.astype(jnp.int32)),
                                   ("leaf", b), ("newid", new_id),
                                   ("act", jnp.asarray(1, jnp.int32))):
                        pend2[k_] = pend2[k_].at[pcnt].set(v_)
                    return (out, slot2, pend2, pcnt + 1)
                return _commit

            def _eg_cond(c):
                s, pend, pcnt = c
                return (s["num_leaves"] < L) & \
                    (jnp.max(s["cand_gain"]) > 0)

            def _eg_body(c):
                s, pend, pcnt = c
                rl = _apply_pending(s["row_leaf"], pend, pcnt)
                s = dict(s)
                s["row_leaf"] = rl
                pend = _pend0()
                pcnt = jnp.asarray(0, jnp.int32)
                vals, sel_leaves = jax.lax.top_k(s["cand_gain"], W)
                sel = vals > 0
                feat = s["cand_feat"][sel_leaves]
                thr = s["cand_bin"][sel_leaves]
                dleft = s["cand_dleft"][sel_leaves]
                lsum = s["cand_lsum"][sel_leaves]
                rsum = s["cand_rsum"][sel_leaves]
                fnanb = jnp.where(hn_full[feat], nb_full[feat] - 1, -1)
                small = lsum[:, 2] <= rsum[:, 2]
                ch = _trial_channels(rl, sel, sel_leaves, feat, thr,
                                     fnanb, dleft, small)
                bank = hist_waves(ch)       # (W, G, Bb, 3); DP: one psum
                slot = jnp.full((L,), -1, jnp.int32).at[
                    jnp.where(sel, sel_leaves, L)].set(
                        jnp.arange(W, dtype=jnp.int32), mode="drop")
                s, slot, pend, pcnt = jax.lax.while_loop(
                    _commit_cond, _make_commit(bank),
                    (s, slot, pend, pcnt))
                s = dict(s)
                s["hist_passes"] = s["hist_passes"] + 1
                return (s, pend, pcnt)

        def cond(s):
            go = jnp.logical_not(s["done"]) & (s["num_leaves"] < L)
            if use_endgame:
                # hand off to the endgame instead of tapering the wave
                go = go & (s["num_leaves"] + 2 * W <= L)
            return go

        for fw in forced_waves:   # pre-committed ForceSplits prefix
            state = body(state, forced=fw)
        s = jax.lax.while_loop(cond, body, state)
        if use_endgame:
            s, pend, pcnt = jax.lax.while_loop(
                _eg_cond, _eg_body,
                (s, _pend0(), jnp.asarray(0, jnp.int32)))
            s = dict(s)
            s["row_leaf"] = _apply_pending(s["row_leaf"], pend, pcnt)
            s["done"] = jnp.asarray(True)

        if quantized and renew_leaf:
            # Exact leaf-value renewal (the reference's
            # quant_train_renew_leaf, gbdt.cpp RenewTreeOutput analog):
            # one cheap exact pass replaces the quantized leaf sums with
            # true f32 gradient/hessian sums before outputs are committed.
            # On the Pallas path this reuses the single-leaf histogram
            # kernel with row_leaf as a one-feature bin column (cost
            # ~1/F of a wave pass); off-TPU it is a segment-sum.
            rl = s["row_leaf"].astype(jnp.int32)
            if pallas:
                parts = []
                for c in range((L + 255) // 256):
                    m = bag_mask * (rl // 256 == c).astype(bag_mask.dtype)
                    bins1 = (rl % 256).astype(jnp.uint8)[None, :]
                    parts.append(build_histogram_pallas(
                        bins1, grad, hess, m, num_bins=256,
                        interpret=interpret, kr=4096,
                        pipeline=pipeline)[0])
                gh = jnp.concatenate(parts, axis=0)[:L, :2]       # (L, 2)
            else:
                gh = jax.ops.segment_sum(
                    jnp.stack([gm, hm], axis=-1), rl, num_segments=L)
            gh = strat.reduce_sum(gh)
            vals = leaf_output(gh[:, 0], gh[:, 1], sp)
            if use_sm:
                # path-smoothed outputs blend with the parent chain; renew
                # against the recorded (pre-renew) value as the parent
                # proxy — matches the reference's renew-in-place behavior
                vals = leaf_output_smoothed(gh[:, 0], gh[:, 1],
                                            s["leaf_count"],
                                            s["leaf_value"], sp)
            if use_mc:
                vals = jnp.clip(vals, s["leaf_mn"], s["leaf_mx"])
            live = jnp.arange(L, dtype=jnp.int32) < s["num_leaves"]
            ok = live & (s["leaf_count"] > 0)
            s["leaf_value"] = jnp.where(ok, vals, s["leaf_value"])
            s["leaf_weight"] = jnp.where(ok, gh[:, 1], s["leaf_weight"])

        if "spec_counts" in nonlocal_dbg:
            s["split_gain"] = s["split_gain"].at[-2:].set(
                nonlocal_dbg["spec_counts"].astype(jnp.float32))
        tree_out = GrownTree(
            split_feature=s["split_feature"],
            threshold_bin=s["threshold_bin"],
            nan_bin=s["nan_bin"], cat_member=s["cat_member"],
            decision_type=s["decision_type"],
            left_child=s["left_child"], right_child=s["right_child"],
            split_gain=s["split_gain"], internal_value=s["internal_value"],
            internal_weight=s["internal_weight"],
            internal_count=s["internal_count"], leaf_value=s["leaf_value"],
            leaf_weight=s["leaf_weight"], leaf_count=s["leaf_count"],
            num_leaves=s["num_leaves"],
            row_leaf=s["row_leaf"].astype(jnp.int32),
            hist_passes=s["hist_passes"])
        if use_lazy:
            return tree_out, s["used"]
        return tree_out

    return jax.jit(grow) if jit else grow
