"""Linear trees: per-leaf linear models on the leaf's branch features.

TPU-native re-implementation of the reference LinearTreeLearner
(reference: src/treelearner/linear_tree_learner.cpp:175 ``CalculateLinear``
— per leaf, solve coef = -(Xᵀ H X + λ)⁻¹ Xᵀ g over the leaf's rows where X
is [branch numerical features | 1]; rows with NaN in any leaf feature are
excluded from the fit and fall back to the plain leaf output at predict
time; near-zero coefficients are pruned; Eq. 3 of "Gradient Boosting With
Piece-Wise Linear Regression Trees", Shi et al.).

TPU design: the per-leaf normal-equation MOMENTS are accumulated on device
with one chunked (L, C) × (C, (K+1)²) MXU contraction over the leaf one-hot
(no per-leaf row gathering); the (K+1)-dim solves are batched on host
(K ≤ tree depth, L ≤ num_leaves — microscopic next to the moment pass).
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_ZERO_THRESHOLD = 1e-35
_CHUNK = 1 << 14


def branch_features(split_feature: np.ndarray, left_child: np.ndarray,
                    right_child: np.ndarray, num_leaves: int,
                    is_cat: np.ndarray) -> List[List[int]]:
    """Unique NUMERICAL features on each leaf's root path (reference
    tree.h branch_features with track_branch_features)."""
    feats: List[List[int]] = [[] for _ in range(num_leaves)]
    if num_leaves <= 1:
        return feats

    def walk(node: int, path: List[int]) -> None:
        f = int(split_feature[node])
        path2 = path + ([f] if not bool(is_cat[f]) else [])
        for child in (int(left_child[node]), int(right_child[node])):
            if child < 0:
                leaf = ~child
                if leaf < num_leaves:
                    feats[leaf] = sorted(set(path2))
            else:
                walk(child, path2)

    walk(0, [])
    return feats


@functools.partial(jax.jit, static_argnames=("k1",))
def _moments(Xr, grad, hess, bag, row_leaf, leaf_feat, leaf_fmask, k1):
    """Per-leaf XᵀHX (L,K+1,K+1), Xᵀg (L,K+1), and fit-row counts (L,).

    leaf_feat: (L, K) int32 feature ids (0 padded); leaf_fmask: (L, K)
    float32 validity.  Rows whose own leaf features contain NaN are
    excluded entirely (reference HAS_NAN path)."""
    n = Xr.shape[0]
    L = leaf_feat.shape[0]

    def chunk_body(start, acc):
        M, b, cnt = acc
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, start, _CHUNK, 0)
        xc = sl(Xr)
        rl = sl(row_leaf)
        rf = leaf_feat[rl]                      # (C, K)
        rm = leaf_fmask[rl]                     # (C, K)
        vals = jnp.take_along_axis(xc, rf, axis=1)  # (C, K)
        nan_row = jnp.any(jnp.isnan(vals) & (rm > 0), axis=1)
        w = sl(bag) * jnp.logical_not(nan_row).astype(jnp.float32)
        vals = jnp.where(rm > 0, jnp.nan_to_num(vals), 0.0)
        A = jnp.concatenate([vals, jnp.ones((_CHUNK, 1), jnp.float32)],
                            axis=1)             # (C, K+1)
        onehot = (rl[:, None] == jnp.arange(L)[None, :]).astype(jnp.float32)
        hw = sl(hess) * w
        gw = sl(grad) * w
        A2 = (A[:, :, None] * A[:, None, :]).reshape(_CHUNK, k1 * k1)
        # Precision.HIGHEST: the TPU MXU rounds f32 operands to bf16 at
        # DEFAULT precision, which corrupts the normal equations (the
        # weighted one-hot and the feature products are full-precision
        # values, unlike the histogram kernels' exact 0/1 + hi/lo
        # channels); the f32 passes cost ~6x but the moments are a tiny
        # fraction of tree time
        M = M + jax.lax.dot_general(
            (onehot * hw[:, None]).T, A2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST).reshape(L, k1, k1)
        b = b + jax.lax.dot_general(
            (onehot * gw[:, None]).T, A, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        cnt = cnt + jnp.sum(onehot * w[:, None], axis=0)
        return M, b, cnt

    nchunks = n // _CHUNK  # caller pads rows to _CHUNK (bag 0)
    acc0 = (jnp.zeros((L, k1, k1), jnp.float32),
            jnp.zeros((L, k1), jnp.float32), jnp.zeros((L,), jnp.float32))
    return jax.lax.fori_loop(
        0, nchunks, lambda i, a: chunk_body(i * _CHUNK, a), acc0)


def fit_linear_leaves(Xr_dev, grad, hess, bag, row_leaf, split_feature,
                      left_child, right_child, num_leaves, is_cat,
                      linear_lambda: float, leaf_value: np.ndarray
                      ) -> Tuple[List[List[int]], List[List[float]],
                                 np.ndarray]:
    """Fit all leaves' linear models for one grown tree.

    Returns (leaf_features per leaf, coefficients per leaf, leaf_const).
    leaf_value is the plain closed-form output (NaN fallback + fallback for
    under-determined leaves, linear_tree_learner.cpp:330-340)."""
    feats = branch_features(split_feature, left_child, right_child,
                            num_leaves, is_cat)
    L = max(num_leaves, 1)
    K = max(1, max((len(f) for f in feats), default=1))
    leaf_feat = np.zeros((L, K), np.int32)
    leaf_fmask = np.zeros((L, K), np.float32)
    for i, f in enumerate(feats):
        leaf_feat[i, :len(f)] = f
        leaf_fmask[i, :len(f)] = 1.0

    n = Xr_dev.shape[0]
    pad = (-n) % _CHUNK
    if pad:
        Xr_dev = jnp.pad(Xr_dev, ((0, pad), (0, 0)))
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
        bag = jnp.pad(bag, (0, pad))
        row_leaf = jnp.pad(row_leaf, (0, pad))
    M, b, cnt = _moments(Xr_dev, grad, hess, bag, row_leaf,
                         jnp.asarray(leaf_feat), jnp.asarray(leaf_fmask),
                         K + 1)
    M = np.asarray(M, np.float64)
    b = np.asarray(b, np.float64)
    cnt = np.asarray(cnt)

    out_feats: List[List[int]] = []
    out_coefs: List[List[float]] = []
    out_const = np.asarray(leaf_value, np.float64).copy()
    for i in range(L):
        k = len(feats[i]) if i < len(feats) else 0
        if i >= num_leaves or cnt[i] < k + 1:
            out_feats.append([])
            out_coefs.append([])
            continue
        Mi = M[i, :k + 1, :k + 1].copy()
        Mi[np.arange(k), np.arange(k)] += linear_lambda  # not the intercept
        try:
            coef = -np.linalg.solve(Mi, b[i, :k + 1])
        except np.linalg.LinAlgError:
            out_feats.append([])
            out_coefs.append([])
            continue
        if not np.all(np.isfinite(coef)):
            out_feats.append([])
            out_coefs.append([])
            continue
        keep = [j for j in range(k) if abs(coef[j]) > _ZERO_THRESHOLD]
        out_feats.append([feats[i][j] for j in keep])
        out_coefs.append([float(coef[j]) for j in keep])
        out_const[i] = float(coef[k])
    return out_feats, out_coefs, out_const


@jax.jit
def linear_score_delta(Xr, row_leaf, leaf_feat, leaf_fmask, leaf_coef,
                       leaf_const, leaf_value, shrinkage):
    """Per-row training-score delta for a linear tree: const + Σ coef·x,
    falling back to the plain leaf output when any leaf feature is NaN
    (reference tree.cpp PredictionFunLinear)."""
    rf = leaf_feat[row_leaf]
    rm = leaf_fmask[row_leaf]
    vals = jnp.take_along_axis(Xr, rf, axis=1)
    nan_row = jnp.any(jnp.isnan(vals) & (rm > 0), axis=1)
    vals = jnp.where(rm > 0, jnp.nan_to_num(vals), 0.0)
    lin = leaf_const[row_leaf] + jnp.sum(leaf_coef[row_leaf] * vals, axis=1)
    return shrinkage * jnp.where(nan_row, leaf_value[row_leaf], lin)
