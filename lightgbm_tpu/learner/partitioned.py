"""Partition-ordered leaf-wise tree grower — the fast single-chip path.

TPU-native analog of the reference's DataPartition (data_partition.hpp:170):
where the reference keeps, per leaf, a contiguous span of row indices and
stable-partitions it on every split, this grower keeps the PACKED ROW DATA
itself leaf-contiguous.  Every per-split operation then works on a
``dynamic_slice`` of the split leaf's segment — there are NO full-N passes
per split (the v1 grower in serial.py pays several: mask rebuild, cumsum,
searchsorted compaction, full-N partition update), which is what dominated
its runtime at 255 leaves.

Packed layout ``P`` (N, W) uint8, leaf-segment ordered:

    [ bin codes (F) | grad f32 (4) | hess f32 (4) | orig row idx i32 (4)
      | bag byte (1) | zero pad to W ]

grad/hess are pre-multiplied by the bagging mask; the bag byte carries the
mask itself for the histogram count channel.  One packed row-scatter per
split moves each row of the split leaf to its child's side (rows move ~depth
times per tree, the same volume as the reference's index partition), and the
smaller child's histogram reads a contiguous slice — no gather at all —
feeding the Pallas MXU kernel (ops/histogram_pallas.py) or the portable
scatter-add path (CPU tests).

Segment slices use a power-of-two bucket ladder of static sizes (jit needs
static shapes); slices are ~free on TPU (contiguous DMA) so the ladder is
fine-grained, unlike serial.py's gather buckets.

Leaf-wise semantics (best-first by gain, serial_tree_learner.cpp:158-209),
histogram subtraction trick (:311-320), and the split candidate logic are
identical to serial.py — the two growers are cross-checked by tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..models.tree import CAT_MASK, DEFAULT_LEFT_MASK, MISSING_NAN
from ..ops.histogram import build_histogram
from ..ops.split import BIG, NEG_INF, leaf_output
from .serial import CommStrategy, GrownTree

__all__ = ["make_partitioned_grow_fn", "PART_ROW_BLOCK"]

PART_ROW_BLOCK = 4096  # ladder quantum; == Pallas kernel row block


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _bucket_ladder(n: int, base: int = PART_ROW_BLOCK):
    """Static power-of-two segment sizes: base, 2*base, ..., n.

    All sizes are <= n (dynamic_slice cannot exceed the array); when n is a
    multiple of ``base`` (the Pallas path pads to this) every size is too."""
    base = min(base, n)
    sizes = []
    s = base
    while s < n:
        sizes.append(s)
        s *= 2
    sizes.append(n)
    return sizes


def make_partitioned_grow_fn(*, num_leaves: int, num_features: int,
                             max_bins: int, max_depth: int, split_params,
                             hist_impl: str, interpret: bool = False,
                             jit: bool = True):
    """Build the partition-ordered single-tree grower.

    Returned signature:
    ``grow(X, grad, hess, bag_mask, num_bins, is_cat, has_nan, feature_mask)
    -> GrownTree`` with X (N, F) uint8 bin codes, N a multiple of
    PART_ROW_BLOCK (pad rows with bag_mask 0).
    """
    L = num_leaves
    F = num_features
    W = _round_up(F + 13, 8)
    pallas = hist_impl == "pallas"
    if pallas:
        from ..ops.histogram_pallas import build_histogram_pallas

    sp = split_params
    strat_template = None  # serial only; parallel strategies use serial.py

    def _hist_from_seg(seg, valid):
        """(F, B, 3) histogram of one packed segment (seg: (S, W) u8)."""
        bins_rows = seg[:, :F]
        gm = jax.lax.bitcast_convert_type(seg[:, F:F + 4], jnp.float32)
        hm = jax.lax.bitcast_convert_type(seg[:, F + 4:F + 8], jnp.float32)
        bag = seg[:, F + 12].astype(jnp.float32)
        mask = bag * valid
        if pallas:
            return build_histogram_pallas(
                jnp.swapaxes(bins_rows, 0, 1), gm, hm, mask,
                num_bins=max_bins, interpret=interpret)
        return build_histogram(bins_rows, gm, hm, mask, num_bins=max_bins,
                               impl=hist_impl)

    use_mc = split_params.use_monotone

    def grow(X: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
             bag_mask: jnp.ndarray, num_bins: jnp.ndarray,
             is_cat: jnp.ndarray, has_nan: jnp.ndarray,
             monotone: jnp.ndarray, feature_mask: jnp.ndarray) -> GrownTree:
        n = X.shape[0]
        strat = CommStrategy(num_bins, is_cat, has_nan, monotone)

        # ---- pack rows: bins | grad*bag | hess*bag | orig idx | bag ----
        gm = (grad * bag_mask).astype(jnp.float32)
        hm = (hess * bag_mask).astype(jnp.float32)
        P = jnp.concatenate([
            X.astype(jnp.uint8),
            jax.lax.bitcast_convert_type(gm, jnp.uint8),
            jax.lax.bitcast_convert_type(hm, jnp.uint8),
            jax.lax.bitcast_convert_type(
                jnp.arange(n, dtype=jnp.int32), jnp.uint8),
            (bag_mask > 0).astype(jnp.uint8)[:, None],
            jnp.zeros((n, W - F - 13), jnp.uint8),
        ], axis=1)

        ladder = _bucket_ladder(n)

        root_hist = _hist_from_seg(P, jnp.ones((n,), jnp.float32))
        root_sum = jnp.stack([jnp.sum(gm), jnp.sum(hm), jnp.sum(bag_mask)])
        root_bound = jnp.asarray([-BIG, BIG], jnp.float32)
        cand = strat.leaf_candidates(root_hist, root_sum, feature_mask, sp,
                                     root_bound, jnp.asarray(0, jnp.int32))

        state = {
            "P": P,
            "leaf_start": jnp.full((L,), n, jnp.int32).at[0].set(0),
            "leaf_seg": jnp.zeros((L,), jnp.int32).at[0].set(n),
            "leaf_sum": jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum),
            "leaf_depth": jnp.zeros((L,), jnp.int32),
            "leaf_parent": jnp.full((L,), -1, jnp.int32),
            "cand_gain": jnp.full((L,), NEG_INF, jnp.float32).at[0].set(cand[0]),
            "cand_feat": jnp.zeros((L,), jnp.int32).at[0].set(cand[1]),
            "cand_bin": jnp.zeros((L,), jnp.int32).at[0].set(cand[2]),
            "cand_dleft": jnp.zeros((L,), jnp.bool_).at[0].set(cand[3]),
            "cand_lsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[4]),
            "cand_rsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[5]),
            "cand_member": jnp.zeros((L, max_bins), jnp.bool_).at[0].set(
                cand[6]),
            "hists": jnp.zeros((L, F, max_bins, 3), jnp.float32).at[0].set(
                root_hist),
            "split_feature": jnp.full((L - 1,), -1, jnp.int32),
            "threshold_bin": jnp.zeros((L - 1,), jnp.int32),
            "nan_bin": jnp.full((L - 1,), -1, jnp.int32),
            "cat_member": jnp.zeros((L - 1, max_bins), jnp.bool_),
            "decision_type": jnp.zeros((L - 1,), jnp.int32),
            "left_child": jnp.zeros((L - 1,), jnp.int32),
            "right_child": jnp.zeros((L - 1,), jnp.int32),
            "split_gain": jnp.zeros((L - 1,), jnp.float32),
            "internal_value": jnp.zeros((L - 1,), jnp.float32),
            "internal_weight": jnp.zeros((L - 1,), jnp.float32),
            "internal_count": jnp.zeros((L - 1,), jnp.float32),
            "leaf_value": jnp.zeros((L,), jnp.float32).at[0].set(
                leaf_output(root_sum[0], root_sum[1], sp)),
            "leaf_weight": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[1]),
            "leaf_count": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[2]),
            "num_leaves": jnp.asarray(1, jnp.int32),
            "done": jnp.asarray(False),
        }
        if use_mc:
            state["leaf_mn"] = jnp.full((L,), -BIG, jnp.float32)
            state["leaf_mx"] = jnp.full((L,), BIG, jnp.float32)

        nb_full, ic_full, hn_full = num_bins, is_cat, has_nan

        def partition_branch(psize):
            """Stable-partition the split leaf's segment of (static) size
            ``psize`` (DataPartition::Split analog) and return
            (P_new, n_left_segment).

            dynamic_slice clamps the start when start+psize > n, so the
            segment's rows live at offset ``off = start - clamped_start``
            within the slice; rows outside [off, off+cnt) belong to other
            leaves and must not move."""
            def fn(op):
                P, start, cnt, feat, thr, dleft, fcat, fnanb, member = op
                cstart = jnp.minimum(start, n - psize)
                off = start - cstart
                seg = jax.lax.dynamic_slice(P, (cstart, 0), (psize, W))
                col = jax.lax.dynamic_slice(seg, (0, feat),
                                            (psize, 1))[:, 0].astype(jnp.int32)
                pos_idx = jnp.arange(psize, dtype=jnp.int32)
                valid = (pos_idx >= off) & (pos_idx < off + cnt)
                is_nanbin = col == fnanb
                go_left = jnp.where(fcat, member[col],
                                    jnp.where(is_nanbin, dleft, col <= thr))
                gl = go_left & valid
                gr = jnp.logical_and(valid, jnp.logical_not(go_left))
                cl = jnp.cumsum(gl.astype(jnp.int32))
                nl = cl[-1]
                cr = jnp.cumsum(gr.astype(jnp.int32))
                pos = off + jnp.where(gl, cl - 1, nl + cr - 1)
                pos = jnp.where(valid, pos, psize)  # dropped
                seg_new = seg.at[pos].set(seg, mode="drop")
                P = jax.lax.dynamic_update_slice(P, seg_new, (cstart, 0))
                return P, nl
            return fn

        def hist_branch(csize):
            def fn(op):
                P, start, cnt = op
                cstart = jnp.minimum(start, n - csize)
                off = start - cstart
                seg = jax.lax.dynamic_slice(P, (cstart, 0), (csize, W))
                pos_idx = jnp.arange(csize, dtype=jnp.int32)
                valid = ((pos_idx >= off) & (pos_idx < off + cnt)
                         ).astype(jnp.float32)
                return _hist_from_seg(seg, valid)
            return fn

        part_fns = [partition_branch(s) for s in ladder]
        hist_fns = [hist_branch(s) for s in ladder]

        def pick(cnt):
            """Index of the smallest ladder size >= cnt."""
            sel = jnp.zeros((), jnp.int32)
            for i, s in enumerate(ladder[:-1]):
                sel = sel + (cnt > s).astype(jnp.int32)
            return sel

        def body(t, s):
            best_leaf = jnp.argmax(s["cand_gain"]).astype(jnp.int32)
            bgain = s["cand_gain"][best_leaf]
            do = jnp.logical_and(jnp.logical_not(s["done"]), bgain > 0)

            feat = s["cand_feat"][best_leaf]
            thr = s["cand_bin"][best_leaf]
            dleft = s["cand_dleft"][best_leaf]
            lsum = s["cand_lsum"][best_leaf]
            rsum = s["cand_rsum"][best_leaf]
            member = s["cand_member"][best_leaf]
            psum_ = s["leaf_sum"][best_leaf]
            new_id = (t + 1).astype(jnp.int32)

            start = s["leaf_start"][best_leaf]
            seg_cnt = jnp.where(do, s["leaf_seg"][best_leaf], 0)
            fcat = ic_full[feat]
            fnan = hn_full[feat]
            f_nan_bin = jnp.where(fnan, nb_full[feat] - 1, -1)

            P_new, nl = jax.lax.switch(
                pick(seg_cnt), part_fns,
                (s["P"], start, seg_cnt, feat, thr, dleft, fcat, f_nan_bin,
                 member))
            nr = seg_cnt - nl

            # ---- smaller-child histogram on its contiguous segment ----
            left_smaller = lsum[2] <= rsum[2]
            s_start = jnp.where(left_smaller, start, start + nl)
            s_cnt = jnp.where(do, jnp.where(left_smaller, nl, nr), 0)
            hist_small = jax.lax.switch(pick(s_cnt), hist_fns,
                                        (P_new, s_start, s_cnt))
            parent_hist = s["hists"][best_leaf]
            hist_big = parent_hist - hist_small
            hist_left = jnp.where(left_smaller, hist_small, hist_big)
            hist_right = jnp.where(left_smaller, hist_big, hist_small)

            # ---- monotone bounds for the children (BasicLeafConstraints::
            # Update, monotone_constraints.hpp:487-501) ----
            if use_mc:
                p_mn = s["leaf_mn"][best_leaf]
                p_mx = s["leaf_mx"][best_leaf]
                out_l = jnp.clip(leaf_output(lsum[0], lsum[1], sp), p_mn, p_mx)
                out_r = jnp.clip(leaf_output(rsum[0], rsum[1], sp), p_mn, p_mx)
                m = jnp.where(fcat, 0, monotone[feat])
                mid = (out_l + out_r) / 2.0
                mn_l = jnp.where(m < 0, jnp.maximum(p_mn, mid), p_mn)
                mx_l = jnp.where(m > 0, jnp.minimum(p_mx, mid), p_mx)
                mn_r = jnp.where(m > 0, jnp.maximum(p_mn, mid), p_mn)
                mx_r = jnp.where(m < 0, jnp.minimum(p_mx, mid), p_mx)
                bound_l = jnp.stack([mn_l, mx_l])
                bound_r = jnp.stack([mn_r, mx_r])
            else:
                bound_l = bound_r = None

            # ---- children candidates ----
            child_depth = s["leaf_depth"][best_leaf] + 1
            depth_ok = jnp.logical_or(max_depth <= 0, child_depth < max_depth)
            cl = strat.leaf_candidates(hist_left, lsum, feature_mask, sp,
                                       bound_l, child_depth)
            cr = strat.leaf_candidates(hist_right, rsum, feature_mask, sp,
                                       bound_r, child_depth)
            gl_ = jnp.where(depth_ok, cl[0], NEG_INF)
            gr_ = jnp.where(depth_ok, cr[0], NEG_INF)

            node = t
            dleft_rec = jnp.where(fcat, member[0], dleft)
            dt_bits = (jnp.where(fcat, CAT_MASK, 0) |
                       jnp.where(dleft_rec, DEFAULT_LEFT_MASK, 0) |
                       jnp.where(fnan & jnp.logical_not(fcat), MISSING_NAN, 0)
                       ).astype(jnp.int32)
            parent_node = s["leaf_parent"][best_leaf]
            enc_best = -(best_leaf + 1)
            node_idx = jnp.arange(L - 1, dtype=jnp.int32)
            patch_l = (node_idx == parent_node) & \
                (s["left_child"] == enc_best) & do
            patch_r = (node_idx == parent_node) & \
                (s["right_child"] == enc_best) & do
            left_child = jnp.where(patch_l, node, s["left_child"])
            right_child = jnp.where(patch_r, node, s["right_child"])

            def upd(arr, idx, val):
                return arr.at[idx].set(jnp.where(do, val, arr[idx]))

            out = dict(s)
            out["P"] = P_new
            out["leaf_start"] = upd(upd(s["leaf_start"], best_leaf, start),
                                    new_id, start + nl)
            out["leaf_seg"] = upd(upd(s["leaf_seg"], best_leaf, nl),
                                  new_id, nr)
            hists = s["hists"]
            hists = hists.at[best_leaf].set(
                jnp.where(do, hist_left, hists[best_leaf]))
            hists = hists.at[new_id].set(
                jnp.where(do, hist_right, hists[new_id]))
            out["hists"] = hists
            out["leaf_sum"] = upd(upd(s["leaf_sum"], best_leaf, lsum),
                                  new_id, rsum)
            out["leaf_depth"] = upd(upd(s["leaf_depth"], best_leaf,
                                        child_depth), new_id, child_depth)
            out["leaf_parent"] = upd(upd(s["leaf_parent"], best_leaf, node),
                                     new_id, node)
            out["cand_gain"] = upd(upd(s["cand_gain"], best_leaf, gl_),
                                   new_id, gr_)
            out["cand_feat"] = upd(upd(s["cand_feat"], best_leaf, cl[1]),
                                   new_id, cr[1])
            out["cand_bin"] = upd(upd(s["cand_bin"], best_leaf, cl[2]),
                                  new_id, cr[2])
            out["cand_dleft"] = upd(upd(s["cand_dleft"], best_leaf, cl[3]),
                                    new_id, cr[3])
            out["cand_lsum"] = upd(upd(s["cand_lsum"], best_leaf, cl[4]),
                                   new_id, cr[4])
            out["cand_rsum"] = upd(upd(s["cand_rsum"], best_leaf, cl[5]),
                                   new_id, cr[5])
            out["cand_member"] = upd(upd(s["cand_member"], best_leaf, cl[6]),
                                     new_id, cr[6])
            out["split_feature"] = upd(s["split_feature"], node, feat)
            out["threshold_bin"] = upd(s["threshold_bin"], node, thr)
            out["nan_bin"] = upd(s["nan_bin"], node, f_nan_bin)
            out["cat_member"] = upd(s["cat_member"], node, member)
            out["decision_type"] = upd(s["decision_type"], node, dt_bits)
            out["left_child"] = upd(left_child, node, enc_best)
            out["right_child"] = upd(right_child, node, -(new_id + 1))
            out["split_gain"] = upd(s["split_gain"], node, bgain)
            out["internal_value"] = upd(s["internal_value"], node,
                                        leaf_output(psum_[0], psum_[1], sp))
            out["internal_weight"] = upd(s["internal_weight"], node, psum_[1])
            out["internal_count"] = upd(s["internal_count"], node, psum_[2])
            if use_mc:
                out["leaf_mn"] = upd(upd(s["leaf_mn"], best_leaf, mn_l),
                                     new_id, mn_r)
                out["leaf_mx"] = upd(upd(s["leaf_mx"], best_leaf, mx_l),
                                     new_id, mx_r)
                lv = upd(s["leaf_value"], best_leaf, out_l)
                out["leaf_value"] = upd(lv, new_id, out_r)
            else:
                lv = upd(s["leaf_value"], best_leaf,
                         leaf_output(lsum[0], lsum[1], sp))
                out["leaf_value"] = upd(lv, new_id,
                                        leaf_output(rsum[0], rsum[1], sp))
            lw = upd(s["leaf_weight"], best_leaf, lsum[1])
            out["leaf_weight"] = upd(lw, new_id, rsum[1])
            lc = upd(s["leaf_count"], best_leaf, lsum[2])
            out["leaf_count"] = upd(lc, new_id, rsum[2])
            out["num_leaves"] = s["num_leaves"] + do.astype(jnp.int32)
            out["done"] = jnp.logical_not(do)
            return out

        s = jax.lax.fori_loop(0, L - 1, body, state)

        # ---- reconstruct row_leaf in ORIGINAL row order ----
        # leaf id per position: markers at segment starts, forward-filled.
        # Empty segments (possible when all in-bag rows go one way but the
        # out-of-bag tail doesn't) must not claim their shared start.
        starts = jnp.where((jnp.arange(L) < s["num_leaves"]) &
                           (s["leaf_seg"] > 0), s["leaf_start"], n)
        marker = jnp.full((n,), -1, jnp.int32)
        marker = marker.at[starts].set(jnp.arange(L, dtype=jnp.int32),
                                       mode="drop")
        leaf_of_pos = jax.lax.associative_scan(
            lambda a, b: jnp.where(b < 0, a, b), marker)
        orig = jax.lax.bitcast_convert_type(s["P"][:, F + 8:F + 12],
                                            jnp.int32)
        row_leaf = jnp.zeros((n,), jnp.int32).at[orig].set(leaf_of_pos)

        return GrownTree(
            split_feature=s["split_feature"],
            threshold_bin=s["threshold_bin"],
            nan_bin=s["nan_bin"], cat_member=s["cat_member"],
            decision_type=s["decision_type"],
            left_child=s["left_child"], right_child=s["right_child"],
            split_gain=s["split_gain"], internal_value=s["internal_value"],
            internal_weight=s["internal_weight"],
            internal_count=s["internal_count"], leaf_value=s["leaf_value"],
            leaf_weight=s["leaf_weight"], leaf_count=s["leaf_count"],
            num_leaves=s["num_leaves"], row_leaf=row_leaf)

    return jax.jit(grow) if jit else grow
