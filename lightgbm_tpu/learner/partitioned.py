"""Partition-ordered leaf-wise tree grower — the fast single-chip path.

TPU-native analog of the reference's DataPartition (data_partition.hpp:170):
where the reference keeps, per leaf, a contiguous span of row indices and
stable-partitions it on every split, this grower keeps the PACKED ROW DATA
itself leaf-contiguous.  Every per-split operation then works on chunked
``dynamic_slice``s of the split leaf's segment — there are NO full-N passes
per split (the v1 grower in serial.py pays several: mask rebuild, cumsum,
searchsorted compaction, full-N partition update), which is what dominated
its runtime at 255 leaves.

Packed layout ``P`` (N, W) uint8, leaf-segment ordered:

    [ bin codes (F) | grad f32 (4) | hess f32 (4) | orig row idx i32 (4)
      | bag byte (1) | zero pad to W ]

grad/hess are pre-multiplied by the bagging mask; the bag byte carries the
mask itself for the histogram count channel.  One packed row-scatter per
split moves each row of the split leaf to its child's side (rows move ~depth
times per tree, the same volume as the reference's index partition), and the
smaller child's histogram reads contiguous chunks — no gather at all —
feeding the Pallas MXU kernel (ops/histogram_pallas.py) or the portable
scatter-add path (CPU tests).

Segments are swept with ``lax.while_loop``s over exactly TWO static chunk
shapes (bulk + tail): static shapes keep XLA happy, dynamic trip counts keep
the work proportional to the segment, and — critically — the whole tree
compiles only two Pallas kernel shapes regardless of N.  (The previous
design used a power-of-two ladder of segment sizes: at 10.5M rows that
meant ~12 distinct kernel shapes per grower and multi-minute XLA compiles;
chunking killed the compile-time cliff and the per-split full-N work at
the same time.)

Leaf-wise semantics (best-first by gain, serial_tree_learner.cpp:158-209),
histogram subtraction trick (:311-320), and the split candidate logic are
identical to serial.py — the two growers are cross-checked by tests.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from ..models.tree import CAT_MASK, DEFAULT_LEFT_MASK, MISSING_NAN
from ..ops.histogram import build_histogram
from ..ops.split import (BIG, NEG_INF, _leaf_gain, leaf_output,
                         leaf_output_smoothed)
from .endgame import patch_child_pointers, write_split_records
from .serial import CommStrategy, GrownTree

__all__ = ["make_partitioned_grow_fn", "PART_ROW_BLOCK"]

PART_ROW_BLOCK = 4096   # pad quantum; == Pallas kernel row-block contract
CHUNK_BULK = 1 << 20    # bulk sweep chunk (rows)
CHUNK_TAIL = 1 << 15    # tail sweep chunk (rows; 16K/64K measured worse)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def make_partitioned_grow_fn(*, num_leaves: int, num_features: int,
                             max_bins: int, max_depth: int, split_params,
                             hist_impl: str, interpret: bool = None,
                             pipeline: str = None,
                             jit: bool = True, forced_splits: tuple = (),
                             efb_dims=None, interaction_groups: tuple = (),
                             feature_contri: tuple = ()):
    """Build the partition-ordered single-tree grower.

    Returned signature:
    ``grow(X, grad, hess, bag_mask, num_bins, is_cat, has_nan, monotone,
    feature_mask) -> GrownTree`` with X (N, F) uint8 bin codes, N a multiple
    of PART_ROW_BLOCK (pad rows with bag_mask 0).
    """
    L = num_leaves
    F = num_features
    # EFB (lightgbm_tpu/efb.py): the packed matrix holds one column per
    # BUNDLE (G <= F) with Bb bundle bins; histograms live in bundle space
    # and are expanded to per-feature space right before each split scan
    use_efb = efb_dims is not None
    G, Bb = efb_dims if use_efb else (F, max_bins)
    W = _round_up(G + 13, 8)
    pallas = hist_impl == "pallas"
    if pallas:
        from ..ops.histogram_pallas import build_histogram_pallas

    sp = split_params
    use_mc = split_params.use_monotone
    use_sm = split_params.path_smooth > 0.0

    def _child_out(s3, parent_out):
        if use_sm:
            return leaf_output_smoothed(s3[0], s3[1], s3[2], parent_out, sp)
        return leaf_output(s3[0], s3[1], sp)
    bynode = split_params.feature_fraction_bynode < 1.0
    import math as _math
    kcnt = max(1, int(_math.ceil(F * split_params.feature_fraction_bynode))) \
        if bynode else F
    # interaction constraints (reference col_sampler.hpp GetByNode): at any
    # node, the allowed features are the union of constraint sets that
    # contain every feature already used on the branch path
    use_ic = len(interaction_groups) > 0
    if use_ic:
        import numpy as _np
        _g = _np.zeros((len(interaction_groups), F), bool)
        for gi, feats in enumerate(interaction_groups):
            for ff in feats:
                if 0 <= ff < F:
                    _g[gi, ff] = True
        ic_groups = jnp.asarray(_g)

        def allowed_features(path):
            compat = jnp.logical_not(
                jnp.any(path[None, :] & jnp.logical_not(ic_groups), axis=1))
            return jnp.any(ic_groups & compat[:, None], axis=0)

    # forced splits (serial_tree_learner.cpp:450 ForceSplits): BFS-ordered
    # (leaf, inner feature, threshold bin) triples applied before best-gain
    # growth; static per grower (they come from a config file)
    n_forced = min(len(forced_splits), L - 1)
    if n_forced:
        f_leaf_c = jnp.asarray([f[0] for f in forced_splits[:n_forced]],
                               jnp.int32)
        f_feat_c = jnp.asarray([f[1] for f in forced_splits[:n_forced]],
                               jnp.int32)
        f_bin_c = jnp.asarray([f[2] for f in forced_splits[:n_forced]],
                              jnp.int32)

    def _hist_from_seg(seg, valid):
        """(G, Bb, 3) bundle-space histogram of one packed chunk."""
        bins_rows = seg[:, :G]
        gm = jax.lax.bitcast_convert_type(seg[:, G:G + 4], jnp.float32)
        hm = jax.lax.bitcast_convert_type(seg[:, G + 4:G + 8], jnp.float32)
        bag = seg[:, G + 12].astype(jnp.float32)
        mask = bag * valid
        if pallas:
            return build_histogram_pallas(
                jnp.swapaxes(bins_rows, 0, 1), gm, hm, mask,
                num_bins=Bb, interpret=interpret, pipeline=pipeline)
        return build_histogram(bins_rows, gm, hm, mask, num_bins=Bb,
                               impl=hist_impl)

    def grow(X: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
             bag_mask: jnp.ndarray, num_bins: jnp.ndarray,
             is_cat: jnp.ndarray, has_nan: jnp.ndarray,
             monotone: jnp.ndarray, cegb_penalty: jnp.ndarray,
             node_key: jnp.ndarray, efb_arrays: tuple,
             feature_mask: jnp.ndarray) -> GrownTree:
        n = X.shape[0]
        strat = CommStrategy(num_bins, is_cat, has_nan, monotone)
        strat.cegb_full = cegb_penalty if split_params.use_cegb else None
        if feature_contri:
            strat.contri_full = jnp.asarray(feature_contri, jnp.float32)
        chunk_bulk = min(CHUNK_BULK, n)
        chunk_tail = min(CHUNK_TAIL, n)

        from ..efb import make_bundle_decode, make_expand_hist
        expand_hist = make_expand_hist(efb_arrays if use_efb else (),
                                       F, G, Bb)
        bundle_decode = make_bundle_decode(efb_arrays if use_efb else ())
        f_bundle = efb_arrays[1] if use_efb else None

        def feature_col(seg, feat, csize):
            """The FEATURE-space bin codes of one chunk for feature
            ``feat`` (reconstructed from its bundle column under EFB;
            efb.make_bundle_decode)."""
            g = f_bundle[feat] if use_efb else feat
            v = jax.lax.dynamic_slice(
                seg, (0, g), (csize, 1))[:, 0].astype(jnp.int32)
            return bundle_decode(v, feat)

        def node_mask(idx):
            """Exact-count per-node feature sample (ColSampler bynode,
            reference col_sampler.hpp).  node_key row 0 is the bynode
            stream (feature_fraction_seed)."""
            r = jax.random.uniform(jax.random.fold_in(node_key[0], idx),
                                   (F,))
            kth = jax.lax.top_k(r, kcnt)[0][-1]
            return r >= kth

        def node_rand(idx):
            """One random threshold bin per feature for this node
            (ExtraTrees, feature_histogram.hpp USE_RAND).  node_key row 1
            is the ExtraTrees stream (extra_seed) — independent of the
            bynode stream, like the reference's separate RNGs.  Numeric
            thresholds live in [0, nb-2]; categorical one-hot bins extend
            to nb-1 (the last category must stay reachable)."""
            u = jax.random.uniform(jax.random.fold_in(node_key[1], idx),
                                   (F,))
            hi = jnp.maximum(jnp.where(is_cat, num_bins - 1, num_bins - 2),
                             0)
            return jnp.minimum((u * (hi + 1).astype(jnp.float32)
                                ).astype(jnp.int32), hi)

        # ---- pack rows: bins | grad*bag | hess*bag | orig idx | bag ----
        gm = (grad * bag_mask).astype(jnp.float32)
        hm = (hess * bag_mask).astype(jnp.float32)
        P = jnp.concatenate([
            X.astype(jnp.uint8),
            jax.lax.bitcast_convert_type(gm, jnp.uint8),
            jax.lax.bitcast_convert_type(hm, jnp.uint8),
            jax.lax.bitcast_convert_type(
                jnp.arange(n, dtype=jnp.int32), jnp.uint8),
            (bag_mask > 0).astype(jnp.uint8)[:, None],
            jnp.zeros((n, W - G - 13), jnp.uint8),
        ], axis=1)

        def _sweep(start, cnt, fn, carry):
            """Run ``fn(chunk_start, chunk_size(static), carry)`` over the
            segment [start, start+cnt): bulk chunks first, then tail
            chunks.  fn must itself mask rows outside [start, start+cnt)."""
            nb = cnt // chunk_bulk

            def bulk(i, c):
                return fn(start + i * chunk_bulk, chunk_bulk, c)

            carry = jax.lax.fori_loop(0, nb, bulk, carry)
            t0 = start + nb * chunk_bulk
            nt = (cnt - nb * chunk_bulk + chunk_tail - 1) // chunk_tail

            def tail(i, c):
                return fn(t0 + i * chunk_tail, chunk_tail, c)

            return jax.lax.fori_loop(0, nt, tail, carry)

        def _chunk_rows(cstart, csize):
            """Load a (csize, W) slice whose row j is global row
            ``clamped + j`` (dynamic_slice clamps near the array end)."""
            clamped = jnp.minimum(cstart, n - csize)
            seg = jax.lax.dynamic_slice(P_ref[0], (clamped, 0), (csize, W))
            return seg, clamped

        # P is rebound per split inside the fori_loop; the sweep helpers
        # read it through this one-element list closure.  The two staging
        # buffers (sized n + one bulk chunk so full-chunk stores never
        # clamp) are scratch carried through the loop for reuse; their
        # stale contents are never read (the combine pass only reads
        # positions the current split wrote).
        P_ref = [P]
        # L stacks lefts ASCENDING from the segment start (tail slack of
        # one bulk chunk absorbs full-chunk store overhang); R stacks
        # rights DESCENDING from the fixed top T0 = n + chunk_bulk, so it
        # needs one bulk chunk of slack on BOTH sides: below T0-nr for
        # each store's garbage overhang, above n for nothing-but-sizing
        # symmetry of the store bounds (see partition_segment).
        stage_ref = [jnp.zeros((n + chunk_bulk, W), jnp.uint8),
                     jnp.zeros((n + 2 * chunk_bulk, W), jnp.uint8)]

        def hist_of_segment(start, cnt):
            def step(cstart, csize, acc):
                seg, clamped = _chunk_rows(cstart, csize)
                j = jnp.arange(csize, dtype=jnp.int32)
                gpos = clamped + j
                valid = ((gpos >= cstart) & (gpos < start + cnt)
                         ).astype(jnp.float32)
                return acc + _hist_from_seg(seg, valid)

            acc0 = jnp.zeros((G, Bb, 3), jnp.float32)
            return _sweep(start, cnt, step, acc0)

        def _decide_col(col, clamped, cstart, cend, csize, feat_args):
            feat, thr, dleft, fcat, fnanb, member = feat_args
            j = jnp.arange(csize, dtype=jnp.int32)
            gpos = clamped + j
            valid = (gpos >= cstart) & (gpos < cend)
            is_nanbin = col == fnanb
            go_left = jnp.where(fcat, member[col],
                                jnp.where(is_nanbin, dleft, col <= thr))
            return go_left & valid, valid

        def partition_segment(start, cnt, feat, thr, dleft, fcat, fnanb,
                              member):
            """Stable chunked partition of [start, start+cnt)
            (DataPartition::Split analog), built from BANDWIDTH-friendly
            primitives: XLA row scatter costs ~150ns/row on TPU, so instead
            each chunk is stable-sorted lefts-first (multi-operand
            ``lax.sort`` on a 1-bit key, ~37ns/row) and written with TWO
            full-chunk contiguous stores into left/right staging buffers at
            final positions (garbage tails are overwritten by the next
            chunk or masked at combine); a final contiguous sweep selects
            staging rows back into P by position.  Returns (P_new, n_left).
            """
            feat_args = (feat, thr, dleft, fcat, fnanb, member)
            cend = start + cnt

            # pass A: per-chunk stable sort + staged contiguous writes.
            # Lefts land in the L staging buffer at their FINAL positions,
            # stacked ASCENDING from ``start``; rights are stacked
            # DESCENDING from the fixed top T0 of the R buffer.  Both
            # directions share the same correctness argument: each store's
            # valid run abuts the previous watermark and its garbage lies
            # strictly beyond the NEW watermark, so the last writer of any
            # position inside the final valid range wrote valid rows there
            # — for ANY mix of chunk sizes.  (An earlier version staged
            # rights ascending at (dr - clt): each chunk's left-garbage
            # then landed BELOW the right watermark, silently clobbering
            # the previous chunks' staged rights whenever a segment
            # spanned multiple chunks.)  One shared buffer would be
            # unsafe: the left/right full-chunk stores collide.
            Wq = W // 4
            T0 = n + chunk_bulk   # top of the descending rights stack

            def stage_step(cstart, csize, carry):
                Lb, Rb, dl, dr = carry
                seg, clamped = _chunk_rows(cstart, csize)
                col = feature_col(seg, feat, csize)
                gl, valid = _decide_col(col, clamped, cstart, cend, csize,
                                        feat_args)
                # order [lefts | invalid | rights]: lefts at the chunk
                # BOTTOM feed the ascending L stack, rights at the chunk
                # TOP feed the descending R stack — garbage (including the
                # invalid middle) then always falls on the safe side of
                # both watermarks
                key = jnp.where(gl, 0, jnp.where(valid, 2, 1))
                cols = jax.lax.bitcast_convert_type(
                    seg.reshape(csize, Wq, 4), jnp.int32)
                ops = [key] + [cols[:, k] for k in range(Wq)]
                out = jax.lax.sort(ops, dimension=0, is_stable=True,
                                   num_keys=1)
                sorted_u8 = jax.lax.bitcast_convert_type(
                    jnp.stack(out[1:], axis=1), jnp.uint8).reshape(csize, W)
                clt = jnp.sum(gl.astype(jnp.int32))
                crt = jnp.sum(valid.astype(jnp.int32)) - clt
                # lefts: rows [0, clt) stored at the ascending watermark
                Lb = jax.lax.dynamic_update_slice(
                    Lb, sorted_u8, (start + dl, 0))
                # rights: the chunk's TOP crt rows land at [T0-dr-crt,
                # T0-dr) — the descending watermark; left/invalid garbage
                # falls strictly below it and is overwritten by later
                # chunks or ignored by the combine's nr bound.  Segment
                # order of rights becomes chunk-reversed, which is
                # irrelevant: row order within a leaf segment is free.
                Rb = jax.lax.dynamic_update_slice(
                    Rb, sorted_u8, (T0 - dr - csize, 0))
                return Lb, Rb, dl + clt, dr + crt

            Lb, Rb, nl, nr = _sweep(start, cnt, stage_step,
                                    (stage_ref[0], stage_ref[1],
                                     jnp.asarray(0, jnp.int32),
                                     jnp.asarray(0, jnp.int32)))
            stage_ref[0] = Lb
            stage_ref[1] = Rb

            # combine: contiguous sweep selecting Lb below start+nl, and
            # the rights block [T0-nr, T0) above
            def combine_step(cstart, csize, P_out):
                clamped = jnp.minimum(cstart, n - csize)
                lrow = jax.lax.dynamic_slice(Lb, (clamped, 0), (csize, W))
                rrow = jax.lax.dynamic_slice(
                    Rb, (jnp.maximum(clamped - (start + nl) + T0 - nr, 0),
                         0), (csize, W))
                cur = jax.lax.dynamic_slice(P_out, (clamped, 0), (csize, W))
                j = jnp.arange(csize, dtype=jnp.int32)
                gpos = clamped + j
                inseg = (gpos >= start) & (gpos < cend)
                use_l = gpos < start + nl
                rows = jnp.where(
                    inseg[:, None],
                    jnp.where(use_l[:, None], lrow, rrow), cur)
                return jax.lax.dynamic_update_slice(P_out, rows, (clamped, 0))

            P_out = _sweep(start, cnt, combine_step, P_ref[0])
            return P_out, nl, Lb, Rb

        root_hist = hist_of_segment(jnp.asarray(0, jnp.int32),
                                    jnp.asarray(n, jnp.int32))
        root_sum = jnp.stack([jnp.sum(gm), jnp.sum(hm), jnp.sum(bag_mask)])
        root_bound = jnp.asarray([-BIG, BIG], jnp.float32)
        fm_root = feature_mask & node_mask(2 * L) if bynode else feature_mask
        if use_ic:
            fm_root = fm_root & allowed_features(
                jnp.zeros((F,), jnp.bool_))
        root_out = _child_out(root_sum, jnp.asarray(0.0, jnp.float32))
        rb_root = node_rand(2 * L) if sp.extra_trees else None
        cand = strat.leaf_candidates(expand_hist(root_hist, root_sum),
                                     root_sum, fm_root, sp,
                                     root_bound, jnp.asarray(0, jnp.int32),
                                     root_out, rb_root)

        state = {
            "P": P,
            "stageL": stage_ref[0],
            "stageR": stage_ref[1],
            "leaf_start": jnp.full((L,), n, jnp.int32).at[0].set(0),
            "leaf_seg": jnp.zeros((L,), jnp.int32).at[0].set(n),
            "leaf_sum": jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum),
            "leaf_depth": jnp.zeros((L,), jnp.int32),
            "cand_gain": jnp.full((L,), NEG_INF, jnp.float32).at[0].set(cand[0]),
            "cand_feat": jnp.zeros((L,), jnp.int32).at[0].set(cand[1]),
            "cand_bin": jnp.zeros((L,), jnp.int32).at[0].set(cand[2]),
            "cand_dleft": jnp.zeros((L,), jnp.bool_).at[0].set(cand[3]),
            "cand_lsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[4]),
            "cand_rsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[5]),
            "cand_member": jnp.zeros((L, max_bins), jnp.bool_).at[0].set(
                cand[6]),
            "hists": jnp.zeros((L, G, Bb, 3), jnp.float32).at[0].set(
                root_hist),
            "split_feature": jnp.full((L - 1,), -1, jnp.int32),
            "threshold_bin": jnp.zeros((L - 1,), jnp.int32),
            "nan_bin": jnp.full((L - 1,), -1, jnp.int32),
            "cat_member": jnp.zeros((L - 1, max_bins), jnp.bool_),
            "decision_type": jnp.zeros((L - 1,), jnp.int32),
            "left_child": jnp.zeros((L - 1,), jnp.int32),
            "right_child": jnp.zeros((L - 1,), jnp.int32),
            "split_gain": jnp.zeros((L - 1,), jnp.float32),
            "internal_value": jnp.zeros((L - 1,), jnp.float32),
            "internal_weight": jnp.zeros((L - 1,), jnp.float32),
            "internal_count": jnp.zeros((L - 1,), jnp.float32),
            "leaf_value": jnp.zeros((L,), jnp.float32).at[0].set(root_out),
            "leaf_weight": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[1]),
            "leaf_count": jnp.zeros((L,), jnp.float32).at[0].set(root_sum[2]),
            "num_leaves": jnp.asarray(1, jnp.int32),
            "done": jnp.asarray(False),
        }
        if use_ic:
            state["leaf_path"] = jnp.zeros((L, F), jnp.bool_)
        if use_mc:
            state["leaf_mn"] = jnp.full((L,), -BIG, jnp.float32)
            state["leaf_mx"] = jnp.full((L,), BIG, jnp.float32)

        nb_full, ic_full, hn_full = num_bins, is_cat, has_nan

        def body(t, s):
            P_ref[0] = s["P"]
            stage_ref[0] = s["stageL"]
            stage_ref[1] = s["stageR"]
            best_leaf = jnp.argmax(s["cand_gain"]).astype(jnp.int32)
            bgain = s["cand_gain"][best_leaf]
            do = jnp.logical_and(jnp.logical_not(s["done"]), bgain > 0)

            feat = s["cand_feat"][best_leaf]
            thr = s["cand_bin"][best_leaf]
            dleft = s["cand_dleft"][best_leaf]
            lsum = s["cand_lsum"][best_leaf]
            rsum = s["cand_rsum"][best_leaf]
            member = s["cand_member"][best_leaf]

            if n_forced:
                # ForceSplits override: fixed (leaf, feature, bin) applied
                # regardless of gain; child sums read from the leaf's
                # pooled histogram
                fi = jnp.minimum(t, n_forced - 1)
                is_forced = t < n_forced
                best_leaf = jnp.where(is_forced, f_leaf_c[fi], best_leaf)
                feat = jnp.where(is_forced, f_feat_c[fi], feat)
                thr = jnp.where(is_forced, f_bin_c[fi], thr)
                dleft = jnp.where(is_forced, False, dleft)
                member = jnp.where(is_forced, jnp.zeros_like(member), member)
                fh = expand_hist(s["hists"][best_leaf],
                                 s["leaf_sum"][best_leaf])[feat]   # (B, 3)
                csum = jnp.cumsum(fh, axis=0)
                lsum_f = csum[jnp.clip(thr, 0, max_bins - 1)]
                rsum_f = s["leaf_sum"][best_leaf] - lsum_f
                lsum = jnp.where(is_forced, lsum_f, lsum)
                rsum = jnp.where(is_forced, rsum_f, rsum)
                # record the forced split's REAL gain (scan-scale), not 0
                psum_f = s["leaf_sum"][best_leaf]
                gain_f = (_leaf_gain(lsum_f[0], lsum_f[1],
                                     split_params.lambda_l1,
                                     split_params.lambda_l2) +
                          _leaf_gain(rsum_f[0], rsum_f[1],
                                     split_params.lambda_l1,
                                     split_params.lambda_l2) -
                          _leaf_gain(psum_f[0], psum_f[1],
                                     split_params.lambda_l1,
                                     split_params.lambda_l2) -
                          split_params.min_gain_to_split)
                bgain = jnp.where(is_forced, gain_f, bgain)
                do = jnp.where(is_forced,
                               s["leaf_seg"][best_leaf] > 0, do)
            psum_ = s["leaf_sum"][best_leaf]
            new_id = (t + 1).astype(jnp.int32)

            start = s["leaf_start"][best_leaf]
            seg_cnt = jnp.where(do, s["leaf_seg"][best_leaf], 0)
            fcat = ic_full[feat]
            fnan = hn_full[feat]
            f_nan_bin = jnp.where(fnan, nb_full[feat] - 1, -1)

            P_new, nl, stage_l, stage_r = partition_segment(
                start, seg_cnt, feat, thr, dleft, fcat, f_nan_bin, member)
            nr = seg_cnt - nl
            P_ref[0] = P_new

            # ---- smaller-child histogram on its contiguous segment ----
            left_smaller = lsum[2] <= rsum[2]
            s_start = jnp.where(left_smaller, start, start + nl)
            s_cnt = jnp.where(do, jnp.where(left_smaller, nl, nr), 0)
            hist_small = hist_of_segment(s_start, s_cnt)
            parent_hist = s["hists"][best_leaf]
            hist_big = parent_hist - hist_small
            hist_left = jnp.where(left_smaller, hist_small, hist_big)
            hist_right = jnp.where(left_smaller, hist_big, hist_small)

            # ---- monotone bounds for the children (BasicLeafConstraints::
            # Update, monotone_constraints.hpp:487-501) ----
            parent_lv = s["leaf_value"][best_leaf]
            out_l = _child_out(lsum, parent_lv)
            out_r = _child_out(rsum, parent_lv)
            if use_mc:
                p_mn = s["leaf_mn"][best_leaf]
                p_mx = s["leaf_mx"][best_leaf]
                out_l = jnp.clip(out_l, p_mn, p_mx)
                out_r = jnp.clip(out_r, p_mn, p_mx)
                m = jnp.where(fcat, 0, monotone[feat])
                mid = (out_l + out_r) / 2.0
                mn_l = jnp.where(m < 0, jnp.maximum(p_mn, mid), p_mn)
                mx_l = jnp.where(m > 0, jnp.minimum(p_mx, mid), p_mx)
                mn_r = jnp.where(m > 0, jnp.maximum(p_mn, mid), p_mn)
                mx_r = jnp.where(m < 0, jnp.minimum(p_mx, mid), p_mx)
                bound_l = jnp.stack([mn_l, mx_l])
                bound_r = jnp.stack([mn_r, mx_r])
            else:
                bound_l = bound_r = None

            # ---- children candidates (one vmapped scan for the pair) ----
            child_depth = s["leaf_depth"][best_leaf] + 1
            depth_ok = jnp.logical_or(max_depth <= 0, child_depth < max_depth)
            if bynode:
                fm_l = feature_mask & node_mask(2 * t)
                fm_r = feature_mask & node_mask(2 * t + 1)
            else:
                fm_l = fm_r = None
            if use_ic:
                child_path = s["leaf_path"][best_leaf] | \
                    (jnp.arange(F) == feat)
                allowed = allowed_features(child_path)
                fm_l = (feature_mask if fm_l is None else fm_l) & allowed
                fm_r = (feature_mask if fm_r is None else fm_r) & allowed
            rb_l = node_rand(2 * t) if sp.extra_trees else None
            rb_r = node_rand(2 * t + 1) if sp.extra_trees else None
            cl, cr = strat.pair_candidates(
                expand_hist(hist_left, lsum), expand_hist(hist_right, rsum),
                lsum, rsum, feature_mask, sp, bound_l, bound_r,
                child_depth, fm_l, fm_r, out_l, out_r, rb_l, rb_r)
            gl_ = jnp.where(depth_ok, cl[0], NEG_INF)
            gr_ = jnp.where(depth_ok, cr[0], NEG_INF)

            node = t
            dleft_rec = jnp.where(fcat, member[0], dleft)
            dt_bits = (jnp.where(fcat, CAT_MASK, 0) |
                       jnp.where(dleft_rec, DEFAULT_LEFT_MASK, 0) |
                       jnp.where(fnan & jnp.logical_not(fcat), MISSING_NAN, 0)
                       ).astype(jnp.int32)
            # sequential selector bookkeeping shared with the wave
            # grower's exact endgame (learner/endgame.py): the split
            # leaf's unique -(leaf+1) child-slot code is patched to the
            # committed node — no parent-index tracking needed
            left_child, right_child = patch_child_pointers(
                s["left_child"], s["right_child"], best_leaf, node,
                active=do)

            def upd(arr, idx, val):
                return arr.at[idx].set(jnp.where(do, val, arr[idx]))

            out = dict(s)
            out["P"] = P_new
            out["stageL"] = stage_l
            out["stageR"] = stage_r
            out["leaf_start"] = upd(upd(s["leaf_start"], best_leaf, start),
                                    new_id, start + nl)
            out["leaf_seg"] = upd(upd(s["leaf_seg"], best_leaf, nl),
                                  new_id, nr)
            hists = s["hists"]
            hists = hists.at[best_leaf].set(
                jnp.where(do, hist_left, hists[best_leaf]))
            hists = hists.at[new_id].set(
                jnp.where(do, hist_right, hists[new_id]))
            out["hists"] = hists
            out["leaf_sum"] = upd(upd(s["leaf_sum"], best_leaf, lsum),
                                  new_id, rsum)
            out["leaf_depth"] = upd(upd(s["leaf_depth"], best_leaf,
                                        child_depth), new_id, child_depth)
            out["cand_gain"] = upd(upd(s["cand_gain"], best_leaf, gl_),
                                   new_id, gr_)
            out["cand_feat"] = upd(upd(s["cand_feat"], best_leaf, cl[1]),
                                   new_id, cr[1])
            out["cand_bin"] = upd(upd(s["cand_bin"], best_leaf, cl[2]),
                                  new_id, cr[2])
            out["cand_dleft"] = upd(upd(s["cand_dleft"], best_leaf, cl[3]),
                                    new_id, cr[3])
            out["cand_lsum"] = upd(upd(s["cand_lsum"], best_leaf, cl[4]),
                                   new_id, cr[4])
            out["cand_rsum"] = upd(upd(s["cand_rsum"], best_leaf, cl[5]),
                                   new_id, cr[5])
            out["cand_member"] = upd(upd(s["cand_member"], best_leaf, cl[6]),
                                     new_id, cr[6])
            write_split_records(
                out, node=node, leaf=best_leaf, new_id=new_id, feat=feat,
                thr=thr, f_nan_bin=f_nan_bin, dt_bits=dt_bits, gain=bgain,
                internal_value=leaf_output(psum_[0], psum_[1], sp),
                internal_weight=psum_[1], internal_count=psum_[2],
                left_child=left_child, right_child=right_child,
                member=member, active=do)
            if use_mc:
                out["leaf_mn"] = upd(upd(s["leaf_mn"], best_leaf, mn_l),
                                     new_id, mn_r)
                out["leaf_mx"] = upd(upd(s["leaf_mx"], best_leaf, mx_l),
                                     new_id, mx_r)
            lv = upd(s["leaf_value"], best_leaf, out_l)
            out["leaf_value"] = upd(lv, new_id, out_r)
            lw = upd(s["leaf_weight"], best_leaf, lsum[1])
            out["leaf_weight"] = upd(lw, new_id, rsum[1])
            lc = upd(s["leaf_count"], best_leaf, lsum[2])
            out["leaf_count"] = upd(lc, new_id, rsum[2])
            if use_ic:
                out["leaf_path"] = upd(upd(s["leaf_path"], best_leaf,
                                           child_path), new_id, child_path)
            out["num_leaves"] = s["num_leaves"] + do.astype(jnp.int32)
            # a skipped FORCED split (empty leaf) must not end growth
            out["done"] = jnp.logical_not(do) & (t >= n_forced) \
                if n_forced else jnp.logical_not(do)
            return out

        s = jax.lax.fori_loop(0, L - 1, body, state)

        # ---- reconstruct row_leaf in ORIGINAL row order ----
        # leaf id per position via binary search over the sorted segment
        # starts (an associative_scan forward-fill here took XLA 30+ min to
        # compile at 10.5M rows — searchsorted over the L-element starts
        # compiles in seconds and is one gather per row at runtime).
        # Empty segments (possible when all in-bag rows go one way but the
        # out-of-bag tail doesn't) are parked at start=n so they never
        # cover a position.
        starts = jnp.where((jnp.arange(L) < s["num_leaves"]) &
                           (s["leaf_seg"] > 0), s["leaf_start"], n)
        order = jnp.argsort(starts)
        starts_sorted = starts[order]
        pos = jnp.arange(n, dtype=jnp.int32)
        leaf_of_pos = order[
            jnp.searchsorted(starts_sorted, pos, side="right") - 1
        ].astype(jnp.int32)
        orig = jax.lax.bitcast_convert_type(s["P"][:, G + 8:G + 12],
                                            jnp.int32)
        row_leaf = jnp.zeros((n,), jnp.int32).at[orig].set(leaf_of_pos)

        return GrownTree(
            split_feature=s["split_feature"],
            threshold_bin=s["threshold_bin"],
            nan_bin=s["nan_bin"], cat_member=s["cat_member"],
            decision_type=s["decision_type"],
            left_child=s["left_child"], right_child=s["right_child"],
            split_gain=s["split_gain"], internal_value=s["internal_value"],
            internal_weight=s["internal_weight"],
            internal_count=s["internal_count"], leaf_value=s["leaf_value"],
            leaf_weight=s["leaf_weight"], leaf_count=s["leaf_count"],
            num_leaves=s["num_leaves"], row_leaf=row_leaf,
            hist_passes=jnp.asarray(0, jnp.int32))

    return jax.jit(grow) if jit else grow
