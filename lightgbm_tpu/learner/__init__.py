from .serial import SerialTreeLearner, GrownTree, make_grow_fn
