"""One-shot histogram-implementation autotune with a persistent cache.

The reference times its col-wise vs row-wise histogram construction on
the first iteration and keeps the winner (reference: src/io/dataset.cpp
:659-670 ``ShareStates`` force_col_wise/force_row_wise timing).  The TPU
analog choice spans the kernel-v2 variant matrix: the Pallas MXU kernel
(DMA-pipelined or BlockSpec-fetched, 4-bit-packed bins when max_bin
fits a nibble) vs the XLA onehot formulation — and on CPU hosts the
scatter-add ``segment`` path vs the joint-nibble ``packed4`` scatter.
The static table in ``resolve_hist_impl`` is right for benchmark-scale
shapes, but small or oddly-shaped datasets (tiny N, very wide F, tiny
max_bin) can go either way — so when the binned matrix is small enough
that a few extra compiles are cheap, time the candidates on the REAL
data once and keep the winner per (N, F, B) shape.

Measured winners persist to a per-(shape, backend) ON-DISK cache
(``LGBM_TPU_AUTOTUNE_CACHE`` env, default
``~/.cache/lightgbm_tpu/hist_autotune.json``; set the env to "" to
disable persistence), so repeated processes — test suites, cron
retrains, sweep workers — skip the re-measurement pass entirely.

Candidate grammar: an impl name (``segment`` / ``onehot`` / ``packed4``
/ ``pallas``), optionally suffixed for the pallas kernel variants —
``pallas:blockspec`` (v1 implicit pipeline), ``pallas:packed4``
(DMA + nibble-packed bins).  ``pallas`` alone is the DMA pipeline.
The caller maps a suffixed winner back onto config knobs
(models/gbdt.py: ``tpu_histogram_impl`` + ``tpu_pallas_pipeline``).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

# shape -> winning impl, process-lifetime cache
_CACHE: Dict[Tuple[str, int, int, int, tuple], str] = {}
_DISK_LOADED: Dict[str, Dict[str, str]] = {}

# above this many binned cells the static choice (pallas on TPU) is
# reliably right and the probe's compile time isn't worth it
AUTOTUNE_MAX_CELLS = 1 << 22


def _cache_path() -> Optional[str]:
    p = os.environ.get("LGBM_TPU_AUTOTUNE_CACHE")
    if p == "":
        return None
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache", "lightgbm_tpu",
                        "hist_autotune.json")


def _disk_load(path: str) -> Dict[str, str]:
    if path in _DISK_LOADED:
        return _DISK_LOADED[path]
    data: Dict[str, str] = {}
    try:
        with open(path) as fh:
            raw = json.load(fh)
        if isinstance(raw, dict) and raw.get("schema") == "hist-autotune-v1":
            data = {str(k): str(v) for k, v in raw.get("winners", {}).items()}
    except Exception:
        data = {}
    _DISK_LOADED[path] = data
    return data


def _disk_store(path: str, key: str, win: str) -> None:
    # merge from a FRESH read, not the memo: concurrent sweep workers
    # append entries between our reads, and a stale-memo merge would
    # silently clobber their persisted winners
    _DISK_LOADED.pop(path, None)
    data = dict(_disk_load(path))
    data[key] = win
    payload = json.dumps({"schema": "hist-autotune-v1", "winners": data},
                         indent=0, sort_keys=True).encode()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        from ..io_utils import atomic_write_bytes
        atomic_write_bytes(path, payload)
        _DISK_LOADED[path] = data
    except Exception:
        pass  # persistence is best-effort; the in-process cache still holds


def _disk_key(backend: str, n: int, f: int, b: int, candidates) -> str:
    return f"{backend}/{n}x{f}x{b}/" + ",".join(candidates)


def default_candidates(backend: str, max_bins: int) -> tuple:
    """The variant set worth probing on this backend/shape."""
    if backend == "tpu":
        cands = ["pallas", "pallas:blockspec", "onehot"]
        if max_bins <= 16:
            cands.insert(1, "pallas:packed4")
        return tuple(cands)
    if max_bins <= 16:
        return ("segment", "packed4")
    return ("segment",)


def _make_runner(impl: str, X_binned: np.ndarray, max_bins: int):
    """Build a zero-arg measured build closure for one candidate."""
    import jax.numpy as jnp
    n, f = X_binned.shape
    rng = np.random.RandomState(0)
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(np.abs(rng.randn(n)).astype(np.float32))
    mask = jnp.ones((n,), jnp.float32)
    base, _, variant = impl.partition(":")
    if base == "pallas":
        from ..ops.histogram_pallas import (build_histogram_pallas,
                                            pack_bins4, pad_rows)
        n_pad = pad_rows(n)
        bins_t = jnp.asarray(
            np.pad(X_binned, ((0, n_pad - n), (0, 0))).T.copy())
        packed = variant == "packed4"
        if packed:
            bins_t = pack_bins4(bins_t.astype(jnp.uint8))
        pipeline = "blockspec" if variant == "blockspec" else "dma"
        gp = jnp.pad(grad, (0, n_pad - n))
        hp = jnp.pad(hess, (0, n_pad - n))
        mp = jnp.pad(mask, (0, n_pad - n))

        def run():
            return build_histogram_pallas(bins_t, gp, hp, mp,
                                          num_bins=int(max_bins),
                                          pipeline=pipeline,
                                          bins_packed=packed)
    else:
        from ..ops.histogram import build_histogram
        bins_d = jnp.asarray(X_binned)

        def run(impl=base):
            return build_histogram(bins_d, grad, hess, mask,
                                   num_bins=int(max_bins), impl=impl)
    return run


def pick_hist_impl(X_binned: np.ndarray, max_bins: int,
                   candidates=None, reps: int = 10) -> str:
    """Time one full histogram build per candidate variant on the actual
    data shapes; return the faster (ties -> first candidate).

    Measurement is amortized over ``reps`` builds with a single host
    sync: through a remote-tunnel device the sync alone costs ~100 ms,
    so it must be a CONSTANT bias shared by both candidates, not part of
    the per-build signal.  The static default (candidates[0]) gets a
    1.3x hysteresis margin: a wrong flip away from the measured-good
    default costs 5-10x per histogram pass at wave-grower shapes, so the
    probe must beat real noise, not tie with it."""
    import jax.numpy as jnp
    n, f = X_binned.shape
    if candidates is None:
        from ..utils.backend import default_backend
        candidates = default_candidates(default_backend(), int(max_bins))
    candidates = tuple(candidates)
    if len(candidates) == 1:
        return candidates[0]
    from ..utils.backend import default_backend
    backend = default_backend()
    key = (backend, n, f, int(max_bins), candidates)
    hit = _CACHE.get(key)
    if hit in candidates:
        return hit
    path = _cache_path()
    dkey = _disk_key(backend, n, f, int(max_bins), candidates)
    if path:
        disk_hit = _disk_load(path).get(dkey)
        if disk_hit in candidates:
            _CACHE[key] = disk_hit
            from ..utils.log import log_info
            log_info(f"histogram autotune at shape ({n}, {f}, {max_bins}): "
                     f"{disk_hit} (cached winner, {path})")
            return disk_hit

    times = {}
    for impl in candidates:
        try:
            run = _make_runner(impl, X_binned, max_bins)
            out = run()                       # compile + warm
            _ = float(jnp.ravel(out)[0])
            t0 = time.perf_counter()
            for _i in range(reps):
                out = run()
            _ = float(jnp.ravel(out)[0])
            times[impl] = (time.perf_counter() - t0) / reps
        except Exception:  # noqa: BLE001 — a failing impl simply loses
            times[impl] = float("inf")
    win = min(candidates, key=lambda i: times[i])
    if win != candidates[0] and \
            times[win] > times[candidates[0]] / 1.3:
        win = candidates[0]
    from ..utils.log import log_info
    log_info("histogram autotune at shape "
             f"({n}, {f}, {max_bins}): " +
             ", ".join(f"{k}={v * 1e3:.2f}ms" for k, v in times.items()) +
             f" -> {win}")
    _CACHE[key] = win
    if path and times.get(win, float("inf")) != float("inf"):
        _disk_store(path, dkey, win)
    return win


def apply_winner(cfg, win: str) -> None:
    """Map a (possibly suffixed) winning variant onto config knobs.

    ALL three knobs are pinned, not just the suffixed one: a plain
    "pallas" winner beat the packed/blockspec candidates, so the
    default-on pack4 must be switched OFF for training to run the
    variant that actually won the measurement."""
    base, _, variant = win.partition(":")
    cfg.tpu_histogram_impl = base
    if base == "pallas":
        cfg.tpu_hist_pack4 = variant == "packed4"
        cfg.tpu_pallas_pipeline = ("blockspec" if variant == "blockspec"
                                   else "dma")
