"""One-shot histogram-implementation autotune.

The reference times its col-wise vs row-wise histogram construction on
the first iteration and keeps the winner (reference: src/io/dataset.cpp
:659-670 ``ShareStates`` force_col_wise/force_row_wise timing).  The TPU
analog choice is the Pallas MXU kernel vs the XLA onehot formulation:
the static table in ``resolve_hist_impl`` is right for benchmark-scale
shapes, but small or oddly-shaped datasets (tiny N, very wide F, tiny
max_bin) can go either way — so when the binned matrix is small enough
that two extra compiles are cheap, time both on the REAL data once and
cache the winner per (N, F, B) shape.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

import numpy as np

# shape -> winning impl, process-lifetime cache
_CACHE: Dict[Tuple[int, int, int], str] = {}

# above this many binned cells the static choice (pallas on TPU) is
# reliably right and the probe's compile time isn't worth it
AUTOTUNE_MAX_CELLS = 1 << 22


def pick_hist_impl(X_binned: np.ndarray, max_bins: int,
                   candidates=("pallas", "onehot"), reps: int = 10) -> str:
    """Time one full histogram build per candidate impl on the actual
    data shapes; return the faster (ties -> first candidate).

    Measurement is amortized over ``reps`` builds with a single host
    sync: through a remote-tunnel device the sync alone costs ~100 ms,
    so it must be a CONSTANT bias shared by both candidates, not part of
    the per-build signal.  The static default (candidates[0] — pallas on
    TPU) additionally gets a 1.3x hysteresis margin: a wrong flip to the
    XLA onehot path costs 5-10x per histogram pass at wave-grower
    shapes, so the probe must beat real noise, not tie with it."""
    import jax
    import jax.numpy as jnp
    n, f = X_binned.shape
    key = (n, f, int(max_bins))
    hit = _CACHE.get(key)
    if hit in candidates:
        return hit

    rng = np.random.RandomState(0)
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(np.abs(rng.randn(n)).astype(np.float32))
    mask = jnp.ones((n,), jnp.float32)
    times = {}
    for impl in candidates:
        try:
            if impl == "pallas":
                from ..ops.histogram_pallas import (build_histogram_pallas,
                                                    pad_rows)
                n_pad = pad_rows(n)
                bins_t = jnp.asarray(
                    np.pad(X_binned, ((0, n_pad - n), (0, 0))).T.copy())
                gp = jnp.pad(grad, (0, n_pad - n))
                hp = jnp.pad(hess, (0, n_pad - n))
                mp = jnp.pad(mask, (0, n_pad - n))

                def run():
                    return build_histogram_pallas(bins_t, gp, hp, mp,
                                                  num_bins=int(max_bins))
            else:
                from ..ops.histogram import build_histogram
                bins_d = jnp.asarray(X_binned)

                def run(impl=impl):
                    return build_histogram(bins_d, grad, hess, mask,
                                           num_bins=int(max_bins),
                                           impl=impl)

            out = run()                       # compile + warm
            _ = float(jnp.ravel(out)[0])
            t0 = time.perf_counter()
            for _i in range(reps):
                out = run()
            _ = float(jnp.ravel(out)[0])
            times[impl] = (time.perf_counter() - t0) / reps
        except Exception:  # noqa: BLE001 — a failing impl simply loses
            times[impl] = float("inf")
    win = min(candidates, key=lambda i: times[i])
    if win != candidates[0] and \
            times[win] > times[candidates[0]] / 1.3:
        win = candidates[0]
    from ..utils.log import log_info
    log_info("histogram autotune at shape "
             f"({n}, {f}, {max_bins}): " +
             ", ".join(f"{k}={v * 1e3:.2f}ms" for k, v in times.items()) +
             f" -> {win}")
    _CACHE[key] = win
    return win
