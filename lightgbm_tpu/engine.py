"""Training entry points: train() and cv()
(reference: python-package/lightgbm/engine.py ``train``:15, ``cv``:391,
``CVBooster``:277)."""

from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster
from .callback import (CallbackEnv, EarlyStopException, early_stopping,
                       print_evaluation)
from .config import Config
from .dataset import Dataset
from .utils.log import log_info, log_warning

__all__ = ["train", "cv", "CVBooster"]


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None,
          feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          **kwargs) -> Booster:
    """Train a boosted model (reference engine.py:15)."""
    params = dict(params or {})
    params.update(kwargs)
    cfg = Config(params)
    if any(k in params for k in ("num_iterations", "num_iteration",
                                 "n_iter", "num_boost_round", "num_round",
                                 "num_rounds", "num_trees", "num_tree",
                                 "n_estimators")):
        num_boost_round = cfg.num_iterations
    if fobj is not None:
        params["objective"] = "none"

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        # Continued training keeps the loaded trees in the model (the
        # reference C++ CLI input_model semantics, application.cpp:87-96 +
        # boosting.cpp:35-67 CreateBoosting-from-file): the returned booster
        # predicts with old+new trees.  (The reference *Python* package
        # instead bakes the old model into init scores only.)
        if isinstance(init_model, Booster):
            init_bst = init_model
        else:
            init_bst = Booster(model_file=str(init_model), params=params)
        booster._gbdt.init_from_model(init_bst._gbdt)
    if valid_sets is not None:
        if not isinstance(valid_sets, (list, tuple)):
            valid_sets = [valid_sets]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                booster._gbdt.config = booster.config.update(
                    {"is_provide_training_metric": True})
                # re-init training metrics
                from .metric import create_metrics
                booster._gbdt.train_metrics = create_metrics(booster._gbdt.config)
                for m in booster._gbdt.train_metrics:
                    m.init(train_set.metadata, train_set.num_data())
                continue
            name = (valid_names[i] if valid_names is not None and
                    i < len(valid_names) else f"valid_{i}")
            booster.add_valid(vs, name)

    callbacks = list(callbacks or [])
    cfg2 = booster.config
    if cfg2.verbosity >= 1 and cfg2.metric_freq > 0:
        callbacks.append(print_evaluation(cfg2.metric_freq))
    if cfg2.early_stopping_round and cfg2.early_stopping_round > 0:
        callbacks.append(early_stopping(cfg2.early_stopping_round,
                                        cfg2.first_metric_only,
                                        verbose=cfg2.verbosity >= 1))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                      if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    for it in range(num_boost_round):
        for cb in callbacks_before:
            cb(CallbackEnv(booster, params, it, 0, num_boost_round, None))
        if booster.update(fobj=fobj):
            # no leaf met the split requirements — stop like the reference
            # CLI train loop (gbdt.cpp:264-283)
            break
        if cfg2.snapshot_freq > 0 and (it + 1) % cfg2.snapshot_freq == 0:
            # periodic checkpoints (reference gbdt.cpp:277-281 Train +
            # config snapshot_freq/save_period)
            booster.save_model(f"{cfg2.output_model}.snapshot_iter_{it + 1}")

        evaluation_result_list = []
        if booster._gbdt.train_metrics or booster._gbdt.valid_sets or feval:
            evaluation_result_list = booster.eval_train(feval) + \
                booster.eval_valid(feval)
        try:
            for cb in callbacks_after:
                cb(CallbackEnv(booster, params, it, 0, num_boost_round,
                               evaluation_result_list))
        except EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1
            for ds_name, eval_name, score, _ in e.best_score:
                booster.best_score.setdefault(ds_name, {})[eval_name] = score
            break
    return booster


class CVBooster:
    """Container of per-fold boosters (reference engine.py:277)."""

    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict[str, Any],
                  seed: int, stratified: bool, shuffle: bool):
    full_data.construct(Config(params))
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    label = full_data.get_label()
    group = full_data.get_group()
    if group is not None:
        # query-aware folds for ranking (reference engine.py group_info path)
        qb = full_data.metadata.query_boundaries
        nq = len(group)
        q_order = rng.permutation(nq) if shuffle else np.arange(nq)
        q_fold = np.empty(nq, np.int32)
        q_fold[q_order] = np.arange(nq) % nfold
        for k in range(nfold):
            test_idx = np.concatenate([np.arange(qb[q], qb[q + 1])
                                       for q in range(nq) if q_fold[q] == k])
            train_idx = np.concatenate([np.arange(qb[q], qb[q + 1])
                                        for q in range(nq) if q_fold[q] != k])
            yield np.sort(train_idx), np.sort(test_idx)
        return
    if stratified and label is not None:
        # stratified fold assignment by label bucket
        order = np.argsort(label, kind="stable")
        folds_assign = np.empty(num_data, np.int32)
        folds_assign[order] = np.arange(num_data) % nfold
        if shuffle:
            perm = rng.permutation(nfold)
            folds_assign = perm[folds_assign]
    else:
        idx = rng.permutation(num_data) if shuffle else np.arange(num_data)
        folds_assign = np.empty(num_data, np.int32)
        folds_assign[idx] = np.arange(num_data) % nfold
    for k in range(nfold):
        test_idx = np.nonzero(folds_assign == k)[0]
        train_idx = np.nonzero(folds_assign != k)[0]
        yield train_idx, test_idx


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       fpreproc=None, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False, **kwargs) -> Dict[str, List[float]]:
    """Cross-validation (reference engine.py:391)."""
    params = dict(params or {})
    params.update(kwargs)
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config(params)
    if cfg.objective in ("lambdarank", "rank_xendcg"):
        stratified = False

    train_set.construct(cfg)
    if folds is None:
        folds = list(_make_n_folds(train_set, nfold, params, seed, stratified
                                   and cfg.objective in ("binary", "multiclass",
                                                         "multiclassova"),
                                   shuffle))
    results = collections.defaultdict(list)
    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        if fpreproc is not None:
            tr, te, fold_params = fpreproc(tr, te, copy.deepcopy(params))
        else:
            fold_params = params
        bst = Booster(params=fold_params, train_set=tr)
        bst.add_valid(te, "valid")
        fold_data.append(bst)
        cvbooster.append(bst)

    es_round = cfg.early_stopping_round
    best_iter = num_boost_round
    stopped = False
    best_signed: Dict[str, float] = {}
    best_it_per_key: Dict[str, int] = {}
    for it in range(num_boost_round):
        agg = collections.defaultdict(list)
        hib_map = {}
        for bst in fold_data:
            bst.update(fobj=fobj)
            for ds, name, val, hib in bst.eval_valid(feval):
                agg[f"{ds} {name}"].append(val)
                hib_map[f"{ds} {name}"] = hib
            if eval_train_metric:
                for ds, name, val, hib in bst.eval_train(feval):
                    agg[f"train {name}"].append(val)
        # early stopping tracks VALIDATION metrics only (reference cv
        # semantics; train metrics are reported but never gate stopping);
        # first_metric_only restricts to the first validation metric key.
        # Stop as soon as ANY tracked metric stalls es_round rounds
        # (reference early_stopping callback semantics, callback.py:147).
        es_keys = [k for k in agg if not k.startswith("train ")]
        if cfg.first_metric_only and es_keys:
            es_keys = es_keys[:1]
        for key, vals in agg.items():
            results[f"{key}-mean"].append(float(np.mean(vals)))
            results[f"{key}-stdv"].append(float(np.std(vals)))
            if key not in es_keys:
                continue
            hib = hib_map.get(key, False)
            cur = float(np.mean(vals))
            signed = -cur if hib else cur
            if key not in best_signed or signed < best_signed[key]:
                best_signed[key] = signed
                best_it_per_key[key] = it + 1
        if es_round and es_round > 0:
            for key in es_keys:
                if it + 1 - best_it_per_key.get(key, it + 1) >= es_round:
                    stopped = True
                    best_iter = best_it_per_key[key]
                    break
            if stopped:
                break
    out = dict(results)
    if stopped:
        for k in out:
            out[k] = out[k][:best_iter]
        cvbooster.best_iteration = best_iter
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
