"""Training entry points: train() and cv()
(reference: python-package/lightgbm/engine.py ``train``:15, ``cv``:391,
``CVBooster``:277).

Fault tolerance: with ``snapshot_freq > 0`` (or ``checkpoint_dir`` set)
the loop periodically flushes a full-state checkpoint bundle through
:mod:`lightgbm_tpu.resilience.checkpoint` — atomic on disk, bounded
ring, ``LATEST`` pointer — and ``train(..., resume_from=...)`` (or
``resume=latest`` in params / ``--resume`` on the CLI) continues a
preempted run bit-identically.  While checkpointing is active a
SIGTERM/SIGINT (TPU preemption notice) drains the in-flight iteration,
flushes one final bundle and raises :class:`TrainingPreempted`."""

from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster
from .callback import (CallbackEnv, EarlyStopException, early_stopping,
                       print_evaluation)
from .config import Config
from .dataset import Dataset
from .resilience.checkpoint import (CKPT_SOFT_KEYS, CKPT_STRUCTURAL_KEYS,
                                    Checkpoint, CheckpointError,
                                    CheckpointManager, PreemptionGuard,
                                    TrainingPreempted, load_checkpoint,
                                    resolve_checkpoint)
from .resilience.faults import faults
from .telemetry.metrics import default_registry
from .utils.log import log_info, log_warning
from .utils.random import rng_checkpoint_state

__all__ = ["train", "cv", "CVBooster"]

# params whose drift across a resume either breaks the continuation
# (validate_config fails on the structural ones) or breaks bit-identity
# (warned); recorded into every checkpoint bundle.  Single-sourced from
# checkpoint.py so the recorded set and the checked set cannot drift.
_CKPT_PARAM_KEYS = CKPT_STRUCTURAL_KEYS + CKPT_SOFT_KEYS


def _resolve_resume(cfg, ckpt_dir: str):
    """Map config's ``resume`` param to a checkpoint path.  The ``latest``
    spelling is cold-start friendly: an empty/absent checkpoint dir means
    "first run of this job", not an error."""
    want = str(cfg.resume).strip()
    if not want:
        return None
    if want.lower() in ("latest", "auto", "true", "1"):
        if not ckpt_dir:
            raise ValueError("resume=latest needs snapshot_freq>0 or "
                             "checkpoint_dir to locate checkpoints")
        path = resolve_checkpoint(ckpt_dir)
        if path is None:
            log_info(f"resume=latest: no checkpoint in {ckpt_dir} yet; "
                     "starting fresh")
        return path
    return want


def _capture(booster: Booster, train_set: Dataset, cfg,
             callbacks_after: List[Callable],
             history: Dict[str, Dict[str, List[float]]]) -> Checkpoint:
    """Bundle the full boosting state at the current iteration boundary
    (called AFTER the iteration's eval callbacks ran, so eval history and
    early-stop bookkeeping land in the same bundle as the model)."""
    g = booster._gbdt
    arrays = g.capture_checkpoint_arrays()
    return Checkpoint(
        iteration=int(g.iter_),
        model_text=booster.model_to_string(),
        score=arrays["score"],
        valid_names=arrays["valid_names"],
        valid_scores=arrays["valid_scores"],
        eval_history=copy.deepcopy(history),
        early_stop=[cb.state_dict() for cb in callbacks_after
                    if hasattr(cb, "state_dict")],
        rng_state=rng_checkpoint_state(cfg),
        fingerprint=train_set.fingerprint(),
        params={k: getattr(cfg, k) for k in _CKPT_PARAM_KEYS},
        cegb_used=arrays["cegb_used"],
        prev_iter_leaves=arrays["prev_iter_leaves"],
    )


def _restore(ckpt: Checkpoint, booster: Booster, train_set: Dataset,
             cfg, callbacks_after: List[Callable]) -> int:
    """Continue from a bundle: validate, restore the boosting state and
    the callback-side bookkeeping, return the first iteration to run."""
    ckpt.validate_config(cfg)
    ckpt.validate_dataset(train_set)
    g = booster._gbdt
    names_now = [name for name, _ in g.valid_sets]
    if list(ckpt.valid_names) != names_now:
        raise CheckpointError(
            f"checkpoint tracked valid sets {list(ckpt.valid_names)} but "
            f"this run registered {names_now}; resume with the same "
            f"valid_sets/valid_names to continue the eval streams")
    g.restore_boosting_state(ckpt.model_text, ckpt.iteration, ckpt.score,
                             ckpt.valid_scores, ckpt.cegb_used,
                             ckpt.prev_iter_leaves)
    stoppers = [cb for cb in callbacks_after if hasattr(cb, "load_state_dict")]
    if ckpt.early_stop and stoppers and \
            len(stoppers) != len(ckpt.early_stop):
        # a positional zip would silently mispair the saved patience
        # bookkeeping and fork the stopping decision
        raise CheckpointError(
            f"checkpoint carries {len(ckpt.early_stop)} early-stopping "
            f"states but this run registered {len(stoppers)} early-stopping "
            f"callbacks; resume with the same callbacks to keep the "
            f"continuation bit-identical")
    for cb, state in zip(stoppers, ckpt.early_stop):
        # any knob that steers the stop decision must match the saved run,
        # or the continuation silently forks from the uninterrupted one
        for key, label in (("rounds", "stopping_rounds"),
                           ("first_metric_only", "first_metric_only")):
            saved = state.get(key)
            now = getattr(cb, key, None)
            if saved is not None and now is not None and saved != now:
                raise CheckpointError(
                    f"checkpoint early-stopping {label} is {saved} but "
                    f"this run registered {label}={now}; resume with the "
                    f"same early-stopping configuration to keep the "
                    f"continuation bit-identical")
        cb.load_state_dict(state)
    if ckpt.early_stop and not stoppers and any(
            s.get("trackers") for s in ckpt.early_stop):
        log_warning("checkpoint carries early-stopping state but this run "
                    "has no early-stopping callback; patience restarts")
    for cb in callbacks_after:
        er = getattr(cb, "eval_result", None)
        if isinstance(er, dict):
            er.clear()
            er.update(copy.deepcopy(ckpt.eval_history))
    default_registry().counter(
        "resume_total", "training runs continued from a checkpoint").inc()
    log_info(f"resuming training from iteration {ckpt.iteration} "
             f"({len(g.models)} trees restored)")
    return int(ckpt.iteration)


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None,
          feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          resume_from: Optional[Union[str, Checkpoint]] = None,
          **kwargs) -> Booster:
    """Train a boosted model (reference engine.py:15).

    ``resume_from`` continues a checkpointed run (a bundle path, a
    checkpoint directory, or a loaded :class:`Checkpoint`); with the
    same data, params and seeds the result is bit-identical to a run
    that never stopped."""
    params = dict(params or {})
    params.update(kwargs)
    cfg = Config(params)
    if any(k in params for k in ("num_iterations", "num_iteration",
                                 "n_iter", "num_boost_round", "num_round",
                                 "num_rounds", "num_trees", "num_tree",
                                 "n_estimators")):
        num_boost_round = cfg.num_iterations
    if fobj is not None:
        params["objective"] = "none"

    # out-of-core route: a StreamedDataset with tpu_ingest_mode=chunked
    # trains via chunk-accumulated wave histograms (ingest/train.py) —
    # HBM bounded by the chunk budget, not by rows.  The default "hbm"
    # mode falls through: the streamed binned cache uploads once and
    # every normal learner path runs unchanged (bit-identical to
    # in-core training).
    if getattr(train_set, "is_streamed", False) and \
            str(cfg.tpu_ingest_mode) == "chunked":
        from .ingest.train import train_streamed
        unsupported = [nm for nm, v in (
            ("fobj", fobj), ("feval", feval),
            ("init_model", init_model), ("callbacks", callbacks)) if v]
        if unsupported:
            raise ValueError(
                "tpu_ingest_mode=chunked training does not support "
                + ", ".join(unsupported) +
                " yet; drop them or use tpu_ingest_mode=hbm")
        if isinstance(resume_from, Checkpoint):
            raise ValueError("tpu_ingest_mode=chunked resume takes a "
                             "bundle/directory path, not a loaded "
                             "Checkpoint object")
        return train_streamed(params, train_set, num_boost_round,
                              valid_sets=valid_sets,
                              valid_names=valid_names,
                              resume_from=(str(resume_from)
                                           if resume_from else None))

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        # Continued training keeps the loaded trees in the model (the
        # reference C++ CLI input_model semantics, application.cpp:87-96 +
        # boosting.cpp:35-67 CreateBoosting-from-file): the returned booster
        # predicts with old+new trees.  (The reference *Python* package
        # instead bakes the old model into init scores only.)
        if isinstance(init_model, Booster):
            init_bst = init_model
        else:
            init_bst = Booster(model_file=str(init_model), params=params)
        booster._gbdt.init_from_model(init_bst._gbdt)
    if valid_sets is not None:
        if not isinstance(valid_sets, (list, tuple)):
            valid_sets = [valid_sets]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                booster._gbdt.config = booster.config.update(
                    {"is_provide_training_metric": True})
                # re-init training metrics
                from .metric import create_metrics
                booster._gbdt.train_metrics = create_metrics(booster._gbdt.config)
                for m in booster._gbdt.train_metrics:
                    m.init(train_set.metadata, train_set.num_data())
                continue
            name = (valid_names[i] if valid_names is not None and
                    i < len(valid_names) else f"valid_{i}")
            booster.add_valid(vs, name)

    callbacks = list(callbacks or [])
    cfg2 = booster.config
    if cfg2.verbosity >= 1 and cfg2.metric_freq > 0:
        callbacks.append(print_evaluation(cfg2.metric_freq))
    if cfg2.early_stopping_round and cfg2.early_stopping_round > 0:
        callbacks.append(early_stopping(cfg2.early_stopping_round,
                                        cfg2.first_metric_only,
                                        verbose=cfg2.verbosity >= 1))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                      if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # -- fault tolerance setup (resilience/checkpoint.py) --------------------
    ckpt_dir = str(cfg2.checkpoint_dir or "")
    snap_freq = int(cfg2.snapshot_freq)
    if not ckpt_dir and snap_freq > 0:
        ckpt_dir = f"{cfg2.output_model}.ckpt"
    if ckpt_dir and snap_freq <= 0:
        # explicit checkpoint_dir without a cadence: ~100 bundles per run.
        # A bundle serializes the whole model so far, so flushing every
        # iteration of a long run would make checkpoint cost quadratic.
        snap_freq = max(1, num_boost_round // 100)
    manager = CheckpointManager(ckpt_dir, keep=int(cfg2.checkpoint_keep)) \
        if ckpt_dir else None

    # -- continuous-learning lane (publish/) ---------------------------------
    publisher = None
    if str(cfg2.publish_dir):
        from .publish.publisher import DeltaPublisher
        publisher = DeltaPublisher(str(cfg2.publish_dir),
                                   every=int(cfg2.publish_every) or 1)

    if resume_from is None and cfg2.resume:
        resume_from = _resolve_resume(cfg2, ckpt_dir)
    ckpt: Optional[Checkpoint] = None
    if isinstance(resume_from, Checkpoint):
        ckpt = resume_from
    elif resume_from:
        ckpt = load_checkpoint(str(resume_from))
    start_iter = 0
    # the engine's own eval-history record: checkpoints carry it even
    # when the user never registered a record_evaluation callback
    run_history: Dict[str, Dict[str, List[float]]] = {}
    if ckpt is not None:
        if init_model is not None:
            # restoring would silently drop init_model's trees and fork
            # the ensemble semantics — refuse instead of guessing
            raise CheckpointError(
                "both init_model and resume_from given: a checkpoint "
                "restore replaces the whole model, which would silently "
                "drop the init_model trees; continue from the checkpoint "
                "alone, or start a fresh run from init_model")
        start_iter = _restore(ckpt, booster, train_set, cfg2,
                              callbacks_after)
        run_history = copy.deepcopy(ckpt.eval_history)

    def _flush(final: bool = False) -> Optional[str]:
        if manager is None:
            return None
        path = manager.save(_capture(booster, train_set, cfg2,
                                     callbacks_after, run_history))
        if final:
            log_info(f"final checkpoint flushed to {path}")
        return path

    def _flight_dump(reason: str) -> Optional[str]:
        """Dump the flight-recorder tape next to the checkpoints (or to
        an explicit flight_dir) — the crash/preemption post-mortem.
        Called AFTER the final checkpoint flush, so the tape's last
        event and the checkpoint land on the same iteration boundary."""
        import os
        fr = getattr(booster._gbdt, "flight", None)
        if fr is None or not fr.enabled or len(fr) == 0:
            return None
        out_dir = str(cfg2.flight_dir) or ckpt_dir
        if not out_dir:
            return None
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = fr.dump(os.path.join(out_dir, "flight.jsonl"),
                           reason=reason)
        except OSError as exc:
            log_warning(f"flight recorder dump failed: {exc}")
            return None
        log_info(f"flight recorder: {len(fr)} events dumped to {path} "
                 f"({reason})")
        return path

    # The guard turns a SIGTERM/SIGINT (TPU preemption notice) into a
    # drain-and-flush exit; installed only while checkpointing is active
    # so a plain Ctrl-C on an uncheckpointed run stays KeyboardInterrupt.
    try:
        with PreemptionGuard(enabled=manager is not None) as guard:
            for it in range(start_iter, num_boost_round):
                faults.check_train_iter(it)  # chaos layer (resilience/)
                for cb in callbacks_before:
                    cb(CallbackEnv(booster, params, it, 0, num_boost_round,
                                   None))
                if booster.update(fobj=fobj):
                    # no leaf met the split requirements — stop like the
                    # reference CLI train loop (gbdt.cpp:264-283)
                    break
                if cfg2.snapshot_freq > 0 and \
                        (it + 1) % cfg2.snapshot_freq == 0:
                    # reference-compatible model-text snapshot
                    # (gbdt.cpp:277-281 Train + snapshot_freq/save_period),
                    # atomically written
                    booster.save_model(
                        f"{cfg2.output_model}.snapshot_iter_{it + 1}")

                evaluation_result_list = []
                if booster._gbdt.train_metrics or booster._gbdt.valid_sets \
                        or feval:
                    evaluation_result_list = booster.eval_train(feval) + \
                        booster.eval_valid(feval)
                booster._gbdt.flight.note_eval(it + 1,
                                               evaluation_result_list)
                if manager is not None:
                    for data_name, eval_name, value, _ in \
                            evaluation_result_list:
                        run_history.setdefault(data_name, {}).setdefault(
                            eval_name, []).append(value)
                try:
                    for cb in callbacks_after:
                        cb(CallbackEnv(booster, params, it, 0,
                                       num_boost_round,
                                       evaluation_result_list))
                except EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    for ds_name, eval_name, score, _ in e.best_score:
                        booster.best_score.setdefault(
                            ds_name, {})[eval_name] = score
                    _flush()
                    break
                # the full-state bundle flushes AFTER the iteration's eval
                # callbacks so eval history / early-stop bookkeeping
                # restore to the exact same boundary
                if manager is not None and (it + 1) % snap_freq == 0:
                    _flush()
                if publisher is not None:
                    publisher.maybe_publish(booster._gbdt, it + 1)
                if guard.fired is not None:
                    final_path = _flush(final=True)
                    if publisher is not None:
                        # drain path: the journal head lands on the same
                        # iteration boundary as the final checkpoint
                        publisher.publish(booster._gbdt)
                    _flight_dump("preempted")
                    raise TrainingPreempted(guard.fired, booster=booster,
                                            checkpoint=final_path)
    except TrainingPreempted:
        raise                      # tape already dumped above
    except (Exception, KeyboardInterrupt):
        # uncaught training error (including injected chaos faults):
        # leave the post-mortem tape next to the checkpoints
        _flight_dump("crash")
        raise
    if publisher is not None:
        # completion flush: early-stop/no-split breaks leave off-cadence
        # rounds unpublished — fold them in so journal head == final model
        publisher.publish(booster._gbdt)
    if str(cfg2.flight_dir):
        # an explicit flight_dir asks for the tape even on success
        _flight_dump("completed")
    return booster


class CVBooster:
    """Container of per-fold boosters (reference engine.py:277)."""

    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler


class CVAggregator:
    """Per-iteration fold-metric aggregation + aggregated early stopping
    shared by cv()'s fold loop and the multitrain fast path
    (multitrain/cv.py) so the two can never fork semantics.

    Early stopping tracks VALIDATION metrics only (reference cv
    semantics; train metrics are reported but never gate stopping);
    ``first_metric_only`` restricts to the first validation metric key.
    Stop as soon as ANY tracked metric stalls ``early_stopping_round``
    rounds (reference early_stopping callback semantics,
    callback.py:147)."""

    def __init__(self, cfg: Config, num_boost_round: int) -> None:
        self._es_round = cfg.early_stopping_round
        self._first_only = bool(cfg.first_metric_only)
        self.results: Dict[str, List[float]] = collections.defaultdict(list)
        self.best_iter = num_boost_round
        self.stopped = False
        self._best_signed: Dict[str, float] = {}
        self._best_it: Dict[str, int] = {}

    def update(self, it: int, agg: Dict[str, List[float]],
               hib_map: Dict[str, bool]) -> bool:
        """Fold one iteration's per-fold metric lists in; True = stop."""
        es_keys = [k for k in agg if not k.startswith("train ")]
        if self._first_only and es_keys:
            es_keys = es_keys[:1]
        for key, vals in agg.items():
            self.results[f"{key}-mean"].append(float(np.mean(vals)))
            self.results[f"{key}-stdv"].append(float(np.std(vals)))
            if key not in es_keys:
                continue
            hib = hib_map.get(key, False)
            cur = float(np.mean(vals))
            signed = -cur if hib else cur
            if key not in self._best_signed or signed < self._best_signed[key]:
                self._best_signed[key] = signed
                self._best_it[key] = it + 1
        if self._es_round and self._es_round > 0:
            for key in es_keys:
                if it + 1 - self._best_it.get(key, it + 1) >= self._es_round:
                    self.stopped = True
                    self.best_iter = self._best_it[key]
                    break
        return self.stopped

    def finalize(self, cvbooster: "CVBooster") -> Dict[str, List[float]]:
        """Truncated results dict; stamps best_iteration when stopped."""
        out = dict(self.results)
        if self.stopped:
            for k in out:
                out[k] = out[k][:self.best_iter]
            cvbooster.best_iteration = self.best_iter
        return out


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict[str, Any],
                  seed: int, stratified: bool, shuffle: bool):
    full_data.construct(Config(params))
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    label = full_data.get_label()
    group = full_data.get_group()
    if group is not None:
        # query-aware folds for ranking (reference engine.py group_info path)
        qb = full_data.metadata.query_boundaries
        nq = len(group)
        q_order = rng.permutation(nq) if shuffle else np.arange(nq)
        q_fold = np.empty(nq, np.int32)
        q_fold[q_order] = np.arange(nq) % nfold
        for k in range(nfold):
            test_idx = np.concatenate([np.arange(qb[q], qb[q + 1])
                                       for q in range(nq) if q_fold[q] == k])
            train_idx = np.concatenate([np.arange(qb[q], qb[q + 1])
                                        for q in range(nq) if q_fold[q] != k])
            yield np.sort(train_idx), np.sort(test_idx)
        return
    if stratified and label is not None:
        # stratified fold assignment by label bucket
        order = np.argsort(label, kind="stable")
        folds_assign = np.empty(num_data, np.int32)
        folds_assign[order] = np.arange(num_data) % nfold
        if shuffle:
            perm = rng.permutation(nfold)
            folds_assign = perm[folds_assign]
    else:
        idx = rng.permutation(num_data) if shuffle else np.arange(num_data)
        folds_assign = np.empty(num_data, np.int32)
        folds_assign[idx] = np.arange(num_data) % nfold
    for k in range(nfold):
        test_idx = np.nonzero(folds_assign == k)[0]
        train_idx = np.nonzero(folds_assign != k)[0]
        yield train_idx, test_idx


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       fpreproc=None, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False, **kwargs) -> Dict[str, List[float]]:
    """Cross-validation (reference engine.py:391)."""
    params = dict(params or {})
    params.update(kwargs)
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config(params)
    if cfg.objective in ("lambdarank", "rank_xendcg"):
        stratified = False

    train_set.construct(cfg)
    if folds is None:
        folds = list(_make_n_folds(train_set, nfold, params, seed, stratified
                                   and cfg.objective in ("binary", "multiclass",
                                                         "multiclassova"),
                                   shuffle))
    else:
        folds = list(folds)

    # fast path: folds = models with held-out sample masks, all trained
    # in ONE vmapped program over the parent dataset's binning
    # (multitrain/cv.py); configs the model axis cannot express fall
    # back to the per-fold loop below
    if cfg.tpu_cv_many:
        from .multitrain.cv import cv_many, cv_reject_reason
        reason = cv_reject_reason(fobj, feval, fpreproc, init_model,
                                  callbacks)
        if reason is None:
            from .multitrain.batched import MultiTrainError
            from .resilience.checkpoint import CheckpointError
            try:
                return cv_many(params, train_set, num_boost_round, folds,
                               cfg, eval_train_metric=eval_train_metric,
                               return_cvbooster=return_cvbooster)
            except (MultiTrainError, CheckpointError) as e:
                reason = str(e)
        log_info(f"cv: per-fold loop (batched fold driver unavailable: "
                 f"{reason})")

    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        if fpreproc is not None:
            tr, te, fold_params = fpreproc(tr, te, copy.deepcopy(params))
        else:
            fold_params = params
        if eval_train_metric:
            # without this the fold boosters never build train metrics
            # and eval_train() below is a silent no-op
            fold_params = {**fold_params, "is_provide_training_metric": True}
        bst = Booster(params=fold_params, train_set=tr)
        bst.add_valid(te, "valid")
        fold_data.append(bst)
        cvbooster.append(bst)

    aggr = CVAggregator(cfg, num_boost_round)
    for it in range(num_boost_round):
        agg = collections.defaultdict(list)
        hib_map = {}
        for bst in fold_data:
            bst.update(fobj=fobj)
            for ds, name, val, hib in bst.eval_valid(feval):
                agg[f"{ds} {name}"].append(val)
                hib_map[f"{ds} {name}"] = hib
            if eval_train_metric:
                for ds, name, val, hib in bst.eval_train(feval):
                    agg[f"train {name}"].append(val)
        if aggr.update(it, agg, hib_map):
            break
    out = aggr.finalize(cvbooster)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
