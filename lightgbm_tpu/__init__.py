"""LightGBM-TPU: a TPU-native gradient boosting framework.

A from-scratch re-design of LightGBM's capabilities (reference surveyed in
SURVEY.md) on JAX/XLA: histogram GBDT with leaf-wise growth compiled to TPU
(MXU one-hot-matmul histograms, vectorized bin-scan split finding, whole-tree
growth under one jit), mesh-sharded data/feature/voting-parallel training via
jax collectives, and the reference's public Python surface::

    import lightgbm_tpu as lgb
    bst = lgb.train({"objective": "binary"}, lgb.Dataset(X, y))
    bst.predict(X)
"""

# Honor JAX_PLATFORMS even when a preloaded PJRT plugin (sitecustomize)
# registered an accelerator backend eagerly: jax.config wins over the
# registered plugin as long as no client exists yet.  Without this, ANY
# import-and-train with JAX_PLATFORMS=cpu silently initializes — or
# hangs on — the accelerator (same guard as tests/conftest.py).
import os as _os

if "cpu" in _os.environ.get("JAX_PLATFORMS", ""):
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except RuntimeError:
        pass  # a backend already initialized; too late to switch


from . import analysis, distributed, ingest, resilience, telemetry
from .basic import Booster
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       print_evaluation, record_evaluation, reset_parameter)
from .config import Config
from .dataset import Dataset
from .engine import CVBooster, cv, train
from .ingest import StreamedDataset, train_streamed
from .models.model_text import ModelCorruptError
from .multitrain import ManyBooster, MultiTrainError, train_many
from .resilience import (Checkpoint, CheckpointError, TrainingPreempted,
                         load_checkpoint)
from .utils.log import register_log_callback, set_verbosity

try:
    from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                          LGBMRegressor)
    _SKLEARN_OK = True
except ImportError:  # sklearn not installed
    _SKLEARN_OK = False

from .plotting import (plot_importance, plot_metric, plot_tree,
                       plot_split_value_histogram, create_tree_digraph)

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "Config", "train", "cv", "CVBooster",
           "train_many", "ManyBooster", "MultiTrainError",
           "early_stopping", "print_evaluation", "log_evaluation",
           "record_evaluation", "reset_parameter", "EarlyStopException",
           "register_log_callback", "set_verbosity", "analysis",
           "distributed", "ingest", "StreamedDataset", "train_streamed",
           "telemetry", "resilience", "Checkpoint", "CheckpointError",
           "TrainingPreempted", "load_checkpoint", "ModelCorruptError",
           "plot_importance", "plot_metric", "plot_tree",
           "plot_split_value_histogram", "create_tree_digraph"]
if _SKLEARN_OK:
    __all__ += ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
