"""Command-line application: ``python -m lightgbm_tpu [config=train.conf]
[key=value ...]``.

TPU-native re-implementation of the reference CLI
(reference: src/main.cpp:11 + src/application/application.cpp:31-265 —
parse ``key=value`` args and config file, dispatch on config.task:
train / predict / refit / convert_model; data loaded from config.data with
``.weight`` / ``.query`` sidecar files; model written to
config.output_model; predictions to config.output_result).

Config files use the reference's ``key = value`` format with ``#``
comments, so reference train.conf files work unmodified.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster
from .config import Config, parse_config_file
from .dataset import Dataset
from .engine import train as train_api
from .io_utils import load_sidecar
from .resilience.checkpoint import TrainingPreempted
from .utils.log import log_fatal, log_info, log_warning


def parse_cli_args(argv: List[str]) -> Dict[str, Any]:
    """``key=value`` arguments + optional config file, command line wins
    (reference application.cpp:52 LoadParameters).  ``--resume`` (bare)
    is sugar for ``resume=latest``; ``--key=value`` strips the dashes."""
    cli: Dict[str, Any] = {}
    for arg in argv:
        if arg.startswith("--"):
            arg = arg[2:]
            if "=" not in arg:
                if arg.strip() == "resume":
                    cli["resume"] = "latest"
                else:
                    # unknown bare flags must not silently become
                    # key=true params (they would land in Config.extra
                    # and leak into the saved model text)
                    log_warning(f"unknown CLI flag ignored: --{arg.strip()}")
                continue
        if "=" not in arg:
            log_warning(f"unknown CLI argument ignored: {arg}")
            continue
        key, value = arg.split("=", 1)
        cli[key.strip()] = value.strip()
    params: Dict[str, Any] = {}
    conf = cli.get("config", cli.get("config_file", ""))
    if conf:
        params.update(parse_config_file(conf))
    params.update(cli)
    return params


def _load_dataset(path: str, params: Dict[str, Any],
                  reference: Optional[Dataset] = None) -> Dataset:
    ds = Dataset(path, params=params) if reference is None else \
        reference.create_valid(path)
    weight = load_sidecar(path, "weight")
    if weight is not None:
        ds.set_weight(weight)
    group = load_sidecar(path, "query")
    if group is None:
        group = load_sidecar(path, "group")
    if group is not None:
        ds.set_group(group.astype(np.int64))
    return ds


def run_train(params: Dict[str, Any], cfg: Config) -> None:
    if not cfg.data:
        log_fatal("task=train needs data=<training file>")
    train_set = _load_dataset(cfg.data, params)
    valid_sets = []
    valid_names = []
    if cfg.valid:
        for i, path in enumerate(str(cfg.valid).split(",")):
            path = path.strip()
            if path:
                valid_sets.append(_load_dataset(path, params,
                                                reference=train_set))
                valid_names.append(f"valid_{i}" if i else "valid_1")
    try:
        booster = train_api(params, train_set,
                            num_boost_round=int(cfg.num_iterations),
                            valid_sets=valid_sets or None,
                            valid_names=valid_names or None)
    except TrainingPreempted as exc:
        # graceful drain done, final checkpoint flushed; exit with the
        # conventional 128+signum so orchestrators see the signal death
        # and re-schedule — the rescheduled run resumes with --resume
        log_warning(f"{exc}; restart with --resume (or resume=latest) "
                    f"to continue this run")
        raise SystemExit(128 + int(exc.signum))
    booster.save_model(cfg.output_model)
    log_info(f"Finished training; model saved to {cfg.output_model}")


def run_predict(params: Dict[str, Any], cfg: Config) -> None:
    if not cfg.input_model:
        log_fatal("task=predict needs input_model=<model file>")
    if not cfg.data:
        log_fatal("task=predict needs data=<data file>")
    booster = Booster(model_file=cfg.input_model, params=params)
    from .io_utils import load_data_file
    X, _, _ = load_data_file(cfg.data, params)
    preds = booster.predict(
        X,
        raw_score=bool(cfg.predict_raw_score),
        pred_leaf=bool(cfg.predict_leaf_index),
        pred_contrib=bool(cfg.predict_contrib),
        start_iteration=int(cfg.start_iteration_predict),
        num_iteration=(None if cfg.num_iteration_predict < 0
                       else int(cfg.num_iteration_predict)))
    out = np.atleast_1d(np.asarray(preds))
    with open(cfg.output_result, "w") as fh:
        if out.ndim == 1:
            fh.write("\n".join(f"{v:.18g}" for v in out) + "\n")
        else:
            for row in out:
                fh.write("\t".join(f"{v:.18g}" for v in row) + "\n")
    log_info(f"Finished prediction; results saved to {cfg.output_result}")


def run_refit(params: Dict[str, Any], cfg: Config) -> None:
    """task=refit / refit_tree (reference application.cpp refit path)."""
    if not cfg.input_model or not cfg.data:
        log_fatal("task=refit needs input_model= and data=")
    booster = Booster(model_file=cfg.input_model, params=params)
    from .io_utils import load_data_file
    X, _, label = load_data_file(cfg.data, params)
    if label is None:
        log_fatal("refit data must include labels")
    new_booster = booster.refit(X, label,
                                decay_rate=float(cfg.refit_decay_rate))
    new_booster.save_model(cfg.output_model)
    log_info(f"Finished refit; model saved to {cfg.output_model}")


def run_convert_model(params: Dict[str, Any], cfg: Config) -> None:
    """task=convert_model: emit the ensemble as standalone C++ if-else code
    (reference gbdt_model_text.cpp:124 ModelToIfElse)."""
    if not cfg.input_model:
        log_fatal("task=convert_model needs input_model=")
    if cfg.convert_model_language not in ("", "cpp"):
        log_fatal(f"convert_model_language="
                  f"{cfg.convert_model_language} not supported (cpp only)")
    booster = Booster(model_file=cfg.input_model, params=params)
    code = model_to_if_else(booster._gbdt)
    with open(cfg.convert_model, "w") as fh:
        fh.write(code)
    log_info(f"Finished converting model; code saved to {cfg.convert_model}")


def model_to_if_else(gbdt) -> str:
    """Standalone C++ prediction source for the ensemble (reference
    gbdt_model_text.cpp ModelToIfElse — per-tree branchy functions plus a
    summing PredictRaw)."""
    lines = ["#include <cmath>", "#include <cstring>", "",
             "// generated by lightgbm_tpu convert_model", ""]
    names = []
    for t, tree in enumerate(gbdt.models):
        name = f"PredictTree{t}"
        names.append(name)
        lines.append(f"static double {name}(const double* row) {{")

        def emit(node: int, indent: str) -> None:
            if node < 0:
                lines.append(f"{indent}return "
                             f"{tree.leaf_value[~node]:.17g};")
                return
            f_idx = int(tree.split_feature[node])
            dt = int(tree.decision_type[node])
            if dt & 1:  # categorical set membership
                cats = tree.cat_values(node)
                cond = " || ".join(
                    f"(long)row[{f_idx}] == {c}" for c in cats) or "false"
                cond = f"(!std::isnan(row[{f_idx}]) && ({cond}))"
                if dt & 2:
                    cond = f"(std::isnan(row[{f_idx}]) || {cond})"
            else:
                thr = float(tree.threshold[node])
                base = f"row[{f_idx}] <= {thr:.17g}"
                if (dt >> 2) & 3 == 2:  # missing nan
                    if dt & 2:
                        cond = f"(std::isnan(row[{f_idx}]) || ({base}))"
                    else:
                        cond = f"(!std::isnan(row[{f_idx}]) && ({base}))"
                else:
                    cond = (f"((std::isnan(row[{f_idx}]) ? 0.0 : "
                            f"row[{f_idx}]) <= {thr:.17g})")
            lines.append(f"{indent}if ({cond}) {{")
            emit(int(tree.left_child[node]), indent + "  ")
            lines.append(f"{indent}}} else {{")
            emit(int(tree.right_child[node]), indent + "  ")
            lines.append(f"{indent}}}")

        if tree.num_leaves <= 1:
            lines.append(f"  return {tree.leaf_value[0]:.17g};")
        else:
            emit(0, "  ")
        lines.append("}")
        lines.append("")
    lines.append("extern \"C\" double PredictRaw(const double* row) {")
    lines.append("  double sum = 0.0;")
    for name in names:
        lines.append(f"  sum += {name}(row);")
    lines.append("  return sum;")
    lines.append("}")
    return "\n".join(lines) + "\n"


def run_profile(argv: List[str]) -> int:
    """``python -m lightgbm_tpu profile [config=train.conf] [key=value ...]``

    Wraps a train or predict run (``config.task``, default train) in a
    ``jax.profiler.trace`` capture plus a telemetry dump: enables the
    span tracer and timetag timer, runs the task, then writes

      * ``<profile_dir>/``            — jax profiler capture
        (TensorBoard / xprof readable), unless ``jax_trace=0``
      * ``<profile_dir>/host_spans.json`` — host span chrome trace
      * ``<profile_dir>/telemetry.json``  — metrics registry + the run's
        TrainRecord (per-phase seconds, hist passes, collective tallies,
        compile events, memory watermark)

    Keys consumed here: ``profile_dir`` (default ``lgbm_tpu_profile``),
    ``telemetry_out``, ``host_trace_out``, ``jax_trace`` (1).
    """
    import contextlib
    import os
    params = parse_cli_args(argv)
    prof_dir = str(params.pop("profile_dir", "lgbm_tpu_profile"))
    jax_trace = str(params.pop("jax_trace", "1")).strip().lower() \
        not in ("0", "false", "no", "off")
    telemetry_out = str(params.pop("telemetry_out", "") or
                        os.path.join(prof_dir, "telemetry.json"))
    host_out = str(params.pop("host_trace_out", "") or
                   os.path.join(prof_dir, "host_spans.json"))
    os.makedirs(prof_dir, exist_ok=True)
    from .telemetry import enable as telemetry_enable
    from .telemetry import global_tracer, write_snapshot
    from .utils.timer import global_timer
    telemetry_enable()
    global_tracer.enable()
    global_tracer.clear()
    global_timer.enable()
    cfg = Config(params)
    task = cfg.task or "train"
    if task not in ("train", "predict", "refit"):
        log_fatal(f"profile wraps task=train/predict/refit only, got "
                  f"task={task}")
    capture = contextlib.nullcontext()
    if jax_trace:
        try:
            import jax.profiler
            capture = jax.profiler.trace(prof_dir)
        except Exception as exc:
            jax_trace = False  # the closing log must not claim a capture
            log_warning(f"jax.profiler.trace unavailable ({exc}); "
                        f"profiling without a device capture")
    with capture:
        if task == "train":
            run_train(params, cfg)
        elif task == "predict":
            run_predict(params, cfg)
        else:
            run_refit(params, cfg)
    n_spans = global_tracer.export_chrome_trace(host_out)
    write_snapshot(telemetry_out)
    log_info(f"profile: telemetry in {telemetry_out}, {n_spans} host "
             f"spans in {host_out}" +
             (f", device capture in {prof_dir}" if jax_trace else ""))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "serve":
        # serving verb: python -m lightgbm_tpu serve model.txt [key=value]
        from .serve.server import main as serve_main
        return serve_main(argv[1:])
    if argv and argv[0] in ("serve-fleet", "serve_fleet"):
        # fleet verb: N supervised worker processes behind a dispatcher
        # with crash-restart, a crash-loop breaker and rolling deploys
        from .serve.fleet import main as fleet_main
        return fleet_main(argv[1:])
    if argv and argv[0] == "profile":
        # profiling verb: python -m lightgbm_tpu profile config=train.conf
        return run_profile(argv[1:])
    if argv and argv[0] in ("lint-trace", "lint_trace"):
        # static-analysis verb: trace the config matrix (serial / wave /
        # DP-scatter / spec-ramp / multitrain / serve), enforce the
        # declared program contracts, print the JSON report, exit
        # nonzero on violations (the blocking CI step)
        from .analysis.lint import main as lint_main
        return lint_main(argv[1:])
    if argv and argv[0] in ("lint-mem", "lint_mem"):
        # memory-lint verb: trace the same matrix at memory geometry,
        # estimate per-device peak HBM + per-kernel VMEM, check the
        # declared MemoryBudget curves (cross-checked against XLA's
        # memory_analysis where the backend reports one); with rows=/
        # devices= also answers "will it fit at that scale?" statically
        from .analysis.memory import main as lint_mem_main
        return lint_mem_main(argv[1:])
    params = parse_cli_args(argv)
    cfg = Config(params)
    task = cfg.task
    if task == "train":
        run_train(params, cfg)
    elif task == "predict":
        run_predict(params, cfg)
    elif task == "refit":
        run_refit(params, cfg)
    elif task == "convert_model":
        run_convert_model(params, cfg)
    elif task == "serve":
        # config-file form: task=serve input_model=model.txt [port=...]
        from .serve.server import main as serve_main
        extra = [f"{k}={v}" for k, v in params.items()
                 if k not in ("task", "config", "config_file", "input_model")]
        if not cfg.input_model:
            log_fatal("task=serve needs input_model=<model file>")
        return serve_main([cfg.input_model] + extra)
    else:
        log_fatal(f"unknown task: {task}")
    return 0
