"""Chunk sources: fixed row-block iterators over out-of-core data.

The ingest subsystem's layer 0 (reference src/io/pipeline_reader.h
``PipelineReader`` + parser.cpp streaming, PAPER.md layer 0): a
:class:`ChunkSource` yields the dataset as fixed ``chunk_rows``-sized raw
row blocks — **no source ever materializes the full matrix**, in host RAM
or anywhere else.  Sources are re-iterable: the sketch pass and the
binning pass (and every training pass that re-reads raw data) call
:meth:`chunks` again and receive identical blocks.

``chunk_rows`` must be a multiple of :data:`CHUNK_QUANTUM` (256); the
Pallas kernel path additionally wants multiples of its 4096 row block —
``lightgbm_tpu.ingest.stream.StreamedDataset`` validates that when it
matters (the chunked trainer pads the final short block, so sources only
guarantee every block except the last is exactly ``chunk_rows`` rows).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, NamedTuple, Optional

import numpy as np

__all__ = ["CHUNK_QUANTUM", "Chunk", "ChunkSource", "ArraySource",
           "NumpyMmapSource", "CSVSource", "ArrowSource", "SyntheticSource",
           "DEFAULT_CHUNK_ROWS"]

CHUNK_QUANTUM = 256
DEFAULT_CHUNK_ROWS = 1 << 20


class Chunk(NamedTuple):
    """One streamed row block."""
    offset: int                      # global row index of the first row
    X: np.ndarray                    # (m, F) raw feature values
    label: Optional[np.ndarray]      # (m,) or None
    weight: Optional[np.ndarray]     # (m,) or None


def _check_chunk_rows(chunk_rows: int) -> int:
    chunk_rows = int(chunk_rows)
    if chunk_rows <= 0 or chunk_rows % CHUNK_QUANTUM:
        raise ValueError(f"chunk_rows must be a positive multiple of "
                         f"{CHUNK_QUANTUM}, got {chunk_rows}")
    return chunk_rows


class ChunkSource:
    """Base protocol: subclasses implement ``num_rows``/``num_features``
    and ``chunks()``.  ``feature_names`` may return None (auto names)."""

    chunk_rows: int = DEFAULT_CHUNK_ROWS

    def num_rows(self) -> int:
        raise NotImplementedError

    def num_features(self) -> int:
        raise NotImplementedError

    def feature_names(self) -> Optional[List[str]]:
        return None

    def chunks(self) -> Iterator[Chunk]:
        raise NotImplementedError

    def num_chunks(self) -> int:
        return -(-self.num_rows() // self.chunk_rows)


class ArraySource(ChunkSource):
    """In-memory adapter (tests / small data): slices views of an
    existing array — still never *copies* the full matrix."""

    def __init__(self, X: np.ndarray, label: Optional[np.ndarray] = None,
                 weight: Optional[np.ndarray] = None,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS) -> None:
        self.X = np.asarray(X)
        if self.X.ndim == 1:
            self.X = self.X.reshape(-1, 1)
        self.label = None if label is None else \
            np.asarray(label, np.float64).ravel()
        self.weight = None if weight is None else \
            np.asarray(weight, np.float64).ravel()
        self.chunk_rows = _check_chunk_rows(chunk_rows)

    def num_rows(self) -> int:
        return int(self.X.shape[0])

    def num_features(self) -> int:
        return int(self.X.shape[1])

    def chunks(self) -> Iterator[Chunk]:
        n = self.num_rows()
        for lo in range(0, n, self.chunk_rows):
            hi = min(lo + self.chunk_rows, n)
            yield Chunk(lo, self.X[lo:hi],
                        None if self.label is None else self.label[lo:hi],
                        None if self.weight is None else self.weight[lo:hi])


class NumpyMmapSource(ChunkSource):
    """``.npy`` file served through ``np.load(mmap_mode='r')`` — the OS
    page cache is the only resident copy; optional ``.npy`` label/weight
    sidecars ride along."""

    def __init__(self, path: str, label_path: Optional[str] = None,
                 weight_path: Optional[str] = None,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS) -> None:
        self.path = os.fspath(path)
        self.label_path = label_path
        self.weight_path = weight_path
        self.chunk_rows = _check_chunk_rows(chunk_rows)
        self._X = np.load(self.path, mmap_mode="r")
        if self._X.ndim == 1:
            raise ValueError(f"{path}: expected a 2-D (rows, features) .npy")
        self._label = None if label_path is None else \
            np.load(label_path, mmap_mode="r")
        self._weight = None if weight_path is None else \
            np.load(weight_path, mmap_mode="r")

    def num_rows(self) -> int:
        return int(self._X.shape[0])

    def num_features(self) -> int:
        return int(self._X.shape[1])

    def chunks(self) -> Iterator[Chunk]:
        n = self.num_rows()
        for lo in range(0, n, self.chunk_rows):
            hi = min(lo + self.chunk_rows, n)
            # np.asarray on the mmap slice pages in ONLY this block
            yield Chunk(
                lo, np.asarray(self._X[lo:hi], np.float64),
                None if self._label is None
                else np.asarray(self._label[lo:hi], np.float64).ravel(),
                None if self._weight is None
                else np.asarray(self._weight[lo:hi], np.float64).ravel())


class CSVSource(ChunkSource):
    """Dense CSV/TSV streamed in ``chunk_rows`` blocks (the reference's
    two_round loading, dataset_loader.cpp:902, as a re-iterable source).
    Label handling follows the CLI convention (first column unless
    ``label_column`` says otherwise; ``header=true`` skips a header)."""

    def __init__(self, path: str, params: Optional[Dict[str, Any]] = None,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS) -> None:
        from ..io_utils import parse_label_column
        self.path = os.fspath(path)
        self.params = dict(params or {})
        self.chunk_rows = _check_chunk_rows(chunk_rows)
        self.header = str(self.params.get("header", "false")).lower() in \
            ("true", "1")
        self.label_col = parse_label_column(self.params)
        # one cheap line pass (O(1) memory): sniff the delimiter and
        # feature count from the first DATA line (comment/blank lines
        # are skipped here exactly like chunks() skips them, so
        # num_rows() and the streamed row count cannot disagree)
        self._names = None
        self._n = 0
        self._skip_physical = 0   # physical lines through the header row
        first_data = None
        header_pending = self.header
        with open(self.path) as fh:
            for lineno, line in enumerate(fh):
                s = line.strip()
                if not s or s.lstrip().startswith("#"):
                    continue
                if header_pending:
                    delim = "\t" if "\t" in s else ","
                    self._names = [c.strip() for c in s.split(delim)]
                    self._skip_physical = lineno + 1
                    header_pending = False
                    continue
                if first_data is None:
                    first_data = s
                self._n += 1
        if first_data is None:
            raise ValueError(f"{path} has no data rows")
        self.delim = "\t" if "\t" in first_data else ","
        self._f = len(first_data.split(self.delim)) - 1

    def num_rows(self) -> int:
        return self._n

    def num_features(self) -> int:
        return self._f

    def feature_names(self) -> Optional[List[str]]:
        if self._names is None:
            return None
        lc = self.label_col
        return self._names[:lc] + self._names[lc + 1:]

    def chunks(self) -> Iterator[Chunk]:
        from ..io_utils import CSV_NA_VALUES
        try:
            import pandas as pd
            reader = pd.read_csv(
                self.path, sep=self.delim, header=None,
                skiprows=self._skip_physical, comment="#",
                chunksize=self.chunk_rows,
                na_values=list(CSV_NA_VALUES))
            off = 0
            for frame in reader:
                try:
                    raw = frame.astype(np.float64).to_numpy()
                except (ValueError, TypeError):
                    raw = frame.apply(pd.to_numeric, errors="coerce") \
                        .to_numpy(np.float64)
                yield self._split(off, raw)
                off += len(raw)
            return
        except ImportError:
            pass
        na = set(CSV_NA_VALUES)

        def tok(t: str) -> float:
            t = t.strip()
            if t in na:
                return np.nan
            try:
                return float(t)
            except ValueError:
                return np.nan   # genfromtxt-ish: junk tokens coerce
        off = 0
        rows: List[List[float]] = []
        with open(self.path) as fh:
            for _ in range(self._skip_physical):
                fh.readline()
            for line in fh:
                s = line.strip()
                if not s or s.startswith("#"):
                    continue
                rows.append([tok(t) for t in s.split(self.delim)])
                if len(rows) == self.chunk_rows:
                    yield self._split(off, np.asarray(rows, np.float64))
                    off += len(rows)
                    rows = []
        if rows:
            yield self._split(off, np.asarray(rows, np.float64))

    def _split(self, off: int, raw: np.ndarray) -> Chunk:
        label = raw[:, self.label_col].copy()
        feats = np.delete(raw, self.label_col, axis=1)
        return Chunk(off, feats, label, None)


class ArrowSource(ChunkSource):
    """Arrow/parquet streamed by record batches (optional ``pyarrow``
    dependency; raises a clear ImportError when absent)."""

    def __init__(self, path: str, label: Optional[str] = None,
                 weight: Optional[str] = None,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS) -> None:
        try:
            import pyarrow.parquet as pq
        except ImportError as exc:  # pragma: no cover - env without arrow
            raise ImportError(
                "ArrowSource requires pyarrow; install it or use "
                "NumpyMmapSource/CSVSource") from exc
        self.path = os.fspath(path)
        self.label_name = label
        self.weight_name = weight
        self.chunk_rows = _check_chunk_rows(chunk_rows)
        self._pf = pq.ParquetFile(self.path)
        names = list(self._pf.schema_arrow.names)
        drop = {n for n in (label, weight) if n}
        self._feat_names = [n for n in names if n not in drop]
        self._n = int(self._pf.metadata.num_rows)

    def num_rows(self) -> int:
        return self._n

    def num_features(self) -> int:
        return len(self._feat_names)

    def feature_names(self) -> Optional[List[str]]:
        return list(self._feat_names)

    def chunks(self) -> Iterator[Chunk]:
        off = 0
        cols = self._feat_names + [n for n in (self.label_name,
                                               self.weight_name) if n]
        for batch in self._pf.iter_batches(batch_size=self.chunk_rows,
                                           columns=cols):
            # native arrow->numpy per column (no Python-object churn)
            def col(name):
                return np.asarray(
                    batch.column(name).to_numpy(zero_copy_only=False),
                    np.float64)
            X = np.stack([col(n) for n in self._feat_names], axis=1)
            lab = col(self.label_name) if self.label_name else None
            wgt = col(self.weight_name) if self.weight_name else None
            yield Chunk(off, X, lab, wgt)
            off += X.shape[0]


class SyntheticSource(ChunkSource):
    """Deterministic synthetic generator — the 10^8-row smoke/bench
    source.  Every chunk is a pure function of (seed, chunk index), so
    re-iteration reproduces identical blocks with zero storage."""

    def __init__(self, rows: int, features: int,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS, seed: int = 0,
                 task: str = "binary") -> None:
        if task not in ("binary", "regression"):
            raise ValueError("task must be binary|regression")
        self._n = int(rows)
        self._f = int(features)
        self.chunk_rows = _check_chunk_rows(chunk_rows)
        self.seed = int(seed)
        self.task = task

    def num_rows(self) -> int:
        return self._n

    def num_features(self) -> int:
        return self._f

    def _gen(self, idx: int, m: int) -> Chunk:
        rng = np.random.RandomState((self.seed * 1_000_003 + idx)
                                    % (2 ** 31 - 1))
        X = rng.rand(m, self._f)
        logit = (X[:, 0] - 0.5) * 4.0 + (X[:, 1 % self._f] - 0.5) * 2.0
        noise = rng.randn(m) * 0.5
        if self.task == "binary":
            label = (logit + noise > 0).astype(np.float64)
        else:
            label = logit + noise
        return Chunk(idx * self.chunk_rows, X, label, None)

    def chunks(self) -> Iterator[Chunk]:
        for idx in range(self.num_chunks()):
            lo = idx * self.chunk_rows
            m = min(self.chunk_rows, self._n - lo)
            yield self._gen(idx, m)
