"""Chunked wave grower: leaf-wise growth over host-resident row chunks.

The training half of the out-of-core ingest subsystem (ROADMAP item 2):
the wave grower's full-data histogram passes (learner/wave.py) replicated
one level up the memory hierarchy — host RAM -> HBM instead of HBM ->
VMEM (PR 8's DMA pipeline pattern).  Each wave's (W, F, B, 3) histogram
batch is accumulated **chunk by chunk**: chunk *i+1*'s ``device_put``
(bins + weight lanes) is issued before chunk *i*'s histogram kernel is
consumed, so the host->HBM copy overlaps the accumulation the same way
the Pallas kernels overlap HBM->VMEM DMA with the MXU contraction.  HBM
holds a bounded ring of two chunk buffers plus the wave state — the
``ingest/chunk_pipeline`` MemoryBudget (ingest/stream.py) has no
total-rows term, and ``lint-mem`` checks it.

**Exactness.** The grower mirrors ``learner/wave.py``'s traced wave body
for its supported envelope (numeric non-EFB features, no monotone/
interaction/bynode/extra-trees/CEGB/forced splits, spec ramp and the
exact endgame off — the wave taper handles the tail).  With
``use_quantized_grad=true`` (the numerically sound mode at out-of-core
scale — f32 histogram counts stop being exact past 2^24 rows anyway) and
``stochastic_rounding=false``, every per-(leaf, feature, bin) channel sum
is an exact int32 regardless of accumulation order, so streamed training
is **bit-identical** to an in-core run of the same configuration
(tests/test_ingest_train.py asserts model-text equality).  The f32 path
is supported but chunk-sums f32 histograms, which reassociates the adds —
trees match in structure and to f32 tolerance, not bitwise.

Per-row state (score, grad/hess, row_leaf, bag mask, quantized weight
lanes) lives on the HOST (~20 B/row + the on-disk binned cache); only
per-chunk slices ever enter HBM.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..learner.serial import GrownTree, local_best_candidate
from ..learner.wave import Q_WAVE_SIZE, WAVE_SIZE, wave_taper_k
from ..models.tree import DEFAULT_LEFT_MASK, MISSING_NAN
from ..ops.histogram import build_histogram_leaves
from ..ops.quantize import dequant_scales, quantize_wch
from ..ops.split import BIG, NEG_INF, SplitParams, leaf_output
from ..telemetry.metrics import default_registry
from ..telemetry.trace import span

__all__ = ["ChunkedWaveGrower", "StreamedEnvelopeError"]


class StreamedEnvelopeError(ValueError):
    """The requested configuration is outside the chunked grower's
    envelope; train in-core (``tpu_ingest_mode=hbm``) instead."""


def _dev(x):
    return jax.device_put(x)


class ChunkedWaveGrower:
    """One-tree grower over a chunk store.

    ``chunks`` is any sequence-like with ``num_chunks()``,
    ``binned_chunk(i)`` -> (m, F) uint8 and ``chunk_bounds(i)``; the
    per-row arrays (grad/hess/mask/row_leaf) are host numpy, sliced and
    uploaded per chunk.
    """

    def __init__(self, *, num_leaves: int, num_features: int, max_bins: int,
                 max_depth: int, split_params: SplitParams,
                 num_bins: np.ndarray, has_nan: np.ndarray,
                 hist_impl: str = "segment", quantized: bool = False,
                 gq_max: int = 127, hq_max: int = 127,
                 wave_size: int = 0, interpret: Optional[bool] = None,
                 pipeline: Optional[str] = None) -> None:
        if split_params.any_cat:
            raise StreamedEnvelopeError(
                "chunked streamed training supports numeric features only")
        if max_bins > 255:
            raise StreamedEnvelopeError(
                "chunked streamed training requires max_bin <= 255")
        self.L = int(num_leaves)
        self.F = int(num_features)
        self.B = int(max_bins)
        self.max_depth = int(max_depth)
        self.sp = split_params
        self.quantized = bool(quantized)
        self.gq_max, self.hq_max = int(gq_max), int(hq_max)
        self.hist_impl = hist_impl
        self.pallas = hist_impl == "pallas"
        self.interpret = interpret
        self.pipeline = pipeline
        ch_cap = Q_WAVE_SIZE if quantized else WAVE_SIZE
        self.W = max(1, min(int(wave_size) or ch_cap, ch_cap, self.L - 1))
        self.rl_dtype = np.uint8 if self.L <= 256 else np.int32
        self.num_bins = jnp.asarray(num_bins, jnp.int32)
        self.has_nan = jnp.asarray(has_nan, jnp.bool_)
        self.monotone = jnp.zeros((self.F,), jnp.int32)
        self._head_fn = jax.jit(self._head)
        self._tail_fn = jax.jit(self._tail)
        self._chunk_fn = jax.jit(self._chunk_step)
        self._root_chunk_fn = jax.jit(self._root_chunk)
        self._root_state_fn = jax.jit(self._root_state)
        self.hist_dtype = jnp.int32 if quantized else jnp.float32
        reg = default_registry()
        self._h2d = reg.counter("ingest_train_h2d_bytes_total",
                                "host->HBM bytes streamed by chunked "
                                "training")
        self._passes = reg.counter("ingest_train_hist_passes_total",
                                   "chunk-accumulated full-data histogram "
                                   "passes")

    # -- per-chunk weight lanes ----------------------------------------------
    def _weights(self, grad_c, hess_c, mask_c, scales):
        """Device weight operands for one chunk: quantized int8 lanes,
        the raw triple for the Pallas weight packer, or the f32
        (gm, hm, cnt) triple for the XLA paths — identical elementwise
        math to the in-core grower's."""
        if self.quantized:
            g_scale, h_scale = scales
            return quantize_wch(grad_c, hess_c, mask_c, g_scale, h_scale,
                                jax.random.PRNGKey(0), gq_max=self.gq_max,
                                hq_max=self.hq_max, stochastic=False)
        if self.pallas:
            # pack_weights8 masks internally, exactly like the in-core
            # wave grower's w8 = pack_weights8(grad, hess, bag_mask)
            return grad_c, hess_c, mask_c
        gm = (grad_c * mask_c).astype(jnp.float32)
        hm = (hess_c * mask_c).astype(jnp.float32)
        cnt = (mask_c > 0).astype(jnp.float32)
        return gm, hm, cnt

    def _chunk_hist(self, bins_c, w, ch):
        """One chunk's (W, F, B, 3) channel histograms — exact int32 when
        quantized (chunk accumulation order cannot change the sums)."""
        if self.pallas:
            from ..ops.histogram_pallas import (
                build_histogram_pallas_leaves,
                build_histogram_pallas_leaves_q8, pack_weights8)
            xt = jnp.swapaxes(bins_c, 0, 1).astype(jnp.uint8)
            if self.quantized:
                h = build_histogram_pallas_leaves_q8(
                    xt, w, ch.astype(jnp.int8), num_bins=self.B,
                    interpret=self.interpret, pipeline=self.pipeline)
            else:
                w8 = pack_weights8(w[0], w[1], w[2])   # raw grad/hess/mask
                h = build_histogram_pallas_leaves(
                    xt, w8, ch.astype(jnp.int8), num_bins=self.B,
                    interpret=self.interpret, pipeline=self.pipeline)
            return h[:self.W]
        if self.quantized:
            h = build_histogram_leaves(
                bins_c, w[0].astype(jnp.float32), w[1].astype(jnp.float32),
                w[2].astype(jnp.float32), ch, num_channels=self.W,
                num_bins=self.B, impl=self.hist_impl)
            return jnp.round(h).astype(jnp.int32)
        return build_histogram_leaves(
            bins_c, w[0], w[1], w[2], ch, num_channels=self.W,
            num_bins=self.B, impl=self.hist_impl)

    # -- jitted pieces -------------------------------------------------------
    def _root_chunk(self, acc, acc_sum, bins_c, grad_c, hess_c, mask_c,
                    scales):
        """Root pass over one chunk: accumulate channel-0 histograms and
        (f32 path) the row-reduction root sums."""
        w = self._weights(grad_c, hess_c, mask_c, scales)
        ch = jnp.zeros((bins_c.shape[0],), jnp.int32)
        h = self._chunk_hist(bins_c, w, ch)
        if self.quantized:
            return acc + h[:1], acc_sum
        # f32 root sums from the raw chunk operands (the in-core
        # root_sum's row reductions, chunk-partial)
        gm = (grad_c * mask_c).astype(jnp.float32)
        hm = (hess_c * mask_c).astype(jnp.float32)
        part = jnp.stack([jnp.sum(gm), jnp.sum(hm),
                          jnp.sum((mask_c > 0).astype(jnp.float32))])
        return acc + h[:1], acc_sum + part

    def _root_state(self, root_hist1, root_sum_acc, feature_mask, qscales):
        """Initial wave state from the accumulated root pass — mirrors
        learner/wave.py's non-spec root block."""
        L, B, W = self.L, self.B, self.W
        sp = self.sp
        root_hist = root_hist1[0]
        if self.quantized:
            # root totals from the exact integer histogram (any feature's
            # bins sum to the total), like the in-core quantized root
            root_sum = self._dq(root_hist1[:, 0].sum(axis=1), qscales)[0]
        else:
            root_sum = root_sum_acc
        root_hist_f = self._dq(root_hist, qscales) if self.quantized \
            else root_hist
        root_bound = jnp.asarray([-BIG, BIG], jnp.float32)
        root_out = leaf_output(root_sum[0], root_sum[1], sp)
        cand = local_best_candidate(
            root_hist_f, root_sum, self.num_bins,
            jnp.zeros((self.F,), jnp.bool_), self.has_nan, feature_mask,
            sp, self.monotone, root_bound, jnp.asarray(0, jnp.int32),
            None, None, root_out)
        state = {
            "leaf_sum": jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum),
            "leaf_depth": jnp.zeros((L,), jnp.int32),
            "cand_gain": jnp.full((L,), NEG_INF,
                                  jnp.float32).at[0].set(cand[0]),
            "cand_feat": jnp.zeros((L,), jnp.int32).at[0].set(cand[1]),
            "cand_bin": jnp.zeros((L,), jnp.int32).at[0].set(cand[2]),
            "cand_dleft": jnp.zeros((L,), jnp.bool_).at[0].set(cand[3]),
            "cand_lsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[4]),
            "cand_rsum": jnp.zeros((L, 3), jnp.float32).at[0].set(cand[5]),
            "cand_member": jnp.zeros((L, B), jnp.bool_).at[0].set(cand[6]),
            "hists": jnp.zeros((L, self.F, B, 3),
                               self.hist_dtype).at[0].set(root_hist),
            "split_feature": jnp.full((L - 1,), -1, jnp.int32),
            "threshold_bin": jnp.zeros((L - 1,), jnp.int32),
            "nan_bin": jnp.full((L - 1,), -1, jnp.int32),
            "cat_member": jnp.zeros((L - 1, B), jnp.bool_),
            "decision_type": jnp.zeros((L - 1,), jnp.int32),
            "left_child": jnp.zeros((L - 1,), jnp.int32),
            "right_child": jnp.zeros((L - 1,), jnp.int32),
            "split_gain": jnp.zeros((L - 1,), jnp.float32),
            "internal_value": jnp.zeros((L - 1,), jnp.float32),
            "internal_weight": jnp.zeros((L - 1,), jnp.float32),
            "internal_count": jnp.zeros((L - 1,), jnp.float32),
            "leaf_value": jnp.zeros((L,), jnp.float32).at[0].set(root_out),
            "leaf_weight": jnp.zeros((L,),
                                     jnp.float32).at[0].set(root_sum[1]),
            "leaf_count": jnp.zeros((L,),
                                    jnp.float32).at[0].set(root_sum[2]),
            "num_leaves": jnp.asarray(1, jnp.int32),
            "done": jnp.asarray(False),
            "hist_passes": jnp.asarray(1, jnp.int32),
        }
        return state

    def _dq(self, h, qscales):
        """Quantized int32 channel sums -> f32 (per-tree scales)."""
        return h.astype(jnp.float32) * qscales

    def _head(self, s):
        """Wave-head selection — the top-k + taper block of the in-core
        body, producing the commit table the chunk passes consume."""
        L, W = self.L, self.W
        jarange = jnp.arange(W, dtype=jnp.int32)
        nl0 = s["num_leaves"]
        k_eff = wave_taper_k(L - nl0, W)
        vals, sel_leaves = jax.lax.top_k(s["cand_gain"], W)
        sel = (vals > 0) & (jarange < k_eff)
        feat = s["cand_feat"][sel_leaves]
        thr = s["cand_bin"][sel_leaves]
        dleft = s["cand_dleft"][sel_leaves]
        lsum = s["cand_lsum"][sel_leaves]
        rsum = s["cand_rsum"][sel_leaves]
        member = s["cand_member"][sel_leaves]
        psum_ = s["leaf_sum"][sel_leaves]
        prefix = jnp.cumsum(sel.astype(jnp.int32))
        total_new = prefix[-1]
        new_ids = nl0 + prefix - 1
        node_ids = (nl0 - 1) + prefix - 1
        left_smaller = lsum[:, 2] <= rsum[:, 2]
        fnan = self.has_nan[feat]
        f_nan_bin = jnp.where(fnan, self.num_bins[feat] - 1, -1)
        return {"vals": vals, "sel_leaves": sel_leaves, "sel": sel,
                "feat": feat, "thr": thr, "dleft": dleft, "lsum": lsum,
                "rsum": rsum, "member": member, "psum": psum_,
                "new_ids": new_ids, "node_ids": node_ids,
                "left_smaller": left_smaller, "fnan": fnan,
                "f_nan_bin": f_nan_bin, "total_new": total_new}

    def _chunk_step(self, acc, bins_c, rl_c, grad_c, hess_c, mask_c,
                    head, scales):
        """One chunk of one wave: the fused row-update + smaller-child
        histogram accumulation (the in-core body's row_leaf/ch update and
        ``hist_waves(ch)``, restricted to this chunk's rows, with the
        accumulator carried across chunks)."""
        w = self._weights(grad_c, hess_c, mask_c, scales)
        sel, feat = head["sel"], head["feat"]
        thr, dleft = head["thr"], head["dleft"]
        f_nan_bin = head["f_nan_bin"]
        left_smaller = head["left_smaller"]
        sel_leaves, new_ids = head["sel_leaves"], head["new_ids"]
        if self.pallas:
            from ..ops.histogram_pallas import wave_row_update_pallas
            xt = jnp.swapaxes(bins_c, 0, 1).astype(jnp.uint8)
            cols_w = jnp.take(xt, feat, axis=0)
            tab = jnp.stack([
                thr, f_nan_bin, dleft.astype(jnp.int32),
                left_smaller.astype(jnp.int32), sel_leaves, new_ids,
                sel.astype(jnp.int32), jnp.zeros_like(thr)])
            rl_new, ch = wave_row_update_pallas(
                cols_w, rl_c, tab, interpret=self.interpret,
                pipeline=self.pipeline)
            rl_new = rl_new.astype(rl_c.dtype)
        else:
            # the in-core body's vectorized XLA row update (_upd_block),
            # restricted to numeric non-EFB shapes — elementwise per row,
            # so per-chunk evaluation is bit-identical to the full pass
            xt = jnp.swapaxes(bins_c, 0, 1)
            cols_w = jnp.take(xt, feat, axis=0)            # (W, m)
            thr_c = thr.astype(bins_c.dtype)[:, None]
            nan_c = jnp.where(f_nan_bin < 0, 255,
                              f_nan_bin).astype(bins_c.dtype)[:, None]
            sel_c = sel_leaves.astype(rl_c.dtype)
            num_go = jnp.where(cols_w == nan_c, dleft[:, None],
                               cols_w <= thr_c)
            match = sel[:, None] & (rl_c[None, :] == sel_c[:, None])
            has = jnp.any(match, axis=0)
            jhit = jnp.argmax(match, axis=0)
            go = jnp.take_along_axis(num_go, jhit[None, :], axis=0)[0]
            ch = jnp.where(has & (go == left_smaller[jhit]),
                           jhit.astype(jnp.int8), jnp.int8(-1))
            rl_new = jnp.where(has & jnp.logical_not(go),
                               new_ids[jhit].astype(rl_c.dtype), rl_c)
        h = self._chunk_hist(bins_c, w, ch)
        return acc + h, rl_new

    def _tail(self, s, head, hist_small, feature_mask, qscales):
        """Post-accumulation half of the in-core wave body: subtraction,
        children candidate scans, state scatter + node records."""
        L, W, F, B = self.L, self.W, self.F, self.B
        sp = self.sp
        sel, sel_leaves = head["sel"], head["sel_leaves"]
        feat, thr, dleft = head["feat"], head["thr"], head["dleft"]
        lsum, rsum, psum_ = head["lsum"], head["rsum"], head["psum"]
        member = head["member"]
        new_ids, node_ids = head["new_ids"], head["node_ids"]
        left_smaller = head["left_smaller"]
        fnan, f_nan_bin = head["fnan"], head["f_nan_bin"]
        vals, total_new = head["vals"], head["total_new"]
        nl0 = s["num_leaves"]

        parents = s["hists"][sel_leaves]
        hist_big = parents - hist_small
        ls4 = left_smaller[:, None, None, None]
        hist_l = jnp.where(ls4, hist_small, hist_big)
        hist_r = jnp.where(ls4, hist_big, hist_small)

        out_l = leaf_output(lsum[:, 0], lsum[:, 1], sp)
        out_r = leaf_output(rsum[:, 0], rsum[:, 1], sp)

        child_depth = s["leaf_depth"][sel_leaves] + 1
        hists2 = jnp.concatenate([hist_l, hist_r])
        sums2 = jnp.concatenate([lsum, rsum])
        hf2 = self._dq(hists2, qscales) if self.quantized else hists2
        depth2 = jnp.concatenate([child_depth, child_depth])
        lv2 = jnp.concatenate([out_l, out_r])
        fm2 = jnp.broadcast_to(feature_mask, (2 * W, F))
        ic = jnp.zeros((F,), jnp.bool_)

        # monotone bounds stay None: use_mc is statically outside the
        # chunked envelope (the in-core body passes None there too)
        def one(h, s_, d, po, fm_):
            return local_best_candidate(
                h, s_, self.num_bins, ic, self.has_nan, fm_, sp,
                self.monotone, None, d, None, None, po)

        cands = jax.vmap(one)(hf2, sums2, depth2, lv2, fm2)
        depth_ok = jnp.logical_or(self.max_depth <= 0,
                                  child_depth < self.max_depth)
        dok2 = jnp.concatenate([depth_ok, depth_ok])
        cg = jnp.where(dok2 & jnp.concatenate([sel, sel]), cands[0],
                       NEG_INF)

        idx_l = jnp.where(sel, sel_leaves, L)
        idx_r = jnp.where(sel, new_ids, L)
        idx2 = jnp.concatenate([idx_l, idx_r])

        def sc2(arr, val2):
            return arr.at[idx2].set(val2, mode="drop")

        out = dict(s)
        out["hists"] = s["hists"].at[idx_l].set(
            hist_l, mode="drop").at[idx_r].set(hist_r, mode="drop")
        out["leaf_sum"] = sc2(s["leaf_sum"], sums2)
        out["leaf_depth"] = sc2(s["leaf_depth"], depth2)
        out["cand_gain"] = sc2(s["cand_gain"], cg)
        out["cand_feat"] = sc2(s["cand_feat"], cands[1])
        out["cand_bin"] = sc2(s["cand_bin"], cands[2])
        out["cand_dleft"] = sc2(s["cand_dleft"], cands[3])
        out["cand_lsum"] = sc2(s["cand_lsum"], cands[4])
        out["cand_rsum"] = sc2(s["cand_rsum"], cands[5])
        out["cand_member"] = sc2(s["cand_member"], cands[6])
        out["leaf_value"] = sc2(s["leaf_value"], lv2)
        out["leaf_weight"] = sc2(s["leaf_weight"], sums2[:, 1])
        out["leaf_count"] = sc2(s["leaf_count"], sums2[:, 2])

        nidx = jnp.where(sel, node_ids, L - 1)
        dt_bits = (jnp.where(dleft, DEFAULT_LEFT_MASK, 0) |
                   jnp.where(fnan, MISSING_NAN, 0)).astype(jnp.int32)

        def scn(arr, val):
            return arr.at[nidx].set(val, mode="drop")

        out["split_feature"] = scn(s["split_feature"], feat)
        out["threshold_bin"] = scn(s["threshold_bin"], thr)
        out["nan_bin"] = scn(s["nan_bin"], f_nan_bin)
        out["cat_member"] = scn(s["cat_member"], member)
        out["decision_type"] = scn(s["decision_type"], dt_bits)
        out["split_gain"] = scn(s["split_gain"], vals)
        out["internal_value"] = scn(
            s["internal_value"], leaf_output(psum_[:, 0], psum_[:, 1], sp))
        out["internal_weight"] = scn(s["internal_weight"], psum_[:, 1])
        out["internal_count"] = scn(s["internal_count"], psum_[:, 2])

        enc = -(sel_leaves + 1)
        for name in ("left_child", "right_child"):
            arr = s[name]
            match = (arr[:, None] == enc[None, :]) & sel[None, :]
            has = jnp.any(match, axis=1)
            pick = jnp.argmax(match, axis=1)
            arr = jnp.where(has, node_ids[pick], arr)
            if name == "left_child":
                arr = arr.at[nidx].set(enc, mode="drop")
            else:
                arr = arr.at[nidx].set(-(new_ids + 1), mode="drop")
            out[name] = arr

        out["num_leaves"] = nl0 + total_new
        out["done"] = total_new == 0
        out["hist_passes"] = s["hist_passes"] + 1
        return out

    # -- host-driven tree growth ---------------------------------------------
    def grow(self, store, grad: np.ndarray, hess: np.ndarray,
             mask: np.ndarray, feature_mask: Optional[np.ndarray] = None
             ) -> tuple:
        """Grow one tree.  Returns (host GrownTree, per-chunk row_leaf
        list).  ``store`` is a StreamedDataset (or equivalent)."""
        nc = store.num_chunks()
        fm = jnp.asarray(feature_mask if feature_mask is not None
                         else np.ones(self.F, bool))
        pad_to = store.chunk_rows

        def chunk_arrays(i):
            lo, hi = store.chunk_bounds(i)
            m = hi - lo
            bins = np.asarray(store.binned_chunk(i))
            g = grad[lo:hi].astype(np.float32)
            h = hess[lo:hi].astype(np.float32)
            mk = mask[lo:hi].astype(np.float32)
            if m < pad_to:
                # the last short block pads to the fixed chunk shape (one
                # compiled program per config); padded rows carry zero
                # weight lanes and cannot touch the histograms
                pad = pad_to - m
                bins = np.pad(bins, ((0, pad), (0, 0)))
                g = np.pad(g, (0, pad))
                h = np.pad(h, (0, pad))
                mk = np.pad(mk, (0, pad))
            self._h2d.inc(bins.nbytes + g.nbytes + h.nbytes + mk.nbytes)
            return (_dev(bins), _dev(g), _dev(h), _dev(mk)), m

        def prefetched():
            """Double-buffered chunk upload: issue chunk i+1's
            device_put before chunk i is consumed."""
            nxt = chunk_arrays(0)
            for i in range(nc):
                cur = nxt
                if i + 1 < nc:
                    nxt = chunk_arrays(i + 1)
                yield i, cur

        # ---- quantized scales: one streaming host max pass --------------
        # max is exact under any chunking, and numpy's f32 multiply is
        # the same IEEE op the in-core jnp.max(|grad*mask|) reduces over,
        # so the derived scales match the in-core tree's bit for bit.
        if self.quantized:
            gmax = 0.0
            hmax = 0.0
            for i in range(nc):
                lo, hi = store.chunk_bounds(i)
                g32 = grad[lo:hi].astype(np.float32, copy=False)
                h32 = hess[lo:hi].astype(np.float32, copy=False)
                m32 = mask[lo:hi].astype(np.float32, copy=False)
                gmax = max(gmax, float(np.max(np.abs(g32 * m32))))
                hmax = max(hmax, float(np.max(h32 * m32)))
            g_scale = jnp.maximum(jnp.float32(gmax),
                                  jnp.float32(1e-30)) / self.gq_max
            h_scale = jnp.maximum(jnp.float32(hmax),
                                  jnp.float32(1e-30)) / self.hq_max
            scales = (g_scale, h_scale)
            qscales = dequant_scales(g_scale, h_scale)
        else:
            scales = (jnp.float32(1.0), jnp.float32(1.0))
            qscales = jnp.ones((3,), jnp.float32)

        # ---- root pass --------------------------------------------------
        with span("ingest/train/root_pass"):
            acc = jnp.zeros((1, self.F, self.B, 3), self.hist_dtype)
            acc_sum = jnp.zeros((3,), jnp.float32)
            for _, ((b, g, h, mk), _m) in prefetched():
                acc, acc_sum = self._root_chunk_fn(acc, acc_sum, b, g, h,
                                                   mk, scales)
            state = self._root_state_fn(acc, acc_sum, fm, qscales)
        self._passes.inc()

        rl_chunks: List[np.ndarray] = [
            np.zeros(store.chunk_bounds(i)[1] - store.chunk_bounds(i)[0],
                     self.rl_dtype) for i in range(nc)]

        # ---- wave loop --------------------------------------------------
        while True:
            done = bool(jax.device_get(state["done"]))
            nl = int(jax.device_get(state["num_leaves"]))
            if done or nl >= self.L:
                break
            head = self._head_fn(state)
            with span("ingest/train/wave_pass"):
                acc = jnp.zeros((self.W, self.F, self.B, 3),
                                self.hist_dtype)
                for i, ((b, g, h, mk), m) in prefetched():
                    rl_c = rl_chunks[i]
                    if len(rl_c) < pad_to:
                        rl_c = np.pad(rl_c, (0, pad_to - len(rl_c)))
                    self._h2d.inc(rl_c.nbytes)   # the row_leaf ring leg
                    acc, rl_new = self._chunk_fn(acc, b, _dev(rl_c), g, h,
                                                 mk, head, scales)
                    rl_chunks[i] = np.asarray(rl_new)[:m]
            state = self._tail_fn(state, head, acc, fm, qscales)
            self._passes.inc()

        host = jax.device_get(state)
        grown = GrownTree(
            split_feature=host["split_feature"],
            threshold_bin=host["threshold_bin"],
            nan_bin=host["nan_bin"], cat_member=host["cat_member"],
            decision_type=host["decision_type"],
            left_child=host["left_child"],
            right_child=host["right_child"],
            split_gain=host["split_gain"],
            internal_value=host["internal_value"],
            internal_weight=host["internal_weight"],
            internal_count=host["internal_count"],
            leaf_value=host["leaf_value"],
            leaf_weight=host["leaf_weight"],
            leaf_count=host["leaf_count"],
            num_leaves=host["num_leaves"],
            row_leaf=np.zeros((0,), np.int32),
            hist_passes=host["hist_passes"])
        return grown, rl_chunks
