"""Out-of-core ingestion subsystem: streaming sketch binning + chunked
host->HBM training toward 10^9 rows (ROADMAP item 2; reference
``pipeline_reader.h`` streaming ingest + sampled bin finding, PAPER.md
layers 0/3; XGBoost external-memory + gradient-based sampling,
arXiv:1806.11248).

Layers:

* :mod:`.source` — ``ChunkSource`` row-block iterators (mmap ``.npy``,
  CSV/TSV, optional Arrow/parquet, deterministic synthetic);
* :mod:`.sketch` — one-pass mergeable per-feature summaries producing
  BinMappers bit-identical to in-core construction;
* :mod:`.stream` — ``StreamedDataset``: two streaming passes into an
  on-disk binned cache, full Dataset API on top;
* :mod:`.grower` / :mod:`.train` — chunk-accumulated wave training with
  a rows-independent HBM budget (``tpu_ingest_mode=chunked``).
"""

from .source import (ArraySource, ArrowSource, Chunk, ChunkSource,
                     CSVSource, DEFAULT_CHUNK_ROWS, NumpyMmapSource,
                     SyntheticSource)
from .sketch import BinningSketch, sample_row_indices
from .stream import StreamedDataset
from .grower import ChunkedWaveGrower, StreamedEnvelopeError
from .train import train_streamed

__all__ = [
    "ArraySource", "ArrowSource", "Chunk", "ChunkSource", "CSVSource",
    "DEFAULT_CHUNK_ROWS", "NumpyMmapSource", "SyntheticSource",
    "BinningSketch", "sample_row_indices", "StreamedDataset",
    "ChunkedWaveGrower", "StreamedEnvelopeError", "train_streamed",
]
