"""Chunked streamed training driver: boosting over a StreamedDataset.

Drives :class:`..ingest.grower.ChunkedWaveGrower` through the boosting
loop with every per-row array host-resident (score, gradients, bag mask,
per-chunk ``row_leaf``) — HBM holds only the bounded chunk ring plus the
wave state, so total rows are limited by disk + host RAM at ~20 B/row,
not by accelerator memory (ROADMAP item 2's 10^8-10^9-row regime).

Envelope (checked, typed errors): numeric features, objective ``regression``
or ``binary``, boosting ``gbdt``/``goss``, single class, no monotone/
interaction/forced-split/CEGB/linear-tree extras; ``stochastic_rounding``
and ``quant_train_renew_leaf`` are forced off (both need full-row device
passes).  Everything else — including bagging, ``feature_fraction``,
quantized gradients and boost-from-average — matches the in-core
trainer's host-side sampling streams exactly.  With
``use_quantized_grad=true`` the produced model text is bit-identical to
an in-core ``engine.train`` run of the same configuration
(tests/test_ingest_train.py).

GOSS (arXiv:1806.11248's gradient-based sampling recipe for the
out-of-core tail): with ``boosting=goss`` the per-tree bag keeps the
top-``top_rate`` rows by |grad*hess| plus a Bernoulli ``other_rate``
sample of the rest (amplified by (1-a)/b), computed host-side over the
streamed gradient array — the thinned rows then skip every chunk's
histogram work for that tree.

Checkpoint/resume rides the PR-6 bundle format
(:mod:`..resilience.checkpoint`): the bundle's dataset fingerprint is the
StreamedDataset's streamed crc, so a resume against re-streamed chunks
validates end-to-end, and the continuation is bit-identical on the
quantized path.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..basic import Booster
from ..config import Config
from ..learner.serial import (resolve_hist_impl, split_params_from_config)
from ..models.gbdt import (EPSILON, GBDT, _grown_to_tree, bagging_mask_np,
                           feature_mask_np)
from ..objective import create_objective
from ..objective.binary import BinaryLogloss
from ..objective.regression import RegressionL2
from ..ops.quantize import quant_levels
from ..resilience.checkpoint import (CKPT_SOFT_KEYS, CKPT_STRUCTURAL_KEYS,
                                     Checkpoint, CheckpointManager,
                                     load_checkpoint)
from ..telemetry.trace import span
from ..utils.log import log_info, log_warning
from ..utils.random import host_rng, rng_checkpoint_state
from .grower import ChunkedWaveGrower, StreamedEnvelopeError
from .stream import StreamedDataset

__all__ = ["train_streamed", "StreamedEnvelopeError"]


def _check_envelope(cfg: Config) -> None:
    bad = []
    if cfg.num_class > 1:
        bad.append("num_class>1")
    if cfg.boosting not in ("gbdt", "goss"):
        bad.append(f"boosting={cfg.boosting}")
    if cfg.linear_tree:
        bad.append("linear_tree")
    if cfg.monotone_constraints and \
            any(int(v) != 0 for v in cfg.monotone_constraints):
        bad.append("monotone_constraints")
    if cfg.interaction_constraints:
        bad.append("interaction_constraints")
    if cfg.forcedsplits_filename:
        bad.append("forcedsplits_filename")
    if cfg.cegb_penalty_split > 0 or cfg.cegb_penalty_feature_coupled or \
            cfg.cegb_penalty_feature_lazy:
        bad.append("cegb penalties")
    if cfg.feature_fraction_bynode < 1.0:
        bad.append("feature_fraction_bynode")
    if cfg.extra_trees:
        bad.append("extra_trees")
    if cfg.path_smooth > 0:
        bad.append("path_smooth")
    if bad:
        raise StreamedEnvelopeError(
            "chunked streamed training (tpu_ingest_mode=chunked) does not "
            "support: " + ", ".join(bad) + "; train with "
            "tpu_ingest_mode=hbm (in-core from the streamed binned cache) "
            "instead")


def _host_objective(cfg: Config, label: Optional[np.ndarray],
                    weight: Optional[np.ndarray], n: int):
    """Objective with HOST-resident label/weight (no O(N) device copy).
    Mirrors ``ObjectiveFunction.init`` minus the device upload; the
    gradient formulas themselves run per chunk."""
    obj = create_objective(cfg.objective, cfg)
    ok = (type(obj) is BinaryLogloss or
          (type(obj) is RegressionL2 and not obj.sqrt))
    if not ok:
        raise StreamedEnvelopeError(
            f"chunked streamed training supports objective=regression|"
            f"binary (got {cfg.objective}); use tpu_ingest_mode=hbm")
    if label is None:
        raise ValueError(f"objective {obj.name} requires labels")
    label = np.asarray(label, np.float32)
    obj.check_label(label)
    obj.label = label
    obj.weight = None if weight is None else np.asarray(weight, np.float32)
    obj.num_data = n
    if type(obj) is BinaryLogloss:
        # the class-weight computation of BinaryLogloss.init, host-side
        cnt_pos = float((label > 0).sum())
        cnt_neg = float((label <= 0).sum())
        w0 = w1 = 1.0
        if obj.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w0 = cnt_pos / cnt_neg
            else:
                w1 = cnt_neg / cnt_pos
        w1 *= obj.scale_pos_weight
        obj.label_weight = (w0, w1)
    return obj


def _chunk_gradients(obj, score_c: np.ndarray, label_c: np.ndarray,
                     weight_c: Optional[np.ndarray]):
    """One chunk's gradients through the objective's own formula —
    elementwise per row, so per-chunk evaluation is bit-identical to the
    in-core full-array call."""
    import jax.numpy as jnp
    saved = (obj.label, obj.weight)
    try:
        obj.label = jnp.asarray(label_c, jnp.float32)
        obj.weight = None if weight_c is None else \
            jnp.asarray(weight_c, jnp.float32)
        g, h = obj.get_gradients(jnp.asarray(score_c, jnp.float32))
        return np.asarray(g), np.asarray(h)
    finally:
        obj.label, obj.weight = saved


def _goss_mult_np(grad: np.ndarray, hess: np.ndarray, top_rate: float,
                  other_rate: float, seed: int, iteration: int):
    """Host GOSS draw (goss.hpp:103-152 semantics, mirroring the in-core
    device GOSS in models/boosting.py): the rest rows sample at
    ``b/(1-a)`` so ~``b*n`` of them survive, and the ``(1-a)/b``
    amplification keeps their expected gradient mass unbiased.  Returns
    (mask, multiplier) or None when sampling keeps everything."""
    n = len(grad)
    a, b = float(top_rate), float(other_rate)
    if a + b >= 1.0:
        return None
    score = np.abs(grad * hess)
    k = max(1, int(n * a))
    thr = np.partition(score, n - k)[n - k]
    top = score >= thr
    rng = host_rng(seed, iteration)
    rest_p = b / max(1.0 - a, 1e-12)
    keep_rest = (~top) & (rng.random(n) < rest_p)
    amp = (1.0 - a) / max(b, 1e-12)
    mask = (top | keep_rest).astype(np.float32)
    mult = np.where(keep_rest, np.float32(amp),
                    np.float32(1.0)).astype(np.float32)
    return mask, mult


def _glue_gbdt(cfg: Config, train_set: StreamedDataset, obj,
               trees: List[Any]) -> GBDT:
    """A host-only GBDT shell carrying the streamed-trained model (for
    model_to_string / Booster surfaces; no device state)."""
    g = GBDT(cfg, None, objective=obj)
    g.train_set = train_set
    g.num_data = train_set.num_data()
    g.num_features = train_set.num_feature()
    g.num_tree_per_iteration = 1
    g.models = list(trees)
    g.iter_ = len(trees)
    return g


def train_streamed(params: Dict[str, Any], train_set: StreamedDataset,
                   num_boost_round: int = 100,
                   resume_from: Optional[str] = None) -> Booster:
    """Boost ``num_boost_round`` trees over a StreamedDataset with
    chunk-accumulated histograms; returns a Booster."""
    cfg = Config(dict(params))
    _check_envelope(cfg)
    if cfg.use_quantized_grad and cfg.stochastic_rounding:
        log_warning("chunked streamed training forces "
                    "stochastic_rounding=false (the per-row rounding "
                    "stream is not chunk-sliceable)")
        cfg.stochastic_rounding = False
    if cfg.use_quantized_grad and cfg.quant_train_renew_leaf:
        log_warning("chunked streamed training forces "
                    "quant_train_renew_leaf=false")
        cfg.quant_train_renew_leaf = False
    train_set.construct(cfg)
    n = train_set.num_data()
    f_used = train_set.num_feature()
    mappers = [train_set.bin_mappers[j] for j in train_set.used_feature_map]
    from ..binning import MissingType
    num_bins = np.array([m.num_bin for m in mappers], np.int32)
    is_cat = np.array([m.is_categorical for m in mappers], bool)
    has_nan = np.array([m.missing_type == MissingType.NAN for m in mappers],
                       bool)
    if np.any(is_cat):
        raise StreamedEnvelopeError(
            "chunked streamed training supports numeric features only; "
            "use tpu_ingest_mode=hbm for categorical data")
    max_bins = int(num_bins.max())
    if cfg.use_quantized_grad:
        # the int32 channel-sum exactness bound GBDT._init_train warns
        # about (single shard here): past it the quantized accumulator
        # can wrap and the chunked==in-core contract is void
        _gq = max(quant_levels(int(cfg.num_grad_quant_bins)))
        if n > (1 << 31) // _gq:
            log_warning(
                f"num_data={n} exceeds the quantized histogram's int32 "
                f"channel-sum exactness bound (2^31/{_gq} rows at "
                f"num_grad_quant_bins={cfg.num_grad_quant_bins}); lower "
                f"num_grad_quant_bins or shard rows across more devices")
    elif n > (1 << 24):
        log_warning(f"num_data={n} exceeds the f32 histogram count "
                    "channel's 16.7M-row exactness range; set "
                    "use_quantized_grad=true for exact int32 counts (and "
                    "the chunked bit-identity contract) at this scale")
    impl = resolve_hist_impl(cfg, wave=True, max_bins=max_bins)
    if impl == "packed4":
        impl = "segment"   # no leaf-channel form (ops/histogram.py)
    if impl == "pallas":
        from ..ops.histogram_pallas import DEFAULT_ROW_BLOCK
        if train_set.chunk_rows % DEFAULT_ROW_BLOCK:
            log_warning(f"chunk_rows={train_set.chunk_rows} is not a "
                        f"multiple of the Pallas row block "
                        f"({DEFAULT_ROW_BLOCK}); using the XLA onehot "
                        f"histogram path")
            impl = "onehot"
    sp = split_params_from_config(cfg, num_bins, is_cat)
    gq_max, hq_max = quant_levels(int(cfg.num_grad_quant_bins))
    grower = ChunkedWaveGrower(
        num_leaves=int(cfg.num_leaves), num_features=f_used,
        max_bins=max_bins, max_depth=int(cfg.max_depth), split_params=sp,
        num_bins=num_bins, has_nan=has_nan, hist_impl=impl,
        quantized=bool(cfg.use_quantized_grad), gq_max=gq_max,
        hq_max=hq_max, wave_size=int(cfg.tpu_wave_size),
        interpret=None, pipeline=(None if cfg.tpu_pallas_pipeline == "auto"
                                  else str(cfg.tpu_pallas_pipeline)))

    md = train_set.metadata
    obj = _host_objective(cfg, md.label, md.weight, n)
    label32 = obj.label
    weight32 = obj.weight

    # ---- initial scores (GBDT._init_train's score0 logic) -----------------
    score = np.zeros(n, np.float32)
    pending_bias = 0.0
    if md.init_score is not None:
        score += md.init_score.reshape(n).astype(np.float32)
    elif cfg.boost_from_average:
        pending_bias = obj.boost_from_score(0)
        if abs(pending_bias) > EPSILON:
            log_info(f"Start training from score {pending_bias:.6f}")
        score += np.float32(pending_bias)

    # ---- checkpoint / resume ----------------------------------------------
    ckpt_dir = str(cfg.checkpoint_dir or "")
    if not ckpt_dir and int(cfg.snapshot_freq) > 0:
        ckpt_dir = f"{cfg.output_model}.ckpt"
    manager = CheckpointManager(ckpt_dir, int(cfg.checkpoint_keep)) \
        if ckpt_dir else None
    freq = int(cfg.snapshot_freq) if int(cfg.snapshot_freq) > 0 else \
        max(1, num_boost_round // 100)
    if resume_from is None and str(cfg.resume).strip():
        want = str(cfg.resume).strip()
        if want in ("latest", "auto"):
            resume_from = manager.latest_path() if manager else None
            if resume_from is None and not manager:
                raise ValueError("resume=latest needs snapshot_freq>0 or "
                                 "checkpoint_dir")
        else:
            resume_from = want
    trees: List[Any] = []
    start_iter = 0
    if resume_from:
        ckpt = load_checkpoint(str(resume_from))
        ckpt.validate_dataset(train_set)
        ckpt.validate_config(cfg)
        from ..models.model_text import string_to_model
        loaded = string_to_model(ckpt.model_text, cfg)
        trees = list(loaded.models)
        start_iter = int(ckpt.iteration)
        score = np.asarray(ckpt.score, np.float32).reshape(n).copy()
        log_info(f"train_streamed: resumed at iteration {start_iter} "
                 f"from {resume_from}")

    def _save_ckpt(it: int) -> None:
        if manager is None:
            return
        text = _glue_gbdt(cfg, train_set, obj, trees) \
            .save_model_to_string()
        manager.save(Checkpoint(
            iteration=it, model_text=text, score=score.copy(),
            rng_state=rng_checkpoint_state(cfg),
            fingerprint=train_set.fingerprint(),
            params={k: getattr(cfg, k)
                    for k in CKPT_STRUCTURAL_KEYS + CKPT_SOFT_KEYS}))

    # ---- flight recorder (telemetry/flight.py) ----------------------------
    # the chunked path is the one where the per-event h2d byte counter
    # actually moves; the tape dumps next to the checkpoints on a crash
    from ..telemetry.flight import FlightRecorder
    flight = FlightRecorder(
        capacity=int(cfg.flight_events), enabled=bool(cfg.flight_recorder),
        meta={"boosting": str(cfg.boosting), "objective": str(cfg.objective),
              "num_data": int(n), "ingest_mode": "chunked"})

    def _flight_dump(reason: str) -> None:
        out_dir = str(cfg.flight_dir) or ckpt_dir
        if not flight.enabled or len(flight) == 0 or not out_dir:
            return
        try:
            os.makedirs(out_dir, exist_ok=True)
            flight.dump(os.path.join(out_dir, "flight.jsonl"),
                        reason=reason)
        except OSError as exc:
            log_warning(f"flight recorder dump failed: {exc}")

    # ---- boosting loop -----------------------------------------------------
    shrinkage = float(cfg.learning_rate)
    goss = cfg.boosting == "goss"
    if goss and cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0:
        # in-core GOSS ignores bagging too (models/boosting.py GOSS)
        log_warning("cannot use bagging in GOSS (ignored)")
    warmup = int(1.0 / max(float(cfg.learning_rate), 1e-12))
    grad = np.empty(n, np.float32)
    hess = np.empty(n, np.float32)
    completed = start_iter

    def _one_iter(it: int) -> bool:
        """One streamed boosting iteration; True = stop (no more
        splittable leaves)."""
        nonlocal completed, grad, hess
        for i in range(train_set.num_chunks()):
            lo, hi = train_set.chunk_bounds(i)
            g, h = _chunk_gradients(
                obj, score[lo:hi], label32[lo:hi],
                None if weight32 is None else weight32[lo:hi])
            grad[lo:hi] = g
            hess[lo:hi] = h
        if goss:
            # GOSS replaces bagging (in-core GOSS overrides
            # _prepare_iter_sampling and never draws a bag)
            mask = np.ones(n, np.float32)
            if it >= warmup:
                gm = _goss_mult_np(grad, hess, float(cfg.top_rate),
                                   float(cfg.other_rate),
                                   int(cfg.bagging_seed), it)
                if gm is not None:
                    mask, mult = gm
                    grad = grad * mult
                    hess = hess * mult
        else:
            mask = bagging_mask_np(
                cfg, n, it,
                label=(np.asarray(label32) if cfg.objective == "binary"
                       else None))
            mask = np.ones(n, np.float32) if mask is None else mask
        fmask = feature_mask_np(cfg, f_used, it)
        grown, rl_chunks = grower.grow(train_set, grad, hess, mask,
                                       feature_mask=fmask)
        nl = int(grown.num_leaves)
        if nl <= 1 and trees:
            log_warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            return True
        tree = _grown_to_tree(grown, shrinkage, train_set)
        bias = pending_bias if it == start_iter and not trees else 0.0
        if abs(bias) > EPSILON:
            tree.add_bias(bias)
        trees.append(tree)
        # score update: the in-core _update_score_impl's
        # score + lv[row_leaf], per chunk, host f32 (same IEEE ops)
        lv = (np.asarray(grown.leaf_value, np.float32) *
              np.float32(shrinkage))
        for i, rl_c in enumerate(rl_chunks):
            lo, hi = train_set.chunk_bounds(i)
            score[lo:hi] = score[lo:hi] + lv[rl_c.astype(np.int64)]
        completed = it + 1
        flight.note_iter(completed, num_leaves=nl)
        if nl <= 1:
            log_warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            return True
        if manager is not None and completed % freq == 0:
            _save_ckpt(completed)
        return False

    try:
        for it in range(start_iter, num_boost_round):
            with span("ingest/train/iteration"):
                if _one_iter(it):
                    break
    except (Exception, KeyboardInterrupt):
        _flight_dump("crash")
        raise
    if manager is not None:
        _save_ckpt(completed)
    if str(cfg.flight_dir):
        _flight_dump("completed")

    gbdt = _glue_gbdt(cfg, train_set, obj, trees)
    bst = Booster.__new__(Booster)
    bst.params = dict(params)
    bst.best_iteration = -1
    bst.best_score = {}
    bst._train_data_name = "training"
    bst.config = cfg
    bst._gbdt = gbdt
    return bst
