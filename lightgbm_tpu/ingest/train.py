"""Chunked streamed training driver: boosting over a StreamedDataset.

Drives :class:`..ingest.grower.ChunkedWaveGrower` through the boosting
loop with every per-row array host-resident (score, gradients, bag mask,
per-chunk ``row_leaf``) — HBM holds only the bounded chunk ring plus the
wave state, so total rows are limited by disk + host RAM at ~20 B/row,
not by accelerator memory (ROADMAP item 2's 10^8-10^9-row regime).

Envelope (checked, typed errors): numeric features, objective
``regression``/``binary``/``multiclass`` (softmax), boosting
``gbdt``/``goss``/``dart``, no monotone/interaction/forced-split/CEGB/
linear-tree extras; ``stochastic_rounding`` and
``quant_train_renew_leaf`` are forced off (both need full-row device
passes).  Everything else — bagging, ``feature_fraction``, quantized
gradients, boost-from-average — matches the in-core trainer's host-side
sampling streams exactly.  With ``use_quantized_grad=true`` the produced
model text is bit-identical to an in-core ``engine.train`` run of the
same configuration (tests/test_ingest_train.py).

GOSS (arXiv:1806.11248's gradient-based sampling recipe for the
out-of-core tail): the per-tree bag rides the SHARED host sampler
(``models.gbdt.goss_sample_np`` — one Philox stream per
(bagging_seed, iteration) across the standalone, chunked and
multi-model trainers), so the streamed run thins exactly the rows the
in-core run thins, warmup included.

DART replays the in-core drop bookkeeping (models/boosting.py DART)
host-side: the per-iteration drop set comes from the same
(drop_seed, iteration) stream, each iteration's raw base predictions
stay as host f32 arrays (~4·iters bytes/row of host RAM — the chunked
regime's resource — mirroring the in-core device cache), and the
drop-subtraction / Normalize re-weighting run as host f32 axpys, the
same IEEE ops the in-core device path executes.  DART does not compose
with checkpoint/resume (the per-tree drop weights are not
reconstructible from model text).

Multiclass softmax grows ``num_class`` trees per iteration from one
per-chunk softmax gradient pass over the host (N, K) score matrix; the
one-hot label matrix stays host-resident and uploads chunk slices per
gradient call.  Ranking objectives stay in-core only: their query
segments straddle chunk boundaries, so per-chunk gradients cannot
reproduce the full-dataset lambdarank pass.

Validation + early stopping: ``valid_sets`` may be StreamedDatasets
(binned against the train set's mappers via ``reference``) or in-core
Datasets.  Each grown tree is walked over the valid set's binned chunks
(the in-core ``_record_tree`` valid update, one bounded chunk at a
time) into a host f32 score; metric eval and the ``early_stopping``
callback then see the same float32 values as the in-core run, so the
stop round matches.

Checkpoint/resume rides the PR-6 bundle format
(:mod:`..resilience.checkpoint`): the bundle's dataset fingerprint is the
StreamedDataset's streamed crc, so a resume against re-streamed chunks
validates end-to-end, and the continuation is bit-identical on the
quantized path.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..basic import Booster
from ..callback import CallbackEnv, EarlyStopException, early_stopping
from ..config import Config
from ..learner.serial import (resolve_hist_impl, split_params_from_config)
from ..metric import create_metrics
from ..models.gbdt import (EPSILON, GBDT, _grown_to_tree, _mappers_equal,
                           _tree_cat_member, bagging_mask_np,
                           feature_mask_np, goss_sample_np, make_walk_fn)
from ..objective import create_objective
from ..objective.binary import BinaryLogloss
from ..objective.multiclass import MulticlassSoftmax
from ..objective.regression import RegressionL2
from ..ops.quantize import quant_levels
from ..resilience.checkpoint import (CKPT_SOFT_KEYS, CKPT_STRUCTURAL_KEYS,
                                     Checkpoint, CheckpointManager,
                                     load_checkpoint)
from ..telemetry.trace import span
from ..utils.log import log_info, log_warning
from ..utils.random import host_rng, rng_checkpoint_state
from .grower import ChunkedWaveGrower, StreamedEnvelopeError
from .stream import StreamedDataset

__all__ = ["train_streamed", "StreamedEnvelopeError"]


def _check_envelope(cfg: Config) -> None:
    bad = []
    if cfg.boosting not in ("gbdt", "goss", "dart"):
        bad.append(f"boosting={cfg.boosting}")
    if cfg.linear_tree:
        bad.append("linear_tree")
    if cfg.monotone_constraints and \
            any(int(v) != 0 for v in cfg.monotone_constraints):
        bad.append("monotone_constraints")
    if cfg.interaction_constraints:
        bad.append("interaction_constraints")
    if cfg.forcedsplits_filename:
        bad.append("forcedsplits_filename")
    if cfg.cegb_penalty_split > 0 or cfg.cegb_penalty_feature_coupled or \
            cfg.cegb_penalty_feature_lazy:
        bad.append("cegb penalties")
    if cfg.feature_fraction_bynode < 1.0:
        bad.append("feature_fraction_bynode")
    if cfg.extra_trees:
        bad.append("extra_trees")
    if cfg.path_smooth > 0:
        bad.append("path_smooth")
    if bad:
        raise StreamedEnvelopeError(
            "chunked streamed training (tpu_ingest_mode=chunked) does not "
            "support: " + ", ".join(bad) + "; train with "
            "tpu_ingest_mode=hbm (in-core from the streamed binned cache) "
            "instead")


def _host_objective(cfg: Config, label: Optional[np.ndarray],
                    weight: Optional[np.ndarray], n: int):
    """Objective with HOST-resident label/weight (no O(N) device copy).
    Mirrors ``ObjectiveFunction.init`` minus the device upload; the
    gradient formulas themselves run per chunk."""
    obj = create_objective(cfg.objective, cfg)
    ok = (type(obj) is BinaryLogloss or
          type(obj) is MulticlassSoftmax or
          (type(obj) is RegressionL2 and not obj.sqrt))
    if not ok:
        raise StreamedEnvelopeError(
            f"chunked streamed training supports objective=regression|"
            f"binary|multiclass (got {cfg.objective}; ranking needs "
            f"full-dataset query segments, multiclassova per-class label "
            f"weights); use tpu_ingest_mode=hbm")
    if label is None:
        raise ValueError(f"objective {obj.name} requires labels")
    label = np.asarray(label, np.float32)
    obj.check_label(label)
    obj.label = label
    obj.weight = None if weight is None else np.asarray(weight, np.float32)
    obj.num_data = n
    if type(obj) is BinaryLogloss:
        # the class-weight computation of BinaryLogloss.init, host-side
        cnt_pos = float((label > 0).sum())
        cnt_neg = float((label <= 0).sum())
        w0 = w1 = 1.0
        if obj.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w0 = cnt_pos / cnt_neg
            else:
                w1 = cnt_neg / cnt_pos
        w1 *= obj.scale_pos_weight
        obj.label_weight = (w0, w1)
    elif type(obj) is MulticlassSoftmax:
        # MulticlassSoftmax.init host-side: the same f32 class-prior
        # sums the in-core init runs over Metadata's f32 label/weight;
        # the one-hot matrix stays a host array (chunk slices upload per
        # gradient call instead of the full (N, K) device residency)
        lab = label.astype(np.int32)
        w = obj.weight
        probs = np.zeros(obj.num_class)
        for c in range(obj.num_class):
            sel = lab == c
            probs[c] = (w[sel].sum() / w.sum()) if w is not None \
                else sel.mean()
        obj.class_init_probs = probs
        obj._onehot_np = np.eye(obj.num_class, dtype=np.float32)[lab]
    return obj


def _chunk_gradients(obj, score_c: np.ndarray, label_c: np.ndarray,
                     weight_c: Optional[np.ndarray],
                     onehot_c: Optional[np.ndarray] = None):
    """One chunk's gradients through the objective's own formula —
    elementwise per row (softmax included: its max/sum reduce within a
    row), so per-chunk evaluation is bit-identical to the in-core
    full-array call."""
    saved = (obj.label, obj.weight)
    saved_oh = getattr(obj, "onehot", None)
    try:
        obj.label = jnp.asarray(label_c, jnp.float32)
        obj.weight = None if weight_c is None else \
            jnp.asarray(weight_c, jnp.float32)
        if onehot_c is not None:
            obj.onehot = jnp.asarray(onehot_c)
        g, h = obj.get_gradients(jnp.asarray(score_c, jnp.float32))
        return np.asarray(g), np.asarray(h)
    finally:
        obj.label, obj.weight = saved
        if onehot_c is not None:
            obj.onehot = saved_oh


def _glue_gbdt(cfg: Config, train_set: StreamedDataset, obj,
               trees: List[Any], k: int = 1) -> GBDT:
    """A host-only GBDT shell carrying the streamed-trained model (for
    model_to_string / Booster surfaces; no device state)."""
    g = GBDT(cfg, None, objective=obj)
    g.train_set = train_set
    g.num_data = train_set.num_data()
    g.num_features = train_set.num_feature()
    g.num_tree_per_iteration = k
    g.models = list(trees)
    g.iter_ = len(trees) // max(1, k)
    return g


class _ValidState:
    """One validation stream: host f32 score matrix + its metric set."""

    __slots__ = ("name", "vset", "nv", "vscore", "metrics")

    def __init__(self, name, vset, nv, vscore, metrics) -> None:
        self.name = name
        self.vset = vset
        self.nv = nv
        self.vscore = vscore
        self.metrics = metrics


def train_streamed(params: Dict[str, Any], train_set: StreamedDataset,
                   num_boost_round: int = 100,
                   valid_sets: Optional[List[Any]] = None,
                   valid_names: Optional[List[str]] = None,
                   resume_from: Optional[str] = None) -> Booster:
    """Boost ``num_boost_round`` trees over a StreamedDataset with
    chunk-accumulated histograms; returns a Booster."""
    cfg = Config(dict(params))
    _check_envelope(cfg)
    if cfg.use_quantized_grad and cfg.stochastic_rounding:
        log_warning("chunked streamed training forces "
                    "stochastic_rounding=false (the per-row rounding "
                    "stream is not chunk-sliceable)")
        cfg.stochastic_rounding = False
    if cfg.use_quantized_grad and cfg.quant_train_renew_leaf:
        log_warning("chunked streamed training forces "
                    "quant_train_renew_leaf=false")
        cfg.quant_train_renew_leaf = False
    train_set.construct(cfg)
    n = train_set.num_data()
    f_used = train_set.num_feature()
    mappers = [train_set.bin_mappers[j] for j in train_set.used_feature_map]
    from ..binning import MissingType
    num_bins = np.array([m.num_bin for m in mappers], np.int32)
    is_cat = np.array([m.is_categorical for m in mappers], bool)
    has_nan = np.array([m.missing_type == MissingType.NAN for m in mappers],
                       bool)
    if np.any(is_cat):
        raise StreamedEnvelopeError(
            "chunked streamed training supports numeric features only; "
            "use tpu_ingest_mode=hbm for categorical data")
    max_bins = int(num_bins.max())
    if cfg.use_quantized_grad:
        # the int32 channel-sum exactness bound GBDT._init_train warns
        # about (single shard here): past it the quantized accumulator
        # can wrap and the chunked==in-core contract is void
        _gq = max(quant_levels(int(cfg.num_grad_quant_bins)))
        if n > (1 << 31) // _gq:
            log_warning(
                f"num_data={n} exceeds the quantized histogram's int32 "
                f"channel-sum exactness bound (2^31/{_gq} rows at "
                f"num_grad_quant_bins={cfg.num_grad_quant_bins}); lower "
                f"num_grad_quant_bins or shard rows across more devices")
    elif n > (1 << 24):
        log_warning(f"num_data={n} exceeds the f32 histogram count "
                    "channel's 16.7M-row exactness range; set "
                    "use_quantized_grad=true for exact int32 counts (and "
                    "the chunked bit-identity contract) at this scale")
    impl = resolve_hist_impl(cfg, wave=True, max_bins=max_bins)
    if impl == "packed4":
        impl = "segment"   # no leaf-channel form (ops/histogram.py)
    if impl == "pallas":
        from ..ops.histogram_pallas import DEFAULT_ROW_BLOCK
        if train_set.chunk_rows % DEFAULT_ROW_BLOCK:
            log_warning(f"chunk_rows={train_set.chunk_rows} is not a "
                        f"multiple of the Pallas row block "
                        f"({DEFAULT_ROW_BLOCK}); using the XLA onehot "
                        f"histogram path")
            impl = "onehot"
    sp = split_params_from_config(cfg, num_bins, is_cat)
    gq_max, hq_max = quant_levels(int(cfg.num_grad_quant_bins))
    grower = ChunkedWaveGrower(
        num_leaves=int(cfg.num_leaves), num_features=f_used,
        max_bins=max_bins, max_depth=int(cfg.max_depth), split_params=sp,
        num_bins=num_bins, has_nan=has_nan, hist_impl=impl,
        quantized=bool(cfg.use_quantized_grad), gq_max=gq_max,
        hq_max=hq_max, wave_size=int(cfg.tpu_wave_size),
        interpret=None, pipeline=(None if cfg.tpu_pallas_pipeline == "auto"
                                  else str(cfg.tpu_pallas_pipeline)))

    md = train_set.metadata
    obj = _host_objective(cfg, md.label, md.weight, n)
    label32 = obj.label
    weight32 = obj.weight
    K = int(obj.num_model_per_iteration)
    shape = (n,) if K == 1 else (n, K)
    onehot_np = getattr(obj, "_onehot_np", None)

    # ---- initial scores (GBDT._init_train's score0 logic) -----------------
    score = np.zeros(shape, np.float32)
    pending_bias = np.zeros(K)
    if md.init_score is not None:
        score = score + md.init_score.reshape(shape).astype(np.float32)
    elif cfg.boost_from_average:
        for cid in range(K):
            b = obj.boost_from_score(cid)
            pending_bias[cid] = b
            if abs(b) > EPSILON:
                log_info(f"Start training from score {b:.6f}")
        score = score + (np.float32(pending_bias[0]) if K == 1 else
                         pending_bias[None, :].astype(np.float32))

    # ---- checkpoint / resume ----------------------------------------------
    ckpt_dir = str(cfg.checkpoint_dir or "")
    if not ckpt_dir and int(cfg.snapshot_freq) > 0:
        ckpt_dir = f"{cfg.output_model}.ckpt"
    manager = CheckpointManager(ckpt_dir, int(cfg.checkpoint_keep)) \
        if ckpt_dir else None
    freq = int(cfg.snapshot_freq) if int(cfg.snapshot_freq) > 0 else \
        max(1, num_boost_round // 100)
    if resume_from is None and str(cfg.resume).strip():
        want = str(cfg.resume).strip()
        if want in ("latest", "auto"):
            resume_from = manager.latest_path() if manager else None
            if resume_from is None and not manager:
                raise ValueError("resume=latest needs snapshot_freq>0 or "
                                 "checkpoint_dir")
        else:
            resume_from = want
    if cfg.boosting == "dart" and (manager is not None or resume_from):
        raise StreamedEnvelopeError(
            "chunked dart training does not support checkpoint/resume: "
            "the per-tree drop weights cannot be reconstructed from the "
            "checkpointed model text; drop checkpoint_dir/snapshot_freq/"
            "resume or use tpu_ingest_mode=hbm")
    trees: List[Any] = []
    start_iter = 0
    if resume_from:
        ckpt = load_checkpoint(str(resume_from))
        ckpt.validate_dataset(train_set)
        ckpt.validate_config(cfg)
        from ..models.model_text import string_to_model
        loaded = string_to_model(ckpt.model_text, cfg)
        trees = list(loaded.models)
        start_iter = int(ckpt.iteration)
        score = np.asarray(ckpt.score, np.float32).reshape(shape).copy()
        log_info(f"train_streamed: resumed at iteration {start_iter} "
                 f"from {resume_from}")

    def _save_ckpt(it: int) -> None:
        if manager is None:
            return
        text = _glue_gbdt(cfg, train_set, obj, trees, K) \
            .save_model_to_string()
        manager.save(Checkpoint(
            iteration=it, model_text=text, score=score.copy(),
            rng_state=rng_checkpoint_state(cfg),
            fingerprint=train_set.fingerprint(),
            params={k: getattr(cfg, k)
                    for k in CKPT_STRUCTURAL_KEYS + CKPT_SOFT_KEYS}))

    # ---- validation streams (in-core add_valid, host-resident) ------------
    walk = make_walk_fn(None, True)   # numeric-only envelope: dense walk

    def _vchunks(vs):
        if getattr(vs, "is_streamed", False):
            for ci in range(vs.num_chunks()):
                lo, hi = vs.chunk_bounds(ci)
                yield lo, hi, vs.binned_chunk(ci)
        else:
            yield 0, vs.num_data(), np.asarray(vs.X_binned)

    def _valid_delta(vst, targs):
        """One tree's walk over the valid set, chunk at a time (the
        in-core _record_tree valid update on bounded device memory;
        eager like the in-core valid walk, so the values are the same
        f32 the in-core run records)."""
        out = np.empty(vst.nv, np.float32)
        for lo, hi, bins in _vchunks(vst.vset):
            out[lo:hi] = np.asarray(walk(jnp.asarray(bins), *targs))
        return out

    valids: List[_ValidState] = []
    provide_train = bool(cfg.is_provide_training_metric)
    if valid_sets:
        if not isinstance(valid_sets, (list, tuple)):
            valid_sets = [valid_sets]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                provide_train = True   # engine.train's vs-is-train contract
                continue
            nm = (valid_names[i] if valid_names is not None and
                  i < len(valid_names) else f"valid_{i}")
            if not vs.constructed and getattr(vs, "reference", None) is None:
                vs.reference = train_set
            vs.construct(cfg)
            if vs.bin_mappers is not train_set.bin_mappers and \
                    not _mappers_equal(vs.bin_mappers, train_set.bin_mappers):
                raise ValueError(
                    "cannot add validation data: it was constructed "
                    "without reference to the training Dataset (different "
                    "bin mappers); pass reference= when creating it")
            if vs.num_feature() != f_used:
                raise ValueError(
                    "validation set feature count differs from train")
            nv = vs.num_data()
            vshape = (nv,) if K == 1 else (nv, K)
            v0 = np.zeros(vshape, np.float32)
            if vs.metadata.init_score is not None:
                v0 = v0 + vs.metadata.init_score.reshape(vshape).astype(
                    np.float32)
            elif cfg.boost_from_average:
                v0 = v0 + (np.float32(pending_bias[0]) if K == 1 else
                           pending_bias[None, :].astype(np.float32))
            mts = create_metrics(cfg)
            for m in mts:
                m.init(vs.metadata, nv)
            vst = _ValidState(nm, vs, nv, v0, mts)
            if trees:   # resumed: fold loaded trees into the valid score
                for t, tree in enumerate(trees):
                    cid = t % K
                    targs = (jnp.asarray(tree.split_feature),
                             jnp.asarray(tree.threshold_bin),
                             jnp.asarray(tree.nan_bin),
                             _tree_cat_member(tree),
                             jnp.asarray(tree.decision_type.astype(np.int32)),
                             jnp.asarray(tree.left_child),
                             jnp.asarray(tree.right_child),
                             jnp.asarray(tree.leaf_value, dtype=jnp.float32),
                             jnp.asarray(tree.num_leaves, dtype=jnp.int32))
                    delta = _valid_delta(vst, targs)
                    if K == 1:
                        vst.vscore = vst.vscore + delta
                    else:
                        vst.vscore[:, cid] = vst.vscore[:, cid] + delta
            valids.append(vst)
    train_metrics: List[Any] = []
    if provide_train:
        train_metrics = create_metrics(cfg)
        for m in train_metrics:
            m.init(md, n)

    # ---- flight recorder (telemetry/flight.py) ----------------------------
    # the chunked path is the one where the per-event h2d byte counter
    # actually moves; the tape dumps next to the checkpoints on a crash
    from ..telemetry.flight import FlightRecorder
    flight = FlightRecorder(
        capacity=int(cfg.flight_events), enabled=bool(cfg.flight_recorder),
        meta={"boosting": str(cfg.boosting), "objective": str(cfg.objective),
              "num_data": int(n), "ingest_mode": "chunked"})

    def _flight_dump(reason: str) -> None:
        out_dir = str(cfg.flight_dir) or ckpt_dir
        if not flight.enabled or len(flight) == 0 or not out_dir:
            return
        try:
            os.makedirs(out_dir, exist_ok=True)
            flight.dump(os.path.join(out_dir, "flight.jsonl"),
                        reason=reason)
        except OSError as exc:
            log_warning(f"flight recorder dump failed: {exc}")

    # ---- boosting loop -----------------------------------------------------
    goss = cfg.boosting == "goss"
    dart = cfg.boosting == "dart"
    if goss and cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0:
        # in-core GOSS ignores bagging too (models/boosting.py GOSS)
        log_warning("cannot use bagging in GOSS (ignored)")
    grad = np.empty(shape, np.float32)
    hess = np.empty(shape, np.float32)
    completed = start_iter

    # DART host state (models/boosting.py DART, host-resident): the raw
    # per-iteration base predictions + per-valid unshrunk deltas back the
    # O(N) drop/Normalize axpys
    dart_weights: List[float] = []
    dart_sum_weight = 0.0
    dart_base: List[np.ndarray] = []
    dart_vb: List[List[np.ndarray]] = []
    cur_shrinkage = float(cfg.learning_rate)

    def _dart_drop(t: int) -> List[int]:
        """The in-core DART drop selection, verbatim (one host_rng
        stream per (drop_seed, iteration))."""
        rng = host_rng(cfg.drop_seed, t)
        drop: List[int] = []
        if t > 0 and not (rng.random() < cfg.skip_drop):
            if cfg.uniform_drop:
                p = cfg.drop_rate
                if cfg.max_drop > 0:
                    p = min(p, cfg.max_drop / float(t))
                for i in range(t):
                    if rng.random() < p:
                        drop.append(i)
                        if cfg.max_drop > 0 and len(drop) >= cfg.max_drop:
                            break
            else:
                inv_avg = t / max(dart_sum_weight, 1e-12)
                p = cfg.drop_rate
                if cfg.max_drop > 0:
                    p = min(p, cfg.max_drop * inv_avg /
                            max(dart_sum_weight, 1e-12))
                for i in range(t):
                    if rng.random() < p * dart_weights[i] * inv_avg:
                        drop.append(i)
                        if cfg.max_drop > 0 and len(drop) >= cfg.max_drop:
                            break
        return drop

    def _dart_normalize(drop: List[int]) -> None:
        """The in-core DART Normalize: shrink dropped host trees by
        k/(k+1) (xgboost mode k/(k+lr)), re-add the train score at the
        new weight, adjust valid scores by the weight delta."""
        nonlocal score, dart_sum_weight
        kd = float(len(drop))
        if kd == 0:
            return
        lr = float(cfg.learning_rate)
        factor = kd / (kd + lr) if cfg.xgboost_dart_mode else kd / (kd + 1.0)
        for d in drop:
            old_w = dart_weights[d]
            new_w = old_w * factor
            dart_weights[d] = new_w
            dart_sum_weight -= old_w - new_w
            for c in range(K):
                trees[d * K + c].shrink(factor)
            score = score + dart_base[d] * np.float32(new_w)
            for vi, vst in enumerate(valids):
                vst.vscore = vst.vscore + \
                    dart_vb[d][vi] * np.float32(new_w - old_w)

    def _tree_args(grown, lv):
        return (jnp.asarray(grown.split_feature),
                jnp.asarray(grown.threshold_bin),
                jnp.asarray(grown.nan_bin), jnp.asarray(grown.cat_member),
                jnp.asarray(grown.decision_type),
                jnp.asarray(grown.left_child),
                jnp.asarray(grown.right_child),
                jnp.asarray(lv, jnp.float32),
                jnp.asarray(grown.num_leaves, jnp.int32))

    def _one_iter(it: int) -> bool:
        """One streamed boosting iteration; True = stop (no more
        splittable leaves)."""
        nonlocal completed, score, cur_shrinkage, dart_sum_weight
        first_iter = it == start_iter and not trees
        drop: List[int] = []
        if dart:
            # drop BEFORE gradients (dart.hpp DroppingTrees): gradients
            # see the thinned ensemble's score
            drop = _dart_drop(it)
            for d in drop:
                score = score - dart_base[d] * np.float32(dart_weights[d])
            kd = float(len(drop))
            lr = float(cfg.learning_rate)
            if cfg.xgboost_dart_mode:
                cur_shrinkage = lr if not drop else lr / (lr + kd)
            else:
                cur_shrinkage = lr / (1.0 + kd)
        shrinkage = cur_shrinkage if dart else float(cfg.learning_rate)
        for i in range(train_set.num_chunks()):
            lo, hi = train_set.chunk_bounds(i)
            g, h = _chunk_gradients(
                obj, score[lo:hi], label32[lo:hi],
                None if weight32 is None else weight32[lo:hi],
                None if onehot_np is None else onehot_np[lo:hi])
            grad[lo:hi] = g
            hess[lo:hi] = h
        gw, hw = grad, hess
        if goss:
            # GOSS replaces bagging (in-core GOSS overrides
            # _prepare_iter_sampling and never draws a bag); the draw is
            # the SHARED host sampler, warmup handled inside
            mask = np.ones(n, np.float32)
            gm = goss_sample_np(cfg, grad, hess, it)
            if gm is not None:
                mask, mult = gm
                scale = mult if K == 1 else mult[:, None]
                gw = grad * scale
                hw = hess * scale
        else:
            mask = bagging_mask_np(
                cfg, n, it,
                label=(np.asarray(label32) if cfg.objective == "binary"
                       else None))
            mask = np.ones(n, np.float32) if mask is None else mask
        fmask = feature_mask_np(cfg, f_used, it)
        grown_cls = []
        for cid in range(K):
            g_c = gw if K == 1 else np.ascontiguousarray(gw[:, cid])
            h_c = hw if K == 1 else np.ascontiguousarray(hw[:, cid])
            grown, rl_chunks = grower.grow(train_set, g_c, h_c, mask,
                                           feature_mask=fmask)
            grown_cls.append((grown, rl_chunks))
        all_stump = all(int(g.num_leaves) <= 1 for g, _ in grown_cls)
        if not dart and all_stump and trees:
            # the in-core deferred-stump pop, without the round trip:
            # an all-stump iteration past the first never enters the
            # model (first iteration kept — it carries boost_from_average)
            log_warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            return True
        base_this: Optional[np.ndarray] = None
        vb_this = [np.zeros_like(v.vscore) for v in valids] if dart else None
        for cid, (grown, rl_chunks) in enumerate(grown_cls):
            lv_raw = np.asarray(grown.leaf_value, np.float32)
            lv = lv_raw * np.float32(shrinkage)
            tree = _grown_to_tree(grown, shrinkage, train_set)
            bias = pending_bias[cid] if first_iter else 0.0
            if abs(bias) > EPSILON:
                tree.add_bias(bias)
            trees.append(tree)
            # score update: the in-core _update_score_impl's
            # score + lv[row_leaf], per chunk, host f32 (same IEEE ops)
            for i, rl_c in enumerate(rl_chunks):
                lo, hi = train_set.chunk_bounds(i)
                step = lv[rl_c.astype(np.int64)]
                if K == 1:
                    score[lo:hi] = score[lo:hi] + step
                else:
                    score[lo:hi, cid] = score[lo:hi, cid] + step
            if dart:
                if base_this is None:
                    base_this = np.zeros(shape, np.float32)
                for i, rl_c in enumerate(rl_chunks):
                    lo, hi = train_set.chunk_bounds(i)
                    b = lv_raw[rl_c.astype(np.int64)]
                    if K == 1:
                        base_this[lo:hi] = b
                    else:
                        base_this[lo:hi, cid] = b
            if valids:
                targs = _tree_args(grown, lv)
                for vi, vst in enumerate(valids):
                    delta = _valid_delta(vst, targs)
                    if K == 1:
                        vst.vscore = vst.vscore + delta
                    else:
                        vst.vscore[:, cid] = vst.vscore[:, cid] + delta
                    if dart:
                        # raw valid base = shrunk delta / weight, the
                        # in-core _record_tree bookkeeping (NOT a
                        # re-walk with raw lv: (lv*w)/w can drift an
                        # ulp, and the in-core Normalize uses exactly
                        # this quotient)
                        dv = delta / np.float32(shrinkage)
                        if K == 1:
                            vb_this[vi] = vb_this[vi] + dv
                        else:
                            vb_this[vi][:, cid] = vb_this[vi][:, cid] + dv
        if dart:
            dart_base.append(base_this if base_this is not None
                             else np.zeros(shape, np.float32))
            dart_weights.append(float(shrinkage))
            dart_sum_weight += float(shrinkage)
            dart_vb.append(vb_this or [])
            _dart_normalize(drop)
        completed = it + 1
        flight.note_iter(completed,
                         num_leaves=int(grown_cls[-1][0].num_leaves))
        if all_stump:
            # first gbdt/goss iteration, or any dart iteration (dart is
            # non-deferred in-core: stump trees stay recorded; stop
            # after Normalize)
            log_warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            return True
        if manager is not None and completed % freq == 0:
            _save_ckpt(completed)
        return False

    stopper = None
    if valids and cfg.early_stopping_round and \
            int(cfg.early_stopping_round) > 0:
        stopper = early_stopping(int(cfg.early_stopping_round),
                                 bool(cfg.first_metric_only),
                                 verbose=cfg.verbosity >= 1)
    best_iteration = -1
    best_score: Dict[str, Dict[str, float]] = {}
    try:
        for it in range(start_iter, num_boost_round):
            with span("ingest/train/iteration"):
                if _one_iter(it):
                    break
            if valids or train_metrics:
                # eval AFTER the iteration, in engine.train's stream
                # order (training metrics first), on the SAME f32 score
                # values the in-core run holds -> same stop round
                results = []
                for m in train_metrics:
                    for mname, val, hib in m.eval(score):
                        results.append(("training", mname, val, hib))
                for vst in valids:
                    for m in vst.metrics:
                        for mname, val, hib in m.eval(vst.vscore):
                            results.append((vst.name, mname, val, hib))
                flight.note_eval(it + 1, results)
                if stopper is not None:
                    try:
                        stopper(CallbackEnv(None, dict(params), it, 0,
                                            num_boost_round, results))
                    except EarlyStopException as e:
                        best_iteration = e.best_iteration + 1
                        for ds_name, eval_name, sc, _ in e.best_score:
                            best_score.setdefault(
                                ds_name, {})[eval_name] = sc
                        break
    except (Exception, KeyboardInterrupt):
        _flight_dump("crash")
        raise
    if manager is not None:
        _save_ckpt(completed)
    if str(cfg.flight_dir):
        _flight_dump("completed")

    gbdt = _glue_gbdt(cfg, train_set, obj, trees, K)
    bst = Booster.__new__(Booster)
    bst.params = dict(params)
    bst.best_iteration = best_iteration
    bst.best_score = best_score
    bst._train_data_name = "training"
    bst.config = cfg
    bst._gbdt = gbdt
    return bst
