"""Streaming sketch binning: one-pass mergeable per-feature summaries.

TPU-native analog of the reference's sampled bin finding over streamed
input (reference: src/io/dataset_loader.cpp:902 ``SampleTextDataFromFile``
feeding ``ConstructBinMappersFromTextData`` while ``pipeline_reader.h``
streams the file, PAPER.md layers 0/3): a :class:`BinningSketch` ingests
fixed row chunks, keeps only the deterministically sampled rows' values as
exact mergeable (distinct, count) summaries
(:class:`..binning.ColumnSummary`), and finalizes into the SAME
``BinMapper`` list a one-shot in-core :meth:`Dataset.construct` would
produce on the full matrix — bit-identical, because both paths route
through :func:`..binning.find_bin_from_summary`.

Memory is a function of ``bin_construct_sample_cnt`` (the sample bound)
and the chunk size only — never of the total row count — which is what
lets the ingest subsystem bin 10^8-10^9-row sources without ever holding
them (ROADMAP item 2).

The sketch is also the one code path for *distributed* binning:
``serialize()``/``merge_serialized()`` pack the per-feature summaries into
two flat arrays that ride the existing host allgather
(``distributed.allgather_host``), replacing the raw sample-row gather of
the pre-partition path — every rank merges the same rank-ordered
summaries and derives identical mappers (the reference's BinMapper
allgather, dataset_loader.cpp:1040-1130, at summary granularity).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..binning import (BinMapper, ColumnSummary, find_bin_from_summary,
                       merge_column_summaries, summarize_column)

__all__ = ["BinningSketch", "sample_row_indices"]


def sample_row_indices(n: int, sample_cnt: int, seed: int,
                       rng: Optional[np.random.RandomState] = None
                       ) -> np.ndarray:
    """The deterministic bin-construct row sample.  ``Dataset.construct``
    itself calls this (passing its own generator, whose remaining stream
    the sparse sampling path keeps consuming), so the streamed sketch
    pass and the in-core construct draw the SAME rows from one code
    path — the root of the streamed-vs-in-core mapper bit-identity."""
    if rng is None:
        rng = np.random.RandomState(seed)
    sample_cnt = min(n, int(sample_cnt))
    if sample_cnt < n:
        return np.sort(rng.choice(n, size=sample_cnt, replace=False))
    return np.arange(n)


class BinningSketch:
    """Per-feature mergeable quantile/count sketch over sampled rows."""

    def __init__(self, num_features: int,
                 cat_indices: Optional[Sequence[int]] = None) -> None:
        self.num_features = int(num_features)
        cats = set(int(c) for c in (cat_indices or ()))
        self._is_cat = [j in cats for j in range(self.num_features)]
        self._summaries: List[Optional[ColumnSummary]] = \
            [None] * self.num_features
        self.rows_seen = 0

    # -- accumulation --------------------------------------------------------
    def update(self, rows: np.ndarray) -> None:
        """Fold one block of sampled rows ((m, F) float64) into the
        sketch.  Cost and memory are functions of the block and the
        distinct-value counts only."""
        rows = np.asarray(rows, np.float64)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.shape[1] != self.num_features:
            raise ValueError(f"sketch expects {self.num_features} features, "
                             f"got {rows.shape[1]}")
        if rows.shape[0] == 0:
            return
        for j in range(self.num_features):
            s = summarize_column(rows[:, j], is_categorical=self._is_cat[j])
            cur = self._summaries[j]
            self._summaries[j] = s if cur is None else \
                merge_column_summaries(cur, s)
        self.rows_seen += rows.shape[0]

    def merge(self, other: "BinningSketch") -> "BinningSketch":
        if other.num_features != self.num_features:
            raise ValueError("cannot merge sketches of different width")
        for j in range(self.num_features):
            o = other._summaries[j]
            if o is None:
                continue
            cur = self._summaries[j]
            self._summaries[j] = o if cur is None else \
                merge_column_summaries(cur, o)
        self.rows_seen += other.rows_seen
        return self

    def summary(self, j: int) -> ColumnSummary:
        s = self._summaries[j]
        if s is None:
            s = summarize_column(np.zeros(0), is_categorical=self._is_cat[j])
        return s

    # -- wire form (distributed binning) -------------------------------------
    # layout per feature: [n_distinct, na_cnt, total_cnt] int64 header in
    # the layout array; distinct values then counts in the flat payload.
    def serialize(self):
        """(payload float64 flat, layout int64 (F, 3)) — fixed-width
        layout rows so rank payloads concatenate through the max-pad
        allgather and split back exactly."""
        payloads = []
        layout = np.zeros((self.num_features, 3), np.int64)
        for j in range(self.num_features):
            s = self.summary(j)
            layout[j] = (len(s.distinct), s.na_cnt, s.total_cnt)
            payloads.append(np.asarray(s.distinct, np.float64))
            payloads.append(np.asarray(s.counts, np.float64))
        flat = np.concatenate(payloads) if payloads else np.zeros(0)
        return flat, layout

    @classmethod
    def deserialize(cls, flat: np.ndarray, layout: np.ndarray,
                    cat_indices: Optional[Sequence[int]] = None
                    ) -> "BinningSketch":
        layout = np.asarray(layout, np.int64)
        sk = cls(layout.shape[0], cat_indices)
        off = 0
        rows = 0
        for j in range(sk.num_features):
            nd, na, tot = (int(v) for v in layout[j])
            d = np.asarray(flat[off:off + nd], np.float64)
            c = np.asarray(flat[off + nd:off + 2 * nd], np.float64) \
                .astype(np.int64)
            off += 2 * nd
            sk._summaries[j] = ColumnSummary(
                distinct=d, counts=c, na_cnt=na, total_cnt=tot,
                is_categorical=sk._is_cat[j])
            rows = max(rows, tot)
        sk.rows_seen = rows
        return sk

    def allgather_merge(self) -> "BinningSketch":
        """Merge this rank's sketch with every other process's (host
        allgather of the serialized summaries, merged in rank order) —
        the distributed-binning collective.  No-op single-process."""
        from .. import distributed as _dist
        if not _dist.is_initialized() or _dist.process_count() == 1:
            return self
        flat, layout = self.serialize()
        # int64 would be silently narrowed in transit (x64 off); counters
        # and sizes ride float64 bit-exactly below 2^53
        sizes = _dist.allgather_host(
            np.asarray([len(flat)], np.float64)).ravel().astype(np.int64)
        flats = _dist.allgather_host(flat)
        layouts = _dist.allgather_host(
            layout.astype(np.float64).reshape(-1)).reshape(
            -1, self.num_features, 3).astype(np.int64)
        merged: Optional[BinningSketch] = None
        off = 0
        for r in range(len(sizes)):
            part = BinningSketch.deserialize(
                flats[off:off + int(sizes[r])], layouts[r],
                [j for j, c in enumerate(self._is_cat) if c])
            off += int(sizes[r])
            merged = part if merged is None else merged.merge(part)
        assert merged is not None
        self._summaries = merged._summaries
        self.rows_seen = merged.rows_seen
        return self

    # -- finalize ------------------------------------------------------------
    def finalize(self, *, max_bin: int, min_data_in_bin: int = 3,
                 use_missing: bool = True, zero_as_missing: bool = False,
                 forced_bins: Optional[Dict[int, list]] = None,
                 pre_filter_cnt_fn=None) -> List[BinMapper]:
        """All features' BinMappers via the shared
        :func:`find_bin_from_summary` machinery.  ``pre_filter_cnt_fn``
        maps a feature's summarized sample size to the reference's
        NeedFilter threshold (0 disables)."""
        forced_bins = forced_bins or {}
        mappers: List[BinMapper] = []
        for j in range(self.num_features):
            s = self.summary(j)
            filt = int(pre_filter_cnt_fn(s.total_cnt)) \
                if pre_filter_cnt_fn is not None else 0
            mappers.append(find_bin_from_summary(
                s, max_bin, min_data_in_bin,
                use_missing=use_missing, zero_as_missing=zero_as_missing,
                forced_bounds=forced_bins.get(j), pre_filter_cnt=filt))
        return mappers
