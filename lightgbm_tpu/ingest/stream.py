"""StreamedDataset: out-of-core ingestion into a Dataset-compatible object.

The ingest subsystem's layer 1 (ROADMAP item 2; reference
``pipeline_reader.h`` streaming ingestion + sampled bin finding, PAPER.md
layers 0/3).  A :class:`StreamedDataset` wraps a
:class:`..ingest.source.ChunkSource` and constructs in two streaming
passes, never materializing the raw matrix:

1. **sketch pass** — the deterministic bin-construct row sample
   (``sketch.sample_row_indices`` — the same RNG draw the in-core
   ``Dataset.construct`` makes) is folded chunk-by-chunk into a
   :class:`..ingest.sketch.BinningSketch`; labels/weights accumulate into
   per-row host arrays.  Finalizing the sketch yields BinMappers
   **bit-identical** to an in-core construct of the same matrix (both run
   through ``binning.find_bin_from_summary``).
2. **bin + spill pass** — every chunk is quantized with the shared
   ``binning.bin_matrix`` fast path and appended to an on-disk
   ``np.memmap`` binned cache (1 B/value at max_bin<=256 — the XGBoost
   external-memory page file analog, arXiv:1806.11248), so later training
   passes stream binned codes from the OS page cache instead of re-parsing
   raw input.

Host working set: the sketch (bounded by ``bin_construct_sample_cnt``),
one raw chunk, and O(bytes-per-row) label/score state — a function of
``chunk_rows`` and features, never of total rows.  The full Dataset API
(fingerprint, device_bins, engine.train) works on top of the memmap: with
``tpu_ingest_mode=hbm`` (default) training uploads the binned matrix to
HBM and is bit-identical to in-core training on every learner path; with
``tpu_ingest_mode=chunked`` the wave grower accumulates histograms
chunk-by-chunk and HBM stays bounded by the declared chunk budget
(``ingest/chunk_pipeline`` MemoryBudget below, checked by ``lint-mem``).
"""

from __future__ import annotations

import os
import tempfile
import zlib
from typing import Any, Dict, Iterator, Optional, Tuple

import numpy as np

from ..analysis.contracts import memory_budget
from ..binning import bin_matrix
from ..config import Config
from ..dataset import Dataset
from ..telemetry.metrics import default_registry
from ..telemetry.trace import span
from ..utils.log import log_info
from .sketch import BinningSketch, sample_row_indices
from .source import ChunkSource, DEFAULT_CHUNK_ROWS

__all__ = ["StreamedDataset", "ingest_chunk_hbm_bytes"]


# ---------------------------------------------------------------------------
# Memory budget for the chunked-ingest program family (lint-mem enforced).
# The whole point of the ingest path: the curve below is a function of
# (chunk_rows, features, bins, wave_size) ONLY — there is deliberately NO
# total-rows term, and tests/test_ingest.py asserts the curve is flat in
# ctx["rows"].  Terms: a double-buffered chunk ring (bin codes + f32
# grad/hess/mask + row_leaf + weight lanes, ~f+24 B/row), the wave
# histogram accumulator batch plus subtraction/scan temporaries (the same
# 6-layer working set the wave curve budgets), and the segment
# histogram's internally-chunked (rows, F, 3) update tensor (bounded at
# 64 MB by ops/histogram.py).
# ---------------------------------------------------------------------------

def ingest_chunk_hbm_bytes(ctx):
    from ..ops.histogram_pallas import LEAF_CHANNELS, Q_LEAF_CHANNELS
    c = int(ctx.get("chunk_rows", DEFAULT_CHUNK_ROWS))
    f = int(ctx["features"])
    b = int(ctx["bins"])
    it = int(ctx.get("itemsize", 4))
    wave = int(ctx.get("wave_size", LEAF_CHANNELS))
    kernel_ch = Q_LEAF_CHANNELS if ctx.get("quantized") else LEAF_CHANNELS
    leaves = int(ctx.get("leaves", 2))
    rows_term = 2 * c * (f + 24)
    hist = (leaves + 6 * max(2 * wave, kernel_ch)) * f * b * 3 * it
    return rows_term + hist + (64 << 20) + (1 << 20)


memory_budget(
    "ingest/chunk_pipeline", ("ingest",), ingest_chunk_hbm_bytes,
    note="double-buffered chunk ring + wave histogram working set; "
         "flat in total rows by construction")


class StreamedDataset(Dataset):
    """Dataset built from a :class:`ChunkSource` without ever holding the
    raw matrix.  ``spill_dir`` hosts the binned on-disk cache (a temp dir
    by default); ``chunk_rows`` is fixed by the source."""

    def __init__(self, source: ChunkSource,
                 params: Optional[Dict[str, Any]] = None,
                 categorical_feature: Any = "auto",
                 spill_dir: Optional[str] = None,
                 free_raw_data: bool = True) -> None:
        super().__init__(None, params=params,
                         categorical_feature=categorical_feature,
                         free_raw_data=free_raw_data)
        self.source = source
        self.chunk_rows = int(source.chunk_rows)
        self.spill_dir = spill_dir
        self._own_spill = spill_dir is None
        self._spill_path: Optional[str] = None
        self._spill_fd: Optional[int] = None
        self.is_streamed = True

    # -- construction (two streaming passes) --------------------------------
    def construct(self, config: Optional[Config] = None) -> "StreamedDataset":
        if self.constructed:
            return self
        cfg = config or Config(self.params)
        ref = self.reference
        if ref is not None and not ref.constructed:
            ref.construct(cfg)
        if cfg.linear_tree:
            raise ValueError("linear_tree needs raw feature values resident "
                             "in memory; StreamedDataset does not keep them")
        reg = default_registry()
        rows_ctr = reg.counter("ingest_rows_total",
                               "rows streamed through ingest")
        chunks_ctr = reg.counter("ingest_chunks_total",
                                 "chunks streamed through ingest")
        spill_ctr = reg.counter("ingest_spill_bytes_total",
                                "binned bytes spilled to the disk cache")
        src = self.source
        n = src.num_rows()
        f = src.num_features()
        self.num_total_features = f
        names = src.feature_names()
        self.feature_names_ = list(names) if names else \
            [f"Column_{i}" for i in range(f)]
        self.efb = None
        self.raw_used = None
        # pre-partitioned multi-host streaming (ISSUE 18 tentpole): each
        # process streams only ITS shard's ChunkSource; the per-rank
        # sketches ride the mergeable-summary wire format over the host
        # allgather (sketch.allgather_merge), so every rank derives
        # identical mappers while no host ever materializes — or even
        # streams — another host's rows
        from .. import distributed as _dist
        dist_rows = (bool(cfg.pre_partition) and _dist.is_initialized()
                     and _dist.process_count() > 1
                     and self.reference is None)
        self.distributed_rows = dist_rows
        if dist_rows and self._group_arg is not None:
            raise ValueError(
                "pre_partition cannot shard query/group data (queries "
                "must not straddle partitions); drop pre_partition or "
                "the group argument")
        cat_indices = self._resolve_categoricals(self.feature_names_)
        forced_bins = self._load_forced_bins(cfg)

        # ---- pass 1: sketch + metadata ------------------------------------
        if dist_rows:
            sample_cnt = max(1, int(cfg.bin_construct_sample_cnt) //
                             _dist.process_count())
        else:
            sample_cnt = int(cfg.bin_construct_sample_cnt)
        sample_idx = sample_row_indices(n, sample_cnt,
                                        cfg.data_random_seed)
        sketch = BinningSketch(f, cat_indices)
        label = None
        weight = None
        with span("ingest/sketch_pass"):
            for chunk in src.chunks():
                m = chunk.X.shape[0]
                lo = np.searchsorted(sample_idx, chunk.offset)
                hi = np.searchsorted(sample_idx, chunk.offset + m)
                if ref is None and hi > lo:
                    local = sample_idx[lo:hi] - chunk.offset
                    sketch.update(np.asarray(chunk.X, np.float64)[local])
                if chunk.label is not None:
                    if label is None:
                        label = np.empty(n, np.float64)
                    label[chunk.offset:chunk.offset + m] = chunk.label
                if chunk.weight is not None:
                    if weight is None:
                        weight = np.ones(n, np.float64)
                    weight[chunk.offset:chunk.offset + m] = chunk.weight
                rows_ctr.inc(m)
                chunks_ctr.inc()

        n_total = n
        if dist_rows:
            # merge every rank's summaries in rank order (the mergeable
            # sketch wire format over distributed.allgather_host) —
            # after this, all ranks hold IDENTICAL summaries and derive
            # identical mappers from their disjoint streamed shards
            n_total = int(_dist.allgather_host(
                np.asarray([n], np.float64)).sum())
            sketch.allgather_merge()

        def _filt(sample_total: int) -> int:
            if not cfg.feature_pre_filter:
                return 0
            return max(1, int(cfg.min_data_in_leaf * sample_total /
                              max(1, n_total)))

        if ref is not None:
            # align bins with the reference dataset (dataset.h:304 — the
            # in-core Dataset.construct reference path): a streamed valid
            # set bins against the TRAIN mappers so tree thresholds
            # transfer; no sketch finalize of its own
            if getattr(ref, "efb", None) is not None:
                raise ValueError(
                    "StreamedDataset cannot bin against an EFB-bundled "
                    "reference (the streamed binning pass has no bundle "
                    "step); construct the reference with "
                    "enable_bundle=false")
            self.bin_mappers = ref.bin_mappers
            self.used_feature_map = ref.used_feature_map
            self.num_bins_per_feature = ref.num_bins_per_feature
            self.efb = ref.efb
        else:
            self.bin_mappers = sketch.finalize(
                max_bin=cfg.max_bin, min_data_in_bin=cfg.min_data_in_bin,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                forced_bins=forced_bins, pre_filter_cnt_fn=_filt)
            self._finalize_used_features(f)   # shared trivial-filter policy
        used_arr = self.used_feature_map
        mappers = [self.bin_mappers[j] for j in used_arr]
        used = [int(j) for j in used_arr]

        # ---- pass 2: bin + spill ------------------------------------------
        max_bins = max(m_.num_bin for m_ in mappers)
        dtype = np.uint8 if max_bins <= 256 else np.uint16
        if self.spill_dir is None:
            self.spill_dir = tempfile.mkdtemp(prefix="lgbm_tpu_ingest_")
        os.makedirs(self.spill_dir, exist_ok=True)
        self._spill_path = os.path.join(self.spill_dir, "binned.dat")
        # sequential buffered FILE writes, not memmap stores: dirty pages
        # of a writable mapping stay in this process's RSS until
        # unmapped, which would make the "flat working set" claim false
        # at 10^8-row scale (scripts/ingest_smoke.py asserts the RSS
        # ceiling).  Sources must stream in offset order (they do).
        with span("ingest/bin_spill"), open(self._spill_path, "wb") as fh:
            expect = 0
            for chunk in src.chunks():
                if chunk.offset != expect:
                    raise ValueError(
                        f"chunk source must stream rows in order (got "
                        f"offset {chunk.offset}, expected {expect})")
                binned = bin_matrix(
                    np.asarray(chunk.X, np.float64)[:, used_arr], mappers)
                fh.write(np.ascontiguousarray(
                    binned.astype(dtype, copy=False)).tobytes())
                spill_ctr.inc(int(binned.size) * binned.dtype.itemsize)
                expect += chunk.X.shape[0]
        # the Dataset-API view: a read-only memmap (no page is resident
        # until touched; the hbm training route reads it once on upload)
        self.X_binned = np.memmap(self._spill_path, dtype=dtype, mode="r",
                                  shape=(n, len(used)))
        self._label_arg = label if self._label_arg is None else \
            self._label_arg
        self._weight_arg = weight if self._weight_arg is None else \
            self._weight_arg
        n_rows = n
        if dist_rows:
            # pad the LOCAL binned shard to the mesh quantum and
            # replicate the small metadata (shared Dataset machinery);
            # the feature shard itself never leaves this process — the
            # padded copy is the per-host upload staging buffer the DP
            # assembly (gbdt pre_partition route) hands to
            # jax.make_array_from_process_local_data
            n_rows = self._finalize_distributed_rows(n)
        self._set_metadata(n_rows)
        self.constructed = True
        log_info(f"StreamedDataset: {n} rows x {len(used)} features binned "
                 f"in {src.num_chunks()} chunks of {self.chunk_rows} "
                 f"(spill: {self._spill_path}, "
                 f"{os.path.getsize(self._spill_path) >> 20} MB)")
        return self

    # -- chunk access for the chunked trainer --------------------------------
    # (LOCAL rows: under pre_partition the spill cache holds only this
    # process's shard, while num_data() reports the global row count)
    def num_chunks(self) -> int:
        self._check_constructed()
        return -(-self._local_rows() // self.chunk_rows)

    def _local_rows(self) -> int:
        return int(self.X_binned.shape[0])

    def chunk_bounds(self, i: int) -> Tuple[int, int]:
        lo = i * self.chunk_rows
        return lo, min(lo + self.chunk_rows, self._local_rows())

    def binned_chunk(self, i: int) -> np.ndarray:
        """(m, F) binned codes of chunk ``i``, read with a positioned
        ``os.pread`` on a persistent fd (NOT through the memmap: a
        mapping's touched pages pile up in RSS for the run's lifetime,
        while ordinary reads recycle one chunk buffer — the difference
        between a flat and an O(rows) working set over a full training
        pass; the kept fd avoids an open/close pair per chunk per
        histogram pass)."""
        self._check_constructed()
        lo, hi = self.chunk_bounds(i)
        f = self.X_binned.shape[1]
        it = self.X_binned.dtype.itemsize
        if self._spill_fd is None:
            self._spill_fd = os.open(self._spill_path, os.O_RDONLY)
        nbytes = (hi - lo) * f * it
        buf = os.pread(self._spill_fd, nbytes, lo * f * it)
        if len(buf) != nbytes:
            raise IOError(f"short read from spill cache {self._spill_path}")
        return np.frombuffer(buf, dtype=self.X_binned.dtype).reshape(
            hi - lo, f)

    # -- spill lifecycle -----------------------------------------------------
    def close(self) -> None:
        """Release the spill cache.  Self-created temp spill dirs are
        deleted (a CV sweep constructing many StreamedDatasets must not
        accumulate orphaned binned caches in /tmp); caller-provided
        ``spill_dir``s are left in place for reuse."""
        if self._spill_fd is not None:
            try:
                os.close(self._spill_fd)
            except OSError:
                pass
            self._spill_fd = None
        self.X_binned = None
        if self._own_spill and self.spill_dir is not None:
            import shutil
            shutil.rmtree(self.spill_dir, ignore_errors=True)
            self.spill_dir = None
        self.constructed = False

    def __del__(self):  # best effort; close() is the reliable path
        try:
            if getattr(self, "_own_spill", False) and \
                    getattr(self, "spill_dir", None):
                self.close()
        except Exception:
            pass

    def binned_chunks(self) -> Iterator[Tuple[int, np.ndarray]]:
        for i in range(self.num_chunks()):
            yield self.chunk_bounds(i)[0], self.binned_chunk(i)

    # -- fingerprint: stream the crc instead of materializing ----------------
    def fingerprint(self) -> Dict[str, Any]:
        self._check_constructed()
        fp = self._device_cache.get("_fingerprint")
        if fp is not None:
            return fp
        # incremental crc over row blocks == one-shot crc over the full
        # buffer (zlib.crc32 chains); the mapper sha + field layout come
        # from the shared Dataset._fingerprint_with_crc, so this equals
        # the in-core fingerprint of the same binned matrix bit for bit
        crc = 0
        for _, block in self.binned_chunks():
            crc = zlib.crc32(np.ascontiguousarray(block).tobytes(), crc)
        fp = self._fingerprint_with_crc(crc)
        self._device_cache["_fingerprint"] = fp
        return fp
