"""Plotting utilities.

TPU-framework equivalent of the reference plotting module
(reference: python-package/lightgbm/plotting.py — ``plot_importance``,
``plot_split_value_histogram``, ``plot_metric``, ``plot_tree``,
``create_tree_digraph``).  matplotlib / graphviz are imported lazily so the
core package has no hard dependency on either; all figures are built from
the Booster's ``feature_importance()`` / ``dump_model()`` surfaces, not from
any plotting-side re-walk of the model.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["plot_importance", "plot_split_value_histogram", "plot_metric",
           "plot_tree", "create_tree_digraph"]


def _require_pair(obj: Any, obj_name: str) -> None:
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a list or tuple of 2 elements")


def _import_matplotlib():
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("You must install matplotlib and restart your "
                          "session to plot.") from e
    return plt


def _to_booster(booster):
    from .basic import Booster
    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be a Booster or LGBMModel instance")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple[float, float]] = None,
                    ylim: Optional[Tuple[float, float]] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: Optional[int] = 3,
                    **kwargs):
    """Horizontal bar chart of feature importances
    (reference plotting.py plot_importance)."""
    plt = _import_matplotlib()
    booster = _to_booster(booster)

    importance = booster.feature_importance(importance_type=importance_type)
    feature_name = booster.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("There are no importances > 0 to plot.")
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        label = (f"{x:.{precision}f}" if importance_type == "gain" and
                 precision is not None else str(int(x)))
        ax.text(x + 1, y, label, va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _require_pair(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _require_pair(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8,
                               xlim=None, ylim=None,
                               title="Split value histogram for "
                                     "feature with @index/name@ @feature@",
                               xlabel="Feature split value",
                               ylabel="Count", figsize=None, dpi=None,
                               grid: bool = True, **kwargs):
    """Histogram of a feature's split THRESHOLD values across the model
    (reference plotting.py plot_split_value_histogram)."""
    plt = _import_matplotlib()
    booster = _to_booster(booster)

    # collect split thresholds of the requested feature from the trees
    names = booster.feature_name()
    if isinstance(feature, str):
        feat_idx = names.index(feature)
        feat_desc = f"name {feature}"
    else:
        feat_idx = int(feature)
        feat_desc = f"index {feature}"
    gbdt = booster._gbdt
    real_map, _, _ = gbdt.feature_mapping()
    values: List[float] = []
    for tree in gbdt.models:
        for i in range(tree.num_leaves - 1):
            f = tree.split_feature[i]
            if f >= 0 and int(real_map[f]) == feat_idx:
                values.append(float(tree.threshold[i]))
    if not values:
        raise ValueError("Cannot plot split value histogram, because "
                         f"feature {feature} was not used in splitting")
    hist, bin_edges = np.histogram(values, bins=bins or max(10, len(set(values))))
    centred = (bin_edges[:-1] + bin_edges[1:]) / 2.0

    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centred, hist, align="center",
           width=width_coef * (bin_edges[1] - bin_edges[0]), **kwargs)
    if xlim is not None:
        _require_pair(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _require_pair(ylim, "ylim")
    else:
        ylim = (0, max(hist) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title.replace("@feature@", str(feature))
                     .replace("@index/name@", feat_desc.split(" ")[0]))
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None,
                ax=None, xlim=None, ylim=None,
                title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    """Plot one metric's evaluation history recorded by the
    ``record_evaluation`` callback (reference plotting.py plot_metric).

    ``booster`` is the evals_result dict from ``record_evaluation`` (the
    sklearn wrapper's ``evals_result_`` also works).
    """
    plt = _import_matplotlib()
    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    else:
        raise TypeError("booster must be a dict from record_evaluation() or "
                        "a fitted LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    elif not dataset_names:
        raise ValueError("dataset_names cannot be empty")

    name = dataset_names[0]
    metrics_for_one = eval_results[name]
    if metric is None:
        if len(metrics_for_one) > 1:
            raise ValueError("more than one metric available, pick one with "
                             "the metric parameter")
        metric, results = list(metrics_for_one.items())[0]
    else:
        if metric not in metrics_for_one:
            raise KeyError("No given metric in eval results.")
        results = metrics_for_one[metric]

    num_iteration = len(results)
    max_result = max(results)
    min_result = min(results)
    x_ = np.arange(num_iteration)
    ax.plot(x_, results, label=name)
    for name in dataset_names[1:]:
        results = eval_results[name][metric]
        max_result = max(max(results), max_result)
        min_result = min(min(results), min_result)
        ax.plot(x_, results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        _require_pair(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _require_pair(ylim, "ylim")
    else:
        range_result = max_result - min_result
        ylim = (min_result - range_result * 0.2,
                max_result + range_result * 0.2)
    ax.set_ylim(ylim)
    if ylabel is not None:
        ylabel = ylabel.replace("@metric@", metric)
        ax.set_ylabel(ylabel)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    ax.grid(grid)
    return ax


def _float2str(value: float, precision: Optional[int] = None) -> str:
    if precision is not None and not isinstance(value, str):
        return f"{value:.{precision}f}"
    return str(value)


def _add_nodes(graph, root: Dict[str, Any], total_count: int,
               show_info: List[str], precision: Optional[int],
               orientation: str, parent: Optional[str] = None,
               decision: Optional[str] = None) -> None:
    """Recursively add one dump_model() subtree to a graphviz digraph."""
    if "split_index" in root:  # internal node
        name = f"split{root['split_index']}"
        label = (f"<B>{root['split_feature_name']}</B> "
                 f"{root['decision_type']} "
                 f"<B>{_float2str(root['threshold'], precision)}</B>")
        for info in ("split_gain", "internal_value", "internal_weight",
                     "internal_count", "data_percentage"):
            if info in show_info:
                if info == "data_percentage":
                    output = _float2str(
                        root["internal_count"] / total_count * 100, 2) + "% of data"
                else:
                    output = f"{info}: " + _float2str(root[info], precision)
                label += f"<br/>{output}"
        label = f"<{label}>"
        graph.node(name, label=label, shape="rectangle")
        l_dec, r_dec = (("yes", "no") if root["decision_type"] == "<=" else
                        ("is", "isn't"))
        _add_nodes(graph, root["left_child"], total_count, show_info,
                   precision, orientation, name, l_dec)
        _add_nodes(graph, root["right_child"], total_count, show_info,
                   precision, orientation, name, r_dec)
    else:  # leaf
        name = f"leaf{root['leaf_index']}"
        label = f"<B>leaf {root['leaf_index']}: </B>"
        label += f"<B>{_float2str(root['leaf_value'], precision)}</B>"
        if "leaf_weight" in show_info:
            label += "<br/>leaf_weight: " + _float2str(root["leaf_weight"],
                                                       precision)
        if "leaf_count" in show_info:
            label += "<br/>leaf_count: " + _float2str(root["leaf_count"])
        if "data_percentage" in show_info:
            label += "<br/>" + _float2str(
                root["leaf_count"] / total_count * 100, 2) + "% of data"
        label = f"<{label}>"
        graph.node(name, label=label)
    if parent is not None:
        graph.edge(parent, name, decision)


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: Optional[int] = 3,
                        orientation: str = "horizontal",
                        name=None, comment=None, filename=None,
                        directory=None, format=None, engine=None,
                        encoding=None, graph_attr=None, node_attr=None,
                        edge_attr=None, body=None, strict: bool = False):
    """Graphviz digraph of one tree (reference plotting.py
    create_tree_digraph); install graphviz to render."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("You must install graphviz and restart your "
                          "session to plot tree.") from e
    booster = _to_booster(booster)

    model = booster.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names", None)
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    tree_info = tree_infos[tree_index]

    # attach feature names to the dump for labels
    def _name_splits(node):
        if "split_index" in node:
            f = node["split_feature"]
            node["split_feature_name"] = (feature_names[f] if feature_names
                                          else f"Column_{f}")
            _name_splits(node["left_child"])
            _name_splits(node["right_child"])
    root = deepcopy(tree_info["tree_structure"])
    if "split_index" in root:
        _name_splits(root)

    show_info = show_info or []
    graph = Digraph(name=name, comment=comment, filename=filename,
                    directory=directory, format=format, engine=engine,
                    encoding=encoding, graph_attr=graph_attr,
                    node_attr=node_attr, edge_attr=edge_attr, body=body,
                    strict=strict)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr("graph", nodesep="0.05", ranksep="0.3", rankdir=rankdir)
    if "split_index" in root:
        total_count = int(root["internal_count"])
        _add_nodes(graph, root, total_count, show_info, precision, orientation)
    else:
        graph.node("leaf0", label=f"leaf0: {root['leaf_value']}")
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info=None, precision: Optional[int] = 3,
              orientation: str = "horizontal", **kwargs):
    """Render one tree with matplotlib via the graphviz digraph
    (reference plotting.py plot_tree)."""
    plt = _import_matplotlib()
    try:
        import matplotlib.image as image
    except ImportError as e:
        raise ImportError("You must install matplotlib to plot tree.") from e
    if ax is None:
        if figsize is not None:
            _require_pair(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    graph = create_tree_digraph(booster=booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    from io import BytesIO
    s = BytesIO()
    s.write(graph.pipe(format="png"))
    s.seek(0)
    img = image.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
