"""Per-training-run instrumentation: the ``TrainRecord``.

The communication-efficient parallel-GBDT literature (Meng et al. 2016;
Mitchell & Frank 2017) argues entirely through per-phase time and
per-pass communication volume; this repo used to reconstruct those
numbers by hand in PERF.md.  A ``TrainRecord`` accumulates them as the
boosting loop runs:

  * per-tree full-data histogram passes (``GrownTree.hist_passes``, the
    counter already asserted by tests/test_endgame.py) and leaf counts —
    kept as device scalars and pulled in batched, lazy fetches so the
    async dispatch pipeline never stalls;
  * collective count and reduced bytes, tallied at the
    ``parallel/*.py`` collective call sites.  Those sites execute at
    TRACE time (the growers are jit/shard_map programs), so the tally
    is per *traced program* — the same quantity
    tests/test_specramp.py asserts by counting ``psum`` ops in the
    jaxpr — and a run that triggers no retrace adds nothing.  The DP
    wave path's merge mode is visible here: the full-batch psum tallies
    at ``data_parallel/wave/hist_psum``, the feature-sliced
    reduce-scatter records its 1/k received payload at
    ``data_parallel/wave/hist_reduce_scatter`` plus the tiny per-scan
    ``data_parallel/wave/winner_exchange`` (tests/test_wave_scatter.py
    asserts the >=4x per-pass byte drop at k=8);
  * XLA compile/retrace events via a ``jax.monitoring`` listener;
  * device-memory watermark via ``device.memory_stats()`` where the
    backend provides it (TPU does; CPU returns None);
  * per-phase wall time (gradients / grow / record / eval).

Accumulation is gated by ``telemetry.enabled()`` and purely
observational: it reads values training already computed, so
telemetry-on and telemetry-off training produce bit-identical models
(asserted in tests/test_telemetry.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from . import _config
from .trace import span

__all__ = ["TrainRecord", "note_collective", "collectives_snapshot",
           "collectives_reset", "last_train_record",
           "set_last_train_record", "device_memory_peak",
           "note_hist_kernel", "hist_kernel_snapshot",
           "hist_kernel_reset"]


# ---------------------------------------------------------------------------
# Collective tally — incremented at TRACE time by the parallel strategies
# ---------------------------------------------------------------------------

_coll_lock = threading.Lock()
# site -> {"op": str, "count": int, "bytes": int}
_collectives: Dict[str, Dict[str, Any]] = {}


def note_collective(site: str, op: str, value) -> None:
    """Record one collective call site being traced.

    ``value`` is the operand (concrete array or tracer — both expose
    shape/dtype).  Called from inside jit/shard_map tracing, so this
    runs once per traced program, never per executed step; runtime cost
    of the compiled program is zero."""
    if not _config.enabled():
        return
    try:
        nbytes = 1
        for d in value.shape:
            nbytes *= int(d)
        nbytes *= value.dtype.itemsize
    except Exception:
        nbytes = 0
    with _coll_lock:
        rec = _collectives.get(site)
        if rec is None:
            rec = _collectives[site] = {"op": op, "count": 0, "bytes": 0}
        rec["count"] += 1
        rec["bytes"] += int(nbytes)


def collectives_snapshot() -> Dict[str, Dict[str, Any]]:
    with _coll_lock:
        return {k: dict(v) for k, v in _collectives.items()}


def collectives_reset() -> None:
    with _coll_lock:
        _collectives.clear()


# ---------------------------------------------------------------------------
# Histogram-kernel tally — incremented by the ops/histogram_pallas entry
# points.  Inside a jitted grower the entry wrapper runs at TRACE time
# (one tally per traced program, like the collective sites); on eager
# paths (autotune probes, benchmarks, the leaf-refit pass) it counts per
# build.  ``bytes`` is the kernel's streamed-byte estimate (bins +
# packed weights in, histogram block out) — the quantity the DMA
# pipeline and the 4-bit bin packing attack.
# ---------------------------------------------------------------------------

_hist_lock = threading.Lock()
# site -> {"count": int, "bytes": int}
_hist_kernels: Dict[str, Dict[str, int]] = {}


def note_hist_kernel(site: str, streamed_bytes: int) -> None:
    if not _config.enabled():
        return
    with _hist_lock:
        rec = _hist_kernels.get(site)
        if rec is None:
            rec = _hist_kernels[site] = {"count": 0, "bytes": 0}
        rec["count"] += 1
        rec["bytes"] += int(streamed_bytes)


def hist_kernel_snapshot() -> Dict[str, Dict[str, int]]:
    with _hist_lock:
        return {k: dict(v) for k, v in _hist_kernels.items()}


def hist_kernel_reset() -> None:
    with _hist_lock:
        _hist_kernels.clear()


# ---------------------------------------------------------------------------
# XLA compile / retrace events via jax.monitoring
# ---------------------------------------------------------------------------

_mon_lock = threading.Lock()
_mon_counts: Dict[str, int] = {}
_mon_secs: Dict[str, float] = {}
_mon_registered = False


def _on_event(event: str, **kwargs) -> None:
    if not _config.enabled():
        return
    with _mon_lock:
        _mon_counts[event] = _mon_counts.get(event, 0) + 1


def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    if not _config.enabled():
        return
    with _mon_lock:
        _mon_counts[event] = _mon_counts.get(event, 0) + 1
        _mon_secs[event] = _mon_secs.get(event, 0.0) + float(duration)


def _ensure_monitoring() -> None:
    """Register the jax.monitoring listeners once per process (listeners
    cannot be unregistered individually, so the callbacks themselves
    check the telemetry switch)."""
    global _mon_registered
    if _mon_registered:
        return
    with _mon_lock:
        if _mon_registered:
            return
        try:
            import jax.monitoring
            jax.monitoring.register_event_listener(_on_event)
            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration)
        except Exception:
            pass  # older jax without monitoring: compile events stay empty
        _mon_registered = True


def _monitoring_snapshot():
    with _mon_lock:
        return dict(_mon_counts), dict(_mon_secs)


_COMPILE_MARKERS = ("compil", "trace", "jit")


def _compile_events(counts: Dict[str, int]) -> Dict[str, int]:
    return {k: v for k, v in counts.items()
            if any(m in k.lower() for m in _COMPILE_MARKERS)}


# ---------------------------------------------------------------------------
# Device memory watermark
# ---------------------------------------------------------------------------

def device_memory_peak() -> Optional[int]:
    """Max over devices of the backend's peak/in-use byte counter, or
    None when the backend exposes no memory_stats (XLA:CPU)."""
    try:
        import jax
        peak = None
        for d in jax.devices():
            stats = d.memory_stats() if hasattr(d, "memory_stats") else None
            if not stats:
                continue
            v = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
            if v is not None:
                peak = max(int(v), peak or 0)
        return peak
    except Exception:
        return None


# ---------------------------------------------------------------------------
# TrainRecord
# ---------------------------------------------------------------------------

class _Phase:
    __slots__ = ("_rec", "_name", "_span", "_t0")

    def __init__(self, rec: "TrainRecord", name: str) -> None:
        self._rec = rec
        self._name = name

    def __enter__(self):
        self._span = span("train/" + self._name)
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._span.__exit__(*exc)
        with self._rec._lock:
            ph = self._rec._phase_s
            ph[self._name] = ph.get(self._name, 0.0) + dt
            cn = self._rec._phase_n
            cn[self._name] = cn.get(self._name, 0) + 1
        return False


class _NoopPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_PHASE = _NoopPhase()

_FLUSH_EVERY = 256  # pending device scalars pulled per batched fetch


class TrainRecord:
    """Accumulates one training run's observability record.

    Created by ``GBDT._init_train`` and surfaced as
    ``Booster.train_record`` (a dict snapshot); the freshest record is
    also published process-wide for the ``/metrics`` exporter."""

    def __init__(self, meta: Optional[Dict[str, Any]] = None) -> None:
        self._lock = threading.Lock()
        self.meta = dict(meta or {})
        self._t_created = time.perf_counter()
        self._phase_s: Dict[str, float] = {}
        self._phase_n: Dict[str, int] = {}
        # per-tree device scalars pending a batched host pull
        self._pending: List[tuple] = []   # (iteration, class_id, hp, nl)
        self._trees: List[Dict[str, int]] = []
        self._mem_peak: Optional[int] = None
        self._coll_base = collectives_snapshot()
        self._hist_base = hist_kernel_snapshot()
        _ensure_monitoring()
        self._mon_base, self._mon_secs_base = _monitoring_snapshot()

    # -- accumulation (boosting loop) ------------------------------------
    def phase(self, name: str):
        """``with record.phase("grow"):`` — adds wall time to the named
        phase and opens a ``train/<name>`` telemetry span."""
        if not _config.enabled():
            return _NOOP_PHASE
        return _Phase(self, name)

    def add_tree(self, iteration: int, class_id: int, hist_passes,
                 num_leaves) -> None:
        """Record one grown tree.  ``hist_passes``/``num_leaves`` may be
        device scalars; they are NOT synced here — batches are pulled
        lazily so the async dispatch pipeline keeps flowing."""
        if not _config.enabled():
            return
        with self._lock:
            self._pending.append((int(iteration), int(class_id),
                                  hist_passes, num_leaves))
            flush = len(self._pending) >= _FLUSH_EVERY
        if flush:
            self._flush()

    def note_memory(self) -> None:
        if not _config.enabled():
            return
        peak = device_memory_peak()
        if peak is not None:
            with self._lock:
                self._mem_peak = max(peak, self._mem_peak or 0)

    def _flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        try:
            import jax
            vals = jax.device_get([(p[2], p[3]) for p in pending])
        except Exception:
            vals = [(p[2], p[3]) for p in pending]
        rows = [{"iteration": it, "class_id": cid,
                 "hist_passes": int(hp), "num_leaves": int(nl)}
                for (it, cid, _, _), (hp, nl) in zip(pending, vals)]
        with self._lock:
            self._trees.extend(rows)

    # -- snapshot --------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready record; pulls any pending device scalars (one
        batched fetch) and diffs the process-wide compile/collective
        tallies against this record's baseline."""
        self._flush()
        self.note_memory()  # final watermark: periodic samples miss the tail
        with self._lock:
            trees = list(self._trees)
            phase_s = dict(self._phase_s)
            phase_n = dict(self._phase_n)
            mem_peak = self._mem_peak
            elapsed = time.perf_counter() - self._t_created
        trees.sort(key=lambda r: (r["iteration"], r["class_id"]))
        coll_now = collectives_snapshot()
        coll = {}
        for site, rec in coll_now.items():
            base = self._coll_base.get(site, {"count": 0, "bytes": 0})
            dc = rec["count"] - base["count"]
            db = rec["bytes"] - base["bytes"]
            if dc > 0:
                coll[site] = {"op": rec["op"], "count": dc, "bytes": db}
        hk_now = hist_kernel_snapshot()
        hist_kernels = {}
        for site, rec in hk_now.items():
            base = self._hist_base.get(site, {"count": 0, "bytes": 0})
            dc = rec["count"] - base["count"]
            db = rec["bytes"] - base["bytes"]
            if dc > 0:
                hist_kernels[site] = {"count": dc, "bytes": db}
        mon_counts, mon_secs = _monitoring_snapshot()
        events = {}
        for k, v in _compile_events(mon_counts).items():
            d = v - self._mon_base.get(k, 0)
            if d > 0:
                events[k] = d
        secs = {}
        for k, v in mon_secs.items():
            d = v - self._mon_secs_base.get(k, 0.0)
            if d > 1e-9 and any(m in k.lower() for m in _COMPILE_MARKERS):
                secs[k] = round(d, 6)
        hp = [r["hist_passes"] for r in trees]
        return {
            "schema": "train-record-v1",
            "meta": dict(self.meta),
            "num_trees": len(trees),
            "trees": trees,
            "hist_passes_total": sum(hp),
            "hist_passes_last": hp[-1] if hp else 0,
            "phase_seconds": {k: round(v, 6) for k, v in phase_s.items()},
            "phase_calls": phase_n,
            "collectives_traced": coll,
            "hist_kernel": hist_kernels,
            "compile_events": events,
            "compile_seconds": secs,
            "device_memory_peak_bytes": mem_peak,
            "elapsed_seconds": round(elapsed, 6),
        }


# ---------------------------------------------------------------------------
# Process-wide "last training run" handle (the /metrics exporter reads it)
# ---------------------------------------------------------------------------

_last_lock = threading.Lock()
_last_record: Optional[TrainRecord] = None


def set_last_train_record(rec: Optional[TrainRecord]) -> None:
    global _last_record
    with _last_lock:
        _last_record = rec


def last_train_record() -> Optional[TrainRecord]:
    with _last_lock:
        return _last_record
